"""Kubernetes backend: parsers, manifests, instance-manager policy.

Reference test pattern (k8s_instance_manager_test.py:16-46): drive pod
lifecycle and event handling against the API; here the API is a fake
(the kubernetes package isn't installed), so start/relaunch/OOM-blacklist
/reform policy is exercised hermetically — manifests are plain dicts, so
nothing else needs the SDK.
"""

from __future__ import annotations

import pytest

from elasticdl_tpu.k8s import resource as k8s_resource
from elasticdl_tpu.k8s import volume as k8s_volume
from elasticdl_tpu.k8s.client import COORDINATOR_PORT, Client
from elasticdl_tpu.k8s.instance_manager import K8sInstanceManager
from elasticdl_tpu.k8s.tensorboard_client import TensorBoardClient


class NotFoundError(Exception):
    """Mimics kubernetes.client.ApiException(status=404): the ONLY
    signal the production classifier accepts as authoritative absence
    (client.py _is_not_found)."""

    status = 404


class FakeApi:
    def __init__(self):
        self.pods: dict[str, dict] = {}
        self.services: dict[str, dict] = {}
        self.deleted_pods: list[str] = []
        self.patches: list[tuple[str, dict]] = []

    def create_namespaced_pod(self, namespace, manifest):
        self.pods[manifest["metadata"]["name"]] = manifest
        return manifest

    def create_namespaced_service(self, namespace, manifest):
        self.services[manifest["metadata"]["name"]] = manifest
        return manifest

    def read_namespaced_pod(self, name, namespace):
        if name not in self.pods:
            raise NotFoundError(name)
        return self.pods[name]

    def read_namespaced_service(self, name, namespace):
        if name not in self.services:
            raise NotFoundError(name)
        return self.services[name]

    def delete_namespaced_pod(self, name, namespace):
        self.deleted_pods.append(name)
        self.pods.pop(name, None)

    def delete_namespaced_service(self, name, namespace):
        self.services.pop(name, None)

    def patch_namespaced_pod(self, name, namespace, body):
        self.patches.append((name, body))


# ---- parsers ---------------------------------------------------------------


def test_resource_parse_and_vendor_rename():
    parsed = k8s_resource.parse("cpu=250m,memory=32Mi,gpu=1,tpu=4")
    assert parsed == {
        "cpu": "250m",
        "memory": "32Mi",
        "nvidia.com/gpu": "1",
        "google.com/tpu": "4",
    }


@pytest.mark.parametrize(
    "bad",
    [
        "cpu=abc",
        "memory=0Mi",
        "memory=32Zi",
        "gpu=0",
        "cpu=1,cpu=2",
        "flux=7",
        "cpu:1",
    ],
)
def test_resource_parse_rejects(bad):
    with pytest.raises(ValueError):
        k8s_resource.parse(bad)


def test_volume_parse_and_manifests():
    conf = "host_path=/data,mount_path=/data;claim_name=c1,mount_path=/ckpt"
    volumes, mounts = k8s_volume.volumes_and_mounts(conf, "pod-x")
    assert volumes[0]["hostPath"]["path"] == "/data"
    assert volumes[1]["persistentVolumeClaim"]["claimName"] == "c1"
    assert [m["mountPath"] for m in mounts] == ["/data", "/ckpt"]
    assert {v["name"] for v in volumes} == {m["name"] for m in mounts}


@pytest.mark.parametrize(
    "bad",
    ["mount_path=/x", "host_path=/a", "bogus=1,mount_path=/x"],
)
def test_volume_parse_rejects(bad):
    with pytest.raises(ValueError):
        k8s_volume.parse(bad)


# ---- client manifests ------------------------------------------------------


def _client(api=None, event_callback=None):
    return Client(
        image_name="img:1",
        namespace="ns",
        job_name="job",
        event_callback=event_callback,
        api=api or FakeApi(),
        watch=False,
    )


def test_pod_manifest_labels_env_owner_volume():
    client = _client()
    owner = {"metadata": {"name": "elasticdl-job-master", "uid": "u-123"}}
    manifest = client.build_pod_manifest(
        pod_name="elasticdl-job-worker-0",
        replica_type="worker",
        replica_index=0,
        command=["python", "-m"],
        args=["elasticdl_tpu.worker.main", "--worker_id", "0"],
        resource_requests="cpu=1,memory=64Mi",
        volume="host_path=/data,mount_path=/data",
        envs={"JAX_PLATFORMS": "tpu"},
        owner_pod=owner,
    )
    labels = manifest["metadata"]["labels"]
    assert labels["elasticdl-job-name"] == "job"
    assert labels["elasticdl-replica-type"] == "worker"
    assert labels["elasticdl-replica-index"] == "0"
    assert manifest["metadata"]["ownerReferences"][0]["uid"] == "u-123"
    container = manifest["spec"]["containers"][0]
    env_names = [e["name"] for e in container["env"]]
    assert "MY_POD_IP" in env_names and "JAX_PLATFORMS" in env_names
    assert container["resources"]["requests"]["cpu"] == "1"
    # limits default to requests (reference behavior)
    assert container["resources"]["limits"]["memory"] == "64Mi"
    assert container["volumeMounts"][0]["mountPath"] == "/data"


# ---- instance manager ------------------------------------------------------


def _argv(worker_id, master_addr, **world):
    argv = [
        "elasticdl_tpu.worker.main",
        "--worker_id",
        str(worker_id),
        "--master_addr",
        master_addr,
    ]
    for key, value in world.items():
        argv.extend([f"--{key}", str(value)])
    return argv


def _manager(api, failures=None, lockstep=False, num_workers=2, reforms=2):
    return K8sInstanceManager(
        num_workers=num_workers,
        build_argv=_argv,
        master_addr="master.ns.svc:50001",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=lockstep,
        max_reforms=reforms,
        on_worker_failure=(failures.append if failures is not None else None),
        api=api,
        watch=False,
    )


def test_start_workers_creates_pods_and_services():
    api = FakeApi()
    im = _manager(api)
    im.start_workers()
    assert sorted(im.worker_ids()) == [0, 1]
    assert set(api.pods) == {
        "elasticdl-job-worker-0",
        "elasticdl-job-worker-1",
    }
    assert set(api.services) == set(api.pods)
    # each per-pod service selects on labels its pod actually carries
    for name, svc in api.services.items():
        assert (
            svc["spec"]["selector"].items()
            <= api.pods[name]["metadata"]["labels"].items()
        )
    args = api.pods["elasticdl-job-worker-1"]["spec"]["containers"][0]["args"]
    assert args[args.index("--worker_id") + 1] == "1"
    assert args[args.index("--master_addr") + 1] == "master.ns.svc:50001"


def test_deleted_pod_event_notifies_master_and_restart_uses_new_id():
    api = FakeApi()
    failures: list[int] = []
    im = _manager(api, failures=failures)
    im.start_workers()
    im._event_cb(
        {
            "type": "DELETED",
            "object": {
                "kind": "Pod",
                "metadata": {"name": "elasticdl-job-worker-0"},
                "status": {"phase": "Running"},
            },
        }
    )
    assert failures == [0]
    im.restart_worker(0)
    assert sorted(im.worker_ids()) == [1, 2]
    assert "elasticdl-job-worker-2" in api.pods


def test_oom_killed_pod_is_blacklisted_from_relaunch():
    api = FakeApi()
    failures: list[int] = []
    im = _manager(api, failures=failures)
    im.start_workers()
    im._event_cb(
        {
            "type": "MODIFIED",
            "object": {
                "kind": "Pod",
                "metadata": {"name": "elasticdl-job-worker-0"},
                "status": {
                    "phase": "Failed",
                    "containerStatuses": [
                        {"state": {"terminated": {"reason": "OOMKilled"}}}
                    ],
                },
            },
        }
    )
    assert failures == [0]
    im.restart_worker(0)
    # pod deleted, NOT relaunched (reference OOM blacklist :225-240)
    assert sorted(im.worker_ids()) == [1]
    assert "elasticdl-job-worker-2" not in api.pods


def test_lockstep_world_coordinator_and_reform():
    api = FakeApi()
    im = _manager(api, lockstep=True, reforms=1)
    im.start_workers()
    coordinator = f"elasticdl-job-worker-0.ns.svc:{COORDINATOR_PORT}"
    for worker_id in (0, 1):
        args = api.pods[f"elasticdl-job-worker-{worker_id}"]["spec"][
            "containers"
        ][0]["args"]
        assert args[args.index("--coordinator_addr") + 1] == coordinator
        assert args[args.index("--process_id") + 1] == str(worker_id)
        assert args[args.index("--num_processes") + 1] == "2"

    im.reform_world(cluster_version=1)
    # old pods deleted; new generation under new ids + new coordinator
    assert "elasticdl-job-worker-0" in api.deleted_pods
    assert sorted(im.worker_ids()) == [2, 3]
    args = api.pods["elasticdl-job-worker-2"]["spec"]["containers"][0]["args"]
    assert (
        args[args.index("--coordinator_addr") + 1]
        == f"elasticdl-job-worker-2.ns.svc:{COORDINATOR_PORT}"
    )
    assert args[args.index("--cluster_version") + 1] == "1"

    # budget: second reform still tears down, then raises
    with pytest.raises(RuntimeError):
        im.reform_world(cluster_version=2)
    assert im.worker_ids() == []


def test_stop_workers_deletes_everything():
    api = FakeApi()
    im = _manager(api)
    im.start_workers()
    im.stop_workers()
    assert api.pods == {} and api.services == {}
    assert im.worker_ids() == []


# ---- submission ------------------------------------------------------------


def test_submit_master_pod_round_trips_args():
    from elasticdl_tpu.k8s.submit import submit_master_pod
    from elasticdl_tpu.utils.args import parse_master_args

    api = FakeApi()
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            "/data/train",
            "--docker_image",
            "img:job",
            "--job_name",
            "sub",
            "--namespace",
            "ns",
        ]
    )
    out = submit_master_pod(args, api=api)
    assert out["master_pod"] == "elasticdl-sub-master"
    pod = api.pods["elasticdl-sub-master"]
    container = pod["spec"]["containers"][0]
    assert container["args"][0] == "elasticdl_tpu.master.main"
    assert "--model_def" in container["args"]
    # the in-cluster master creates workers from the SAME resolved image
    argv = container["args"]
    assert argv[argv.index("--docker_image") + 1] == "img:job"
    # master service selects on labels the master pod actually carries
    svc = api.services["elasticdl-sub-master"]
    selector = svc["spec"]["selector"]
    assert selector.items() <= pod["metadata"]["labels"].items()


def test_submit_rewrites_model_zoo_to_image_path(tmp_path):
    from elasticdl_tpu.k8s.submit import submit_master_pod
    from elasticdl_tpu.utils.args import parse_master_args

    api = FakeApi()
    args = parse_master_args(
        [
            "--model_def",
            "tiny.custom_model",
            "--model_zoo",
            str(tmp_path / "myzoo"),
            "--training_data",
            "/data/train",
            "--docker_image",
            "img:job",
            "--job_name",
            "sub2",
        ]
    )
    submit_master_pod(args, api=api)
    argv = api.pods["elasticdl-sub2-master"]["spec"]["containers"][0]["args"]
    assert argv[argv.index("--model_zoo") + 1] == "/model_zoo/myzoo"


def test_dockerfile_synthesis(tmp_path):
    from elasticdl_tpu.image_builder import create_dockerfile

    text = create_dockerfile(str(tmp_path / "zoo"), base_image="my/base:1")
    assert "FROM my/base:1" in text
    assert "COPY elasticdl_tpu /framework/elasticdl_tpu" in text
    assert f"COPY zoo /model_zoo/zoo" in text
    assert "import jax" in text

    remote = create_dockerfile("https://example.com/zoo.git")
    assert "git clone --recursive https://example.com/zoo.git" in remote


def test_tensorboard_service_and_ingress():
    api = FakeApi()
    client = _client(api=api)
    tb = TensorBoardClient(client)
    manifest = tb.create_tensorboard_service()
    assert manifest["spec"]["type"] == "LoadBalancer"
    name = manifest["metadata"]["name"]
    api.services[name]["status"] = {
        "loadBalancer": {"ingress": [{"ip": "1.2.3.4"}]}
    }
    assert tb.get_tensorboard_external_ip(max_checks=1) == "1.2.3.4"


def test_k8s_standby_pool_reform_activates_without_cold_start():
    """Re-formation assigns pre-warmed standby pods into the new world
    through the assignment mailbox instead of cold-starting pods; the
    worker-id service is re-pointed at the standby so it can coordinate."""
    import time as _time

    api = FakeApi()
    mailbox: dict = {}
    im = K8sInstanceManager(
        num_workers=2,
        build_argv=_argv,
        master_addr="master.ns.svc:50001",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        max_reforms=2,
        api=api,
        watch=False,
        standby_workers=2,
        post_assignment=lambda sid, a: mailbox.__setitem__(sid, a),
    )
    im.start_workers()
    # 2 worker pods + 2 warm standby pods carrying their mailbox identity
    assert "elasticdl-job-standby-0" in api.pods
    assert "elasticdl-job-standby-1" in api.pods
    spec = api.pods["elasticdl-job-standby-0"]["spec"]["containers"][0]
    env = {e["name"]: e.get("value") for e in spec["env"]}
    assert env["EDL_STANDBY_ID"] == "elasticdl-job-standby-0"
    assert "--standby" in spec["args"]

    im.reform_world(cluster_version=1)
    assert im.standby_activations == 2
    assert sorted(im.worker_ids()) == [2, 3]
    # assignments posted, process 0 on the first standby, with the
    # coordinator at the NEW worker id's stable DNS name
    a0 = mailbox["elasticdl-job-standby-0"]
    assert a0["worker_id"] == 2 and a0["process_id"] == 0
    assert a0["cluster_version"] == 1 and a0["num_processes"] == 2
    assert (
        a0["coordinator_addr"]
        == f"elasticdl-job-worker-2.ns.svc:{COORDINATOR_PORT}"
    )
    # the worker-2 service selects the standby pod's labels
    selector = api.services["elasticdl-job-worker-2"]["spec"]["selector"]
    assert selector["elasticdl-replica-type"] == "worker-standby"
    assert selector["elasticdl-replica-index"] == "0"
    # no cold worker pods were created for the new generation
    assert "elasticdl-job-worker-2" not in api.pods

    # the pool refills off the recovery path (background thread)
    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline:
        with im._lock:
            if len(im._standbys) == 2:
                break
        _time.sleep(0.05)
    with im._lock:
        assert [name for name, _ in im._standbys] == [
            "elasticdl-job-standby-2",
            "elasticdl-job-standby-3",
        ]

    # a standby that CRASHED while waiting (pod object persists in phase
    # Failed) is skipped and reaped, not assigned
    api.pods["elasticdl-job-standby-2"]["status"] = {"phase": "Failed"}
    im.reform_world(cluster_version=2)
    assert im.standby_activations == 3  # only the live one activated
    assert "elasticdl-job-standby-3" in mailbox
    assert "elasticdl-job-standby-2" in api.deleted_pods
    # the standby-activated worker-2's service was deleted with its world
    assert "elasticdl-job-worker-2" not in api.services


def test_pending_standby_left_pooled_not_activated():
    """A standby still Pending (scheduling / image pull) is not polling
    the mailbox yet: activating it would silently revert to cold-start
    latency. It must stay in the pool and the reform cold-start instead."""
    api = FakeApi()
    mailbox: dict = {}
    im = K8sInstanceManager(
        num_workers=2,
        build_argv=_argv,
        master_addr="master.ns.svc:50001",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        max_reforms=2,
        api=api,
        watch=False,
        standby_workers=1,
        post_assignment=lambda sid, a: mailbox.__setitem__(sid, a),
    )
    im.start_workers()
    api.pods["elasticdl-job-standby-0"]["status"] = {"phase": "Pending"}
    im.reform_world(cluster_version=1)
    assert im.standby_activations == 0
    assert "elasticdl-job-standby-0" not in mailbox
    # cold-start pod for the new generation instead
    assert any(
        name.startswith("elasticdl-job-worker-")
        for name, pod in api.pods.items()
        if name != "elasticdl-job-worker-0"
    )
    # still pooled for the next reform (refill saw a full pool)
    with im._lock:
        assert ("elasticdl-job-standby-0", 0) in im._standbys


def test_rpc_standby_wait_round_trip(tmp_path):
    """A standby polls the REAL wire for its assignment; drain tells a
    late standby to exit."""
    import threading

    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.rpc.service import create_server
    from elasticdl_tpu.worker.main import _poll_world_assignment

    servicer = MasterServicer(
        16, TaskDispatcher({"s": (0, 16)}, records_per_task=16)
    )
    server = create_server(servicer, port=0)
    server.start()

    class _Args:
        master_addr = f"localhost:{server._edl_bound_port}"

    try:
        results: list = []
        t = threading.Thread(
            target=lambda: results.append(
                _poll_world_assignment(_Args, "pod-a", poll_secs=0.05)
            )
        )
        t.start()
        servicer.post_world_assignment(
            "pod-a",
            {
                "worker_id": 7,
                "coordinator_addr": "c:1",
                "num_processes": 2,
                "process_id": 1,
                "cluster_version": 3,
            },
        )
        t.join(timeout=30)
        assert not t.is_alive()
        assert results[0]["worker_id"] == 7
        assert results[0]["coordinator_addr"] == "c:1"
        assert results[0]["cluster_version"] == 3

        # drained mailbox -> a polling standby exits with None
        servicer.drain_standbys()
        assert _poll_world_assignment(_Args, "pod-b", poll_secs=0.05) is None
    finally:
        server.stop(grace=None)


def test_cluster_spec_hooks_applied_to_manifests(tmp_path):
    """--cluster_spec module's with_pod/with_service hooks customize
    every manifest (reference k8s_client.py:271-272,468-469)."""
    spec_file = tmp_path / "my_cluster.py"
    spec_file.write_text(
        "class _Cluster:\n"
        "    def with_pod(self, pod):\n"
        "        pod['spec']['tolerations'] = [{'key': 'tpu'}]\n"
        "        return pod\n"
        "    def with_service(self, service):\n"
        "        service['metadata'].setdefault('annotations', {})[\n"
        "            'cloud'] = 'internal'\n"
        "        return service\n"
        "cluster = _Cluster()\n"
    )
    client = Client(
        image_name="img:1",
        namespace="ns",
        job_name="job",
        api=FakeApi(),
        watch=False,
        cluster_spec=str(spec_file),
    )
    pod = client.build_pod_manifest(
        pod_name="p", replica_type="worker", replica_index=0
    )
    assert pod["spec"]["tolerations"] == [{"key": "tpu"}]
    svc = client.build_service_manifest(
        "s", client.replica_selector("worker", 0), 1234
    )
    assert svc["metadata"]["annotations"]["cloud"] == "internal"


def test_submit_yaml_dumps_without_cluster(tmp_path):
    """--yaml writes the master pod+service manifests and submits
    NOTHING (reference api.py:147-161); no kubernetes SDK, no docker."""
    import yaml as yaml_lib

    from elasticdl_tpu.api import _dispatch
    from elasticdl_tpu.utils.args import parse_master_args

    spec_file = tmp_path / "my_cluster.py"
    spec_file.write_text(
        "class _Cluster:\n"
        "    def with_pod(self, pod):\n"
        "        pod['spec']['tolerations'] = [{'key': 'tpu'}]\n"
        "        return pod\n"
        "    def with_service(self, service):\n"
        "        return service\n"
        "cluster = _Cluster()\n"
    )
    out = tmp_path / "job.yaml"
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            "/data/train",
            "--distribution_strategy",
            "AllreduceStrategy",
            "--num_workers",
            "2",
            "--docker_image",
            "img:7",
            "--yaml",
            str(out),
            "--cluster_spec",
            str(spec_file),
        ]
    )
    result = _dispatch(args)
    assert result["yaml"] == str(out)
    docs = list(yaml_lib.safe_load_all(out.read_text()))
    assert [d["kind"] for d in docs] == ["Pod", "Service"]
    # the cluster hook customized the dumped master pod too
    assert docs[0]["spec"]["tolerations"] == [{"key": "tpu"}]
    pod_args = docs[0]["spec"]["containers"][0]["args"]
    assert pod_args[0] == "elasticdl_tpu.master.main"
    # with a PREBUILT image no /cluster_spec COPY ever ran: the path is
    # passed through (it must exist inside the image or on a volume);
    # only a built-by-this-submission image gets the rewrite
    idx = pod_args.index("--cluster_spec")
    assert pod_args[idx + 1] == str(spec_file)
    assert "--yaml" not in pod_args  # the in-cluster master must submit


GOLDEN_SMOKE_ARGV = [
    # the argv scripts/client_test.sh train submits (data paths fixed) —
    # the clusterless fallback for the real-cluster smoke harness
    "--model_def",
    "mnist_functional_api.mnist_functional_api.custom_model",
    "--distribution_strategy",
    "AllreduceStrategy",
    "--training_data",
    "/tmp/edl-smoke-data/train",
    "--validation_data",
    "/tmp/edl-smoke-data/test",
    "--minibatch_size",
    "64",
    "--num_minibatches_per_task",
    "2",
    "--evaluation_steps",
    "4",
    "--num_epochs",
    "1",
    "--job_name",
    "smoke-train",
    "--docker_image",
    "elasticdl-smoke:ci",
    "--image_pull_policy",
    "Never",
    "--num_workers",
    "2",
    "--master_resource_request",
    "cpu=0.2,memory=1024Mi",
    "--worker_resource_request",
    "cpu=0.4,memory=2048Mi",
    "--envs",
    "JAX_PLATFORMS=cpu",
    "--volume",
    "host_path=/tmp/edl-smoke-data,mount_path=/tmp/edl-smoke-data",
]


def _golden_manifest_docs(tmp_path):
    import yaml as yaml_lib

    from elasticdl_tpu.api import _dispatch
    from elasticdl_tpu.utils.args import parse_master_args

    out = tmp_path / "smoke.yaml"
    args = parse_master_args(GOLDEN_SMOKE_ARGV + ["--yaml", str(out)])
    _dispatch(args)
    return list(yaml_lib.safe_load_all(out.read_text()))


def test_smoke_manifest_matches_golden(tmp_path):
    """Clusterless fallback for scripts/client_test.sh: the --yaml dump
    of the smoke job must match the committed golden manifest byte for
    byte (structure-compared), so manifest regressions (labels, argv
    round-trip, env injection, volumes) are caught without a cluster.
    Regenerate after INTENTIONAL changes:
        python -m pytest tests/test_k8s.py::test_smoke_manifest_matches_golden --regen
    (or run _golden_manifest_docs and rewrite the file)."""
    import json
    import os

    import yaml as yaml_lib

    docs = _golden_manifest_docs(tmp_path)
    golden_path = os.path.join(
        os.path.dirname(__file__), "testdata", "golden_smoke_manifest.yaml"
    )
    if not os.path.exists(golden_path):  # first run: write the golden
        with open(golden_path, "w") as f:
            yaml_lib.safe_dump_all(docs, f, sort_keys=False)
        raise AssertionError(
            f"golden manifest was missing; wrote {golden_path} — rerun"
        )
    golden = list(yaml_lib.safe_load_all(open(golden_path).read()))
    assert json.dumps(docs, sort_keys=True) == json.dumps(
        golden, sort_keys=True
    ), "manifest drifted from tests/testdata/golden_smoke_manifest.yaml"


def test_stop_workers_grace_waits_for_terminal_pods():
    """stop_workers(grace_secs>0) waits for worker pods to reach a
    terminal phase before deleting them — deleting earlier SIGTERMs an
    epilogue (final dump / checkpoint flush) mid-collective."""
    import threading
    import time as _time

    api = FakeApi()
    im = K8sInstanceManager(
        num_workers=2,
        build_argv=_argv,
        master_addr="m:1",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        api=api,
        watch=False,
        standby_workers=0,
    )
    im.start_workers()
    with im._lock:
        pods = list(im._pods.values())
    assert len(pods) == 2

    done = threading.Event()
    threading.Thread(
        target=lambda: (im.stop_workers(grace_secs=10.0), done.set()),
        daemon=True,
    ).start()
    _time.sleep(0.8)
    # still waiting: pods are not terminal, nothing deleted yet
    assert not done.is_set()
    assert not any(p in api.deleted_pods for p in pods)
    for p in pods:
        api.pods[p]["status"] = {"phase": "Succeeded"}
    assert done.wait(timeout=10)
    assert all(p in api.deleted_pods for p in pods)


def test_stuck_pending_standby_evicted_after_max_skips():
    """A standby stuck Pending across _MAX_PENDING_SKIPS reforms is
    presumed unschedulable and evicted (deleted + dropped) so it cannot
    wedge a pool slot forever; the refill then creates a fresh pod."""
    api = FakeApi()
    mailbox: dict = {}
    im = K8sInstanceManager(
        num_workers=2,
        build_argv=_argv,
        master_addr="m:1",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        max_reforms=10,
        api=api,
        watch=False,
        standby_workers=1,
        post_assignment=lambda sid, a: mailbox.__setitem__(sid, a),
    )
    im.start_workers()
    pod = "elasticdl-job-standby-0"
    api.pods[pod]["status"] = {"phase": "Pending"}

    # skips 1 and 2: deferred but kept pooled
    for _ in range(im._MAX_PENDING_SKIPS - 1):
        assert im._take_live_standbys(1) == []
        with im._lock:
            assert (pod, 0) in im._standbys
    assert pod not in api.deleted_pods

    # skip 3: presumed unschedulable -> evicted
    assert im._take_live_standbys(1) == []
    assert pod in api.deleted_pods
    with im._lock:
        assert (pod, 0) not in im._standbys
    assert pod not in im._pending_skips  # aging state cleaned up

    # the refill creates a FRESH pod for the freed slot
    im._replenish_standbys()
    assert "elasticdl-job-standby-1" in api.pods
    with im._lock:
        assert ("elasticdl-job-standby-1", 1) in im._standbys


def test_read_pod_distinguishes_not_found_from_transient():
    """read_pod: None ONLY for authoritative absence (status == 404);
    any other API failure — even a KeyError from a broken wrapper —
    returns the TRANSIENT_READ_ERROR sentinel so life-or-death callers
    don't treat a blip as pod-gone (ADVICE r3)."""
    from elasticdl_tpu.k8s.client import TRANSIENT_READ_ERROR, Client

    class FlakyApi(FakeApi):
        def __init__(self):
            super().__init__()
            self.fail_with: Exception | None = None

        def read_namespaced_pod(self, name, namespace):
            if self.fail_with is not None:
                raise self.fail_with
            return super().read_namespaced_pod(name, namespace)

    api = FlakyApi()
    client = Client(
        image_name="img:1", namespace="ns", job_name="job", api=api
    )
    assert client.read_pod("missing") is None  # 404 -> not found

    api.fail_with = ConnectionError("apiserver hiccup")
    assert client.read_pod("x") is TRANSIENT_READ_ERROR
    # a bare KeyError from a broken wrapper is NOT authoritative absence
    api.fail_with = KeyError("partial api response")
    assert client.read_pod("x") is TRANSIENT_READ_ERROR
    # best-effort consumer maps the sentinel to None
    assert client.get_master_pod() is None


def test_stop_workers_grace_survives_transient_read_errors():
    """One flaky read during the grace poll must NOT cut the voluntary-
    exit window short (the exact failure the window exists to avoid)."""
    import threading
    import time as _time

    api = FakeApi()
    im = K8sInstanceManager(
        num_workers=1,
        build_argv=_argv,
        master_addr="m:1",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        api=api,
        watch=False,
        standby_workers=0,
    )
    im.start_workers()
    with im._lock:
        pods = list(im._pods.values())

    orig = api.read_namespaced_pod
    fail = {"on": True}

    def flaky(name, namespace):
        if fail["on"]:
            raise ConnectionError("apiserver hiccup")
        return orig(name, namespace)

    api.read_namespaced_pod = flaky

    done = threading.Event()
    threading.Thread(
        target=lambda: (im.stop_workers(grace_secs=15.0), done.set()),
        daemon=True,
    ).start()
    _time.sleep(0.8)
    # reads are erroring: the window must still be open, nothing deleted
    assert not done.is_set()
    assert not any(p in api.deleted_pods for p in pods)
    # API recovers, pod reaches terminal phase -> grace completes
    for p in pods:
        api.pods[p]["status"] = {"phase": "Succeeded"}
    fail["on"] = False
    assert done.wait(timeout=10)


def test_transient_read_keeps_standby_pooled():
    """An errored standby health read keeps the pod in the pool (unknown
    is not dead) and does not advance the Pending-skip aging."""
    api = FakeApi()
    mailbox: dict = {}
    im = K8sInstanceManager(
        num_workers=2,
        build_argv=_argv,
        master_addr="m:1",
        image_name="img:1",
        namespace="ns",
        job_name="job",
        lockstep=True,
        api=api,
        watch=False,
        standby_workers=1,
        post_assignment=lambda sid, a: mailbox.__setitem__(sid, a),
    )
    im.start_workers()
    pod = "elasticdl-job-standby-0"
    assert pod in api.pods

    orig = api.read_namespaced_pod

    def explode(name, namespace):
        raise ConnectionError("apiserver hiccup")

    api.read_namespaced_pod = explode
    assert im._take_live_standbys(1) == []
    with im._lock:
        assert (pod, 0) in im._standbys  # kept pooled
    assert pod not in api.deleted_pods
    assert pod not in im._pending_skips  # aging untouched

    # API recovers, pod Running -> taken normally
    api.read_namespaced_pod = orig
    api.pods[pod]["status"] = {"phase": "Running"}
    assert im._take_live_standbys(1) == [(pod, 0)]
