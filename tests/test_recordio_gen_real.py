"""Real-dataset ingestion: each recordio_gen module parses its dataset's
NATIVE distribution format from a local file (no egress) and writes
EDLIO shards the model zoo trains on.

Mirrors the reference's recordio_gen scripts
(``elasticdl/python/data/recordio_gen/{census,frappe,heart}_recordio_gen.py``,
``image_label.py``) — fixtures here are tiny files written in the genuine
on-disk formats (IDX, adult.data CSV, libfm, heart CSV), so the parsers
are exercised for real; the no-source fallback path is covered by the
train-to-accuracy test at the bottom (VERDICT r1 acceptance: shards from
``python -m elasticdl_tpu.data.recordio_gen.mnist`` train the zoo MNIST
model past 0.9 accuracy).
"""

import glob
import gzip
import os
import struct

import numpy as np

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.reader import decode_example
from elasticdl_tpu.data.recordio_gen import census, frappe, heart, mnist
from elasticdl_tpu.utils.hash_utils import string_to_id


def _read_examples(split_dir):
    out = []
    for path in sorted(glob.glob(os.path.join(split_dir, "*.edlio"))):
        with recordio.Scanner(path) as s:
            for payload in s:
                out.append(decode_example(payload))
    return out


def _write_idx(path, array, dtype_code):
    data = np.ascontiguousarray(array)
    with gzip.open(path, "wb") as f:
        f.write(struct.pack("BBBB", 0, 0, dtype_code, data.ndim))
        f.write(struct.pack(f">{data.ndim}I", *data.shape))
        f.write(data.tobytes())


def test_mnist_ingests_idx_source(tmp_path):
    src = tmp_path / "idx"
    src.mkdir()
    images = np.arange(3 * 28 * 28, dtype=np.uint8).reshape(3, 28, 28)
    labels = np.array([5, 0, 9], dtype=np.uint8)
    _write_idx(str(src / "train-images-idx3-ubyte.gz"), images, 0x08)
    _write_idx(str(src / "train-labels-idx1-ubyte.gz"), labels, 0x08)

    out = mnist.generate(str(tmp_path / "out"), source=str(src))
    examples = _read_examples(os.path.join(out, "train"))
    assert len(examples) == 3
    np.testing.assert_array_equal(examples[0]["image"], images[0])
    assert [int(e["label"]) for e in examples] == [5, 0, 9]


ADULT_ROWS = """\
39, State-gov, 77516, Bachelors, 13, Never-married, Adm-clerical, Not-in-family, White, Male, 2174, 0, 40, United-States, <=50K
50, Self-emp-not-inc, 83311, Bachelors, 13, Married-civ-spouse, Exec-managerial, Husband, White, Male, 0, 0, 13, United-States, >50K
38, ?, 215646, HS-grad, 9, Divorced, Handlers-cleaners, Not-in-family, White, Male, 0, 0, 40, ?, <=50K.
"""


def test_census_ingests_adult_format(tmp_path):
    src = tmp_path / "adult.data"
    src.write_text(ADULT_ROWS + "\n")  # trailing blank line, as shipped
    out = census.generate(
        str(tmp_path / "out"), source=str(src), eval_fraction=0.0
    )
    examples = _read_examples(os.path.join(out, "train"))
    assert len(examples) == 3
    by_age = {int(e["age"]): e for e in examples}
    assert set(by_age) == {39, 50, 38}
    assert int(by_age[50]["label"]) == 1
    assert int(by_age[39]["label"]) == 0
    assert int(by_age[38]["label"]) == 0  # adult.test-style trailing dot
    assert float(by_age[39]["capital-gain"]) == 2174.0
    assert int(by_age[39]["education-num"]) == 13

    # hashed-column parity: stored sha256 id mod a power-of-two bucket
    # count equals hashing the raw string (census columns use 64)
    stored = int(by_age[39]["workclass"])
    assert stored % 64 == string_to_id("State-gov", 64)
    # '?' missing marker gets its own consistent bucket
    assert int(by_age[38]["workclass"]) % 64 == string_to_id("?", 64)


LIBFM_TRAIN = """\
1 10:1 20:1 30:1
-1 10:1 40:1
"""
LIBFM_TEST = "0 50:1 20:1 30:1 60:1\n"


def test_frappe_ingests_libfm_format(tmp_path):
    src = tmp_path / "frappe"
    src.mkdir()
    (src / "frappe.train.libfm").write_text(LIBFM_TRAIN)
    (src / "frappe.test.libfm").write_text(LIBFM_TEST)

    out = frappe.generate(str(tmp_path / "out"), source=str(src))
    train = _read_examples(os.path.join(out, "train"))
    test = _read_examples(os.path.join(out, "test"))
    assert [int(e["label"]) for e in train] == [1, 0]
    assert [int(e["label"]) for e in test] == [0]
    # corpus-wide maxlen padding (test row has 4 ids) and dense remap:
    # raw ids 10,20,30,40,50,60 -> 1..6 in first-seen order, 0 = pad
    assert train[0]["feature"].shape == (4,)
    np.testing.assert_array_equal(train[0]["feature"], [1, 2, 3, 0])
    np.testing.assert_array_equal(train[1]["feature"], [1, 4, 0, 0])
    np.testing.assert_array_equal(test[0]["feature"], [5, 2, 3, 6])


HEART_CSV = """\
age,sex,cp,trestbps,chol,fbs,restecg,thalach,exang,oldpeak,slope,ca,thal,target
63,1,1,145,233,1,2,150,0,2.3,3,0,fixed,0
67,1,4,160,286,0,2,108,1,1.5,2,?,normal,1
"""


def test_heart_ingests_csv_format(tmp_path):
    src = tmp_path / "heart.csv"
    src.write_text(HEART_CSV)
    out = heart.generate(
        str(tmp_path / "out"), source=str(src), eval_fraction=0.0
    )
    examples = _read_examples(os.path.join(out, "train"))
    assert len(examples) == 2
    by_age = {int(e["age"]): e for e in examples}
    assert float(by_age[63]["oldpeak"]) == np.float32(2.3)
    assert int(by_age[63]["target"]) == 0
    assert int(by_age[67]["target"]) == 1
    # thal kept as an exact int64 id; distinct strings stay distinct
    assert by_age[63]["thal"].dtype == np.int64
    assert int(by_age[63]["thal"]) != int(by_age[67]["thal"])
    # '?' in a NUMERIC column (raw Cleveland data) is missing -> 0.0,
    # never a hash id
    assert float(by_age[67]["ca"]) == 0.0


def test_mnist_fallback_trains_past_90pct(tmp_path):
    """The VERDICT acceptance bar: ``recordio_gen.mnist OUT`` (no source)
    produces shards the zoo MNIST model trains on to >0.9 accuracy
    (reference bar is >0.8, worker_ps_interaction_test.py)."""
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.trainer.metrics import (
        metric_tree_results,
        update_metric_tree,
    )
    from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
    from elasticdl_tpu.trainer.step import (
        build_eval_step,
        build_train_step,
        resolve_optimizer,
    )
    from elasticdl_tpu.utils.model_utils import get_model_spec

    out = mnist.generate(
        str(tmp_path / "mnist"), num_records=1024, records_per_shard=1024
    )

    def _batches(split, mode, batch):
        reader = RecordIODataReader(data_dir=os.path.join(out, split))
        shards = reader.create_shards()

        def _gen():
            for name, (start, count) in shards.items():
                task = type(
                    "T", (), {"shard_name": name, "start": start, "end": start + count}
                )
                yield from reader.read_records(task)

        ds = Dataset.from_generator(_gen)
        spec_ds = spec.dataset_fn(ds, mode, reader.metadata)
        return list(spec_ds.batch(batch))

    spec = get_model_spec(
        "", "mnist_functional_api.mnist_functional_api.custom_model"
    )
    model = spec.build_model()
    train_batches = _batches("train", Modes.TRAINING, 64)
    features, _ = train_batches[0]
    params, model_state = init_model(model, features)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    train_step = build_train_step(spec.loss, compute_dtype=None)
    for _ in range(3):  # epochs
        for feats, labs in train_batches:
            state, _m = train_step(state, feats, labs)

    eval_step = build_eval_step(spec.loss)
    tree = spec.eval_metrics_fn()
    for feats, labs in _batches("test", Modes.EVALUATION, 64):
        outputs, _l = eval_step(state, feats, labs)
        update_metric_tree(tree, np.asarray(labs), np.asarray(outputs))
    results = metric_tree_results(tree)
    acc = float(results["accuracy"])
    assert acc > 0.9, f"eval accuracy {acc} <= 0.9"


def test_deepfm_sharded_embedding_trains_past_85pct(tmp_path):
    """BASELINE.md config-4 acceptance: the sharded-embedding DeepFM
    trains on EDLIO frappe-shape shards to >0.85 accuracy / >0.9 AUC on
    held-out data (mirrors the mnist config-1 bar above; reference
    quality gate is accuracy > 0.8, worker_ps_interaction_test.py).

    Vocab 512 keeps per-id observation counts high enough that the
    factorization can actually generalize within test-size data."""
    import jax
    import optax

    from elasticdl_tpu.data.dataset import Dataset, batched_model_pipeline
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.metrics import (
        metric_tree_results,
        update_metric_tree,
    )
    from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
    from elasticdl_tpu.trainer.step import build_eval_step, build_train_step
    from elasticdl_tpu.utils.model_utils import get_model_spec

    train_dir = synthetic.gen_frappe(
        str(tmp_path / "train"),
        num_records=4096,
        num_shards=1,
        seed=2,
        vocab_size=512,
    )
    test_dir = synthetic.gen_frappe(
        str(tmp_path / "test"),
        num_records=512,
        num_shards=1,
        seed=99,
        vocab_size=512,
    )
    spec = get_model_spec(
        "",
        "deepfm_edl_embedding.deepfm_edl_embedding.custom_model",
        model_params={"input_dim": 512},
    )

    def batches(data_dir, mode):
        reader = RecordIODataReader(data_dir=data_dir)
        shards = reader.create_shards()

        def gen():
            for name, (start, count) in shards.items():
                task = type(
                    "T",
                    (),
                    {"shard_name": name, "start": start, "end": start + count},
                )
                yield from reader.read_records(task)

        return list(
            batched_model_pipeline(
                Dataset.from_generator(gen),
                spec,
                mode,
                reader.metadata,
                128,
                shuffle_records=mode == Modes.TRAINING,
            )
        )

    train_batches = batches(train_dir, Modes.TRAINING)
    features, _ = train_batches[0]
    model = spec.build_model()
    params, model_state = init_model(model, features)
    state = TrainState.create(model.apply, params, optax.adam(5e-3), model_state)
    train_step = build_train_step(spec.loss, compute_dtype=None)
    for _ in range(15):
        for feats, labs in train_batches:
            state, _m = train_step(state, feats, labs)

    eval_step = build_eval_step(spec.loss)
    tree = spec.eval_metrics_fn()
    for feats, labs in batches(test_dir, Modes.EVALUATION):
        outputs, _l = eval_step(state, feats, labs)
        update_metric_tree(tree, np.asarray(labs), jax.device_get(outputs))
    results = metric_tree_results(tree)
    assert results["accuracy_logits"] > 0.85, results
    assert results["auc_probs"] > 0.9, results


def test_census_feature_columns_train_past_80pct(tmp_path):
    """BASELINE.md config-4, census half: the feature-column DNN (numeric
    + embedding_column categoricals) trains on EDLIO census-shape shards
    past the reference's >0.8 quality bar
    (worker_ps_interaction_test.py)."""
    import jax
    import optax

    from elasticdl_tpu.data.dataset import Dataset, batched_model_pipeline
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.metrics import (
        metric_tree_results,
        update_metric_tree,
    )
    from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
    from elasticdl_tpu.trainer.step import build_eval_step, build_train_step
    from elasticdl_tpu.utils.model_utils import get_model_spec

    train_dir = synthetic.gen_census(
        str(tmp_path / "train"),
        num_records=8192,
        num_shards=1,
        seed=2,
        # vocab 30 keeps per-value observation counts high enough for
        # the embedding_column weights to generalize within test-size data
        vocab_size=30,
    )
    test_dir = synthetic.gen_census(
        str(tmp_path / "test"), num_records=512, num_shards=1, seed=77,
        vocab_size=30,
    )
    spec = get_model_spec(
        "", "census_dnn_model.census_functional_api.custom_model"
    )

    def batches(data_dir, mode):
        reader = RecordIODataReader(data_dir=data_dir)

        def gen():
            for name, (start, count) in reader.create_shards().items():
                task = type(
                    "T",
                    (),
                    {"shard_name": name, "start": start, "end": start + count},
                )
                yield from reader.read_records(task)

        return list(
            batched_model_pipeline(
                Dataset.from_generator(gen),
                spec,
                mode,
                reader.metadata,
                128,
                shuffle_records=mode == Modes.TRAINING,
            )
        )

    train_batches = batches(train_dir, Modes.TRAINING)
    features, _ = train_batches[0]
    model = spec.build_model()
    params, model_state = init_model(model, features)
    state = TrainState.create(
        model.apply, params, optax.adam(2e-3), model_state
    )
    train_step = build_train_step(spec.loss, compute_dtype=None)
    for _ in range(20):
        for feats, labs in train_batches:
            state, _m = train_step(state, feats, labs)

    eval_step = build_eval_step(spec.loss)
    tree = spec.eval_metrics_fn()
    for feats, labs in batches(test_dir, Modes.EVALUATION):
        outputs, _l = eval_step(state, feats, labs)
        update_metric_tree(tree, np.asarray(labs), jax.device_get(outputs))
    results = metric_tree_results(tree)
    assert results["accuracy"] > 0.8, results
