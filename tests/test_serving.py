"""Serving plane: micro-batcher policy, pre-compiled engine + hot swap,
replica servicer over gRPC, router routing/eviction, telemetry buckets,
and the ``predict --serving_addr`` client path.

The model under serve is the iris linear classifier (4-float features,
3 logits) — small enough that every engine build is cheap on CPU while
exercising the full export -> load -> conform -> canonical-pad ->
predict -> slice-out chain the heavier zoo models share.
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.rpc.deadline import DeadlinePolicy
from elasticdl_tpu.serving.batcher import (
    MicroBatcher,
    ServingError,
    ServingOverloadError,
    ShapeMismatchError,
    tree_rows,
)
from elasticdl_tpu.serving.engine import ExportDirWatcher, ServingEngine
from elasticdl_tpu.serving.metrics import ServingMetrics
from elasticdl_tpu.serving.replica import (
    SERVING_METHODS,
    ServingClient,
    ServingReplica,
    ServingReplicaServicer,
)
from elasticdl_tpu.serving.router import ServingRouter, _ReplicaHandle
from elasticdl_tpu.telemetry.registry import (
    SERVING_LATENCY_BUCKETS,
    STEP_LATENCY_BUCKETS,
    Histogram,
)
from elasticdl_tpu.trainer.state import TrainState, init_model
from elasticdl_tpu.trainer.step import resolve_optimizer
from elasticdl_tpu.utils.export_utils import export_model, read_manifest
from elasticdl_tpu.utils.model_utils import get_model_spec

IRIS_DEF = "odps_iris_dnn_model.odps_iris_dnn_model.custom_model"
ROWS = 8  # canonical batch shape for these tests


def _iris_args(**overrides) -> argparse.Namespace:
    ns = argparse.Namespace(
        model_zoo="",
        model_def=IRIS_DEF,
        model_params_dict={},
    )
    for key, value in overrides.items():
        setattr(ns, key, value)
    return ns


def _export_iris(out_dir: str, version: int, scale: float = 1.0) -> str:
    """Export an iris model at ``version`` (deterministic params scaled
    by ``scale``, so distinct exports give distinct outputs)."""
    spec = get_model_spec("", IRIS_DEF)
    model = spec.build_model()
    sample = {"features": np.zeros((1, 4), np.float32)}
    params, model_state = init_model(model, sample)
    params = jax.tree_util.tree_map(lambda x: x * scale + 0.01, params)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    state = state.replace(step=jnp.asarray(version, jnp.int32))
    return export_model(out_dir, state, spec, _iris_args())


def _feats(n: int, seed: int = 0) -> dict:
    rng = np.random.RandomState(seed)
    return {"features": rng.rand(n, 4).astype(np.float32)}


@pytest.fixture
def export_v1(tmp_path):
    return _export_iris(str(tmp_path / "export_v1"), version=3)


# ---- micro-batcher ----------------------------------------------------------


def test_tree_rows_validates():
    assert tree_rows(np.zeros((5, 2))) == 5
    assert tree_rows({"a": np.zeros((3, 1)), "b": np.zeros(3)}) == 3
    with pytest.raises(ShapeMismatchError):
        tree_rows({"a": np.zeros((3, 1)), "b": np.zeros(4)})
    with pytest.raises(ShapeMismatchError):
        tree_rows({})


def test_batcher_coalesces_small_requests():
    batcher = MicroBatcher(ROWS, max_wait_secs=10.0)
    t1 = batcher.submit("a", _feats(3))
    t2 = batcher.submit("b", _feats(5))
    group = batcher.next_group(0.1)
    assert group.n_real == ROWS  # full: dispatched without waiting
    assert [(t.request_id, lo, hi) for t, lo, hi in group.segments] == [
        ("a", 0, 3),
        ("b", 0, 5),
    ]
    assert t1.rows == 3 and t2.rows == 5


def test_batcher_splits_large_request_across_groups():
    batcher = MicroBatcher(ROWS, max_wait_secs=0.0)
    ticket = batcher.submit("big", _feats(ROWS * 2 + 3))
    sizes = []
    for _ in range(3):
        group = batcher.next_group(0.1)
        sizes.append(group.n_real)
        assert group.segments[0][0] is ticket
    assert sizes == [ROWS, ROWS, 3]
    assert batcher.queue_rows() == 0


def test_batcher_max_wait_flushes_partial():
    batcher = MicroBatcher(ROWS, max_wait_secs=0.01)
    batcher.submit("a", _feats(2))
    t0 = time.monotonic()
    group = batcher.next_group(1.0)
    waited = time.monotonic() - t0
    assert group.n_real == 2
    assert waited < 0.5  # flushed by max-wait, not the poll timeout


def test_batcher_zero_wait_dispatches_immediately():
    batcher = MicroBatcher(ROWS, max_wait_secs=0.0)
    batcher.submit("a", _feats(1))
    group = batcher.next_group(0.1)
    assert group.n_real == 1


def test_batcher_overload_rejects_with_retryable_error():
    batcher = MicroBatcher(ROWS, max_wait_secs=10.0, max_queue_rows=10)
    batcher.submit("a", _feats(8))
    with pytest.raises(ServingOverloadError) as exc:
        batcher.submit("b", _feats(3))
    assert exc.value.retryable
    batcher.submit("c", _feats(2))  # still fits


def test_batcher_admits_single_request_larger_than_bound():
    """'1 row or 10,000': a request bigger than max_queue_rows must be
    servable against an empty queue (it spans groups), and shed only
    when real backlog sits in front of it."""
    batcher = MicroBatcher(ROWS, max_wait_secs=0.0, max_queue_rows=10)
    big = batcher.submit("big", _feats(25))  # > bound, empty queue: in
    assert big.rows == 25
    with pytest.raises(ServingOverloadError):
        batcher.submit("late", _feats(25))  # backlog in front: shed
    drained = 0
    while drained < 25:
        group = batcher.next_group(0.1)
        drained += group.n_real
    batcher.submit("again", _feats(25))  # drained: admitted again


def test_batcher_close_fails_pending_tickets_retryably():
    """Draining is RETRYABLE: predict is read-only, so the router must
    be allowed to re-route a rolling-restart casualty."""
    batcher = MicroBatcher(ROWS, max_wait_secs=10.0)
    ticket = batcher.submit("a", _feats(2))
    batcher.close()
    with pytest.raises(ServingError) as exc:
        ticket.result(1.0)
    assert exc.value.retryable
    with pytest.raises(ServingError) as exc:
        batcher.submit("b", _feats(1))
    assert exc.value.retryable
    assert batcher.next_group(0.01) is None


def test_ticket_completion_deferred_until_finish():
    """deliver() must NOT wake the waiter: the engine closes the phase
    decomposition first, then finish() releases — otherwise a response
    can ship a half-closed (non-sum-exact) phase set."""
    from elasticdl_tpu.serving.batcher import Ticket

    ticket = Ticket("x", np.zeros((2, 1), np.float32), 2)
    assert ticket.deliver(np.zeros((2, 3), np.float32), 2, 1) is True
    assert not ticket.done
    ticket.finish()
    assert ticket.done


def test_predict_with_retry_retries_only_retryable():
    from elasticdl_tpu.serving.predict_client import _predict_with_retry

    calls = []

    class _Shedding:
        def predict(self, _request):
            calls.append(1)
            return msg.PredictResponse(error="queue full", retryable=True)

    response = _predict_with_retry(_Shedding(), None, attempts=3)
    assert response.error and len(calls) == 3  # retried to exhaustion

    calls.clear()

    class _Broken:
        def predict(self, _request):
            calls.append(1)
            return msg.PredictResponse(error="bad request", retryable=False)

    response = _predict_with_retry(_Broken(), None, attempts=3)
    assert response.error and len(calls) == 1  # not retried


def test_group_features_concatenates_in_row_order():
    batcher = MicroBatcher(ROWS, max_wait_secs=10.0)
    a, b = _feats(3, seed=1), _feats(5, seed=2)
    batcher.submit("a", a)
    batcher.submit("b", b)
    group = batcher.next_group(0.1)
    feats = group.features()
    np.testing.assert_array_equal(feats["features"][:3], a["features"])
    np.testing.assert_array_equal(feats["features"][3:], b["features"])


# ---- engine -----------------------------------------------------------------


def _run_one(engine, request_id, features, max_wait=0.0):
    """Drive one request through a private batcher + the engine (the
    dispatch-loop body, synchronously)."""
    batcher = MicroBatcher(engine.canonical_rows, max_wait_secs=max_wait)
    ticket = batcher.submit(request_id, features)
    while not ticket.done:
        group = batcher.next_group(0.1)
        if group is None:
            break
        engine.run_group(group)
    return ticket


def test_engine_parity_with_direct_apply(export_v1):
    engine = ServingEngine(export_v1, ROWS)
    feats = _feats(5)
    served = engine.predict_rows(feats)
    spec = get_model_spec("", IRIS_DEF)
    model = spec.build_model()
    from elasticdl_tpu.utils.export_utils import (
        load_exported_model,
        rebuild_variables,
    )

    model2, flat_params, flat_state = load_exported_model(export_v1)
    params, model_state = rebuild_variables(
        model2, {"features": feats["features"][:1]}, flat_params, flat_state
    )
    direct = model.apply(
        {"params": params, **model_state}, feats, training=False
    )
    np.testing.assert_allclose(served, np.asarray(direct), atol=1e-5)
    assert served.shape == (5, 3)


def test_engine_zero_recompiles_across_mixed_sizes(export_v1):
    from elasticdl_tpu.telemetry import compile_tracker

    compile_tracker.install()
    engine = ServingEngine(export_v1, ROWS)
    _run_one(engine, "warm", _feats(ROWS))  # warmup compiles here
    flat0 = compile_tracker.compile_count()
    for i, n in enumerate([1, 7, ROWS, ROWS + 3, 2, ROWS * 3 + 1]):
        ticket = _run_one(engine, f"r{i}", _feats(n, seed=i))
        assert ticket.error is None
        assert np.asarray(ticket.result(1.0)).shape == (n, 3)
    assert compile_tracker.compile_count() == flat0  # compile-once


def test_engine_conform_rejects_mismatches(export_v1):
    engine = ServingEngine(export_v1, ROWS)
    engine.predict_rows(_feats(2))  # locks the feature spec
    with pytest.raises(ShapeMismatchError):
        engine.conform({"features": np.zeros((2, 5), np.float32)})
    with pytest.raises(ShapeMismatchError):
        engine.conform({"wrong_key": np.zeros((2, 4), np.float32)})
    with pytest.raises(ShapeMismatchError):
        engine.conform(np.zeros((2, 4), np.float32))  # bare vs dict


def test_engine_conform_casts_dtype_instead_of_recompiling(export_v1):
    from elasticdl_tpu.telemetry import compile_tracker

    compile_tracker.install()
    engine = ServingEngine(export_v1, ROWS)
    engine.predict_rows(_feats(2))
    flat0 = compile_tracker.compile_count()
    out = engine.predict_rows({"features": np.ones((3, 4), np.float64)})
    assert out.shape == (3, 3)
    assert compile_tracker.compile_count() == flat0


def test_engine_request_anatomy_sums_exactly(export_v1):
    engine = ServingEngine(export_v1, ROWS)
    _run_one(engine, "warm", _feats(ROWS))
    ticket = _run_one(engine, "r", _feats(ROWS * 2 + 1))  # spans 3 groups
    assert ticket.dispatches == 3
    phases = ticket.phases_secs
    from elasticdl_tpu.telemetry.anatomy import (
        PHASE_QUEUE_WAIT,
        PHASE_UNTRACKED,
        SERVING_REQUEST_PHASES,
    )

    assert set(SERVING_REQUEST_PHASES) <= set(phases)
    assert PHASE_QUEUE_WAIT in phases and PHASE_UNTRACKED in phases
    assert abs(sum(phases.values()) - ticket.total_secs()) < 1e-6


def test_engine_hot_swap_advances_and_refuses_stale(export_v1, tmp_path):
    export_v2 = _export_iris(str(tmp_path / "export_v2"), version=9, scale=3.0)
    engine = ServingEngine(export_v1, ROWS)
    feats = _feats(4)
    before = engine.predict_rows(feats)
    accepted, version, reason = engine.swap_from_export(export_v2)
    assert accepted and version == 9 and not reason
    after = engine.predict_rows(feats)
    assert not np.allclose(before, after)  # new leaves actually serve
    # stale re-delivery (the versioned-put contract) is absorbed
    accepted2, version2, reason2 = engine.swap_from_export(export_v2)
    assert not accepted2 and version2 == 9 and "stale" in reason2
    # and a swap to a DIFFERENT model family is refused outright
    other = _export_iris(str(tmp_path / "export_v3"), version=20)
    manifest = read_manifest(other)
    manifest["model_def"] = "mnist_functional_api.something"
    import json

    with open(os.path.join(other, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    accepted3, _v, reason3 = engine.swap_from_export(other)
    assert not accepted3 and "model_def mismatch" in reason3


def test_engine_swap_zero_recompiles_and_prebuild_swap(export_v1, tmp_path):
    from elasticdl_tpu.telemetry import compile_tracker

    export_v2 = _export_iris(str(tmp_path / "export_v2"), version=7, scale=2.0)
    # swap BEFORE the lazy build: the pending flats are replaced
    engine = ServingEngine(export_v1, ROWS)
    accepted, version, _ = engine.swap_from_export(export_v2)
    assert accepted and version == 7 and not engine.built
    out = engine.predict_rows(_feats(2))
    assert engine.version == 7 and out.shape == (2, 3)
    # swap AFTER build: program reused, compile counter flat
    compile_tracker.install()
    export_v3 = _export_iris(str(tmp_path / "export_v3"), version=11, scale=4.0)
    flat0 = compile_tracker.compile_count()
    accepted, _, _ = engine.swap_from_export(export_v3)
    assert accepted
    engine.predict_rows(_feats(3))
    assert compile_tracker.compile_count() == flat0


def test_engine_swap_state_dicts_in_memory(export_v1):
    """The ReplicaStore/checkpoint-stream seam: flat name-keyed arrays
    swap in without any disk artifact."""
    from elasticdl_tpu.utils import tree_utils

    engine = ServingEngine(export_v1, ROWS)
    feats = _feats(3)
    before = engine.predict_rows(feats)
    flat = tree_utils.tree_to_dict(engine._state.params)
    flat = {k: v * 5.0 for k, v in flat.items()}
    accepted, version, _ = engine.swap_state_dicts(
        flat, {}, engine.version + 4, source="replica-store"
    )
    assert accepted and version == engine.version
    after = engine.predict_rows(feats)
    assert not np.allclose(before, after)


def test_engine_swap_incompatible_state_refused(export_v1):
    engine = ServingEngine(export_v1, ROWS)
    engine.predict_rows(_feats(2))
    accepted, _v, reason = engine.swap_state_dicts(
        {"not_a_param": np.zeros(3)}, {}, engine.version + 1
    )
    assert not accepted and "incompatible state" in reason


def test_export_watcher_applies_new_version(export_v1):
    engine = ServingEngine(export_v1, ROWS)
    watcher = ExportDirWatcher(engine, export_v1)
    assert not watcher.poll_once()  # same version: no-op
    _export_iris(export_v1, version=21, scale=2.0)  # re-export in place
    assert watcher.poll_once()
    assert engine.version == 21
    assert not watcher.poll_once()


def test_serving_events_and_metrics_emitted(export_v1, tmp_path):
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import (
        EVENT_MODEL_SWAP,
        EVENT_SERVING_REQUEST,
        read_events,
    )

    telemetry_dir = str(tmp_path / "telemetry")
    worker_hooks.install(telemetry_dir)
    try:
        metrics = ServingMetrics()
        engine = ServingEngine(export_v1, ROWS, metrics=metrics)
        _run_one(engine, "req-1", _feats(5))
        export_v2 = _export_iris(
            str(tmp_path / "export_v2"), version=30, scale=2.0
        )
        engine.swap_from_export(export_v2)
        events = read_events(
            os.path.join(telemetry_dir, "events.jsonl")
        )
        requests = [
            e for e in events if e["event"] == EVENT_SERVING_REQUEST
        ]
        swaps = [e for e in events if e["event"] == EVENT_MODEL_SWAP]
        assert len(requests) == 1 and len(swaps) == 1
        req = requests[0]
        assert req["rows"] == 5 and req["request_id"] == "req-1"
        tracked = sum(
            v for k, v in req.items() if k.endswith("_ms") and k != "total_ms"
        )
        assert abs(tracked - req["total_ms"]) < 1e-3  # sum-exact in ms
        assert swaps[0]["model_version"] == 30
        assert metrics.requests.value == 1
        assert metrics.rows.value == 5
        assert metrics.swaps.value == 1
        assert metrics.model_version.value == 30
    finally:
        worker_hooks.uninstall()


# ---- messages ---------------------------------------------------------------


def test_pack_array_tree_roundtrip():
    bare = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = msg.unpack_array_tree(msg.pack_array_tree(bare))
    np.testing.assert_array_equal(out, bare)
    tree = {"a": np.ones((2, 3)), "b": np.zeros(2, np.int64)}
    out = msg.unpack_array_tree(msg.pack_array_tree(tree))
    assert set(out) == {"a", "b"}
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert out["b"].dtype == np.int64


def test_replica_stale_swap_response_carries_structured_field(
    replica, export_v1, tmp_path
):
    """The router's convergence logic reads SwapModelResponse.stale,
    never the reason wording — pin that the replica sets it."""
    export_v2 = _export_iris(str(tmp_path / "v2"), version=40, scale=2.0)
    first = replica.servicer.swap_model(
        msg.SwapModelRequest(model_dir=export_v2)
    )
    assert first.accepted and not first.stale
    replay = replica.servicer.swap_model(
        msg.SwapModelRequest(model_dir=export_v2)
    )
    assert not replay.accepted and replay.stale
    assert replay.model_version == 40


def test_serving_messages_encode_decode_roundtrip():
    request = msg.PredictRequest(
        request_id="r1", features=msg.pack_array_tree(np.ones((2, 4))), rows=2
    )
    decoded = msg.decode(msg.encode(request))
    assert decoded.request_id == "r1" and decoded.rows == 2
    np.testing.assert_array_equal(
        msg.unpack_array_tree(decoded.features), np.ones((2, 4))
    )
    response = msg.PredictResponse(
        outputs=msg.pack_array_tree({"y": np.zeros(3)}),
        model_version=7,
        rows=3,
        phases={"queue_wait": 0.5, "total_ms": 2.0},
    )
    decoded = msg.decode(msg.encode(response))
    assert decoded.model_version == 7 and decoded.phases["total_ms"] == 2.0
    status = msg.decode(
        msg.encode(msg.ServingStatusResponse(replica_id=2, compile_count=5))
    )
    assert status.replica_id == 2 and status.compile_count == 5
    swap = msg.decode(
        msg.encode(msg.SwapModelRequest(model_dir="/x", min_version=3))
    )
    assert swap.model_dir == "/x" and swap.min_version == 3


def test_serving_methods_all_classified():
    from elasticdl_tpu.rpc.idempotency import IDEMPOTENCY

    for method in SERVING_METHODS:
        assert method in IDEMPOTENCY, method


# ---- replica servicer + gRPC ------------------------------------------------


@pytest.fixture
def replica(export_v1):
    rep = ServingReplica(
        export_v1, ROWS, max_wait_secs=0.002, replica_id=0, port=0
    ).start()
    yield rep
    rep.close()


def test_replica_grpc_mixed_sizes_concurrent(replica):
    client = ServingClient(
        f"localhost:{replica.port}", deadlines=DeadlinePolicy.from_secs(10)
    )
    try:
        sizes = [1, 7, ROWS, ROWS + 3]
        with ThreadPoolExecutor(4) as pool:
            futures = [
                pool.submit(
                    client.predict,
                    msg.PredictRequest(
                        request_id=f"q{i}",
                        features=msg.pack_array_tree(_feats(n, seed=i)),
                    ),
                )
                for i, n in enumerate(sizes)
            ]
            responses = [f.result() for f in futures]
        for n, response in zip(sizes, responses):
            assert not response.error, response.error
            out = msg.unpack_array_tree(response.outputs)
            assert np.asarray(out).shape == (n, 3)
            assert response.phases["total_ms"] > 0
        status = client.serving_status()
        assert status.requests == len(sizes)
        assert status.rows == sum(sizes)
        assert status.canonical_rows == ROWS
    finally:
        client.close()


def test_replica_grpc_parity_per_row(replica, export_v1):
    engine = ServingEngine(export_v1, ROWS)
    client = ServingClient(
        f"localhost:{replica.port}", deadlines=DeadlinePolicy.from_secs(10)
    )
    try:
        feats = _feats(6, seed=42)
        response = client.predict(
            msg.PredictRequest(
                request_id="p", features=msg.pack_array_tree(feats)
            )
        )
        assert not response.error
        np.testing.assert_allclose(
            msg.unpack_array_tree(response.outputs),
            engine.predict_rows(feats),
            atol=1e-5,
        )
    finally:
        client.close()


def test_replica_overload_response_is_retryable(export_v1):
    # no dispatch thread: the queue only fills
    rep = ServingReplica(export_v1, ROWS, max_queue_rows=8)
    servicer = rep.servicer
    first = threading.Thread(
        target=servicer.predict,
        args=(
            msg.PredictRequest(
                request_id="fill", features=msg.pack_array_tree(_feats(8))
            ),
        ),
        daemon=True,
    )
    first.start()
    for _ in range(100):
        if rep.batcher.queue_rows() == 8:
            break
        time.sleep(0.01)
    response = servicer.predict(
        msg.PredictRequest(
            request_id="shed", features=msg.pack_array_tree(_feats(1))
        )
    )
    assert response.error and response.retryable
    assert rep.engine.metrics.rejected.value == 1
    rep.batcher.close()  # releases the filler thread
    first.join(timeout=5)


def test_replica_bad_payload_answers_not_crashes(replica):
    response = replica.servicer.predict(
        msg.PredictRequest(request_id="bad", features=b"not tensors")
    )
    assert response.error and not response.retryable


# ---- router -----------------------------------------------------------------


class _FakeClient:
    def __init__(self, outcome):
        self.outcome = outcome  # callable or response
        self.calls = 0
        self.swaps = []
        self.closed = False

    def predict(self, request):
        self.calls += 1
        if callable(self.outcome):
            return self.outcome(request)
        return self.outcome

    def serving_status(self, request=None):
        return msg.ServingStatusResponse(replica_id=0, model_version=1)

    def swap_model(self, request):
        self.swaps.append(request)
        return msg.SwapModelResponse(accepted=True, model_version=5)

    def close(self):
        self.closed = True


def _inject(router, replica_id, client, last_seen=None):
    handle = _ReplicaHandle(replica_id, f"fake:{replica_id}", client)
    if last_seen is not None:
        handle.last_seen = last_seen
    router._replicas[replica_id] = handle
    return handle


def _unavailable_error():
    from elasticdl_tpu.chaos.netem import InjectedRpcError
    import grpc

    return InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "down")


def test_router_routes_around_dead_replica():
    router = ServingRouter()
    ok = msg.PredictResponse(outputs=b"", model_version=1, rows=1)

    def dead(_request):
        raise _unavailable_error()

    dead_client = _FakeClient(dead)
    live_client = _FakeClient(ok)
    _inject(router, 0, dead_client)
    _inject(router, 1, live_client)
    # make replica 0 preferred (least outstanding tie -> first found);
    # force deterministic: give live one outstanding so dead is tried
    router._replicas[1].outstanding = 1
    response = router.predict(msg.PredictRequest(request_id="r"))
    assert not response.error
    assert dead_client.calls == 1 and live_client.calls == 1
    # leases released either way (replica 1 keeps its preset baseline)
    assert router._replicas[0].outstanding == 0
    assert router._replicas[1].outstanding == 1


def test_router_nonretryable_error_raises():
    router = ServingRouter()

    def boom(_request):
        raise ValueError("bug, not outage")

    _inject(router, 0, _FakeClient(boom))
    with pytest.raises(ValueError):
        router.predict(msg.PredictRequest(request_id="r"))


def test_router_skips_evicted_replica():
    router = ServingRouter(evict_after_secs=0.5)
    ok = msg.PredictResponse(outputs=b"", model_version=1, rows=1)
    stale_client = _FakeClient(ok)
    _inject(router, 0, stale_client, last_seen=time.monotonic() - 10)
    response = router.predict(msg.PredictRequest(request_id="r"))
    assert response.error and response.retryable  # nothing live
    assert stale_client.calls == 0
    assert router.live_replicas() == []


def test_router_retryable_overload_tries_next_replica():
    router = ServingRouter()
    shed = msg.PredictResponse(error="queue full", retryable=True)
    ok = msg.PredictResponse(outputs=b"", model_version=1, rows=1)
    a, b = _FakeClient(shed), _FakeClient(ok)
    _inject(router, 0, a)
    _inject(router, 1, b)
    router._replicas[1].outstanding = 1  # a first
    response = router.predict(msg.PredictRequest(request_id="r"))
    assert not response.error
    assert a.calls == 1 and b.calls == 1


def test_router_swap_fans_to_all_and_merges():
    router = ServingRouter()
    a, b = _FakeClient(None), _FakeClient(None)
    _inject(router, 0, a)
    _inject(router, 1, b)
    response = router.swap_model(msg.SwapModelRequest(model_dir="/m"))
    assert response.accepted and response.model_version == 5
    assert len(a.swaps) == 1 and len(b.swaps) == 1
    assert len(response.replicas) == 2


def test_router_swap_redelivery_absorbed_and_unreachable_not():
    """The versioned-put contract at the ROUTER level: a re-delivered
    swap every replica refuses as stale IS converged (accepted); an
    unreachable replica means the fleet is NOT consistently swapped."""
    router = ServingRouter()

    class _StaleClient(_FakeClient):
        def swap_model(self, request):
            return msg.SwapModelResponse(
                accepted=False,
                model_version=5,
                reason="stale swap: version 5 <= served 5",
                stale=True,
            )

    _inject(router, 0, _StaleClient(None))
    _inject(router, 1, _StaleClient(None))
    response = router.swap_model(msg.SwapModelRequest(model_dir="/m"))
    assert response.accepted  # replay fully absorbed
    assert all(o["absorbed"] for o in response.replicas)

    class _DownClient(_FakeClient):
        def swap_model(self, request):
            raise _unavailable_error()

    _inject(router, 2, _DownClient(None))
    response = router.swap_model(msg.SwapModelRequest(model_dir="/m"))
    assert not response.accepted  # one replica missed the swap
    assert "unreachable" in response.reason


def test_router_probe_refreshes_and_forgets():
    router = ServingRouter(evict_after_secs=0.5, forget_after_secs=1.0)
    ok_client = _FakeClient(msg.PredictResponse())
    handle = _inject(router, 0, ok_client, last_seen=time.monotonic() - 0.9)

    class _DeadStatus:
        def serving_status(self, request=None):
            raise _unavailable_error()

        def close(self):
            pass

    dead = _DeadStatus()
    _inject(router, 1, dead, last_seen=time.monotonic() - 5.0)
    router.probe_once()
    assert 0 in router.live_replicas()  # probe refreshed it
    assert handle.last_status is not None
    assert 1 not in router._replicas  # silent past forget horizon


def test_router_e2e_grpc(replica):
    router = ServingRouter(deadlines=DeadlinePolicy.from_secs(10))
    try:
        router.add_replica(f"localhost:{replica.port}")
        router.probe_once()
        response = router.predict(
            msg.PredictRequest(
                request_id="r", features=msg.pack_array_tree(_feats(3))
            )
        )
        assert not response.error
        assert np.asarray(
            msg.unpack_array_tree(response.outputs)
        ).shape == (3, 3)
        status = router.serving_status(msg.ServingStatusRequest(detail=True))
        assert status.model_version == 3
        assert len(status.replicas) == 1
    finally:
        router.close()


# ---- chaos: the netem seam applies to serving RPCs --------------------------


def test_serving_predict_survives_injected_unavailable(replica):
    """A client-side injected UNAVAILABLE rides the SAME retry loop as
    control-plane RPCs — predict is classified retry-safe."""
    from elasticdl_tpu.chaos.netem import NetemShim
    from elasticdl_tpu.chaos.plan import Fault, FaultKind
    from elasticdl_tpu.rpc import service as rpc_service
    from elasticdl_tpu.rpc.retry import RetryPolicy

    shim = NetemShim(
        [
            Fault(
                kind=FaultKind.NET_UNAVAILABLE,
                fault_id="u",
                method="predict",
                count=1,
            )
        ],
        plan_seed=1,
    )
    rpc_service.set_client_fault_shim(shim)
    try:
        client = ServingClient(
            f"localhost:{replica.port}",
            retry=RetryPolicy(max_attempts=5),
            deadlines=DeadlinePolicy.from_secs(10),
        )
        try:
            response = client.predict(
                msg.PredictRequest(
                    request_id="r", features=msg.pack_array_tree(_feats(2))
                )
            )
        finally:
            client.close()
        assert not response.error  # the injected failure was retried
    finally:
        rpc_service.set_client_fault_shim(None)


def test_serving_predict_duplicate_delivery_harmless(replica):
    """Server-side duplicate delivery re-executes predict — read-only,
    so the caller still gets one correct answer."""
    from elasticdl_tpu.chaos.netem import NetemShim
    from elasticdl_tpu.chaos.plan import Fault, FaultKind
    from elasticdl_tpu.rpc import service as rpc_service

    shim = NetemShim(
        [
            Fault(
                kind=FaultKind.NET_DUPLICATE,
                fault_id="d",
                method="predict",
                count=1,
            )
        ],
        plan_seed=1,
    )
    rpc_service.set_server_fault_shim(shim)
    try:
        client = ServingClient(
            f"localhost:{replica.port}",
            deadlines=DeadlinePolicy.from_secs(10),
        )
        try:
            feats = _feats(4, seed=9)
            response = client.predict(
                msg.PredictRequest(
                    request_id="dup", features=msg.pack_array_tree(feats)
                )
            )
        finally:
            client.close()
        assert not response.error
        assert np.asarray(
            msg.unpack_array_tree(response.outputs)
        ).shape == (4, 3)
    finally:
        rpc_service.set_server_fault_shim(None)


# ---- histogram buckets (satellite: sub-ms serving resolution) ---------------


def test_step_buckets_pinned_unchanged():
    """The monotone set_totals mirror depends on stable step-bucket
    boundaries; serving got its OWN family instead of changing these."""
    assert STEP_LATENCY_BUCKETS == (
        0.001,
        0.0025,
        0.005,
        0.01,
        0.025,
        0.05,
        0.1,
        0.25,
        0.5,
        1.0,
        2.5,
        5.0,
        10.0,
        30.0,
        60.0,
    )
    assert Histogram().bounds == STEP_LATENCY_BUCKETS


def test_serving_buckets_sub_millisecond_resolution():
    assert SERVING_LATENCY_BUCKETS[0] == pytest.approx(0.0001)
    assert sum(1 for b in SERVING_LATENCY_BUCKETS if b < 0.001) == 3
    assert SERVING_LATENCY_BUCKETS == tuple(sorted(SERVING_LATENCY_BUCKETS))
    assert SERVING_LATENCY_BUCKETS[-1] == 10.0
    metrics = ServingMetrics()
    metrics.observe_latency("total", 0.0004)
    hist = metrics._latency["total"]
    assert hist.bounds == SERVING_LATENCY_BUCKETS
    snap = hist.snapshot()
    assert snap["buckets"][0.0005] == 1  # sub-ms observation resolved
    assert snap["buckets"][0.00025] == 0


# ---- predict --serving_addr (satellite) -------------------------------------


def test_serving_addr_flag_preserves_argv_byte_identity():
    from elasticdl_tpu.utils.args import (
        build_arguments_from_parsed_result,
        parse_master_args,
    )

    base = ["--model_def", IRIS_DEF, "--prediction_data", "/tmp/x"]
    args_unset = parse_master_args(base)
    args_set = parse_master_args(base + ["--serving_addr", "localhost:1"])
    rebuilt_unset = build_arguments_from_parsed_result(args_unset)
    rebuilt_set = build_arguments_from_parsed_result(args_set)
    assert "--serving_addr" not in rebuilt_unset  # None is dropped
    assert "--serving_addr" in rebuilt_set
    assert [a for a in rebuilt_set if a != "--serving_addr"
            and a != "localhost:1"] == rebuilt_unset


def test_predict_cli_targets_serving_endpoint(replica, tmp_path):
    from elasticdl_tpu import api
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.utils.args import parse_master_args

    data_dir = synthetic.gen_iris(
        str(tmp_path / "iris"), num_records=24, num_shards=1, seed=3
    )
    args = parse_master_args(
        [
            "--model_def",
            IRIS_DEF,
            "--prediction_data",
            data_dir,
            "--minibatch_size",
            "8",
            "--records_per_task",
            "24",
            "--serving_addr",
            f"localhost:{replica.port}",
        ]
    )
    result = api.predict(args)
    assert result["rows"] == 24
    assert result["failures"] == 0
    assert result["model_version"] == 3
    assert replica.engine.requests_served >= 3
