"""Step anatomy (ISSUE 10): sum-exact per-dispatch phase attribution,
the heartbeat-shipped /metrics mirror, the report's goodput ledger, the
/healthz progress/degradation fields, and the flag-off byte-identity
contract."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from elasticdl_tpu.telemetry import anatomy
from elasticdl_tpu.telemetry.anatomy import (
    ALL_PHASES,
    PHASE_ASSEMBLE,
    PHASE_DEVICE_COMPUTE,
    PHASE_HOST_FETCH,
    PHASE_STEP_BOOKKEEPING,
    PHASE_UNTRACKED,
    AnatomyRecorder,
)


@pytest.fixture(autouse=True)
def _clean_installs(monkeypatch):
    monkeypatch.delenv(anatomy.STEP_ANATOMY_ENV, raising=False)
    yield
    anatomy.uninstall()
    from elasticdl_tpu.telemetry import tracing, worker_hooks

    worker_hooks.uninstall()
    tracing.uninstall()


# ---- recorder: the sum-exact contract ---------------------------------------


def test_phases_plus_untracked_sum_exactly_to_wall():
    rec = AnatomyRecorder()
    with rec.phase(PHASE_ASSEMBLE):
        pass
    with rec.phase(PHASE_DEVICE_COMPUTE, sub="enqueue"):
        pass
    phases = rec.commit(steps=1, records=4)
    assert set(phases) <= set(ALL_PHASES)
    # untracked is the residual BY CONSTRUCTION: reconstructing wall
    # from the committed phases is exact to float noise
    tracked = sum(v for k, v in phases.items() if k != PHASE_UNTRACKED)
    assert phases[PHASE_UNTRACKED] >= 0.0
    # a second commit with no intervals is a no-op
    assert rec.commit() is None
    assert rec.dispatches == 1
    assert tracked >= 0.0


def test_wrap_fetches_attributes_next_time_to_host_fetch():
    rec = AnatomyRecorder()
    items = list(rec.wrap_fetches([1, 2, 3]))
    assert items == [1, 2, 3]
    phases = rec.commit(steps=3, records=3)
    assert PHASE_HOST_FETCH in phases
    snap = rec.heartbeat_snapshot()
    assert snap[PHASE_HOST_FETCH]["count"] == 1
    assert snap[PHASE_HOST_FETCH]["ms"] >= 0.0
    # bucket counts are string-keyed (msgpack strict_map_key) and sum
    # to the dispatch count
    assert sum(snap[PHASE_HOST_FETCH]["buckets"].values()) == 1


def test_wrapped_hook_times_as_bookkeeping():
    rec = AnatomyRecorder()
    calls = []
    hook = rec.wrapped_hook(calls.append)
    hook("x")
    assert calls == ["x"]
    phases = rec.commit()
    assert PHASE_STEP_BOOKKEEPING in phases
    assert rec.wrapped_hook(None) is None


def test_heartbeat_snapshot_is_monotone_across_commits():
    rec = AnatomyRecorder()
    with rec.phase(PHASE_ASSEMBLE):
        pass
    rec.commit()
    first = rec.heartbeat_snapshot()[PHASE_ASSEMBLE]
    with rec.phase(PHASE_ASSEMBLE):
        pass
    rec.commit()
    second = rec.heartbeat_snapshot()[PHASE_ASSEMBLE]
    assert second["count"] == first["count"] + 1
    assert second["ms"] >= first["ms"]


# ---- disabled contract ------------------------------------------------------


def test_disabled_module_hooks_take_no_clock_reads(monkeypatch):
    anatomy.uninstall()

    def boom():
        raise AssertionError("clock read on the disabled path")

    monkeypatch.setattr("time.monotonic", boom)
    assert anatomy.get_recorder() is None
    assert anatomy.heartbeat_snapshot() == {}


def test_install_if_enabled_honors_flag_and_env(monkeypatch):
    assert anatomy.install_if_enabled(None) is None
    assert anatomy.get_recorder() is None
    assert anatomy.install_if_enabled(True) is not None
    anatomy.uninstall()
    monkeypatch.setenv(anatomy.STEP_ANATOMY_ENV, "1")
    assert anatomy.install_from_env() is not None


# ---- run_stacked_steps integration ------------------------------------------


class _Trainer:
    step = 7

    def pad_to(self, tree, rows):
        import jax

        def _pad(x):
            x = np.asarray(x)
            if x.shape[0] == rows:
                return x
            return np.concatenate(
                [x, np.repeat(x[-1:], rows - x.shape[0], axis=0)]
            )

        return jax.tree_util.tree_map(_pad, tree)

    def row_mask(self, n, rows):
        mask = np.zeros(rows, np.float32)
        mask[:n] = 1.0
        return mask

    def place_batch(self, tree):
        return tree

    def place_stacked(self, tree):
        return tree

    def train_step(self, features, labels, weights=None):
        return np.float32(0.0)

    def train_steps_stacked(self, features, labels, weights=None):
        return np.float32(0.0)


def _batches(sizes):
    return [
        (np.ones((n, 2), np.float32), np.arange(n, dtype=np.int32))
        for n in sizes
    ]


def test_run_stacked_steps_commits_one_anatomy_per_group():
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    rec = AnatomyRecorder()
    processed = run_stacked_steps(
        lambda: _Trainer(),
        iter(_batches([4, 4, 3])),
        3,
        canonical_rows=4,
        anatomy=rec,
    )
    assert processed == 11
    assert rec.dispatches == 1
    snap = rec.heartbeat_snapshot()
    for phase in (
        PHASE_HOST_FETCH,
        PHASE_ASSEMBLE,
        "h2d_transfer",
        PHASE_DEVICE_COMPUTE,
        PHASE_UNTRACKED,
    ):
        assert phase in snap, f"missing {phase}: {sorted(snap)}"


def test_run_stacked_steps_partial_group_still_one_commit():
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    rec = AnatomyRecorder()
    run_stacked_steps(
        lambda: _Trainer(),
        iter(_batches([4, 4, 3])),
        2,
        canonical_rows=4,
        anatomy=rec,
    )
    # groups: [4,4] stacked + [3] trailing single = 2 commits
    assert rec.dispatches == 2


def test_run_stacked_steps_prestacked_group_committed():
    from elasticdl_tpu.trainer.stacking import PreStacked, run_stacked_steps

    rec = AnatomyRecorder()
    feats = np.ones((2, 4, 2), np.float32)
    labels = np.zeros((2, 4), np.int32)
    run_stacked_steps(
        lambda: _Trainer(),
        iter([PreStacked(feats, labels, 8, feats[0])]),
        2,
        canonical_rows=4,
        anatomy=rec,
    )
    assert rec.dispatches == 1
    snap = rec.heartbeat_snapshot()
    assert "h2d_transfer" in snap and PHASE_DEVICE_COMPUTE in snap


def test_run_stacked_steps_emits_events_with_exact_sums(tmp_path):
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import read_events
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    worker_hooks.install(str(tmp_path), worker_id=3, generation=2)
    rec = AnatomyRecorder()
    run_stacked_steps(
        lambda: _Trainer(),
        iter(_batches([4, 4, 3])),
        2,
        canonical_rows=4,
        anatomy=rec,
    )
    events = [
        e
        for e in read_events(str(tmp_path / "events.jsonl"))
        if e["event"] == "step_anatomy"
    ]
    assert len(events) == 2
    for event in events:
        assert event["worker_id"] == 3 and event["generation"] == 2
        tracked = sum(
            event.get(f"{p}_ms", 0.0) for p in ALL_PHASES
        )
        assert abs(event["wall_ms"] - tracked) < 1e-6
        # the device_compute sub-split sums to the phase
        split = event.get("enqueue_ms", 0.0) + event.get(
            "ready_wait_ms", 0.0
        )
        assert abs(split - event["device_compute_ms"]) < 1e-6
    assert events[0]["records"] == 8 and events[1]["records"] == 3


def test_sampled_step_anatomy_spans(tmp_path):
    from elasticdl_tpu.telemetry import tracing
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_STEP_ANATOMY,
        read_spans,
    )
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    tracing.install(str(tmp_path), sample_rate=1.0)
    rec = AnatomyRecorder()
    run_stacked_steps(
        lambda: _Trainer(),
        iter(_batches([4, 4])),
        2,
        canonical_rows=4,
        anatomy=rec,
    )
    tracing.flush()
    spans = [
        s
        for s in read_spans(str(tmp_path / "spans.jsonl"))
        if s["span"] == SPAN_STEP_ANATOMY
    ]
    assert spans, "no step_anatomy spans at sample_rate=1.0"
    assert {s["phase"] for s in spans} >= {
        PHASE_ASSEMBLE,
        PHASE_DEVICE_COMPUTE,
    }


def test_anatomy_none_keeps_dispatch_behavior_and_no_clock(monkeypatch):
    """The disabled path: identical dispatches, no anatomy calls."""
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    processed = run_stacked_steps(
        lambda: _Trainer(),
        iter(_batches([4, 3])),
        1,
        canonical_rows=4,
        anatomy=None,
    )
    assert processed == 7


# ---- heartbeat merge + /metrics mirror --------------------------------------


def _servicer():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    shards = {"s": (0, 8)}
    return MasterServicer(4, TaskDispatcher(shards, records_per_task=4))


def test_heartbeat_phase_merge_is_monotone_and_summed():
    from elasticdl_tpu.rpc import messages as msg

    servicer = _servicer()
    beat = {
        "device_compute": {
            "ms": 100.0,
            "count": 4,
            "buckets": {"0.025": 4},
        }
    }
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=0, step=1, phases=beat)
    )
    # a REORDERED (older) beat can't walk anything backward
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=1,
            phases={
                "device_compute": {
                    "ms": 50.0,
                    "count": 2,
                    "buckets": {"0.025": 2},
                }
            },
        )
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=1, step=1, phases=beat)
    )
    totals = servicer.phase_stats_totals()
    assert totals["device_compute"]["ms"] == 200.0
    assert totals["device_compute"]["count"] == 8
    assert totals["device_compute"]["buckets"]["0.025"] == 8


def test_master_telemetry_mirrors_phase_families():
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    servicer = _servicer()
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=1,
            phases={
                "host_fetch": {
                    "ms": 30.0,
                    "count": 3,
                    "buckets": {"0.01": 3},
                }
            },
        )
    )
    telemetry = MasterTelemetry()
    telemetry._servicer = servicer
    text = telemetry.registry.exposition()
    assert (
        'elasticdl_step_phase_ms_total{phase="host_fetch"} 30' in text
    )
    assert 'elasticdl_step_phase_seconds_bucket{phase="host_fetch"' in text
    assert 'elasticdl_step_phase_seconds_count{phase="host_fetch"} 3' in text


def test_histogram_set_totals_monotone_mirror():
    from elasticdl_tpu.telemetry.registry import Histogram

    hist = Histogram()
    hist.set_totals({"0.01": 3, "inf": 1}, 0.5, 4)
    snap = hist.snapshot()
    assert snap["count"] == 4 and snap["sum"] == 0.5
    assert snap["buckets"][0.01] == 3
    # lower mirror input never walks the exposed counts backward
    hist.set_totals({"0.01": 1}, 0.1, 2)
    snap = hist.snapshot()
    assert snap["count"] == 4 and snap["buckets"][0.01] == 3


# ---- /healthz: progress vs liveness -----------------------------------------


def test_healthz_last_step_age_and_degraded_network():
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    servicer = _servicer()
    telemetry = MasterTelemetry()
    telemetry._servicer = servicer
    health = telemetry.build_health_fn("training")
    payload = health()
    assert payload["last_step_age_secs"] is None
    assert payload["degraded_network"] is False

    servicer.heartbeat(msg.HeartbeatRequest(worker_id=0, step=5))
    payload = health()
    assert payload["last_step_age_secs"] is not None
    assert payload["last_step_age_secs"] < 5.0
    # liveness without PROGRESS does not reset the staleness clock
    age_before = servicer.last_step_age_secs()
    servicer.heartbeat(msg.HeartbeatRequest(worker_id=0, step=5))
    assert servicer.last_step_age_secs() >= age_before

    # an outage-class RPC counter rising flags the network degraded
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0, step=5, rpc={"deadline_exceeded": 2}
        )
    )
    assert health()["degraded_network"] is True
    # ...but a worker's FIRST beat to a (restarted) master carrying
    # stale lifetime totals seeds silently — rpc/stats.py counters are
    # process-lifetime, and re-learning an hours-old failure as a
    # fresh degradation would page on every master restart
    fresh = _servicer()
    fresh.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0, step=5, rpc={"deadline_exceeded": 2}
        )
    )
    assert fresh.network_degraded() is False
    # a subsequent RISE on the same link does flag
    fresh.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0, step=5, rpc={"deadline_exceeded": 3}
        )
    )
    assert fresh.network_degraded() is True
    # version reports also advance the progress clock
    servicer.report_version(
        msg.ReportVersionRequest(model_version=9, worker_id=0)
    )
    assert servicer.last_step_age_secs() < 1.0


# ---- goodput section --------------------------------------------------------


def _anat_event(gen=0, worker=0, wall=10.0, fetch=2.0, compute=6.0, **extra):
    fields = {
        "event": "step_anatomy",
        "monotonic": 1.0,
        "generation": gen,
        "worker_id": worker,
        "steps": 1,
        "records": 4,
        "wall_ms": wall,
        "host_fetch_ms": fetch,
        "assemble_ms": 0.5,
        "h2d_transfer_ms": 0.5,
        "device_compute_ms": compute,
        "step_bookkeeping_ms": wall - fetch - compute - 1.0,
        "untracked_ms": 0.0,
        "n_chips": 1,
    }
    fields.update(extra)
    return fields


def test_goodput_section_computes_roofline_and_percentiles():
    from elasticdl_tpu.telemetry.report import goodput_section

    events = [_anat_event() for _ in range(5)]
    section = goodput_section(events)
    overall = section["overall"]
    assert overall["dispatches"] == 5
    # device path = 0.5 + 0.5 + 6.0 = 7.0 of 10.0 wall
    assert overall["binding"] == "device_path"
    assert abs(overall["e2e_vs_roofline"] - 0.7) < 1e-6
    assert overall["phases"]["device_compute"]["p50_ms"] == 6.0
    assert overall["phases"]["host_fetch"]["p99_ms"] == 2.0
    assert overall["max_sum_residual_ms"] < 1e-6
    assert overall["untracked_share"] == 0.0
    # no flops info -> explicit reason, never an invented number
    assert overall["mfu"] is None
    assert "unknown" in overall["mfu_reason"]


def test_goodput_mfu_when_costs_known():
    from elasticdl_tpu.telemetry.report import goodput_section

    events = [
        _anat_event(
            flops_per_record=1e9,
            peak_flops_per_chip=1e12,
        )
        for _ in range(2)
    ]
    overall = goodput_section(events)["overall"]
    # 2 dispatches x 4 records x 1e9 / (12ms x 1e12) = 8/12 = 0.6667
    assert abs(overall["mfu"] - 8e9 / (0.012 * 1e12)) < 1e-3


def test_goodput_straggler_attribution_names_the_phase():
    from elasticdl_tpu.telemetry.report import goodput_section

    # worker 1's dispatches take 2x wall, and the excess is fetch
    events = [_anat_event(worker=0) for _ in range(4)] + [
        _anat_event(worker=1, fetch=15.0, compute=1.0, wall=20.0)
        for _ in range(4)
    ]
    overall = goodput_section(events)["overall"]
    workers = overall["workers"]
    assert workers[1]["straggler"] is True
    assert workers[1]["lagging_phase"] == "host_fetch"
    # a worker whose WALL keeps fleet pace is not a straggler, even
    # though the bimodal per-phase medians would naively flag it
    assert workers[0]["straggler"] is False


def test_goodput_absent_without_anatomy_events():
    from elasticdl_tpu.telemetry.report import analyze_events

    out = analyze_events(
        [{"event": "step", "monotonic": 1.0, "generation": 0}], []
    )
    assert "goodput" not in out


# ---- report: empty/partial run dirs -----------------------------------------


def test_report_empty_events_file_reports_no_data(tmp_path):
    from elasticdl_tpu.telemetry import report as report_cli

    run = tmp_path / "telemetry"
    run.mkdir()
    (run / "events.jsonl").write_text("")
    assert report_cli.main([str(tmp_path)]) == 0
    report = report_cli.build_report(str(tmp_path))
    rel = os.path.join("telemetry", "events.jsonl")
    assert report["runs"][rel]["no_data"]


def test_report_events_without_spans_no_traceback(tmp_path, capsys):
    from elasticdl_tpu.telemetry import report as report_cli

    run = tmp_path / "telemetry"
    run.mkdir()
    with open(run / "events.jsonl", "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                {
                    "event": "step",
                    "monotonic": 1.0,
                    "generation": 0,
                    "step": 1,
                }
            )
            + "\n"
        )
    assert report_cli.main([str(tmp_path)]) == 0
    assert "Traceback" not in capsys.readouterr().err


def test_report_rotated_shards_mid_run(tmp_path):
    from elasticdl_tpu.telemetry import report as report_cli

    run = tmp_path / "telemetry"
    run.mkdir()
    # a rotated shard (.1) plus an active file: both must be read
    with open(run / "events.jsonl.1", "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                {
                    "event": "step",
                    "monotonic": 1.0,
                    "generation": 0,
                    "step": 1,
                    "duration_secs": 0.1,
                }
            )
            + "\n"
        )
    with open(run / "events.jsonl", "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                {
                    "event": "step",
                    "monotonic": 2.0,
                    "generation": 0,
                    "step": 2,
                    "duration_secs": 0.1,
                }
            )
            + "\n"
        )
    report = report_cli.build_report(str(tmp_path))
    rel = os.path.join("telemetry", "events.jsonl")
    assert report["runs"][rel]["generations"][0]["steps"] == 2
    assert report_cli.main([str(tmp_path)]) == 0


# ---- trace analyze steady-state mode ----------------------------------------


def test_trace_analyze_steady_state_section(tmp_path):
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    run = tmp_path / "telemetry"
    run.mkdir()
    with open(run / "events.jsonl", "w", encoding="utf-8") as f:
        for event in [_anat_event(), _anat_event(gen=1)]:
            f.write(json.dumps(event) + "\n")
    (run / "spans.jsonl").write_text("")
    analysis = analyze_telemetry_dir(str(run))
    steady = analysis["steady_state"]
    assert steady[0]["dispatches"] == 1 and steady[1]["dispatches"] == 1
    phases = steady[0]["phases"]
    assert phases["device_compute"]["total_ms"] == 6.0
    # shares of ONE generation's wall sum to ~1 (untracked was 0)
    assert (
        abs(
            sum(p["share"] for p in phases.values())
            - 1.0
        )
        < 1e-3
    )


# ---- flag-off byte identity -------------------------------------------------


def test_step_anatomy_flag_never_reaches_worker_argv():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data",
        "/tmp/x",
    ]
    off = parse_master_args(base)
    on = parse_master_args(base + ["--step_anatomy", "true"])
    argv_off = build_worker_arguments(off, 0, "localhost:1")
    argv_on = build_worker_arguments(on, 0, "localhost:1")
    # even when SET it travels by env, never worker argv — and the off
    # argv is byte-identical to a build without the flag
    assert "--step_anatomy" not in argv_on
    assert argv_on == argv_off


def test_model_flops_table_and_peak_env(monkeypatch):
    assert (
        anatomy.model_flops_per_record(
            "mnist_functional_api.mnist_functional_api.custom_model"
        )
        == anatomy.MODEL_FLOPS_PER_RECORD["mnist_functional_api"]
    )
    assert anatomy.model_flops_per_record("unknown_model.custom") is None
    monkeypatch.setenv(anatomy.PEAK_FLOPS_ENV, "123.5")
    assert anatomy.peak_flops_per_chip() == 123.5
