"""Network-chaos tests: the gray-failure RPC plane.

Unit coverage for the netem shim (deterministic arming, seeded jitter,
blackhole-to-deadline degradation, one-way partition semantics,
server-side duplicate delivery), the per-method deadline policy, the
retry-loop edge cases the shim exercises, the duplicate-safety of the
report handlers (the MASTER_RETRYABLE_METHODS contract, proven under
actual duplication), and the new telemetry (rpc stats by heartbeat,
dedup counters, degraded_network trace phase).  The end-to-end
blackhole -> deadline -> retry -> complete path is gated by
``scripts/netchaos_smoke.py`` in tier-1; the full eviction plans run
under the slow marker.
"""

from __future__ import annotations

import json
import os

import grpc
import pytest

from elasticdl_tpu.chaos import netem
from elasticdl_tpu.chaos.harness import (
    ChaosJobConfig,
    _check_duplicate_delivery,
    _check_no_false_dead,
)
from elasticdl_tpu.chaos.invariants import InvariantChecker
from elasticdl_tpu.chaos.netem import InjectedRpcError, NetemShim
from elasticdl_tpu.chaos.plan import (
    Fault,
    FaultKind,
    FaultPlan,
    builtin_plans,
    named_plan,
)
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.rpc import stats as rpc_stats
from elasticdl_tpu.rpc.deadline import (
    DEADLINE_SECS_ENV,
    DeadlinePolicy,
)
from elasticdl_tpu.rpc.retry import RetryPolicy, call_with_retry
from elasticdl_tpu.rpc.service import (
    RpcClient,
    _retryable_grpc_error,
    set_client_fault_shim,
)
from elasticdl_tpu.utils.constants import TaskType


@pytest.fixture(autouse=True)
def _clean_seams():
    """Module-global seams must never leak between tests."""
    yield
    netem.uninstall()
    rpc_stats.reset_for_tests()


# ---- fault plan model -------------------------------------------------------


NETWORK_PLAN_NAMES = (
    "slow_network_mid_epoch",
    "blackhole_master_link",
    "oneway_partition_worker",
    "dup_report_storm",
)


def test_network_plans_exist_and_round_trip(tmp_path):
    plans = builtin_plans(2)
    for name in NETWORK_PLAN_NAMES:
        assert name in plans
        plan = plans[name]
        assert all(f.kind in FaultKind.NETWORK_SIDE for f in plan.faults)
        path = str(tmp_path / f"{name}.json")
        plan.save(path)
        loaded = FaultPlan.load(path)
        # method/direction are part of the replayability contract
        assert loaded.faults == plan.faults


def test_network_kinds_partition_client_vs_server():
    assert FaultKind.NET_DUPLICATE in FaultKind.NETWORK_SERVER_SIDE
    assert FaultKind.NET_BLACKHOLE in FaultKind.NETWORK_CLIENT_SIDE
    assert not (
        FaultKind.NETWORK_CLIENT_SIDE & FaultKind.NETWORK_SERVER_SIDE
    )
    # network kinds must NOT be worker-side: the step-armed injector
    # would otherwise try to fire them with no network semantics
    assert not (FaultKind.NETWORK_SIDE & FaultKind.WORKER_SIDE)


def test_fault_rejects_bad_direction():
    with pytest.raises(ValueError):
        Fault(
            kind=FaultKind.NET_PARTITION,
            fault_id="x",
            direction="sideways",
        )


# ---- netem shim: client seam ------------------------------------------------


def _shim(faults, **kwargs):
    kwargs.setdefault("sleep", lambda s: None)
    return NetemShim(faults, **kwargs)


def test_delay_applies_and_jitter_is_seeded():
    sleeps_a, sleeps_b = [], []
    fault = Fault(
        kind=FaultKind.NET_DELAY,
        fault_id="d",
        delay_ms=100.0,
        duration_secs=30.0,
    )
    a = _shim([fault], plan_seed=7, sleep=sleeps_a.append)
    b = _shim([fault], plan_seed=7, sleep=sleeps_b.append)
    for shim, out in ((a, "x"), (b, "x")):
        assert shim.client_call("svc", "m", lambda: out, None) == out
    assert sleeps_a == sleeps_b  # same seed -> same jitter draw
    assert 0.1 <= sleeps_a[0] <= 0.15  # base + uniform(0, base/2)
    c = _shim([fault], plan_seed=8, sleep=sleeps_b.append)
    c.client_call("svc", "m", lambda: "x", None)
    assert sleeps_b[-1] != sleeps_a[0]


def test_delay_past_the_deadline_is_a_deadline_expiry():
    """A real link's delay beyond the caller's deadline IS a deadline
    expiry — the shim must raise DEADLINE_EXCEEDED after the deadline,
    not deliver a slow success."""
    sleeps = []
    invoked = []
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_DELAY,
                fault_id="d",
                delay_ms=2000.0,
                duration_secs=30.0,
            )
        ],
        sleep=sleeps.append,
    )
    with pytest.raises(InjectedRpcError) as exc:
        shim.client_call("svc", "m", lambda: invoked.append(1), 1.0)
    assert exc.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert invoked == [] and sleeps[-1] == 1.0  # waited out the deadline
    # a delay UNDER the deadline still succeeds, just late
    assert shim.client_call("svc", "m", lambda: "ok", 5.0) == "ok"


def test_blackhole_with_deadline_degrades_to_deadline_exceeded():
    sleeps = []
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_BLACKHOLE,
                fault_id="b",
                duration_secs=30.0,
            )
        ],
        sleep=sleeps.append,
    )
    invoked = []
    with pytest.raises(InjectedRpcError) as exc:
        shim.client_call("svc", "m", lambda: invoked.append(1), 1.5)
    assert exc.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    # the dropped request never reached the server, and the caller
    # waited out its full deadline — silence, not an error
    assert invoked == []
    assert sleeps == [1.5]
    # a deadline expiry is retryable: the whole point is that it feeds
    # the existing full-jitter loop
    assert _retryable_grpc_error(exc.value)


def test_blackhole_without_deadline_hangs_until_window_closes():
    """The deadline-less hang is bounded by the fault window (the link
    'flaps back'), so a policy-less run still terminates — with the
    UNAVAILABLE a reset connection would produce."""
    clock = [0.0]

    def fake_clock():
        return clock[0]

    def fake_sleep(s):
        clock[0] += max(s, 0.01)

    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_BLACKHOLE,
                fault_id="b",
                duration_secs=2.0,
            )
        ],
        sleep=fake_sleep,
        clock=fake_clock,
    )
    with pytest.raises(InjectedRpcError) as exc:
        shim.client_call("svc", "m", lambda: 1, None)
    assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
    assert clock[0] >= 2.0


def test_partition_response_direction_executes_then_drops_reply():
    invoked = []
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_PARTITION,
                fault_id="p",
                direction="response",
                duration_secs=30.0,
            )
        ]
    )
    with pytest.raises(InjectedRpcError) as exc:
        shim.client_call("svc", "m", lambda: invoked.append(1), 0.5)
    # THE gray-failure signature: the request landed, the caller saw a
    # deadline — its retry will re-deliver a landed request
    assert invoked == [1]
    assert exc.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED


def test_partition_request_direction_never_executes():
    invoked = []
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_PARTITION,
                fault_id="p",
                direction="request",
                duration_secs=30.0,
            )
        ]
    )
    with pytest.raises(InjectedRpcError):
        shim.client_call("svc", "m", lambda: invoked.append(1), 0.5)
    assert invoked == []


def test_unavailable_counts_and_at_step_skips():
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_UNAVAILABLE,
                fault_id="u",
                at_step=1,
                count=1,
            )
        ]
    )
    # at_step=1: the first matched call passes unharmed
    assert shim.client_call("svc", "m", lambda: "a", None) == "a"
    with pytest.raises(InjectedRpcError) as exc:
        shim.client_call("svc", "m", lambda: "b", None)
    assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
    # count exhausted: the fault retires
    assert shim.client_call("svc", "m", lambda: "c", None) == "c"
    assert shim.armed_count == 0


def test_method_filter_only_matches_named_method():
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_UNAVAILABLE,
                fault_id="u",
                method="report_task_result",
                count=1,
            )
        ]
    )
    assert shim.client_call("svc", "get_task", lambda: "ok", None) == "ok"
    with pytest.raises(InjectedRpcError):
        shim.client_call("svc", "report_task_result", lambda: "x", None)


def test_window_close_retires_fault():
    clock = [0.0]
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_DELAY,
                fault_id="d",
                delay_ms=10.0,
                duration_secs=5.0,
            )
        ],
        clock=lambda: clock[0],
    )
    shim.client_call("svc", "m", lambda: 1, None)  # opens the window
    clock[0] = 6.0  # past the window
    shim.client_call("svc", "m", lambda: 1, None)
    assert shim.armed_count == 0


# ---- netem install: env arming + generation/process fence ------------------


def test_install_from_env_fences_process_and_generation(
    tmp_path, monkeypatch
):
    from elasticdl_tpu.chaos import hooks as chaos_hooks
    from elasticdl_tpu.rpc import service as rpc_service

    plan = named_plan("blackhole_master_link", 2)
    path = str(tmp_path / "plan.json")
    plan.save(path)
    monkeypatch.setenv(chaos_hooks.PLAN_ENV, path)
    # wrong process: nothing installs
    assert (
        netem.install_from_env(
            process_id=0, cluster_version=0, worker_id=0
        )
        is None
    )
    # wrong generation (fault is gen 0): nothing installs
    assert (
        netem.install_from_env(
            process_id=1, cluster_version=1, worker_id=1
        )
        is None
    )
    # the targeted process/generation arms the shim at the client seam
    shim = netem.install_from_env(
        process_id=1, cluster_version=0, worker_id=1
    )
    assert shim is not None and shim.armed_count == 1
    assert rpc_service._client_fault_shim is shim
    netem.uninstall()
    assert rpc_service._client_fault_shim is None


def test_install_from_env_no_plan_is_noop(monkeypatch):
    from elasticdl_tpu.chaos import hooks as chaos_hooks

    monkeypatch.delenv(chaos_hooks.PLAN_ENV, raising=False)
    assert (
        netem.install_from_env(
            process_id=0, cluster_version=0, worker_id=0
        )
        is None
    )


def test_firing_is_recorded_to_chaos_event_log(tmp_path):
    events_path = str(tmp_path / "chaos_events.jsonl")
    shim = _shim(
        [
            Fault(
                kind=FaultKind.NET_UNAVAILABLE,
                fault_id="u-1",
                count=1,
            )
        ],
        events_path=events_path,
        process_id=1,
        worker_id=3,
    )
    with pytest.raises(InjectedRpcError):
        shim.client_call("svc", "m", lambda: 1, None)
    lines = [
        json.loads(line)
        for line in open(events_path, encoding="utf-8")
        if line.strip()
    ]
    assert lines and lines[0]["fault_id"] == "u-1"
    assert lines[0]["kind"] == FaultKind.NET_UNAVAILABLE
    assert lines[0]["process_id"] == 1 and lines[0]["worker_id"] == 3


def test_firing_record_survives_installed_step_recorder(tmp_path):
    """Regression: with the worker telemetry recorder installed, the
    firing mirror must not collide with the recorder's own identity
    keywords — a TypeError here once escaped through the RPC seam as a
    bogus non-retryable failure that crashed the worker."""
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.events import (
        EVENT_RPC_FAULT_INJECTED,
        read_jsonl,
    )

    worker_hooks.install(
        str(tmp_path / "telemetry"), worker_id=3, process_id=1, generation=0
    )
    try:
        shim = _shim(
            [Fault(kind=FaultKind.NET_UNAVAILABLE, fault_id="u", count=1)],
            process_id=1,
            worker_id=3,
        )
        with pytest.raises(InjectedRpcError) as exc:
            shim.client_call("svc", "m", lambda: 1, None)
        assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
    finally:
        worker_hooks.uninstall()
    events = read_jsonl(str(tmp_path / "telemetry" / "events.jsonl"))
    fired = [
        e for e in events if e.get("event") == EVENT_RPC_FAULT_INJECTED
    ]
    assert fired and fired[0]["fault_id"] == "u"
    assert fired[0]["worker_id"] == 3  # the recorder's identity stamp


# ---- server seam: duplicate delivery vs the dedup contract ------------------


def _lease_one(dispatcher, worker_id=0):
    tid, task = dispatcher.get(worker_id)
    assert task is not None
    return tid, task


def test_duplicate_report_is_deduped_by_task_id():
    """The MASTER_RETRYABLE_METHODS claim, proven: a server-side
    re-execution of report_task_result counts the task ONCE and
    visibly drops the duplicate."""
    checker = InvariantChecker(expected_records=128)
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    servicer = MasterServicer(32, d)
    shim = NetemShim(
        [
            Fault(
                kind=FaultKind.NET_DUPLICATE,
                fault_id="dup",
                method="report_task_result",
                count=8,
            )
        ]
    )
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        request = msg.ReportTaskResultRequest(task_id=tid)
        # the duplicated delivery: handler re-executes server-side
        shim.server_call(
            "elasticdl_tpu.Master",
            "report_task_result",
            servicer.report_task_result,
            request,
        )
    assert checker.check(d.counters(TaskType.TRAINING)) == []
    assert checker.dropped_reports == 2  # one drop per duplicated pair
    assert checker.double_counted_tasks() == []


def test_duplicate_report_does_not_double_bank_compile_delta():
    """The dedup contract covers exec counters too: a duplicated
    report's compile_count was already summed by its first execution —
    the unknown-lease bank (which exists for STALE reclaimed reports,
    where nothing was summed) must not add it again."""
    from elasticdl_tpu.telemetry.compile_tracker import COMPILE_COUNT_KEY

    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    tid, _task = _lease_one(d)
    d.report(tid, success=True, exec_counters={COMPILE_COUNT_KEY: 2})
    # duplicate delivery of the SAME processed report: dropped, no bank
    d.report(tid, success=True, exec_counters={COMPILE_COUNT_KEY: 2})
    assert (
        d.counters(TaskType.TRAINING).exec_metrics[COMPILE_COUNT_KEY] == 2
    )
    # a STALE report (never processed: the lease was reclaimed before
    # any report landed) still banks — that recompile really happened
    # and the worker's watermark advanced on RPC success
    d.report(10**6, success=True, exec_counters={COMPILE_COUNT_KEY: 3})
    assert (
        d.counters(TaskType.TRAINING).exec_metrics[COMPILE_COUNT_KEY] == 5
    )


def test_master_shim_survives_sink_rebind_without_rearming():
    """A MASTER_KILL relaunch rebinds the telemetry sink on the SAME
    shim: exhausted faults must not re-fire (the server-side analogue
    of the capacity-fault fired-set)."""
    calls = []
    shim = NetemShim(
        [
            Fault(
                kind=FaultKind.NET_DUPLICATE,
                fault_id="dup",
                method="report",
                count=1,
            )
        ]
    )
    shim.server_call("svc", "report", lambda req: calls.append(req), "A")
    assert calls == ["A", "A"] and shim.armed_count == 0
    shim.set_telemetry_sink(lambda *a, **k: None)  # the relaunch rebind
    shim.server_call("svc", "report", lambda req: calls.append(req), "B")
    assert calls == ["A", "A", "B"]  # exhausted: no re-fire


def test_duplicate_eval_metrics_are_deduped_while_lease_active():
    """The fixed non-idempotence: a duplicated
    report_evaluation_metrics arrives while the lease is STILL active
    (lost reply + retry), so the is_active guard alone cannot catch it
    — the lease-id dedup must."""

    class _EvalService:
        def __init__(self):
            self.reports = 0

        def set_master_servicer(self, s):
            pass

        def report_evaluation_metrics(self, outputs, labels, **kwargs):
            self.reports += 1

    eval_service = _EvalService()
    d = TaskDispatcher({"s": (0, 64)}, records_per_task=64, shuffle_seed=3)
    servicer = MasterServicer(32, d, evaluation_service=eval_service)
    tid, _task = _lease_one(d)
    request = msg.ReportEvaluationMetricsRequest(task_id=tid)
    servicer.report_evaluation_metrics(request)
    servicer.report_evaluation_metrics(request)  # duplicate delivery
    assert eval_service.reports == 1
    assert servicer.duplicate_eval_drops == 1
    # a DIFFERENT lease still reports normally
    tid2 = tid + 1000  # unknown lease: inactive guard drops it first
    servicer.report_evaluation_metrics(
        msg.ReportEvaluationMetricsRequest(task_id=tid2)
    )
    assert eval_service.reports == 1


def test_duplicate_report_version_is_monotone_safe():
    checker = InvariantChecker()
    d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    servicer = MasterServicer(32, d)
    servicer.add_version_observer(checker.on_version_report)
    shim = NetemShim(
        [
            Fault(
                kind=FaultKind.NET_DUPLICATE,
                fault_id="dupv",
                method="report_version",
                count=4,
            )
        ]
    )
    for version in (2, 4, 6):
        shim.server_call(
            "elasticdl_tpu.Master",
            "report_version",
            servicer.report_version,
            msg.ReportVersionRequest(model_version=version, worker_id=0),
        )
    assert servicer.get_model_version() == 6
    assert not any(
        v.invariant == "version_monotonic" for v in checker.check()
    )


# ---- deadline policy --------------------------------------------------------


def test_deadline_policy_tiers():
    policy = DeadlinePolicy.from_secs(1.0)
    assert policy.deadline_for("get_task") == 1.0
    assert policy.deadline_for("report_task_result") == 1.0
    # state transfer gets the long tier, floored at 30s (the historical
    # replication timeouts) so a tight control deadline can't squeeze it
    assert policy.deadline_for("get_restore_state") == 30.0
    assert policy.deadline_for("push_replica") == 30.0
    assert DeadlinePolicy.from_secs(5.0).deadline_for("fetch_replica") == 50.0


def test_deadline_policy_from_env(monkeypatch):
    monkeypatch.delenv(DEADLINE_SECS_ENV, raising=False)
    assert DeadlinePolicy.from_env() is None
    monkeypatch.setenv(DEADLINE_SECS_ENV, "2.5")
    policy = DeadlinePolicy.from_env()
    assert policy is not None and policy.control_secs == 2.5
    monkeypatch.setenv(DEADLINE_SECS_ENV, "not-a-number")
    assert DeadlinePolicy.from_env() is None


def _client_with_fake_call(recorded, deadlines=None):
    client = RpcClient("localhost:1", deadlines=deadlines)

    def fake_call(payload, timeout=None):
        recorded.append(timeout)
        return msg.encode(msg.TaskResponse())

    client._calls = {name: fake_call for name in client._methods}
    return client


def test_client_applies_per_method_deadlines():
    recorded = []
    client = _client_with_fake_call(
        recorded, deadlines=DeadlinePolicy.from_secs(1.0)
    )
    client._call("get_task", msg.GetTaskRequest(worker_id=0))
    client._call(
        "get_restore_state", msg.GetRestoreStateRequest(cluster_version=0)
    )
    # an explicit caller timeout wins over the policy
    client._call("get_task", msg.GetTaskRequest(worker_id=0), timeout=9.0)
    assert recorded == [1.0, 30.0, 9.0]


def test_client_without_policy_passes_no_timeout():
    recorded = []
    client = _client_with_fake_call(recorded)
    client._call("get_task", msg.GetTaskRequest(worker_id=0))
    assert recorded == [None]


def test_client_routes_attempts_through_fault_shim():
    recorded = []
    client = _client_with_fake_call(recorded)

    class _Shim:
        calls = []

        def client_call(self, service, method, invoke, timeout):
            self.calls.append((service, method, timeout))
            return invoke()

    shim = _Shim()
    set_client_fault_shim(shim)
    try:
        client._call("heartbeat", msg.HeartbeatRequest(worker_id=0))
    finally:
        set_client_fault_shim(None)
    assert shim.calls == [("elasticdl_tpu.Master", "heartbeat", None)]
    assert recorded == [None]


def test_client_failure_counts_into_rpc_stats():
    rpc_stats.reset_for_tests()
    client = RpcClient("localhost:1")

    def failing_call(payload, timeout=None):
        raise InjectedRpcError(
            grpc.StatusCode.DEADLINE_EXCEEDED, "injected"
        )

    client._calls = {name: failing_call for name in client._methods}
    with pytest.raises(InjectedRpcError):
        client._call("get_task", msg.GetTaskRequest(worker_id=0))
    assert rpc_stats.snapshot() == {"deadline_exceeded": 1}


def test_retried_client_counts_retries_and_failures():
    rpc_stats.reset_for_tests()
    from elasticdl_tpu.rpc.service import MASTER_RETRYABLE_METHODS

    client = RpcClient(
        "localhost:1",
        retry=RetryPolicy(max_attempts=3, base_delay_secs=0.0),
        retryable_methods=MASTER_RETRYABLE_METHODS,
    )
    attempts = []

    def flaky_call(payload, timeout=None):
        attempts.append(1)
        if len(attempts) < 3:
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE, "injected"
            )
        return b""

    client._calls = {name: flaky_call for name in client._methods}
    client._call("heartbeat", msg.HeartbeatRequest(worker_id=0))
    assert len(attempts) == 3
    assert rpc_stats.snapshot() == {"unavailable": 2, "retries": 2}


# ---- retry edge cases (the paths netem exercises) ---------------------------


def test_on_retry_hook_raising_does_not_end_the_loop():
    attempts = []

    def fn():
        attempts.append(1)
        if len(attempts) < 3:
            raise ValueError("boom")
        return "done"

    def bad_hook(attempt, ex):
        raise RuntimeError("hook died")

    out = call_with_retry(
        fn,
        RetryPolicy(max_attempts=5, base_delay_secs=0.0),
        on_retry=bad_hook,
        sleep=lambda s: None,
    )
    assert out == "done" and len(attempts) == 3


def test_deadline_expiring_exactly_between_attempts_ends_the_loop():
    clock_values = iter([0.0, 10.0])  # deadline calc, then the check

    def fn():
        raise ValueError("always")

    with pytest.raises(ValueError):
        call_with_retry(
            fn,
            RetryPolicy(max_attempts=100, total_timeout_secs=10.0),
            sleep=lambda s: None,
            clock=lambda: next(clock_values),
        )


def test_total_timeout_clamps_the_final_backoff_sleep():
    sleeps = []
    clock_values = iter([0.0, 5.0, 6.0, 99.0])

    class _MaxRng:
        def uniform(self, lo, hi):
            return hi  # always draw the cap

    def fn():
        raise ValueError("always")

    with pytest.raises(ValueError):
        call_with_retry(
            fn,
            RetryPolicy(
                max_attempts=100,
                base_delay_secs=50.0,
                max_delay_secs=50.0,
                total_timeout_secs=10.0,
            ),
            rng=_MaxRng(),
            sleep=sleeps.append,
            clock=lambda: next(clock_values),
        )
    # drew the 50s cap, but only 10-6=4s of budget remained
    assert sleeps == [4.0]


# ---- heartbeat-shipped rpc stats -------------------------------------------


def test_heartbeat_rpc_stats_max_merge_and_totals():
    d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    servicer = MasterServicer(32, d)
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=1, rpc={"retries": 3, "deadline_exceeded": 2}
        )
    )
    # a reordered (older) beat must not walk the totals backward
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=1, rpc={"retries": 1})
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=2, rpc={"retries": 4})
    )
    assert servicer.rpc_stats_totals() == {
        "retries": 7,
        "deadline_exceeded": 2,
    }
    # beats without the field change nothing (wire-compat default)
    servicer.heartbeat(msg.HeartbeatRequest(worker_id=1))
    assert servicer.rpc_stats_totals()["retries"] == 7


def test_master_telemetry_exposes_rpc_counters():
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    telemetry = MasterTelemetry("")
    d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    servicer = MasterServicer(32, d)
    telemetry.attach(d, servicer)
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=1, rpc={"retries": 5, "deadline_exceeded": 3}
        )
    )
    # a dropped (duplicate/stale) report increments the dedup counter
    d.report(10**9, success=True)
    telemetry.observe_rpc("heartbeat", 0.01)
    text = telemetry.registry.exposition()
    assert "elasticdl_rpc_retries_total 5" in text
    assert "elasticdl_rpc_deadline_exceeded_total 3" in text
    assert "elasticdl_rpc_reports_deduped_total 1" in text
    assert 'elasticdl_rpc_latency_seconds_count{method="heartbeat"} 1' in text


# ---- harness invariants -----------------------------------------------------


def _config(plan, tmp_path, **kwargs):
    return ChaosJobConfig(
        plan=plan, workdir=str(tmp_path / "w"), **kwargs
    )


def test_no_false_dead_applies_only_to_delay_plans(tmp_path):
    config = _config(named_plan("slow_network_mid_epoch"), tmp_path)
    ok = _check_no_false_dead(config, [])
    assert ok is not None and ok["status"] == "PASS"
    bad = _check_no_false_dead(
        config, [{"reason": "worker_failure", "detected_at": 0.0}]
    )
    assert bad["status"] == "FAIL"
    # a plan with any non-delay fault is out of contract
    assert (
        _check_no_false_dead(
            _config(named_plan("blackhole_master_link"), tmp_path), []
        )
        is None
    )
    assert (
        _check_no_false_dead(
            _config(named_plan("preempt_one_worker"), tmp_path), []
        )
        is None
    )


def test_duplicate_delivery_invariant_requires_realization(tmp_path):
    config = _config(named_plan("dup_report_storm"), tmp_path)
    checker = InvariantChecker()
    # nothing fired: the invariant must refuse to pass vacuously
    verdict = _check_duplicate_delivery(config, checker, [])
    assert verdict["status"] == "FAIL"
    assert any("none fired" in v for v in verdict["violations"])


def test_duplicate_delivery_invariant_requires_dedup_engagement(tmp_path):
    config = _config(named_plan("dup_report_storm"), tmp_path)
    fired = [
        {"kind": FaultKind.NET_DUPLICATE, "method": "report_task_result"},
        {"kind": FaultKind.NET_DUPLICATE, "method": "report_version"},
    ]
    checker = InvariantChecker()
    verdict = _check_duplicate_delivery(config, checker, fired)
    assert verdict["status"] == "FAIL"  # no drops observed
    checker.on_task_reported(1, None, True, False)  # the dedup drop
    verdict = _check_duplicate_delivery(config, checker, fired)
    assert verdict["status"] == "PASS"


def test_duplicate_delivery_invariant_flags_double_counting(tmp_path):
    config = _config(named_plan("dup_report_storm"), tmp_path)
    checker = InvariantChecker(expected_records=128)
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    tid, task = _lease_one(d)
    d.report(tid, success=True)
    checker.on_task_reported(tid, task, True, True)  # dedup disabled
    checker.on_task_reported(1, None, True, False)
    fired = [
        {"kind": FaultKind.NET_DUPLICATE, "method": "report_task_result"}
    ]
    verdict = _check_duplicate_delivery(config, checker, fired)
    assert verdict["status"] == "FAIL"
    assert any("double-counted" in v for v in verdict["violations"])


def test_drop_dedup_corruption_requires_duplicate_plan(tmp_path):
    from elasticdl_tpu.chaos.harness import run_chaos_job

    with pytest.raises(ValueError, match="drop_dedup"):
        run_chaos_job(
            _config(
                named_plan("preempt_one_worker"),
                tmp_path,
                corrupt="drop_dedup",
                num_records=64,
            )
        )


def test_drop_dedup_corruption_counts_duplicates(tmp_path):
    """The corruption itself: with dedup disabled, a duplicated report
    for a no-longer-active lease is counted AGAIN — exactly_once must
    then trip."""
    from elasticdl_tpu.chaos.harness import _install_corruption

    checker = InvariantChecker(expected_records=128)
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)

    class _FakeMaster:
        task_d = d
        servicer = None

    _install_corruption(_FakeMaster(), checker, "drop_dedup")
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        d.report(tid, success=True)
        d.report(tid, success=True)  # the duplicate delivery
    violations = checker.check(d.counters(TaskType.TRAINING))
    assert any(v.invariant == "exactly_once" for v in violations)


# ---- runner surface ---------------------------------------------------------


def test_runner_list_describes_network_plans_and_invariants(capsys):
    from elasticdl_tpu.chaos.runner import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in NETWORK_PLAN_NAMES:
        assert name in out
    assert "no_false_dead" in out
    assert "duplicate_delivery_exactly_once" in out


def test_runner_network_plan_config():
    from elasticdl_tpu.chaos.runner import NETWORK_PLANS

    for name in NETWORK_PLAN_NAMES:
        assert name in NETWORK_PLANS
        assert NETWORK_PLANS[name].get("rpc_deadline_secs")
    # eviction plans need the budget the window outlasts + lease reclaim
    for name in ("blackhole_master_link", "oneway_partition_worker"):
        cfg = NETWORK_PLANS[name]
        assert cfg["rpc_retry_secs"] < 60.0
        assert cfg["task_timeout_secs"]


def test_drop_dedup_in_corruptions_choices():
    from elasticdl_tpu.chaos.harness import CORRUPTIONS

    assert "drop_dedup" in CORRUPTIONS


# ---- argv / env byte-identity ----------------------------------------------


def test_rpc_deadline_flag_is_master_only_and_default_none():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def",
        "m.model",
        "--training_data",
        "/tmp/x",
    ]
    args = parse_master_args(base)
    assert getattr(args, "rpc_deadline_secs") is None
    argv = build_worker_arguments(args, 0, "localhost:1")
    assert "--rpc_deadline_secs" not in argv
    # even when SET it travels by env, never worker argv
    args = parse_master_args(base + ["--rpc_deadline_secs", "2.0"])
    argv = build_worker_arguments(args, 0, "localhost:1")
    assert "--rpc_deadline_secs" not in argv


def test_master_exports_deadline_and_retry_envs(tmp_path):
    from elasticdl_tpu.master.main import build_master
    from elasticdl_tpu.rpc.deadline import DEADLINE_SECS_ENV
    from elasticdl_tpu.rpc.retry import RETRY_SECS_ENV
    from elasticdl_tpu.utils.args import parse_master_args

    (tmp_path / "d").mkdir()
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            str(tmp_path / "d"),
            "--num_workers",
            "1",
            "--rpc_deadline_secs",
            "1.5",
            "--rpc_retry_secs",
            "7.0",
        ]
    )
    master = build_master(args)
    envs = master.instance_manager._envs
    assert envs[DEADLINE_SECS_ENV] == "1.5"
    # --rpc_retry_secs alone (no journal) now enables worker retries:
    # a gray network deserves the backoff loop without full master HA
    assert envs[RETRY_SECS_ENV] == "7.0"


def test_no_flags_no_envs(tmp_path):
    from elasticdl_tpu.master.main import build_master
    from elasticdl_tpu.rpc.deadline import DEADLINE_SECS_ENV
    from elasticdl_tpu.rpc.retry import RETRY_SECS_ENV
    from elasticdl_tpu.utils.args import parse_master_args

    (tmp_path / "d").mkdir()
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            str(tmp_path / "d"),
            "--num_workers",
            "1",
        ]
    )
    master = build_master(args)
    envs = master.instance_manager._envs
    assert DEADLINE_SECS_ENV not in envs
    assert RETRY_SECS_ENV not in envs


# ---- trace analyze: degraded_network phase ---------------------------------


def test_degraded_network_phase_sums_exactly(tmp_path):
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    run = tmp_path / "telemetry"
    run.mkdir()
    events = [
        {
            "event": "step",
            "monotonic": 100.0,
            "generation": 0,
            "worker_id": 0,
            "step": 5,
            "duration_secs": 0.1,
        },
        {
            "event": "step",
            "monotonic": 110.0,
            "generation": 1,
            "worker_id": 0,
            "step": 6,
            "duration_secs": 0.1,
        },
    ]
    spans = [
        {
            "span": "reform",
            "start": 104.0,
            "end": 106.0,
            "trace_id": "t1",
            "span_id": "s1",
            "generation": 1,
            "role": "master",
        },
        {
            "span": "rpc_degraded",
            "start": 101.0,
            "end": 105.0,
            "trace_id": "t2",
            "span_id": "s2",
            "generation": 0,
            "role": "worker",
        },
    ]
    with open(run / "events.jsonl", "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    with open(run / "spans.jsonl", "w", encoding="utf-8") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    report = analyze_telemetry_dir(str(run))
    gap = report["reform_downtime"][0]
    phases = gap["phases_secs"]
    # the degraded window refines detection time, clamped to the reform
    assert phases["degraded_network"] == pytest.approx(3.0)
    assert phases["death_detection"] == pytest.approx(1.0)
    # sum-exactness is the analyze contract and must survive the new
    # phase
    assert sum(phases.values()) == pytest.approx(gap["downtime_secs"])


# ---- slow end-to-end: the dedup contract under real duplication ------------


@pytest.mark.slow
def test_dup_report_storm_end_to_end(tmp_path):
    from elasticdl_tpu.chaos.harness import run_chaos_job
    from elasticdl_tpu.chaos.runner import NETWORK_PLANS

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("dup_report_storm", 2),
            workdir=str(tmp_path / "chaos"),
            num_records=256,
            num_epochs=2,
            num_workers=2,
            run_timeout_secs=300.0,
            **NETWORK_PLANS["dup_report_storm"],
        )
    )
    assert report["invariants_ok"], report["invariants"]
    names = {i["name"]: i["status"] for i in report["invariants"]}
    assert names["duplicate_delivery_exactly_once"] == "PASS"
    assert report["rpc"]["reports_deduped"] >= 1
