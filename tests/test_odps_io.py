"""Threaded ODPS table IO against a fake in-memory client (the SDK is
not installed here; the reference gates its ODPS tests on credentials the
same way, .travis.yml:44-50).  The logic under test is real: windowed
concurrent chunk downloads in order, worker range splits, retry, and
buffered writes."""

import threading

import pytest

from elasticdl_tpu.data.odps_io import ODPSTableReader, ODPSTableWriter


class _FakeRecord(dict):
    def keys(self):  # ODPS records iterate column names in schema order
        return sorted(super().keys())


class _FakeReaderCtx:
    def __init__(self, rows):
        self._rows = rows
        self.count = len(rows)

    def read(self, start, count):
        return iter(self._rows[start : start + count])

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _FakeWriterCtx:
    def __init__(self, sink):
        self._sink = sink

    def write(self, records):
        self._sink.append(list(records))

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _FakeTable:
    def __init__(self, rows, fail_first=0):
        self._rows = rows
        self.blocks_written = []
        self._fail_remaining = fail_first
        self._lock = threading.Lock()

    def open_reader(self, partition=None):
        with self._lock:
            if self._fail_remaining > 0:
                self._fail_remaining -= 1
                raise ConnectionError("flaky odps endpoint")
        return _FakeReaderCtx(self._rows)

    def open_writer(self, partition=None, **kw):
        return _FakeWriterCtx(self.blocks_written)


class _FakeClient:
    def __init__(self, table):
        self._table = table

    def get_table(self, name):
        return self._table


def _rows(n):
    return [_FakeRecord(a=i, b=i * 10) for i in range(n)]


def _reader(table, **kw):
    kw.setdefault("retry_backoff_secs", 0.0)
    return ODPSTableReader(_FakeClient(table), "t", **kw)


def test_iterator_preserves_order_across_chunks():
    reader = _reader(_FakeTable(_rows(100)))
    batches = list(
        reader.to_iterator(batch_size=7, cache_batch_count=2)
    )
    flat = [row for batch in batches for row in batch]
    assert [r[0] for r in flat] == list(range(100))  # columns sorted: a, b
    assert all(len(b) <= 7 for b in batches)


def test_worker_splits_cover_table_disjointly():
    table = _FakeTable(_rows(96))
    seen = []
    for w in range(3):
        reader = _reader(table)
        for batch in reader.to_iterator(
            num_workers=3, worker_index=w, batch_size=8, cache_batch_count=2
        ):
            seen.extend(r[0] for r in batch)
    assert sorted(seen) == list(range(96))


def test_epochs_repeat_worker_range():
    reader = _reader(_FakeTable(_rows(32)))
    flat = [
        r[0]
        for b in reader.to_iterator(
            batch_size=8, cache_batch_count=1, epochs=3
        )
        for r in b
    ]
    assert flat == list(range(32)) * 3


def test_read_retries_transient_failures():
    table = _FakeTable(_rows(16), fail_first=2)
    reader = _reader(table, max_retries=3)
    flat = [
        r[0]
        for b in reader.to_iterator(batch_size=4, cache_batch_count=4)
        for r in b
    ]
    assert flat == list(range(16))


def test_read_gives_up_after_max_retries():
    table = _FakeTable(_rows(8), fail_first=10)
    reader = _reader(table, max_retries=2)
    with pytest.raises(ConnectionError):
        list(reader.to_iterator(batch_size=4, cache_batch_count=2))


def test_column_projection():
    reader = _reader(_FakeTable(_rows(8)))
    batches = list(
        reader.to_iterator(batch_size=4, cache_batch_count=2, columns=["b"])
    )
    assert batches[0][0] == [0] and batches[0][1] == [10]


def test_writer_buffers_blocks():
    table = _FakeTable([])
    writer = ODPSTableWriter(_FakeClient(table), "t")
    n = writer.from_iterator(([i, i] for i in range(25)), buffer_rows=10)
    assert n == 25
    assert [len(b) for b in table.blocks_written] == [10, 10, 5]
