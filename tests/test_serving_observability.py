"""Serving fleet observability: cross-process request tracing,
probe-beat telemetry fan-in, and the router-side SLO watchdog.

Three planes under test: (1) one request = ONE trace — the client's
``predict_request`` root, the router's route/reroute children, the
replica's queue/engine split, and the batched dispatch group LINKED to
every member trace; (2) replicas ship monotone counters + phase totals
on the ``serving_status`` probe beat and the router max-merges them
into per-replica and fleet state; (3) the watchdog turns per-tick
deltas of that fan-in into burn-rate signals and incidents that NAME
the offending replica with a queue-bound / compute-bound cause.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import msgpack
import numpy as np
import pytest

from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.serving import watchdog as wd
from elasticdl_tpu.serving.batcher import MicroBatcher
from elasticdl_tpu.serving.router import ServingRouter, _ReplicaHandle
from elasticdl_tpu.telemetry import slo as slo_mod
from elasticdl_tpu.telemetry import tracing
from elasticdl_tpu.telemetry.incident import (
    CAUSE_COMPUTE_BOUND,
    CAUSE_QUEUE_BOUND,
    CAUSE_REPLICA_DOWN,
    CAUSE_SWAP_IN_PROGRESS,
    read_incidents,
)
from elasticdl_tpu.telemetry.tracing import (
    SPAN_PREDICT_REQUEST,
    SPAN_SERVING_DISPATCH,
    SPAN_SERVING_ENGINE,
    SPAN_SERVING_QUEUE,
    SPAN_SERVING_REROUTE,
    SPAN_SERVING_ROUTE,
    gen_span_id,
    gen_trace_id,
    read_spans,
)

IRIS_DEF = "odps_iris_dnn_model.odps_iris_dnn_model.custom_model"
ROWS = 8


@pytest.fixture(autouse=True)
def _fresh_tracer():
    tracing.uninstall()
    yield
    tracing.uninstall()


def _ctx() -> dict:
    return {"trace_id": gen_trace_id(), "span_id": gen_span_id()}


def _all_spans(tmp_path) -> list[dict]:
    tracer = tracing.get_tracer()
    if tracer is not None:
        tracer.flush()
    return read_spans(os.path.join(str(tmp_path), tracing.SPANS_FILENAME))


# ---- wire compat ------------------------------------------------------------


def test_serving_trace_fields_roundtrip():
    ctx = _ctx()
    for message in (
        msg.PredictRequest(request_id="r", trace=dict(ctx)),
        msg.ServingStatusRequest(trace=dict(ctx)),
        msg.SwapModelRequest(model_dir="/m", trace=dict(ctx)),
    ):
        decoded = msg.decode(msg.encode(message))
        assert decoded.trace == ctx, type(message).__name__


def test_probe_beat_payload_roundtrips():
    response = msg.ServingStatusResponse(
        replica_id=2,
        counters={"requests": 5, "errors": 1},
        phases={"total": {"ms": 9.5, "count": 5, "buckets": {"0.01": 5}}},
        memory={"at": 12.0, "components": {}},
    )
    decoded = msg.decode(msg.encode(response))
    assert decoded.counters == {"requests": 5, "errors": 1}
    assert decoded.phases["total"]["buckets"] == {"0.01": 5}
    assert decoded.memory["at"] == 12.0


def test_old_serving_payloads_without_new_fields_decode():
    """A pre-observability peer's msgpack payload (no trace / probe-beat
    keys) must decode into the new dataclasses with empty defaults."""
    bodies = {
        "PredictRequest": {"request_id": "r", "features": b"", "rows": 0},
        "ServingStatusRequest": {"detail": False},
        "SwapModelRequest": {"model_dir": "/m", "min_version": -1},
        "ServingStatusResponse": {"replica_id": 0, "model_version": 3},
    }
    for kind, body in bodies.items():
        buf = msgpack.packb({"kind": kind, "body": body}, use_bin_type=True)
        decoded = msg.decode(buf)
        if hasattr(decoded, "trace"):
            assert decoded.trace == {}, kind
    status = msg.decode(
        msgpack.packb(
            {
                "kind": "ServingStatusResponse",
                "body": {"replica_id": 0, "model_version": 3},
            },
            use_bin_type=True,
        )
    )
    assert status.counters == {} and status.phases == {}
    assert status.memory == {}


# ---- replica-side spans (engine + batcher) ----------------------------------


def _export_iris(out_dir: str, version: int):
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.trainer.state import TrainState, init_model
    from elasticdl_tpu.trainer.step import resolve_optimizer
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.utils.model_utils import get_model_spec

    spec = get_model_spec("", IRIS_DEF)
    model = spec.build_model()
    sample = {"features": np.zeros((1, 4), np.float32)}
    params, model_state = init_model(model, sample)
    params = jax.tree_util.tree_map(lambda x: x + 0.01, params)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    state = state.replace(step=jnp.asarray(version, jnp.int32))
    args = argparse.Namespace(
        model_zoo="", model_def=IRIS_DEF, model_params_dict={}
    )
    return export_model(out_dir, state, spec, args)


@pytest.fixture
def export_v1(tmp_path):
    return _export_iris(str(tmp_path / "export_v1"), version=3)


def _feats(n: int, seed: int = 0) -> dict:
    rng = np.random.RandomState(seed)
    return {"features": rng.rand(n, 4).astype(np.float32)}


def _run_traced(engine, request_id, features, trace):
    batcher = MicroBatcher(engine.canonical_rows, max_wait_secs=0.0)
    ticket = batcher.submit(request_id, features, trace=trace)
    while not ticket.done:
        group = batcher.next_group(0.1)
        if group is None:
            break
        engine.run_group(group)
    return ticket


def test_engine_traced_request_records_queue_engine_split(
    export_v1, tmp_path
):
    from elasticdl_tpu.serving.engine import ServingEngine

    tracing.install(str(tmp_path), role="replica", worker_id=0)
    engine = ServingEngine(export_v1, ROWS)
    ctx = _ctx()
    ticket = _run_traced(engine, "traced-1", _feats(ROWS * 2 + 1), ctx)
    assert ticket.error is None
    spans = _all_spans(tmp_path)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["span"], []).append(s)
    queue = by_name[SPAN_SERVING_QUEUE][0]
    eng = by_name[SPAN_SERVING_ENGINE][0]
    # both children of the client's root span, in the SAME trace
    for child in (queue, eng):
        assert child["trace_id"] == ctx["trace_id"]
        assert child["parent_span_id"] == ctx["span_id"]
        assert child["role"] == "replica"
    # queue (submit -> first dispatch) + engine (first dispatch ->
    # delivered) partition the request wall exactly
    assert queue["end"] == eng["start"]
    wall = eng["end"] - queue["start"]
    assert abs(wall - ticket.total_secs()) < 1e-6


def test_dispatch_span_links_every_member_trace(export_v1, tmp_path):
    from elasticdl_tpu.serving.engine import ServingEngine

    tracing.install(str(tmp_path), role="replica", worker_id=0)
    engine = ServingEngine(export_v1, ROWS)
    ctx_a, ctx_b = _ctx(), _ctx()
    batcher = MicroBatcher(ROWS, max_wait_secs=0.0)
    a = batcher.submit("a", _feats(3), trace=ctx_a)
    b = batcher.submit("b", _feats(3, seed=1), trace=ctx_b)
    while not (a.done and b.done):
        group = batcher.next_group(0.1)
        if group is None:
            break
        engine.run_group(group)
    dispatches = [
        s for s in _all_spans(tmp_path) if s["span"] == SPAN_SERVING_DISPATCH
    ]
    # the group is one span LINKED (not parented — one group serves many
    # traces) to every member request's root
    linked = {
        link["trace_id"] for d in dispatches for link in d.get("links", [])
    }
    assert {ctx_a["trace_id"], ctx_b["trace_id"]} <= linked


def test_hot_swap_under_tracing_parents_swap_span(export_v1, tmp_path):
    from elasticdl_tpu.serving.engine import ServingEngine
    from elasticdl_tpu.telemetry.tracing import SPAN_MODEL_SWAP

    tracing.install(str(tmp_path), role="replica", worker_id=0)
    export_v2 = _export_iris(str(tmp_path / "export_v2"), version=9)
    engine = ServingEngine(export_v1, ROWS)
    ctx = _ctx()
    accepted, version, _reason = engine.swap_from_export(
        export_v2, trace=ctx
    )
    assert accepted and version == 9
    swaps = [
        s for s in _all_spans(tmp_path) if s["span"] == SPAN_MODEL_SWAP
    ]
    assert swaps and swaps[0]["trace_id"] == ctx["trace_id"]
    assert swaps[0]["parent_span_id"] == ctx["span_id"]


# ---- router: route spans + probe-beat fan-in --------------------------------


class _FakeClient:
    def __init__(self, outcome, status=None):
        self.outcome = outcome  # callable or canned response
        self.status = status
        self.calls = 0
        self.swap_outcome = None

    def predict(self, request):
        self.calls += 1
        if callable(self.outcome):
            return self.outcome(request)
        return self.outcome

    def serving_status(self, request=None):
        if callable(self.status):
            return self.status()
        return self.status or msg.ServingStatusResponse(
            replica_id=0, model_version=1
        )

    def swap_model(self, request):
        if callable(self.swap_outcome):
            return self.swap_outcome(request)
        return self.swap_outcome or msg.SwapModelResponse(
            accepted=True, model_version=5
        )

    def close(self):
        pass


def _inject(router, replica_id, client):
    handle = _ReplicaHandle(replica_id, f"fake:{replica_id}", client)
    router._replicas[replica_id] = handle
    return handle


def _unavailable(_request):
    import grpc

    from elasticdl_tpu.chaos.netem import InjectedRpcError

    raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "down")


def test_router_records_route_then_reroute_in_same_trace(tmp_path):
    tracing.install(str(tmp_path), role="router")
    router = ServingRouter()
    ok = msg.PredictResponse(outputs=b"", model_version=1, rows=1)
    dead, live = _FakeClient(_unavailable), _FakeClient(ok)
    _inject(router, 0, dead)
    _inject(router, 1, live)
    router._replicas[1].outstanding = 1  # dead replica preferred first
    ctx = _ctx()
    response = router.predict(
        msg.PredictRequest(request_id="r", trace=dict(ctx))
    )
    assert not response.error
    spans = _all_spans(tmp_path)
    route = next(s for s in spans if s["span"] == SPAN_SERVING_ROUTE)
    reroute = next(s for s in spans if s["span"] == SPAN_SERVING_REROUTE)
    # the detour stays ONE trace: both attempts parent under the root
    for s in (route, reroute):
        assert s["trace_id"] == ctx["trace_id"]
        assert s["parent_span_id"] == ctx["span_id"]
    assert route["replica_id"] == 0 and route["error"]
    assert reroute["replica_id"] == 1 and "error" not in reroute
    assert reroute["attempt"] == 1


def test_untraced_request_records_no_route_spans(tmp_path):
    tracing.install(str(tmp_path), role="router")
    router = ServingRouter()
    ok = msg.PredictResponse(outputs=b"", model_version=1, rows=1)
    _inject(router, 0, _FakeClient(ok))
    router.predict(msg.PredictRequest(request_id="r"))
    assert _all_spans(tmp_path) == []


def _beat_status(requests, queue_ms, total_ms, at=1.0):
    return msg.ServingStatusResponse(
        replica_id=0,
        model_version=1,
        queue_rows=0,
        counters={"requests": requests, "errors": 0, "rejected": 0},
        phases={
            "queue_wait": {
                "ms": queue_ms,
                "count": requests,
                "buckets": {"0.01": requests},
            },
            "total": {
                "ms": total_ms,
                "count": requests,
                "buckets": {"0.01": requests},
            },
        },
        memory={"at": at, "rss_bytes": 1},
    )


def test_probe_beat_fan_in_merges_monotone_and_fleet_totals():
    router = ServingRouter()
    client = _FakeClient(None, status=_beat_status(5, 10.0, 50.0, at=1.0))
    handle = _inject(router, 0, client)
    router.probe_once()
    assert handle.counters["requests"] == 5
    assert handle.phases["total"]["ms"] == 50.0
    # a stale/duplicated payload racing a fresher one max-merges to a
    # no-op; a fresher one advances both the handle and the fleet totals
    client.status = _beat_status(3, 6.0, 30.0, at=0.5)  # stale replay
    router.probe_once()
    assert handle.counters["requests"] == 5
    assert handle.memory["at"] == 1.0  # last-writer-wins by stamp
    client.status = _beat_status(9, 20.0, 90.0, at=2.0)
    router.probe_once()
    assert handle.counters["requests"] == 9
    assert router._fleet_counters["requests"] == 9
    assert router._fleet_phases["total"]["ms"] == 90.0
    assert handle.memory["at"] == 2.0
    # fleet totals survive eviction (incremental, never recomputed)
    router.remove_replica(0)
    assert router._fleet_counters["requests"] == 9


def test_fleet_snapshot_shape_and_probe_age():
    router = ServingRouter(evict_after_secs=100.0)
    client = _FakeClient(None, status=_beat_status(2, 1.0, 5.0))
    _inject(router, 0, client)
    router.probe_once()
    snap = router.fleet_snapshot()
    assert snap["live"] == [0]
    r = snap["replicas"][0]
    assert r["last_probe_age_secs"] < 5.0
    assert 0.0 < r["evict_in_secs"] <= 100.0
    assert r["live"] and not r["swap_unreachable"]
    assert r["counters"]["requests"] == 2
    assert snap["phases"]["total"]["ms"] == 5.0
    # the copies are diff-safe: mutating the snapshot must not touch
    # the router's merged state
    snap["phases"]["total"]["ms"] = 0.0
    assert router.fleet_snapshot()["phases"]["total"]["ms"] == 5.0


def test_swap_partial_failure_marks_unreachable_and_probe_clears(tmp_path):
    tracing.install(str(tmp_path), role="router")
    router = ServingRouter()
    good = _FakeClient(None, status=_beat_status(1, 1.0, 2.0))
    bad = _FakeClient(None, status=_beat_status(1, 1.0, 2.0))

    def _swap_unreachable(_request):
        raise ConnectionError("replica gone")

    bad.swap_outcome = _swap_unreachable
    _inject(router, 0, good)
    h1 = _inject(router, 1, bad)
    ctx = _ctx()
    response = router.swap_model(
        msg.SwapModelRequest(model_dir="/m", trace=dict(ctx))
    )
    assert not response.accepted
    assert "unreachable" in response.reason
    assert h1.swap_unreachable
    # every fan-out leg is a route child of the swap's trace; the
    # failed leg carries the error
    legs = [
        s for s in _all_spans(tmp_path) if s["span"] == SPAN_SERVING_ROUTE
    ]
    assert {s["replica_id"] for s in legs} == {0, 1}
    failed = next(s for s in legs if s["replica_id"] == 1)
    assert failed["error"] == "unreachable"
    assert failed["method"] == "swap_model"
    # the next successful probe clears the flag (the watchdog's
    # swap_unreachable signal recovers)
    router.probe_once()
    assert not h1.swap_unreachable


# ---- watchdog: signal derivation --------------------------------------------


def test_p99_from_bucket_deltas():
    assert wd.p99_ms_from_buckets({}) is None
    assert wd.p99_ms_from_buckets({"0.001": 98, "0.1": 2}) == 100.0
    assert wd.p99_ms_from_buckets({"0.005": 100}) == 5.0
    # overflow bucket reports as 2x the ladder top — comparable, honest
    from elasticdl_tpu.telemetry.registry import SERVING_LATENCY_BUCKETS

    assert (
        wd.p99_ms_from_buckets({"inf": 10})
        == SERVING_LATENCY_BUCKETS[-1] * 2000.0
    )


def test_delta_buckets_positive_only():
    prev = {"0.01": 5, "0.1": 2}
    cur = {"0.01": 9, "0.1": 2, "inf": 1}
    assert wd._delta_buckets(prev, cur) == {"0.01": 4, "inf": 1}
    assert wd._delta_buckets(cur, prev) == {}


def _tick_snap(at, replicas, live=None):
    """fleet_snapshot-shaped dict from {rid: (queue_ms, compute_ms,
    requests, errors, queue_rows)} cumulative per-replica state."""
    out_replicas = {}
    fleet_phases = {"queue_wait": 0.0, "device_compute": 0.0, "total": 0.0}
    fleet_counters = {"requests": 0, "errors": 0, "rejected": 0}
    fleet_buckets: dict[str, int] = {}
    for rid, (queue, compute, requests, errors, queue_rows) in (
        replicas.items()
    ):
        buckets = {"0.05": requests}
        out_replicas[rid] = {
            "replica_id": rid,
            "addr": f"fake:{rid}",
            "outstanding": 0,
            "last_probe_age_secs": 0.1,
            "live": live is None or rid in live,
            "evict_in_secs": 9.0,
            "queue_rows": queue_rows,
            "model_version": 1,
            "counters": {
                "requests": requests,
                "errors": errors,
                "rejected": 0,
            },
            "phases": {
                "queue_wait": {
                    "ms": queue,
                    "count": requests,
                    "buckets": {},
                },
                "device_compute": {
                    "ms": compute,
                    "count": requests,
                    "buckets": {},
                },
                "total": {
                    "ms": queue + compute,
                    "count": requests,
                    "buckets": buckets,
                },
            },
            "memory": {},
            "swap_unreachable": False,
        }
        fleet_phases["queue_wait"] += queue
        fleet_phases["device_compute"] += compute
        fleet_phases["total"] += queue + compute
        fleet_counters["requests"] += requests
        fleet_counters["errors"] += errors
        for key, n in buckets.items():
            fleet_buckets[key] = fleet_buckets.get(key, 0) + n
    return {
        "at": at,
        "replicas": out_replicas,
        "live": [r for r, v in out_replicas.items() if v["live"]],
        "counters": fleet_counters,
        "phases": {
            "queue_wait": {
                "ms": fleet_phases["queue_wait"],
                "count": fleet_counters["requests"],
                "buckets": {},
            },
            "device_compute": {
                "ms": fleet_phases["device_compute"],
                "count": fleet_counters["requests"],
                "buckets": {},
            },
            "total": {
                "ms": fleet_phases["total"],
                "count": fleet_counters["requests"],
                "buckets": fleet_buckets,
            },
        },
    }


def test_derive_serving_signals_deltas_and_offenders():
    prev = _tick_snap(
        0.0, {0: (10.0, 90.0, 10, 0, 0), 1: (10.0, 90.0, 10, 0, 0)}
    )
    cur = _tick_snap(
        10.0, {0: (20.0, 180.0, 20, 0, 0), 1: (910.0, 190.0, 20, 2, 40)}
    )
    signals, offenders = wd.derive_serving_signals(prev, cur)
    # queue share of THIS TICK's deltas: (10+900)/(10+900+90+100)
    assert signals[slo_mod.SIGNAL_QUEUE_WAIT_SHARE] == pytest.approx(
        910.0 / 1100.0
    )
    # p99 from the total-bucket delta histogram (all in the 0.05 slot)
    assert signals[slo_mod.SIGNAL_SERVING_LATENCY_P99_MS] == 50.0
    # error rate over this tick's attempts: 2 bad / (20 ok + 2 bad)
    assert signals[slo_mod.SIGNAL_SERVING_ERROR_RATE] == pytest.approx(
        2.0 / 22.0
    )
    assert signals[slo_mod.SIGNAL_SERVING_LIVE_REPLICAS] == 2.0
    assert signals[slo_mod.SIGNAL_SERVING_SWAP_UNREACHABLE] == 0.0
    # replica 1 moved queue_wait, total AND errors the most this tick
    assert offenders[slo_mod.SIGNAL_QUEUE_WAIT_SHARE] == 1
    assert offenders[slo_mod.SIGNAL_SERVING_LATENCY_P99_MS] == 1
    assert offenders[slo_mod.SIGNAL_SERVING_ERROR_RATE] == 1


def test_derive_serving_signals_idle_tick_stays_dormant():
    snap = _tick_snap(0.0, {0: (10.0, 90.0, 10, 0, 0)})
    signals, _offenders = wd.derive_serving_signals(snap, dict(snap))
    # no traffic this tick: latency/error objectives stay DORMANT (an
    # idle fleet must not fire a latency alarm) — only the
    # instantaneous state signals evaluate
    assert slo_mod.SIGNAL_SERVING_LATENCY_P99_MS not in signals
    assert slo_mod.SIGNAL_SERVING_ERROR_RATE not in signals
    assert signals[slo_mod.SIGNAL_SERVING_LIVE_REPLICAS] == 1.0


def test_parse_serving_slo_config_injects_serving_defaults(tmp_path):
    assert wd.parse_serving_slo_config("") is None
    config = wd.parse_serving_slo_config("default")
    names = {o["name"] for o in config["objectives"]}
    assert "serving_latency_p99" in names
    assert "serving_replica_floor" in names
    explicit = wd.parse_serving_slo_config(
        '{"objectives": [{"name": "x", "signal": "s", '
        '"comparator": "above", "threshold": 1.0}]}'
    )
    assert [o["name"] for o in explicit["objectives"]] == ["x"]


# ---- watchdog: cause classification -----------------------------------------


def test_classify_replica_down_wins_and_names_replica():
    cause, rationale = wd.classify_serving_cause(
        [
            {
                "signal": slo_mod.SIGNAL_QUEUE_WAIT_SHARE,
                "replica_id": 0,
            },
            {
                "signal": slo_mod.SIGNAL_SERVING_LIVE_REPLICAS,
                "replica_id": 2,
            },
        ],
        None,
        None,
    )
    assert cause == CAUSE_REPLICA_DOWN
    assert "replica 2" in rationale


def test_classify_swap_in_progress():
    cause, rationale = wd.classify_serving_cause(
        [
            {
                "signal": slo_mod.SIGNAL_SERVING_SWAP_UNREACHABLE,
                "replica_id": 1,
            }
        ],
        None,
        None,
    )
    assert cause == CAUSE_SWAP_IN_PROGRESS
    assert "replica 1" in rationale


def test_classify_queue_vs_compute_from_anatomy_delta():
    open_ctx = {
        "anatomy": {
            "queue_wait": {"ms": 100.0},
            "total": {"ms": 1000.0},
        }
    }
    queue_close = {
        "anatomy": {
            "queue_wait": {"ms": 5100.0},
            "total": {"ms": 7000.0},
        }
    }
    cause, rationale = wd.classify_serving_cause(
        [{"signal": slo_mod.SIGNAL_QUEUE_WAIT_SHARE, "replica_id": 3}],
        open_ctx,
        queue_close,
    )
    assert cause == CAUSE_QUEUE_BOUND
    assert "replica 3" in rationale
    compute_close = {
        "anatomy": {
            "queue_wait": {"ms": 200.0},
            "total": {"ms": 9000.0},
        }
    }
    cause, _r = wd.classify_serving_cause(
        [{"signal": slo_mod.SIGNAL_SERVING_LATENCY_P99_MS}],
        open_ctx,
        compute_close,
    )
    assert cause == CAUSE_COMPUTE_BOUND


# ---- watchdog: the full loop ------------------------------------------------


class _ScriptedRouter:
    """fleet_snapshot stub with a settable current snapshot (the
    watchdog reads it at tick AND at incident open/close context)."""

    def __init__(self):
        self.snap = _tick_snap(0.0, {0: (0.0, 0.0, 0, 0, 0)})

    def fleet_snapshot(self) -> dict:
        return self.snap


def test_watchdog_fires_once_names_replica_and_recovers(tmp_path):
    events: list[tuple[str, dict]] = []
    config = wd.parse_serving_slo_config(
        json.dumps(
            {
                "objectives": [
                    {
                        "name": "serving_queue_wait",
                        "signal": slo_mod.SIGNAL_QUEUE_WAIT_SHARE,
                        "comparator": "above",
                        "threshold": 0.5,
                    }
                ]
            }
        )
    )
    router = _ScriptedRouter()
    watchdog = wd.ServingWatchdog(
        router,
        config,
        telemetry_dir=str(tmp_path),
        emit=lambda event, **fields: events.append((event, fields)),
    )

    # cumulative per-replica state: replica 0 healthy throughout,
    # replica 1 goes queue-bound for the middle stretch
    state = {0: [0.0, 0.0, 0], 1: [0.0, 0.0, 0]}
    at = 0.0

    def tick(r1_queue_ms, r1_compute_ms):
        nonlocal at
        at += 10.0
        state[0][0] += 1.0
        state[0][1] += 99.0
        state[0][2] += 10
        state[1][0] += r1_queue_ms
        state[1][1] += r1_compute_ms
        state[1][2] += 10
        router.snap = _tick_snap(
            at,
            {
                rid: (s[0], s[1], s[2], 0, 0)
                for rid, s in state.items()
            },
        )
        watchdog.tick()

    watchdog.tick()  # first tick only seeds the baseline
    for _ in range(12):
        tick(1.0, 99.0)  # healthy: queue share ~1%
    for _ in range(12):
        tick(900.0, 100.0)  # queue-bound burn: share ~82%
    for _ in range(12):
        tick(1.0, 99.0)  # recovery

    names = [e for e, _f in events]
    assert names.count("slo_violation") == 1
    assert names.count("slo_recovered") == 1
    assert names.count("incident_open") == 1
    assert names.count("incident_close") == 1
    records = read_incidents(str(tmp_path))
    assert len(records) == 1
    record = records[0]
    assert record["suspected_cause"] == CAUSE_QUEUE_BOUND
    # the postmortem names the offending replica, in the enriched
    # violation transition AND the rationale
    assert record["violations"][0]["replica_id"] == 1
    assert "replica 1" in record["rationale"]
    assert record["objectives"] == ["serving_queue_wait"]


def test_watchdog_health_and_metrics_delegate(tmp_path):
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    config = wd.parse_serving_slo_config("default")
    watchdog = wd.ServingWatchdog(_ScriptedRouter(), config)
    block = watchdog.health_block()
    assert "objectives" in block
    registry = MetricsRegistry()
    watchdog.mirror_metrics(registry)
    assert "elasticdl_slo_objective_ok" in registry.exposition()


# ---- fleet /metrics families ------------------------------------------------


class _SnapshotRouter:
    def __init__(self, snap):
        self.snap = snap

    def fleet_snapshot(self):
        return self.snap


def test_fleet_metrics_families_render_per_replica(tmp_path):
    from elasticdl_tpu.serving.metrics import FleetMetrics
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    snap = _tick_snap(
        1.0, {0: (5.0, 20.0, 4, 1, 3), 1: (2.0, 10.0, 2, 0, 0)}
    )
    registry = MetricsRegistry()
    FleetMetrics(_SnapshotRouter(snap), registry)
    text = registry.exposition()
    assert 'elasticdl_serving_replica_queue_rows{replica="0"} 3' in text
    assert 'elasticdl_serving_replica_errors_total{replica="0"} 1' in text
    assert (
        'elasticdl_serving_replica_phase_ms_total'
        '{phase="queue_wait",replica="1"}' in text
        or 'elasticdl_serving_replica_phase_ms_total'
        '{replica="1",phase="queue_wait"}' in text
    )
    assert 'replica="other"' not in text


def test_fleet_metrics_collapse_over_cardinality_budget(monkeypatch):
    from elasticdl_tpu.serving.metrics import FleetMetrics
    from elasticdl_tpu.telemetry.master_hooks import WORKER_SERIES_MAX_ENV
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    monkeypatch.setenv(WORKER_SERIES_MAX_ENV, "2")
    snap = _tick_snap(
        1.0,
        {rid: (1.0, 2.0, 1, 0, rid) for rid in range(4)},
    )
    # make one overflow replica silent for a long time: its probe age
    # must surface as the "other" bucket's MAX, not vanish
    snap["replicas"][3]["last_probe_age_secs"] = 42.0
    registry = MetricsRegistry()
    FleetMetrics(_SnapshotRouter(snap), registry)
    text = registry.exposition()
    assert 'replica="0"' in text
    assert 'replica="1"' not in text  # collapsed
    assert 'replica="other"' in text
    # other = replicas 1+2+3: queue_rows 1+2+3, probe age max 42
    assert (
        'elasticdl_serving_replica_queue_rows{replica="other"} 6' in text
    )
    assert (
        'elasticdl_serving_replica_probe_age_secs{replica="other"} 42'
        in text
    )


# ---- trace analysis ---------------------------------------------------------


def _canned_serving_spans(trace_id: str) -> list[dict]:
    root_span = gen_span_id()
    base = {"trace_id": trace_id, "worker_id": 0, "process_id": 0}
    return [
        dict(
            base,
            span=SPAN_PREDICT_REQUEST,
            span_id=root_span,
            role="client",
            start=0.0,
            end=1.0,
            request_id="r1",
        ),
        dict(
            base,
            span=SPAN_SERVING_ROUTE,
            span_id=gen_span_id(),
            parent_span_id=root_span,
            role="router",
            start=0.0,
            end=0.95,
            replica_id=0,
            attempt=0,
        ),
        dict(
            base,
            span=SPAN_SERVING_QUEUE,
            span_id=gen_span_id(),
            parent_span_id=root_span,
            role="replica",
            start=0.1,
            end=0.3,
        ),
        dict(
            base,
            span=SPAN_SERVING_ENGINE,
            span_id=gen_span_id(),
            parent_span_id=root_span,
            role="replica",
            start=0.3,
            end=0.9,
        ),
        {
            "span": SPAN_SERVING_DISPATCH,
            "span_id": gen_span_id(),
            "trace_id": gen_trace_id(),
            "role": "replica",
            "worker_id": 0,
            "start": 0.3,
            "end": 0.9,
            "links": [{"trace_id": trace_id, "span_id": root_span}],
        },
    ]


def test_serving_critical_path_sums_exactly():
    from elasticdl_tpu.telemetry.trace import _serving_critical_path

    trace_id = gen_trace_id()
    section = _serving_critical_path(_canned_serving_spans(trace_id))
    assert section["requests"] == 1
    assert section["reroutes"] == 0
    phases = section["phases_secs"]
    # route keeps only the router's own pick/transport time (0.0-0.1
    # before the replica starts, 0.9-0.95 shipping the reply back up);
    # the replica's finer queue/compute split takes the overlap, and
    # the residual after every span is the response's return leg
    assert phases["route"] == pytest.approx(0.15, abs=1e-6)
    assert phases["queue_wait"] == pytest.approx(0.2, abs=1e-6)
    assert phases["compute"] == pytest.approx(0.6, abs=1e-6)
    assert phases["response_return"] == pytest.approx(0.05, abs=1e-6)
    assert sum(phases.values()) == pytest.approx(
        section["wall_secs_total"], abs=1e-6
    )
    assert section["dispatch_groups"] == 1
    assert section["linked_dispatch_groups"] == 1


def test_analyze_dir_includes_serving_section(tmp_path):
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    spans = _canned_serving_spans(gen_trace_id())
    with open(tmp_path / "spans.jsonl", "w", encoding="utf-8") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
    (tmp_path / "events.jsonl").write_text("")
    analysis = analyze_telemetry_dir(str(tmp_path))
    serving = analysis["serving"]
    assert serving["requests"] == 1
    assert serving["coverage"] == pytest.approx(1.0, abs=1e-3)


def test_chrome_export_lays_out_serving_tracks(tmp_path):
    from elasticdl_tpu.telemetry.trace import build_chrome_trace

    spans = _canned_serving_spans(gen_trace_id())
    with open(tmp_path / "spans.jsonl", "w", encoding="utf-8") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
    chrome = build_chrome_trace(str(tmp_path))
    json.dumps(chrome)  # valid Chrome JSON
    names = {
        e["args"]["name"]
        for e in chrome["traceEvents"]
        if e.get("name") == "process_name"
    }
    # one track per serving actor: client -> router -> replica N
    assert {"client", "router", "replica 0"} <= names
    slices = [e for e in chrome["traceEvents"] if e.get("ph") == "X"]
    assert {s["name"] for s in slices} >= {
        SPAN_PREDICT_REQUEST,
        SPAN_SERVING_ROUTE,
        SPAN_SERVING_QUEUE,
        SPAN_SERVING_ENGINE,
    }


# ---- argv byte-identity + report digest -------------------------------------


def test_replica_argv_byte_identical_with_observability_on():
    from elasticdl_tpu.serving.main import _replica_argv, build_parser

    base = [
        "--model_dir",
        "/m",
        "--num_replicas",
        "2",
    ]
    plain = build_parser().parse_args(base)
    observed = build_parser().parse_args(
        base
        + [
            "--slo_config",
            "default",
            "--telemetry_dir",
            "/tmp/t",
            "--metrics_port",
            "0",
        ]
    )
    assert _replica_argv(plain, 0, "/w") == _replica_argv(observed, 0, "/w")


def test_summary_json_covers_serving_runs():
    from elasticdl_tpu.telemetry.report import summarize_report

    report = {
        "run_dir": "/r",
        "runs": {
            "a": {
                "events_total": 4,
                "serving": {
                    "requests": 10,
                    "rows": 50,
                    "sheds": 1,
                    "errors": 2,
                },
            },
            "b": {"events_total": 1},
        },
    }
    summary = summarize_report(report)
    assert summary["serving"] == {
        "runs": 1,
        "requests": 10,
        "rows": 50,
        "sheds": 1,
        "errors": 2,
    }
    assert summary["verdict"] == "ok"
    no_serving = summarize_report({"runs": {"b": {"events_total": 1}}})
    assert no_serving["serving"] is None


def test_predict_client_raise_names_failed_traces():
    """The residual-failure raise carries the failed trace ids (the
    satellite bugfix): simulated by the same formatting path."""
    from elasticdl_tpu.serving import predict_client

    # _client_tracer without a telemetry dir stays off (no install)
    os.environ.pop("ELASTICDL_TPU_TELEMETRY_DIR", None)
    assert predict_client._client_tracer() is None
