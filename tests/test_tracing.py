"""Distributed-tracing tests (ISSUE 3).

Covers: span recorder mechanics (ids, parenting, sampling, rotation),
trace-context round-trips through the RPC wire format INCLUDING old
payloads without trace fields, master-side task traces with recovered-
task linkage, Perfetto export schema, the reform critical-path
analyzer's phase attribution (≥90% coverage on a canned reform), the
straggler report's wait-vs-work split, and the disabled-path overhead
contract.  The chaos acceptance run (a real preempt under
``preempt_one_worker``) is slow-marked.
"""

from __future__ import annotations

import json
import os

import msgpack
import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.telemetry import trace as trace_cli
from elasticdl_tpu.telemetry import tracing
from elasticdl_tpu.telemetry.events import (
    read_jsonl,
    rotate_if_needed,
)
from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry
from elasticdl_tpu.telemetry.tracing import (
    SPAN_CHECKPOINT_RESTORE,
    SPAN_REFORM,
    SPAN_REFORM_FENCE,
    SPAN_REFORM_RELAUNCH,
    SPAN_TASK_EXECUTE,
    SPAN_TASK_LIFECYCLE,
    SPAN_WORLD_JOIN,
    SpanRecorder,
    gen_span_id,
    gen_trace_id,
    read_spans,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_tracer():
    tracing.uninstall()
    yield
    tracing.uninstall()


def _spans_path(tmp_path) -> str:
    return os.path.join(str(tmp_path), "spans.jsonl")


# ---- recorder mechanics -----------------------------------------------------


def test_trace_and_span_id_widths():
    assert len(gen_trace_id()) == 32
    assert len(gen_span_id()) == 16
    int(gen_trace_id(), 16)  # hex
    assert gen_trace_id() != gen_trace_id()


def test_span_records_parenting_and_attrs(tmp_path):
    rec = SpanRecorder(_spans_path(tmp_path), worker_id=7, generation=2)
    with rec.span("outer_span", task_id=3) as outer:
        with rec.span("inner_span") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_span_id == outer.span_id
    rec.flush()
    spans = read_spans(_spans_path(tmp_path))
    by_name = {s["span"]: s for s in spans}
    assert by_name["inner_span"]["parent_span_id"] == (
        by_name["outer_span"]["span_id"]
    )
    assert by_name["outer_span"]["task_id"] == 3
    assert by_name["outer_span"]["worker_id"] == 7
    assert by_name["outer_span"]["generation"] == 2
    assert by_name["outer_span"]["end"] >= by_name["outer_span"]["start"]


def test_explicit_trace_context_wins_over_stack(tmp_path):
    rec = SpanRecorder(_spans_path(tmp_path))
    ctx = {"trace_id": gen_trace_id(), "span_id": gen_span_id()}
    with rec.span("outer_span"):
        with rec.span("adopted_span", trace_ctx=ctx) as sp:
            assert sp.trace_id == ctx["trace_id"]
            assert sp.parent_span_id == ctx["span_id"]


def test_retroactive_record_span_and_sampling(tmp_path):
    rec = SpanRecorder(_spans_path(tmp_path), sample_rate=0.5)
    kept = sum(
        rec.record_span("sampled_span", 1.0, 2.0, sampled=True)
        for _ in range(10)
    )
    assert kept == 5  # deterministic 1-in-2
    # lifecycle spans bypass the sampler entirely
    for _ in range(3):
        assert rec.record_span("always_span", 1.0, 2.0)
    rec.flush()
    spans = read_spans(_spans_path(tmp_path))
    assert sum(1 for s in spans if s["span"] == "sampled_span") == 5
    assert sum(1 for s in spans if s["span"] == "always_span") == 3


def test_sample_rate_zero_drops_and_one_keeps(tmp_path):
    rec = SpanRecorder(_spans_path(tmp_path), sample_rate=0.0)
    assert not rec.record_span("x_span", 0.0, 1.0, sampled=True)
    rec = SpanRecorder(_spans_path(tmp_path), sample_rate=1.0)
    assert rec.record_span("x_span", 0.0, 1.0, sampled=True)


def test_on_step_records_interval_spans(tmp_path):
    rec = SpanRecorder(_spans_path(tmp_path), sample_rate=1.0)
    rec.on_step(10)  # no interval yet
    rec.on_step(11)
    rec.on_step(12)
    rec.flush()
    steps = [
        s
        for s in read_spans(_spans_path(tmp_path))
        if s["span"] == "train_step"
    ]
    assert [s["step"] for s in steps] == [10, 11]
    assert all(s["end"] >= s["start"] for s in steps)


def test_disabled_module_hooks_are_single_early_return(monkeypatch):
    """No tracer installed: the hot-path hooks must not even read the
    clock (the worker_hooks overhead contract, applied to spans)."""
    assert tracing.get_tracer() is None

    def boom(*_a, **_k):
        raise AssertionError("disabled path touched the clock")

    monkeypatch.setattr(tracing.time, "monotonic", boom)
    monkeypatch.setattr(tracing.time, "time", boom)
    tracing.record_step_span(5)
    tracing.flush()
    with tracing.trace_span("anything_span") as sp:
        assert sp is None


def test_disabled_recorder_is_usable_but_writes_nothing(tmp_path):
    rec = SpanRecorder("")  # master without --telemetry_dir
    with rec.span("reform"):
        pass
    rec.record_span("x_span", 0.0, 1.0)
    rec.flush()  # no crash, nothing on disk
    assert not os.listdir(str(tmp_path))


# ---- rotation ---------------------------------------------------------------


def test_jsonl_rotation_caps_shards(tmp_path):
    path = os.path.join(str(tmp_path), "log.jsonl")
    line = json.dumps({"n": 0}) + "\n"
    for i in range(12):
        rotate_if_needed(path, max_bytes=len(line) * 2, keep_shards=3)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps({"n": i}) + "\n")
    shards = sorted(p for p in os.listdir(str(tmp_path)))
    assert "log.jsonl" in shards
    rotated = [p for p in shards if p.startswith("log.jsonl.")]
    assert rotated == ["log.jsonl.1", "log.jsonl.2", "log.jsonl.3"]
    # reader walks shards oldest-first; the newest record is last
    records = read_jsonl(path)
    assert records[-1]["n"] == 11
    assert [r["n"] for r in records] == sorted(r["n"] for r in records)


def test_event_log_rotation_end_to_end(tmp_path, monkeypatch):
    from elasticdl_tpu.telemetry import events as events_mod

    monkeypatch.setattr(events_mod, "ROTATE_MAX_BYTES", 200)
    log = events_mod.EventLog(os.path.join(str(tmp_path), "events.jsonl"))
    for i in range(50):
        log.emit("step", step=i)
    names = os.listdir(str(tmp_path))
    assert any(n.startswith("events.jsonl.") for n in names)
    assert (
        len([n for n in names if n.startswith("events.jsonl")])
        <= events_mod.ROTATE_KEEP_SHARDS + 1
    )
    records = events_mod.read_events(
        os.path.join(str(tmp_path), "events.jsonl")
    )
    assert records[-1]["step"] == 49


def test_span_log_rotation(tmp_path, monkeypatch):
    from elasticdl_tpu.telemetry import events as events_mod

    monkeypatch.setattr(events_mod, "ROTATE_MAX_BYTES", 400)
    rec = SpanRecorder(_spans_path(tmp_path), buffer_spans=1)
    for i in range(30):
        rec.record_span("rotated_span", float(i), float(i) + 0.5)
    rec.flush()
    names = [n for n in os.listdir(str(tmp_path)) if "spans" in n]
    assert any(n.startswith("spans.jsonl.") for n in names)
    assert len(read_spans(_spans_path(tmp_path))) > 0


# ---- RPC wire format --------------------------------------------------------


def test_trace_context_round_trips_all_messages():
    ctx = {"trace_id": gen_trace_id(), "span_id": gen_span_id()}
    for message in (
        msg.GetTaskRequest(worker_id=1, trace=dict(ctx)),
        msg.TaskResponse(task_id=2, shard_name="s", trace=dict(ctx)),
        msg.ReportTaskResultRequest(task_id=2, trace=dict(ctx)),
        msg.WorldAssignmentResponse(has=True, worker_id=1, trace=dict(ctx)),
    ):
        decoded = msg.decode(msg.encode(message))
        assert decoded.trace == ctx, type(message).__name__


def test_old_payloads_without_trace_fields_decode():
    """Backward compat: a pre-trace peer's msgpack payload (no ``trace``
    key) must decode into the new dataclasses with an empty context."""
    bodies = {
        "GetTaskRequest": {"worker_id": 3, "task_type": -1},
        "TaskResponse": {
            "task_id": 1,
            "shard_name": "s",
            "start": 0,
            "end": 64,
            "type": 0,
            "model_version": 5,
            "minibatch_size": 32,
            "extended": {},
        },
        "ReportTaskResultRequest": {
            "task_id": 1,
            "err_message": "",
            "exec_counters": {},
        },
        "WorldAssignmentResponse": {
            "has": True,
            "shutdown": False,
            "worker_id": 0,
            "coordinator_addr": "localhost:1",
            "num_processes": 2,
            "process_id": 1,
            "cluster_version": 3,
        },
    }
    for kind, body in bodies.items():
        buf = msgpack.packb(
            {"kind": kind, "body": body}, use_bin_type=True
        )
        decoded = msg.decode(buf)
        assert decoded.trace == {}, kind
    # and the new encoding still satisfies an old-style field read
    resp = msg.decode(msg.encode(msg.TaskResponse(task_id=9)))
    assert resp.task_id == 9


# ---- master-side task traces ------------------------------------------------


def _master_fixture(tmp_path):
    telemetry = MasterTelemetry(str(tmp_path), trace_sample_rate=1.0)
    task_d = TaskDispatcher(
        {"s": (0, 128)}, records_per_task=64, shuffle_seed=1
    )
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)
    return telemetry, task_d, servicer


def test_task_response_carries_dispatch_trace(tmp_path):
    telemetry, task_d, servicer = _master_fixture(tmp_path)
    resp = servicer.get_task(msg.GetTaskRequest(worker_id=1))
    assert resp.trace.get("trace_id")
    assert resp.trace == telemetry.trace_for_task(resp.task_id)


def test_recovered_task_links_to_original_trace(tmp_path):
    """Preemption path: fail the first lease, re-lease, and check the
    new dispatch span shares the trace and parents to the original."""
    telemetry, task_d, servicer = _master_fixture(tmp_path)
    first = servicer.get_task(msg.GetTaskRequest(worker_id=0))
    task_d.report(first.task_id, success=False)  # worker died / errored
    second = servicer.get_task(msg.GetTaskRequest(worker_id=1))
    assert second.trace["trace_id"] == first.trace["trace_id"]
    assert second.trace["span_id"] != first.trace["span_id"]
    task_d.report(second.task_id, success=True)
    # drain remaining work so spans close
    tid, _ = task_d.get(2)
    task_d.report(tid, success=True)
    telemetry.tracer.flush()
    spans = read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    roots = [
        s
        for s in spans
        if s["span"] == SPAN_TASK_LIFECYCLE
        and s["trace_id"] == first.trace["trace_id"]
    ]
    assert len(roots) == 2
    original = next(s for s in roots if not s["recovered"])
    recovered = next(s for s in roots if s["recovered"])
    assert recovered["parent_span_id"] == original["span_id"]
    assert original["success"] is False
    assert recovered["success"] is True


def test_lease_timeout_reclaim_closes_span(tmp_path):
    telemetry = MasterTelemetry(str(tmp_path))
    task_d = TaskDispatcher(
        {"s": (0, 64)}, records_per_task=64, task_timeout_secs=0.001
    )
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)
    resp = servicer.get_task(msg.GetTaskRequest(worker_id=0))
    import time as _time

    _time.sleep(0.01)
    release = servicer.get_task(msg.GetTaskRequest(worker_id=1))
    assert release.trace["trace_id"] == resp.trace["trace_id"]
    task_d.report(release.task_id, success=True)
    telemetry.tracer.flush()
    spans = read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    reclaimed = [s for s in spans if s.get("reclaimed")]
    assert len(reclaimed) == 1


# ---- export schema ----------------------------------------------------------


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record) + "\n")


def _canned_reform_run(tmp_path) -> str:
    """Two generations with a 10s downtime gap fully described by reform
    spans: detect 2s -> fence 1s -> relaunch 3s -> join 2s -> restore 1s
    -> warmup 1s."""
    run = str(tmp_path / "run")
    t0 = 1000.0
    events = []
    for i in range(5):
        events.append(
            {
                "monotonic": t0 + i * 1.0,
                "time": 1.7e9 + i,
                "event": "step",
                "step": i,
                "generation": 0,
                "worker_id": i % 2,
                "records": 32,
                **({"duration_secs": 1.0} if i else {}),
            }
        )
    gap_start = t0 + 4.0  # last gen-0 step
    for i in range(4):
        events.append(
            {
                "monotonic": gap_start + 10.0 + i * 1.0,
                "time": 1.7e9 + 20 + i,
                "event": "step",
                "step": 5 + i,
                "generation": 1,
                "worker_id": i % 2,
                "records": 32,
                **({"duration_secs": 1.0} if i else {}),
            }
        )
    trace_id = gen_trace_id()
    reform_root = gen_span_id()
    spans = [
        {
            "span": SPAN_REFORM,
            "trace_id": trace_id,
            "span_id": reform_root,
            "parent_span_id": "",
            "role": "master",
            "worker_id": 0,
            "process_id": 0,
            "generation": 1,
            "start": gap_start + 2.0,
            "end": gap_start + 6.0,
            "reason": "worker_failure",
        },
        {
            "span": SPAN_REFORM_FENCE,
            "trace_id": trace_id,
            "span_id": gen_span_id(),
            "parent_span_id": reform_root,
            "role": "master",
            "generation": 1,
            "start": gap_start + 2.0,
            "end": gap_start + 3.0,
        },
        {
            "span": SPAN_REFORM_RELAUNCH,
            "trace_id": trace_id,
            "span_id": gen_span_id(),
            "parent_span_id": reform_root,
            "role": "master",
            "generation": 1,
            "start": gap_start + 3.0,
            "end": gap_start + 6.0,
        },
        {
            "span": SPAN_WORLD_JOIN,
            "trace_id": trace_id,
            "span_id": gen_span_id(),
            "parent_span_id": reform_root,
            "role": "worker",
            "worker_id": 2,
            "generation": 1,
            "start": gap_start + 6.0,
            "end": gap_start + 8.0,
        },
        {
            "span": SPAN_CHECKPOINT_RESTORE,
            "trace_id": gen_trace_id(),
            "span_id": gen_span_id(),
            "parent_span_id": "",
            "role": "worker",
            "worker_id": 2,
            "generation": 1,
            "start": gap_start + 8.0,
            "end": gap_start + 9.0,
        },
    ]
    _write_jsonl(os.path.join(run, "events.jsonl"), events)
    _write_jsonl(os.path.join(run, "spans.jsonl"), spans)
    return run


def test_export_emits_valid_chrome_trace(tmp_path):
    run = _canned_reform_run(tmp_path)
    out = str(tmp_path / "trace.json")
    rc = trace_cli.main(["export", run, "--output", out])
    assert rc == 0
    with open(out, encoding="utf-8") as f:
        chrome = json.load(f)
    events = chrome["traceEvents"]
    assert isinstance(events, list) and events
    slices = [e for e in events if e["ph"] == "X"]
    assert slices, "no complete events"
    for e in slices:
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["name"]
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    # one track per worker per generation + a master track
    labels = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert any("worker 0 gen 0" in label for label in labels)
    assert any("worker 0 gen 1" in label for label in labels)
    assert any("master" in label for label in labels)
    # span slices carry their causal ids for Perfetto queries
    reform = next(e for e in slices if e["name"] == SPAN_REFORM)
    assert reform["args"]["trace_id"]


def test_export_cli_on_empty_dir(tmp_path):
    rc = trace_cli.main(["export", str(tmp_path)])
    assert rc == 0  # an empty (but valid) trace
    assert trace_cli.main(["analyze", str(tmp_path / "missing")]) == 2


# ---- critical-path analyzer -------------------------------------------------


def test_analyze_attributes_reform_downtime_phases(tmp_path):
    run = _canned_reform_run(tmp_path)
    report = trace_cli.analyze_run_dir(run)
    (rel, analysis) = next(iter(report["runs"].items()))
    gaps = analysis["reform_downtime"]
    assert len(gaps) == 1
    gap = gaps[0]
    assert abs(gap["downtime_secs"] - 10.0) < 1e-6
    phases = gap["phases_secs"]
    # acceptance: ≥ 90% of the downtime lands in NAMED phases
    assert gap["coverage"] >= 0.9, phases
    assert abs(phases["death_detection"] - 2.0) < 1e-6
    assert abs(phases["quiesce_recover"] - 1.0) < 1e-6
    assert abs(phases["world_relaunch"] - 3.0) < 1e-6
    assert abs(phases["world_join"] - 2.0) < 1e-6
    assert abs(phases["checkpoint_restore"] - 1.0) < 1e-6
    assert abs(phases["warmup_compile"] - 1.0) < 1e-6
    # the phase sum IS the downtime (sweep attribution is exhaustive)
    assert abs(sum(phases.values()) - gap["downtime_secs"]) < 1e-6


def test_analyze_without_spans_reports_unattributed(tmp_path):
    run = _canned_reform_run(tmp_path)
    os.remove(os.path.join(run, "spans.jsonl"))
    report = trace_cli.analyze_run_dir(run)
    (_rel, analysis) = next(iter(report["runs"].items()))
    gap = analysis["reform_downtime"][0]
    assert gap["coverage"] == 0.0
    assert abs(
        gap["phases_secs"]["unattributed"] - gap["downtime_secs"]
    ) < 1e-6


def test_straggler_report_wait_vs_work(tmp_path):
    """Worker 1 is 3x slower on every shared step: it must be flagged
    and worker 0 must carry the barrier wait."""
    run = str(tmp_path / "run")
    events = []
    for step in range(1, 9):
        for worker, dur in ((0, 0.1), (1, 0.3)):
            events.append(
                {
                    "monotonic": 100.0 + step * 0.4 + worker * 0.001,
                    "time": 1.7e9,
                    "event": "step",
                    "step": step,
                    "generation": 0,
                    "worker_id": worker,
                    "records": 32,
                    "duration_secs": dur,
                }
            )
    _write_jsonl(os.path.join(run, "events.jsonl"), events)
    _write_jsonl(os.path.join(run, "spans.jsonl"), [])
    report = trace_cli.analyze_run_dir(run)
    (_rel, analysis) = next(iter(report["runs"].items()))
    stats = analysis["stragglers"][0]
    workers = stats["workers"]
    assert workers[1]["straggler"] is True
    assert workers[0]["straggler"] is False
    # the fast worker waits at the barrier, the straggler works
    assert workers[0]["barrier_wait_secs"] > workers[1]["barrier_wait_secs"]
    assert workers[0]["barrier_wait_pct"] > 50
    assert workers[1]["barrier_wait_pct"] == 0


# ---- report CLI + profiler integration --------------------------------------


def test_report_cli_includes_trace_section(tmp_path):
    run = _canned_reform_run(tmp_path)
    from elasticdl_tpu.telemetry import report as report_cli

    report = report_cli.build_report(run)
    analysis = report["runs"]["events.jsonl"]["trace"]
    assert analysis["reform_downtime"][0]["coverage"] >= 0.9


def test_step_profiler_emits_window_events_and_span(tmp_path, monkeypatch):
    calls = []
    import jax

    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.utils.profiling import StepProfiler

    worker_hooks.install(str(tmp_path), worker_id=1)
    tracing.install(str(tmp_path), worker_id=1, sample_rate=1.0)
    try:
        profiler = StepProfiler(
            str(tmp_path / "xla"), start_step=1, num_steps=2
        )
        for _ in range(6):
            profiler.on_step()
        profiler.stop()
        tracing.flush()
    finally:
        worker_hooks.uninstall()
    assert [c[0] for c in calls] == ["start", "stop"]
    events = read_jsonl(os.path.join(str(tmp_path), "events.jsonl"))
    names = [e["event"] for e in events]
    assert "profile_window_open" in names
    assert "profile_window_close" in names
    spans = read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    window = [s for s in spans if s["span"] == "profile_window"]
    assert len(window) == 1
    assert window[0]["end"] > window[0]["start"]


def test_worker_task_span_adopts_dispatch_trace(tmp_path):
    """The worker-side task_execute span lands in the master's dispatch
    trace (in-process master wiring, no transport)."""
    tracing.install(str(tmp_path), worker_id=5, sample_rate=1.0)
    ctx = {"trace_id": gen_trace_id(), "span_id": gen_span_id()}
    with tracing.trace_span(
        SPAN_TASK_EXECUTE, trace_ctx=ctx, task_id=1
    ) as sp:
        tracing.record_step_span(0)
        tracing.record_step_span(1)
    tracing.flush()
    spans = read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    task = next(s for s in spans if s["span"] == SPAN_TASK_EXECUTE)
    assert task["trace_id"] == ctx["trace_id"]
    assert task["parent_span_id"] == ctx["span_id"]
    steps = [s for s in spans if s["span"] == "train_step"]
    assert steps and all(s["trace_id"] == ctx["trace_id"] for s in steps)
    assert all(s["parent_span_id"] == task["span_id"] for s in steps)


def test_trace_fetches_records_first_fetch(tmp_path):
    tracing.install(str(tmp_path), sample_rate=1.0)
    ctx = {"trace_id": gen_trace_id(), "span_id": gen_span_id()}
    out = list(tracing.trace_fetches(iter([1, 2, 3]), trace_ctx=ctx))
    assert out == [1, 2, 3]
    tracing.flush()
    spans = read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    fetch = [s for s in spans if s["span"] == "data_fetch"]
    assert len(fetch) == 1
    assert fetch[0]["trace_id"] == ctx["trace_id"]


# ---- chaos acceptance (slow) ------------------------------------------------


def _run_chaos_with_tracing(tmp_path, plan_name: str) -> dict:
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    return run_chaos_job(
        ChaosJobConfig(
            plan=named_plan(plan_name, num_workers=2),
            workdir=str(tmp_path),
            num_records=512,
            num_epochs=2,
            extra_master_args=["--trace_sample_rate", "1.0"],
        )
    )


def _all_spans(run_dir: str) -> list[dict]:
    spans = []
    for root, _dirs, files in os.walk(run_dir):
        if "spans.jsonl" in files:
            spans.extend(read_spans(os.path.join(root, "spans.jsonl")))
    return spans


@pytest.mark.slow
def test_chaos_preempt_trace_critical_path(tmp_path):
    """Acceptance: on a deterministic preempt_one_worker run, `trace
    analyze` attributes ≥90% of the reform downtime to named phases,
    and chaos_result.json carries the breakdown."""
    report = _run_chaos_with_tracing(tmp_path, "preempt_one_worker")
    assert report["invariants_ok"], report
    analysis = trace_cli.analyze_run_dir(str(tmp_path))
    runs_with_gaps = [
        run
        for run in analysis["runs"].values()
        if run["reform_downtime"]
    ]
    assert runs_with_gaps, "no reform downtime captured"
    gap = runs_with_gaps[0]["reform_downtime"][0]
    assert gap["coverage"] >= 0.9, gap
    # chaos_result.json carries the trace summary
    from elasticdl_tpu.chaos.runner import write_result_json

    path = write_result_json(report, str(tmp_path))
    with open(path, encoding="utf-8") as f:
        result = json.load(f)
    assert result["trace"], "chaos_result.json missing trace section"
    gaps = [
        g
        for run in result["trace"].values()
        for g in run["reform_downtime"]
    ]
    assert gaps and gaps[0]["coverage"] >= 0.9


@pytest.mark.slow
def test_chaos_coordinator_kill_links_recovered_task_trace(tmp_path):
    """Killing the CHIEF (the task reporter) mid-task guarantees an
    unreported lease: the recovered task's new dispatch span must link
    back into the original trace.  (A plain worker preempt can leave no
    active lease — the surviving chief reports the in-flight tasks
    host-side before it blocks on the dead peer's collective.)"""
    report = _run_chaos_with_tracing(tmp_path, "preempt_coordinator")
    assert report["invariants_ok"], report
    spans = _all_spans(str(tmp_path))
    recovered = [s for s in spans if s.get("recovered")]
    assert recovered, "no recovered-task span"
    originals = {
        s["trace_id"]
        for s in spans
        if s["span"] == SPAN_TASK_LIFECYCLE and not s.get("recovered")
    }
    assert all(s["trace_id"] in originals for s in recovered)
    # the re-lease parents to the previous attempt's span
    by_id = {s["span_id"]: s for s in spans}
    for span in recovered:
        parent = by_id.get(span["parent_span_id"])
        assert parent is not None and parent["span"] == SPAN_TASK_LIFECYCLE
