"""Master orchestration: evaluation service, step-based triggers, the full
train+eval in-process job, and the real-gRPC transport round trip
(reference pattern: in-process master + real servers on localhost,
test_utils.py:192-214 + worker_ps_interaction_test.py)."""

import numpy as np
import pytest

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.master.evaluation_service import (
    EvaluationJob,
    EvaluationService,
)
from elasticdl_tpu.master.master import Master, derive_job_type
from elasticdl_tpu.trainer.metrics import Accuracy
from elasticdl_tpu.utils.args import parse_master_args
from elasticdl_tpu.utils.constants import JobType, TaskType
from elasticdl_tpu.utils.tensor import ndarray_to_tensor
from elasticdl_tpu.worker.worker import Worker


def _master_args(train_dir="", eval_dir="", extra=()):
    argv = [
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--minibatch_size",
        "16",
        "--records_per_task",
        "32",
        "--compute_dtype",
        "float32",
        "--port",
        "0",
    ]
    if train_dir:
        argv += ["--training_data", train_dir]
    if eval_dir:
        argv += ["--validation_data", eval_dir]
    return parse_master_args(argv + list(extra))


class TestEvaluationJob:
    def test_metrics_from_wire_tensors(self):
        job = EvaluationJob({"accuracy": Accuracy()}, model_version=3,
                            total_tasks=2)
        outputs = {
            "output": ndarray_to_tensor("output", np.eye(3, dtype=np.float32))
        }
        labels = ndarray_to_tensor("labels", np.array([0, 1, 2]))
        assert job.report_evaluation_metrics(outputs, labels)
        assert job.get_evaluation_summary() == {"accuracy": 1.0}
        job.complete_task()
        assert not job.finished()
        job.complete_task()
        assert job.finished()


def test_step_based_eval_trigger(tmp_path):
    """report_version at evaluation_steps milestones creates eval tasks
    (reference ps/servicer.py:198-205 -> servicer.py:79-85 -> eval service)."""
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(train_dir, eval_dir, ["--evaluation_steps", "2"])
    master = Master(args)
    assert master.job_type == JobType.TRAINING_WITH_EVALUATION

    from elasticdl_tpu.rpc import messages as msg

    master.servicer.report_version(
        msg.ReportVersionRequest(model_version=2, worker_id=0)
    )
    assert master.task_d._pending_eval  # eval tasks created at milestone
    # same milestone doesn't double-trigger
    n = len(master.task_d._pending_eval)
    master.servicer.report_version(
        msg.ReportVersionRequest(model_version=2, worker_id=0)
    )
    assert len(master.task_d._pending_eval) == n


def test_train_with_evaluation_end_to_end(tmp_path):
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=128, num_shards=2, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(
        train_dir,
        eval_dir,
        ["--evaluation_steps", "4", "--tensorboard_log_dir",
         str(tmp_path / "tb")],
    )
    master = Master(args)
    worker = Worker(args_worker(train_dir, eval_dir), master.servicer)
    worker.run()

    assert master.task_d.finished()
    summary = getattr(master.evaluation_service, "latest_summary", None)
    assert summary is not None and "accuracy" in summary
    # tensorboard sidecar wrote events + jsonl
    tb_dir = str(tmp_path / "tb")
    import os

    files = os.listdir(tb_dir)
    assert "metrics.jsonl" in files
    assert any(f.startswith("events") for f in files)


def args_worker(train_dir, eval_dir=""):
    from elasticdl_tpu.utils.args import parse_worker_args

    argv = [
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data",
        train_dir,
        "--minibatch_size",
        "16",
        "--worker_id",
        "0",
        "--master_addr",
        "inprocess",
        "--compute_dtype",
        "float32",
    ]
    if eval_dir:
        argv += ["--validation_data", eval_dir]
    return parse_worker_args(argv)


def test_eval_milestones_queue_not_dropped(tmp_path):
    """A milestone arriving while an eval job runs is queued, not dropped
    (reference keeps _eval_checkpoint_versions for this)."""
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(train_dir, eval_dir, ["--evaluation_steps", "2"])
    master = Master(args)
    from elasticdl_tpu.rpc import messages as msg

    master.servicer.report_version(msg.ReportVersionRequest(model_version=2))
    # eval job for v2 is running (tasks pending); v4 arrives
    master.servicer.report_version(msg.ReportVersionRequest(model_version=4))
    svc = master.evaluation_service
    assert svc._eval_job is not None and svc._eval_job.model_version == 2
    assert svc._eval_checkpoint_versions == [4]  # queued, not dropped


def test_inactive_lease_metrics_dropped(tmp_path):
    """Metrics for a reclaimed/unknown lease are rejected — the
    double-count guard for retried eval tasks."""
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args("", eval_dir)
    master = Master(args)
    from elasticdl_tpu.rpc import messages as msg

    req = msg.ReportEvaluationMetricsRequest(
        model_outputs={
            "output": ndarray_to_tensor("output", np.eye(3, dtype=np.float32))
        },
        labels=ndarray_to_tensor("labels", np.array([0, 1, 2])),
        task_id=999,  # never leased
    )
    master.servicer.report_evaluation_metrics(req)
    job = master.evaluation_service._eval_job
    assert job.get_evaluation_summary()["accuracy"] == 0.0  # nothing counted


def test_final_eval_without_triggers(tmp_path):
    """TRAINING_WITH_EVALUATION with neither evaluation_steps nor
    throttle configured still evaluates once when training drains."""
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(train_dir, eval_dir)
    master = Master(args)
    worker = Worker(args_worker(train_dir, eval_dir), master.servicer)
    worker.run()
    assert master.task_d.finished()
    assert "accuracy" in master.evaluation_service.latest_summary


def test_evaluation_only_job(tmp_path):
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=48, num_shards=1, seed=1
    )
    args = _master_args("", eval_dir)
    master = Master(args)
    assert master.job_type == JobType.EVALUATION_ONLY

    worker = Worker(
        args_worker("", eval_dir),
        master.servicer,
        job_type=JobType.EVALUATION_ONLY,
    )
    worker.run()
    assert master.task_d.finished()
    assert master.evaluation_service.trigger.is_set()
    assert "accuracy" in master.evaluation_service.latest_summary


def test_grpc_transport_round_trip(tmp_path):
    """A real gRPC server on an ephemeral port with a worker driving the
    whole job through the wire."""
    from elasticdl_tpu.rpc.service import MasterClient, create_server

    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    args = _master_args(train_dir)
    master = Master(args)
    server = create_server(master.servicer, port=0)
    server.start()
    client = MasterClient(f"localhost:{server._edl_bound_port}")
    try:
        worker = Worker(
            args_worker(train_dir), client, job_type=JobType.TRAINING_ONLY
        )
        worker.run()
        assert master.task_d.finished()
        assert master.task_d.counters(TaskType.TRAINING).total_records == 64
        assert master.servicer.get_model_version() == worker.trainer.step
    finally:
        client.close()
        server.stop(grace=None)


def test_master_run_completes(tmp_path):
    """Master.run() returns once a worker thread finishes the job."""
    import threading

    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    args = _master_args(train_dir, extra=["--output", str(tmp_path / "out")])
    master = Master(args)
    master.prepare()
    worker = Worker(
        args_worker(train_dir), master.servicer, job_type=JobType.TRAINING_ONLY
    )
    t = threading.Thread(target=worker.run, daemon=True)
    t.start()
    rc = master.run(poll_secs=0.2)
    t.join(timeout=30)
    assert rc == 0
    assert master.task_d.finished()
    summary = master.job_summary()
    assert summary["training"]["total_records"] == 64
    # SAVE_MODEL deferred callback exported the model
    from elasticdl_tpu.utils.export_utils import load_exported_model

    model, flat, _ = load_exported_model(str(tmp_path / "out"))
    assert flat


def test_concurrent_report_version_queues_each_milestone_once(tmp_path):
    """Every worker's report_version lands on the 64-thread gRPC pool
    concurrently; the milestone check-and-set is lock-guarded so each
    milestone is queued exactly once (the race fixed after round 1 —
    duplicate milestones double-count eval).

    Eval jobs are *serialized* — same as the reference, whose
    try_to_create_new_job only materializes tasks when no eval job is
    running and drains the version queue on completion
    (evaluation_service.py:221-243, 267-292).  So after the pings: one
    eval job's tasks pending (milestone 1), one version queued
    (milestone 2), and completing the first job creates the second's
    tasks — nothing dropped, nothing duplicated."""
    import threading

    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(train_dir, eval_dir, ["--evaluation_steps", "2"])
    master = Master(args)

    from elasticdl_tpu.rpc import messages as msg

    barrier = threading.Barrier(16)

    def ping(worker_id):
        barrier.wait()
        for version in (2, 3, 4):  # milestones 1, 1, 2
            master.servicer.report_version(
                msg.ReportVersionRequest(
                    model_version=version, worker_id=worker_id
                )
            )

    threads = [
        threading.Thread(target=ping, args=(i,)) for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert all(not t.is_alive() for t in threads)
    # 2 milestones crossed (versions 2 and 4) across 48 concurrent pings.
    # The 32-record eval set at records_per_task=32 is 1 task per job:
    # milestone 1's job is running, milestone 2 waits in the queue.
    eval_service = master.evaluation_service
    assert len(master.task_d._pending_eval) == 1
    assert eval_service._eval_checkpoint_versions == [4]
    assert eval_service._eval_job.model_version == 2

    # Drain the first eval job: its completion must materialize the
    # queued milestone's tasks (the serialized hand-off — reference
    # complete_task -> try_to_create_new_job).
    task_id, task = master.task_d.get_eval_task(worker_id=0)
    assert task is not None and task.model_version == 2
    master.task_d.report(task_id, success=True)
    assert eval_service._eval_checkpoint_versions == []
    assert eval_service._eval_job.model_version == 4
    assert len(master.task_d._pending_eval) == 1
    task_id, task = master.task_d.get_eval_task(worker_id=0)
    assert task is not None and task.model_version == 4
    master.task_d.report(task_id, success=True)
    assert eval_service._eval_job is None
    assert len(master.task_d._pending_eval) == 0


def test_summary_carries_evaluated_version_when_it_differs(tmp_path):
    """Deviation D5 pinned: workers evaluate with whatever state they hold
    (no checkpoint restore at the milestone), so the published summary
    must surface BOTH the milestone model_version and the step actually
    evaluated with when they differ."""
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(train_dir, eval_dir, ["--evaluation_steps", "2"])
    master = Master(args)

    from elasticdl_tpu.rpc import messages as msg

    # milestone crossing at version 4 queues one eval job
    master.servicer.report_version(
        msg.ReportVersionRequest(model_version=4, worker_id=0)
    )
    task_id, task = master.task_d.get_eval_task(worker_id=0)
    assert task is not None and task.model_version == 4

    # the worker's state has advanced to step 7 by the time it evaluates
    outputs = {
        "output": ndarray_to_tensor("output", np.eye(10, dtype=np.float32))
    }
    labels = ndarray_to_tensor("labels", np.arange(10))
    master.servicer.report_evaluation_metrics(
        msg.ReportEvaluationMetricsRequest(
            model_outputs=outputs,
            labels=labels,
            model_version=4,
            task_id=task_id,
            evaluated_version=7,
        )
    )
    master.task_d.report(task_id, success=True)

    summary = master.evaluation_service.latest_summary
    assert summary["model_version"] == 4
    assert summary["evaluated_version"] == 7
    assert summary["accuracy"] == 1.0
    # and the job-level summary the CLI prints carries the same dict
    assert master.job_summary()["evaluation_metrics"] is summary
