"""Shape-canonical batching + the compile-count regression gate.

Pins ISSUE 5's guarantees:

- masked padded steps are EXACT over the real rows (train, stacked
  train, eval) — and the old repeat-last-row padding demonstrably was
  not (the tail-gradient bias this replaces);
- the canonical grouping policy: ragged tails join the dispatch group
  as masked members (no flush on shape change), trailing partial groups
  reuse the single-step program, the program cache holds two entries;
- the process-wide compile counter: increments on the first dispatch,
  stays flat across subsequent tasks and tails, survives reform
  generations monotonically on the master mirror;
- ``trace analyze`` attributes measured ``compile`` spans to the
  ``warmup_compile`` reform phase.
"""

import json
import os

import flax.linen as nn
import jax
import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel.distributed import SPMDTrainer, trim_pad
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.telemetry import compile_tracker
from elasticdl_tpu.trainer import stacking
from elasticdl_tpu.trainer.stacking import (
    PreStacked,
    canonical_batch_rows,
    run_stacked_steps,
)


class _Dense(nn.Module):
    """Deterministic per-row model: no batch stats, no dropout — batch
    composition cannot leak between rows, so masked-pad parity is exact
    up to float reduction order."""

    @nn.compact
    def __call__(self, x, training=False):
        return nn.Dense(3)(x)


def _loss(labels, predictions):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels
    ).mean()


def _mesh():
    # ONE device: the parity reference runs genuinely unpadded batches,
    # which a multi-device data axis would reject as indivisible
    return MeshConfig.from_string("dp=1").create()


def _data(n=8, seed=0):
    rng = np.random.RandomState(seed)
    feats = rng.randn(n, 4).astype(np.float32)
    labels = rng.randint(0, 3, size=(n,)).astype(np.int32)
    return feats, labels


def _trainer(mesh, tx=None):
    feats, _ = _data()
    return SPMDTrainer(
        mesh,
        _Dense(),
        _loss,
        tx if tx is not None else optax.sgd(0.1, momentum=0.9),
        feats[:1],
        embedding_threshold=None,
    )


def _params(trainer):
    return jax.device_get(trainer.state.params)


def _assert_tree_allclose(a, b, atol=1e-6):
    for left, right in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_allclose(left, right, atol=atol)


def _tree_max_delta(a, b):
    return max(
        float(np.abs(np.asarray(x) - np.asarray(y)).max())
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    )


# ---- canonical shape policy -------------------------------------------------


def test_canonical_batch_rows_policy():
    assert canonical_batch_rows(64, 1) == 64
    assert canonical_batch_rows(64, 8) == 64
    assert canonical_batch_rows(65, 8) == 72  # round UP to the divisor
    assert canonical_batch_rows(3, 8) == 8  # never below one shard row
    assert canonical_batch_rows(1, 1) == 1


# ---- masked-step exactness (the tail-gradient bias, pinned) -----------------


class TestMaskedStepParity:
    def test_masked_train_step_matches_unpadded(self):
        mesh = _mesh()
        feats, labels = _data()
        n, rows = 5, 8
        ref = _trainer(mesh)
        masked = _trainer(mesh)

        ref_metrics = ref.train_step(
            ref.place_batch(feats[:n]), ref.place_batch(labels[:n])
        )
        padded_f = masked.pad_to(feats[:n], rows)
        padded_l = masked.pad_to(labels[:n], rows)
        masked_metrics = masked.train_step(
            masked.place_batch(padded_f),
            masked.place_batch(padded_l),
            masked.place_batch(masked.row_mask(n, rows)),
        )
        assert abs(
            float(ref_metrics["loss"]) - float(masked_metrics["loss"])
        ) < 1e-6
        _assert_tree_allclose(_params(ref), _params(masked))

    def test_repeat_row_padding_without_mask_is_biased(self):
        """The bug the mask fixes: an UNWEIGHTED step over the padded
        batch over-weights the repeated last row and diverges from the
        unpadded step — this must stay visibly broken so the mask's
        value is falsifiable."""
        mesh = _mesh()
        feats, labels = _data()
        n, rows = 5, 8
        ref = _trainer(mesh)
        biased = _trainer(mesh)

        ref.train_step(
            ref.place_batch(feats[:n]), ref.place_batch(labels[:n])
        )
        biased.train_step(
            biased.place_batch(biased.pad_to(feats[:n], rows)),
            biased.place_batch(biased.pad_to(labels[:n], rows)),
        )
        assert _tree_max_delta(_params(ref), _params(biased)) > 1e-5

    def test_masked_stacked_steps_match_sequential_unpadded(self):
        mesh = _mesh()
        feats, labels = _data()
        n_tail, rows = 5, 8
        ref = _trainer(mesh)
        masked = _trainer(mesh)

        # reference: a full batch then an unpadded ragged tail
        ref.train_step(ref.place_batch(feats), ref.place_batch(labels))
        ref.train_step(
            ref.place_batch(feats[:n_tail]),
            ref.place_batch(labels[:n_tail]),
        )

        # canonical: ONE stacked dispatch, tail as a masked member
        stacked_f = np.stack([feats, masked.pad_to(feats[:n_tail], rows)])
        stacked_l = np.stack([labels, masked.pad_to(labels[:n_tail], rows)])
        stacked_w = np.stack(
            [masked.row_mask(rows, rows), masked.row_mask(n_tail, rows)]
        )
        masked.train_steps_stacked(
            masked.place_stacked(stacked_f),
            masked.place_stacked(stacked_l),
            masked.place_stacked(stacked_w),
        )
        assert masked.step == ref.step == 2
        _assert_tree_allclose(_params(ref), _params(masked), atol=1e-5)

    def test_masked_eval_loss_matches_host_recompute(self):
        """Satellite: the masked in-step eval loss is exact over the
        real rows — the host-side recompute LocalExecutor used to do is
        redundant."""
        mesh = _mesh()
        feats, labels = _data()
        n, rows = 5, 8
        trainer = _trainer(mesh)
        outputs, in_step_loss = trainer.eval_step(
            trainer.place_batch(trainer.pad_to(feats[:n], rows)),
            trainer.place_batch(trainer.pad_to(labels[:n], rows)),
            trainer.place_batch(trainer.row_mask(n, rows)),
        )
        trimmed = trim_pad(jax.device_get(outputs), n)
        host_loss = float(np.asarray(_loss(labels[:n], trimmed)))
        assert abs(float(jax.device_get(in_step_loss)) - host_loss) < 1e-6


# ---- canonical grouping policy ----------------------------------------------


class _RecordingTrainer:
    """pad_to/row_mask/dispatch shim recording every dispatch's kind,
    label shape and weights."""

    def __init__(self):
        self.dispatches = []

    def pad_to(self, tree, rows):
        def _pad(x):
            x = np.asarray(x)
            if x.shape[0] == rows:
                return x
            return np.concatenate(
                [x, np.repeat(x[-1:], rows - x.shape[0], axis=0)]
            )

        return jax.tree_util.tree_map(_pad, tree)

    def row_mask(self, n, rows):
        mask = np.zeros(rows, np.float32)
        mask[:n] = 1.0
        return mask

    def place_batch(self, tree):
        return tree

    def place_stacked(self, tree):
        return tree

    def train_step(self, features, labels, weights=None):
        self.dispatches.append(
            ("single", np.shape(labels), np.array(weights))
        )

    def train_steps_stacked(self, features, labels, weights=None):
        self.dispatches.append(
            ("stacked", np.shape(labels), np.array(weights))
        )


def _plain_batches(sizes):
    return [
        (np.ones((n, 2), np.float32) * i, np.arange(n, dtype=np.int32))
        for i, n in enumerate(sizes)
    ]


class TestCanonicalGrouping:
    def test_tail_joins_group_as_masked_member(self):
        """A ragged tail no longer flushes the group: (4,4,3) at k=3 is
        ONE stacked dispatch whose last member is masked."""
        trainer = _RecordingTrainer()
        processed = run_stacked_steps(
            lambda: trainer,
            iter(_plain_batches([4, 4, 3])),
            3,
            canonical_rows=4,
        )
        assert processed == 11
        assert [d[0] for d in trainer.dispatches] == ["stacked"]
        kind, shape, weights = trainer.dispatches[0]
        assert shape == (3, 4)
        np.testing.assert_array_equal(
            weights,
            [[1, 1, 1, 1], [1, 1, 1, 1], [1, 1, 1, 0]],
        )

    def test_trailing_partial_group_dispatches_singles(self):
        """Fewer than k leftovers run through the already-compiled
        single-step program — never a new scan length."""
        trainer = _RecordingTrainer()
        processed = run_stacked_steps(
            lambda: trainer,
            iter(_plain_batches([4, 4, 3])),
            2,
            canonical_rows=4,
        )
        assert processed == 11
        assert [d[0] for d in trainer.dispatches] == ["stacked", "single"]
        assert trainer.dispatches[0][1] == (2, 4)
        assert trainer.dispatches[1][1] == (4,)
        np.testing.assert_array_equal(
            trainer.dispatches[1][2], [1, 1, 1, 0]
        )

    def test_prestacked_group_gets_all_ones_mask(self):
        trainer = _RecordingTrainer()
        feats = np.ones((2, 4, 2), np.float32)
        labels = np.zeros((2, 4), np.int32)
        item = PreStacked(feats, labels, 8, feats[0])
        processed = run_stacked_steps(
            lambda: trainer, iter([item]), 2, canonical_rows=4
        )
        assert processed == 8
        kind, shape, weights = trainer.dispatches[0]
        assert kind == "stacked" and shape == (2, 4)
        np.testing.assert_array_equal(weights, np.ones((2, 4)))

    def test_k1_is_a_group_of_one_masked_single(self):
        trainer = _RecordingTrainer()
        processed = run_stacked_steps(
            lambda: trainer,
            iter(_plain_batches([4, 3])),
            1,
            canonical_rows=4,
        )
        assert processed == 7
        assert [d[0] for d in trainer.dispatches] == ["single", "single"]
        np.testing.assert_array_equal(
            trainer.dispatches[1][2], [1, 1, 1, 0]
        )


# ---- compile counting -------------------------------------------------------


def _unique_jit_compile():
    """Force exactly one fresh backend compile (a shape this process
    has never jitted)."""
    _unique_jit_compile.dim += 1
    dim = 7000 + _unique_jit_compile.dim
    jax.jit(lambda x: x * 2 + 1)(np.ones(dim, np.float32))


_unique_jit_compile.dim = 0


class TestCompileTracking:
    def test_install_and_count(self):
        assert compile_tracker.install()
        before = compile_tracker.compile_count()
        _unique_jit_compile()
        assert compile_tracker.compile_count() == before + 1
        assert compile_tracker.compile_secs_total() > 0.0

    def test_compile_span_recorded(self, tmp_path):
        from elasticdl_tpu.telemetry import tracing

        assert compile_tracker.install()
        tracing.install(str(tmp_path), role="worker", sample_rate=1.0)
        try:
            _unique_jit_compile()
            tracing.flush()
        finally:
            tracing.uninstall()
        spans = tracing.read_spans(str(tmp_path / "spans.jsonl"))
        compile_spans = [
            s for s in spans if s.get("span") == tracing.SPAN_COMPILE
        ]
        assert compile_spans
        span = compile_spans[-1]
        assert span["end"] >= span["start"]

    def test_master_mirror_is_monotone_across_generation_resets(self):
        """Reset semantics: a re-formed world's processes start their
        per-process counters at zero, but the master's
        ``elasticdl_compile_total`` (set_total = monotone max, plus
        worker-reported exec-counter sums) never walks backward."""
        from elasticdl_tpu.telemetry.compile_tracker import (
            COMPILE_COUNT_KEY,
        )
        from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

        class _Dispatcher:
            exec_compiles = 0

            def add_observer(self, obs):
                pass

            def snapshot(self):
                return {
                    "pending": 0,
                    "pending_eval": 0,
                    "active": [],
                    "epoch": 0,
                }

            def exec_metrics_snapshot(self, _task_type):
                return {COMPILE_COUNT_KEY: self.exec_compiles}

        class _Servicer:
            cluster_version = 0

            def add_version_observer(self, cb):
                pass

            def set_event_sink(self, cb):
                pass

            def set_trace_provider(self, cb):
                pass

            def live_workers(self):
                return []

        telemetry = MasterTelemetry()
        dispatcher = _Dispatcher()
        telemetry.attach(dispatcher, _Servicer())

        def scraped_total():
            for line in telemetry.registry.exposition().splitlines():
                if line.startswith("elasticdl_compile_total "):
                    return float(line.split()[-1])
            raise AssertionError("elasticdl_compile_total not exposed")

        assert compile_tracker.install()
        _unique_jit_compile()
        dispatcher.exec_compiles = 5  # generation-0 worker reports
        gen0_total = scraped_total()
        assert gen0_total >= compile_tracker.compile_count() + 5

        # generation 1: fresh worker processes -> per-process counters
        # restart at zero (simulated via the test reset)...
        compile_tracker._reset_for_tests()
        assert compile_tracker.compile_count() == 0
        dispatcher.exec_compiles = 5
        # ...yet the exposed total never decreases
        assert scraped_total() >= gen0_total
        # and new generation compiles keep accumulating on top
        _unique_jit_compile()
        dispatcher.exec_compiles = 7
        assert scraped_total() >= gen0_total

    def test_stale_report_still_accumulates_compile_delta(self):
        """A report landing on a reclaimed/unknown lease is dropped for
        task accounting — but its compile delta is PROCESS-level, and
        the worker's watermark advances on RPC success, so the
        dispatcher must bank it anyway or the recompile disappears from
        the /metrics mirror forever."""
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.telemetry.compile_tracker import (
            COMPILE_COUNT_KEY,
        )
        from elasticdl_tpu.utils.constants import TaskType

        dispatcher = TaskDispatcher(None)
        dispatcher.report(999, True, exec_counters={COMPILE_COUNT_KEY: 3})
        snapshot = dispatcher.exec_metrics_snapshot(TaskType.TRAINING)
        assert snapshot.get(COMPILE_COUNT_KEY) == 3
        # non-compile counters of a stale report stay dropped
        dispatcher.report(998, True, exec_counters={"time_foo_ms": 7})
        snapshot = dispatcher.exec_metrics_snapshot(TaskType.TRAINING)
        assert "time_foo_ms" not in snapshot

    def test_exec_counter_reporter_reships_delta_after_failed_report(self):
        """ExecCounterReporter advances its watermark only on commit():
        an attach whose report RPC failed re-ships the same delta."""
        assert compile_tracker.install()
        reporter = compile_tracker.ExecCounterReporter()
        _unique_jit_compile()
        first: dict = {}
        mark = reporter.attach(first)
        assert first.get(compile_tracker.COMPILE_COUNT_KEY, 0) >= 1
        # RPC failed -> no commit -> the delta stays pending
        second: dict = {}
        reporter.attach(second)
        assert second == first
        reporter.commit(mark)
        third: dict = {}
        reporter.attach(third)
        assert compile_tracker.COMPILE_COUNT_KEY not in third

    def test_compile_metric_visible_without_dispatcher(self):
        from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

        telemetry = MasterTelemetry()
        text = telemetry.registry.exposition()
        assert "# TYPE elasticdl_compile_total counter" in text


# ---- the compile-once guarantee, end to end ---------------------------------


def _ragged_local_args(tmp_path, steps_per_dispatch="1"):
    """3 tasks (9, 9, 6 records at minibatch 4) -> batch streams
    (4,4,1), (4,4,1), (4,2): two distinct tail lengths."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.utils.args import parse_master_args

    train = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=24, num_shards=1, seed=3
    )
    return parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "4",
            "--records_per_task",
            "9",
            "--num_epochs",
            "1",
            "--steps_per_dispatch",
            steps_per_dispatch,
            "--compute_dtype",
            "float32",
        ]
    )


def test_local_executor_ragged_tails_compile_once(tmp_path, monkeypatch):
    """Acceptance: >= 3 tasks with >= 2 distinct tail lengths execute
    with exactly ONE train-step compile — the counter increments on the
    first dispatch and stays flat across subsequent tasks and tails."""
    from elasticdl_tpu.trainer.local_executor import LocalExecutor

    assert compile_tracker.install()
    args = _ragged_local_args(tmp_path, steps_per_dispatch="1")
    executor = LocalExecutor(args)

    dispatch_compiles = []
    orig = SPMDTrainer.train_step

    def wrapped(self, *a, **kw):
        before = compile_tracker.compile_count()
        result = orig(self, *a, **kw)
        dispatch_compiles.append(compile_tracker.compile_count() - before)
        return result

    monkeypatch.setattr(SPMDTrainer, "train_step", wrapped)
    executor.run()
    assert int(executor.state.step) == 8  # ceil(9/4)*2 + ceil(6/4)
    assert len(dispatch_compiles) == 8
    assert dispatch_compiles[0] > 0  # first dispatch compiles the step
    # ...and every later dispatch (other tasks, BOTH tail lengths)
    # reuses it: zero mid-task recompiles
    assert dispatch_compiles[1:] == [0] * 7, dispatch_compiles


# ---- trace analyze: measured compile spans ----------------------------------


def test_analyze_attributes_measured_compile_span(tmp_path):
    from elasticdl_tpu.telemetry import trace as trace_cli
    from elasticdl_tpu.telemetry.tracing import SPAN_COMPILE, gen_span_id, gen_trace_id

    run = str(tmp_path / "run")
    os.makedirs(run)
    t0 = 1000.0
    events = []
    for generation, base in ((0, t0), (1, t0 + 14.0)):
        for i in range(2):
            events.append(
                {
                    "monotonic": base + i,
                    "time": 1.7e9 + base + i,
                    "event": "step",
                    "step": i,
                    "generation": generation,
                    "worker_id": 0,
                    "records": 8,
                    **({"duration_secs": 1.0} if i else {}),
                }
            )
    # gap: 10s (last gen-0 step at t0+1 -> first gen-1 step at t0+14);
    # a measured 4s compile sits inside it
    spans = [
        {
            "span": SPAN_COMPILE,
            "trace_id": gen_trace_id(),
            "span_id": gen_span_id(),
            "parent_span_id": "",
            "role": "worker",
            "worker_id": 0,
            "generation": 1,
            "start": t0 + 8.0,
            "end": t0 + 12.0,
        }
    ]
    for name, records in (("events.jsonl", events), ("spans.jsonl", spans)):
        with open(os.path.join(run, name), "w", encoding="utf-8") as f:
            for record in records:
                f.write(json.dumps(record) + "\n")

    report = trace_cli.analyze_run_dir(run)
    analysis = next(iter(report["runs"].values()))
    gap = analysis["reform_downtime"][0]
    phases = gap["phases_secs"]
    # the compile span (4s) plus the bridge to the first step (2s) are
    # measured warmup_compile; the 7s before the span are unattributed
    assert abs(phases["warmup_compile"] - 6.0) < 1e-6, phases
    assert abs(phases["unattributed"] - 7.0) < 1e-6, phases
    assert abs(sum(phases.values()) - gap["downtime_secs"]) < 1e-6


# ---- dispatch-probe warm ----------------------------------------------------


def test_warm_dispatch_overhead_async(monkeypatch):
    monkeypatch.setattr(stacking, "_DISPATCH_OVERHEAD", [None])
    calls = []

    def fake_probe(trials=3):
        calls.append(trials)
        return 0.001

    monkeypatch.setattr(stacking, "probe_dispatch_overhead", fake_probe)
    thread = stacking.warm_dispatch_overhead_async()
    assert thread is not None
    thread.join(timeout=5)
    assert stacking._DISPATCH_OVERHEAD[0] == 0.001
    # cache hot -> the real consumer pays nothing and no second probe
    assert stacking.measured_dispatch_overhead() == 0.001
    assert calls == [3]
    # warm again: no-op once measured
    assert stacking.warm_dispatch_overhead_async() is None


def test_eval_reported_loss_matches_host_recompute_end_to_end(tmp_path):
    """Satellite: LocalExecutor's reported eval loss (now the masked
    in-step loss) equals the deleted host-side recompute."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    train = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=16, num_shards=1, seed=5
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "eval"), num_records=10, num_shards=1, seed=6
    )
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--validation_data",
            eval_dir,
            "--minibatch_size",
            "4",
            "--records_per_task",
            "16",
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
        ]
    )
    executor = LocalExecutor(args)
    executor.run()
    # recompute the eval loss host-side over the REAL rows, the way the
    # deleted code did, and compare to the reported (in-step) loss
    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.trainer.state import Modes

    spec = executor._spec
    reader = create_data_reader(
        args.validation_data, records_per_task=args.records_per_task
    )
    dispatcher = TaskDispatcher(
        None,
        evaluation_shards=reader.create_shards(),
        records_per_task=args.records_per_task,
    )
    total, weight = 0.0, 0
    while True:
        tid, task = dispatcher.get_eval_task(0)
        if task is None:
            break
        for features, labels in executor._task_dataset(
            reader, task, Modes.EVALUATION
        ):
            n = int(np.shape(np.asarray(labels))[0])
            outputs = executor.trainer.predict_step(
                executor._place_canonical(features)
            )
            outputs = trim_pad(jax.device_get(outputs), n)
            total += float(np.asarray(spec.loss(labels, outputs))) * n
            weight += n
        dispatcher.report(tid, True)
    host_loss = total / weight
    reported = executor.evaluate()["loss"]
    assert reported == pytest.approx(host_loss, rel=1e-6)
