"""Mesh / sharding / SPMD trainer tests on the virtual 8-device CPU mesh
(SURVEY §4: collapse the pod slice, keep the sharding real)."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import mnist_functional_api as mnist
from elasticdl_tpu.parallel.distributed import SPMDTrainer
from elasticdl_tpu.parallel.mesh import MeshConfig, batch_divisor, parse_mesh_shape
from elasticdl_tpu.parallel.sharding import (
    Rule,
    infer_param_specs,
    batch_sharding,
)


class _FakeDev:
    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}s{self.slice_index}"


class TestMultiSlice:
    """Hybrid (multi-slice) mesh planning: the slice dimension lands on
    dp (DCN-tolerant gradient all-reduce) and everything else stays
    intra-slice on ICI."""

    def test_detect_and_default_plan(self):
        from elasticdl_tpu.parallel.mesh import (
            detect_num_slices,
            plan_dcn_axes,
        )

        devs = [_FakeDev(i, i // 4) for i in range(8)]
        assert detect_num_slices(devs) == 2
        sizes = {"dp": 4, "fsdp": 1, "tp": 2, "sp": 1, "ep": 1, "pp": 1}
        assert plan_dcn_axes(sizes, 2, None) == {"dp": 2}

    def test_plan_rejects_bad_shapes(self):
        from elasticdl_tpu.parallel.mesh import plan_dcn_axes

        sizes = {"dp": 3, "fsdp": 1, "tp": 1, "sp": 1, "ep": 1, "pp": 1}
        with pytest.raises(ValueError):
            plan_dcn_axes(sizes, 2, None)  # dp=3 not divisible by 2 slices
        with pytest.raises(ValueError):
            plan_dcn_axes(sizes, 2, {"dp": 3})  # product != slices

    def test_explicit_dcn_axes(self):
        from elasticdl_tpu.parallel.mesh import plan_dcn_axes

        sizes = {"dp": 2, "fsdp": 4, "tp": 1, "sp": 1, "ep": 1, "pp": 1}
        assert plan_dcn_axes(sizes, 4, {"fsdp": 4}) == {"fsdp": 4}

    def test_fallback_ordering_keeps_ici_axes_intra_slice(self):
        from elasticdl_tpu.parallel.mesh import order_devices_hybrid

        devs = [_FakeDev(i, i // 4) for i in range(8)]
        sizes = {"dp": 4, "fsdp": 1, "tp": 2, "sp": 1, "ep": 1, "pp": 1}
        arr = order_devices_hybrid(devs, sizes, {"dp": 2})
        assert arr.shape == (4, 1, 2, 1, 1, 1)
        # tp neighbors (last varying axis) never cross a slice
        for i in range(4):
            pair = arr[i, 0, :, 0, 0, 0]
            assert pair[0].slice_index == pair[1].slice_index
        # the dp axis crosses slices exactly at its halfway stride
        dp_slices = [arr[i, 0, 0, 0, 0, 0].slice_index for i in range(4)]
        assert dp_slices == [0, 0, 1, 1]

    def test_single_slice_create_unchanged(self):
        mesh = MeshConfig.from_string("dp=4,tp=2").create()
        assert dict(mesh.shape) == {
            "dp": 4, "fsdp": 1, "tp": 2, "sp": 1, "ep": 1, "pp": 1
        }


class TestMeshConfig:
    def test_parse(self):
        assert parse_mesh_shape("dp=4,tp=2") == {"dp": 4, "tp": 2}
        assert parse_mesh_shape("") == {}
        with pytest.raises(ValueError):
            parse_mesh_shape("zz=2")
        with pytest.raises(ValueError):
            parse_mesh_shape("dp=0")

    def test_default_all_dp(self):
        mesh = MeshConfig.from_string("").create()
        assert mesh.shape["dp"] == 8
        assert mesh.shape["tp"] == 1

    def test_mixed_axes(self):
        mesh = MeshConfig.from_string("dp=2,tp=2,sp=2").create()
        assert mesh.shape["dp"] == 2
        assert mesh.shape["tp"] == 2
        assert mesh.shape["sp"] == 2
        assert batch_divisor(mesh) == 2

    def test_dp_inferred_from_remaining(self):
        mesh = MeshConfig.from_string("tp=2").create()
        assert mesh.shape["dp"] == 4

    def test_bad_product_raises(self):
        with pytest.raises(ValueError):
            MeshConfig.from_string("dp=16").create()  # more than 8 devices
        with pytest.raises(ValueError):
            MeshConfig.from_string("tp=3").create()  # 8 % 3 != 0

    def test_explicit_subset_mesh(self):
        mesh = MeshConfig.from_string("dp=3").create()
        assert mesh.shape["dp"] == 3 and len(mesh.devices.flatten()) == 3


class TestShardingRules:
    def _mesh(self, shape):
        return MeshConfig.from_string(shape).create()

    def test_rules_first_match_wins(self):
        mesh = self._mesh("dp=4,tp=2")
        params = {
            "attention": {"query": {"kernel": np.zeros((16, 8))}},
            "mlp": {"down": {"kernel": np.zeros((8, 16))}},
            "bias": np.zeros((7,)),
        }
        from elasticdl_tpu.parallel.sharding import default_tp_rules

        specs = infer_param_specs(params, mesh, default_tp_rules())
        assert specs["attention"]["query"]["kernel"] == P(None, "tp")
        assert specs["mlp"]["down"]["kernel"] == P("tp", None)
        assert specs["bias"] == P()  # 7 not divisible, no rule

    def test_rule_that_does_not_fit_falls_back(self):
        mesh = self._mesh("dp=4,tp=2")
        specs = infer_param_specs(
            {"q": {"kernel": np.zeros((16, 7))}},  # 7 % 2 != 0
            mesh,
            [Rule(r"q/kernel$", P(None, "tp"))],
        )
        assert specs["q"]["kernel"] == P()

    def test_fsdp_auto_sharding(self):
        mesh = self._mesh("fsdp=8")
        specs = infer_param_specs(
            {"w": np.zeros((24, 33)), "tiny": np.zeros((3,))}, mesh
        )
        assert specs["w"] == P("fsdp", None)
        assert specs["tiny"] == P()

    def test_batch_sharding_spans_dp_and_fsdp(self):
        mesh = self._mesh("dp=2,fsdp=4")
        sh = batch_sharding(mesh, ndim=2)
        assert sh.spec == P(("dp", "fsdp"), None)
        assert batch_divisor(mesh) == 8


def _make_batch(n=64):
    rng = np.random.RandomState(0)
    feats = {"image": rng.rand(n, 28, 28).astype(np.float32)}
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    return feats, labels


class TestSPMDTrainer:
    def _trainer(self, mesh_shape, **kw):
        mesh = MeshConfig.from_string(mesh_shape).create()
        feats, _ = _make_batch(8)
        return SPMDTrainer(
            mesh,
            mnist.custom_model(),
            mnist.loss,
            optax.sgd(0.01),
            feats,
            **kw,
        )

    def test_dp_step_runs_and_updates(self):
        tr = self._trainer("dp=8")
        feats, labels = _make_batch(64)
        losses = [
            float(
                tr.train_step(
                    tr.place_batch(feats), tr.place_batch(labels)
                )["loss"]
            )
            for _ in range(24)
        ]
        assert tr.step == 24
        # memorizing one fixed batch: loss must drop substantially (noisy
        # early steps allowed — dropout is live in training mode)
        assert min(losses[-4:]) < losses[0] * 0.5, losses

    def test_dp_matches_single_device_training(self):
        """DP over 8 devices must produce the same math as one device
        (the reference's quality bar 'PS-trained ≈ local-trained',
        worker_ps_interaction_test.py)."""
        feats, labels = _make_batch(64)
        tr8 = self._trainer("dp=8")
        losses8 = [
            float(
                tr8.train_step(
                    tr8.place_batch(feats), tr8.place_batch(labels)
                )["loss"]
            )
            for _ in range(3)
        ]
        tr1 = self._trainer("dp=1")
        losses1 = [
            float(
                tr1.train_step(
                    tr1.place_batch(feats), tr1.place_batch(labels)
                )["loss"]
            )
            for _ in range(3)
        ]
        np.testing.assert_allclose(losses8, losses1, rtol=2e-4)

    def test_fsdp_state_is_sharded(self):
        tr = self._trainer("fsdp=8")
        # at least one parameter leaf must actually be sharded over fsdp
        sharded = [
            leaf.sharding.spec
            for leaf in jax.tree_util.tree_leaves(tr.state.params)
            if any(s is not None for s in leaf.sharding.spec)
        ]
        assert sharded, "no parameter was fsdp-sharded"
        feats, labels = _make_batch(32)
        m = tr.train_step(tr.place_batch(feats), tr.place_batch(labels))
        assert np.isfinite(float(m["loss"]))

    def test_fsdp_matches_dp_training(self):
        feats, labels = _make_batch(64)
        tr_dp = self._trainer("dp=8")
        tr_fsdp = self._trainer("fsdp=8")
        for _ in range(2):
            ld = tr_dp.train_step(
                tr_dp.place_batch(feats), tr_dp.place_batch(labels)
            )
            lf = tr_fsdp.train_step(
                tr_fsdp.place_batch(feats), tr_fsdp.place_batch(labels)
            )
        np.testing.assert_allclose(
            float(ld["loss"]), float(lf["loss"]), rtol=2e-4
        )

    def test_eval_and_predict_steps(self):
        tr = self._trainer("dp=8")
        feats, labels = _make_batch(16)
        outputs, loss = tr.eval_step(
            tr.place_batch(feats), tr.place_batch(labels)
        )
        assert np.asarray(outputs).shape == (16, 10)
        assert np.isfinite(float(loss))
        preds = tr.predict_step(tr.place_batch(feats))
        assert np.asarray(preds).shape == (16, 10)

    def test_pad_batch(self):
        tr = self._trainer("dp=8")
        feats, labels = _make_batch(13)
        (pf, pl), div = tr.pad_batch((feats, labels))
        assert div == 8
        assert pl.shape[0] == 16
        assert pf["image"].shape[0] == 16
