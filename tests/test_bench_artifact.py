"""The bench artifact contract (VERDICT r4 weak #1 / next #1, #5).

The driver records only a ~2000-char tail of bench.py's stdout, so the
LAST line must be a compact JSON summary that carries EVERY config's
headline numbers and gate verdicts in <= 1500 bytes, pointing at
``BENCH_full.json`` for detail — and the degraded-window retry must
derive its "typical" rates from measurements (committed history +
in-run budget roofline), never from hard-coded per-config constants.
"""

import importlib.util
import json
import os
import sys

import pytest

_BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    # import only — main() is never called, so no jax/device work happens
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    return mod


def _fully_populated_models():
    """Every config the bench can emit, every optional field present —
    the worst case for compact-line size."""
    step = {
        "samples_per_sec_per_chip": 142857.3,
        "samples_per_sec_per_chip_median": 139000.1,
        "spread_pct": 31.4,
        "batch": 2048,
        "mfu": 0.2712,
        "model_tflops_per_sec_per_chip": 53.42,
        "vs_baseline": 1234.56,
        "link_degraded_retry": True,
        "first_attempt_samples_per_sec": 9200.0,
    }
    tokens = dict(
        step, tokens_per_sec_per_chip=137000, vs_baseline=None
    )
    anatomy_overall = {
        "dispatches": 32,
        "e2e_vs_roofline": 0.912,
        "binding": "device_path",
        "phases": {"device_compute": {"p50_ms": 210.0, "p99_ms": 260.0}},
        "boundary_stall": {
            "boundaries": 3,
            "stall_ms": 412,
            "share_of_wall": 0.0312,
        },
    }
    e2e = {
        "e2e_samples_per_sec_per_chip": 234517.3,
        "batch": 4096,
        "records_measured": 1835008,
        "tasks_measured": 7,
        "vs_step_only": 0.211,
        "link_degraded": True,
        "retry_samples_per_sec": 9000.0,
        # the instrumented anatomy windows: device prefetch on AND off
        "anatomy": {
            "prefetch_on": dict(anatomy_overall),
            "prefetch_off": dict(anatomy_overall, e2e_vs_roofline=0.695),
        },
        "budget": {
            "host_pipeline_records_per_sec": 1650000,
            "device_path_records_per_sec": 282000,
            "binding": "device_path",
            "e2e_vs_roofline": 0.831,
            "probe_dispatch_secs_e2e_start": 0.2468,
            "probe_dispatch_secs_before": 0.2471,
            "probe_dispatch_secs_after": 0.2513,
        },
    }
    return {
        "mnist": dict(step),
        "resnet50_cifar10": dict(step),
        "deepfm": dict(step),
        "imagenet_resnet50": dict(step),
        "transformer_seq8192": dict(tokens),
        "transformer_gpt2s_seq2048": dict(tokens),
        "mnist_e2e": dict(e2e),
        "deepfm_e2e": dict(e2e),
        "runtime_ratios": {
            "local_records_per_sec": 131072,
            "taskstream_records_per_sec": 120000,
            "taskstream_vs_local": 0.915,
            "lockstep_records_per_sec": 65000,
            "lockstep_e2e_vs_local": 0.496,
            "world_size": 2,
            "records": 131072,
            "batch": 512,
            "host_cores": 1,
        },
        "accuracy": {
            "mnist": {"accuracy": 0.9712, "steps": 937, "pass": True,
                      "threshold": 0.8},
            "census": {"accuracy": 0.818, "steps": 256, "pass": True,
                       "threshold": 0.8},
            "deepfm_frappe": {"accuracy": 0.9301, "steps": 256,
                              "pass": True, "threshold": 0.8},
        },
        "elastic_reform": {
            "reform_latency_secs": 0.38,
            "records_ok": True,
            "standby_activated": 2,
        },
        "accuracy_under_preemption": {
            "accuracy": 1.0,
            "records_ok": True,
            "pass": True,
            "reform_latency_secs": 0.38,
        },
    }


def test_compact_line_fits_the_driver_tail(bench):
    models = _fully_populated_models()
    compact = bench._compact_models(models)
    line = json.dumps(
        {
            "metric": "resnet50_cifar10_train_samples_per_sec_per_chip",
            "value": 142857.3,
            "unit": "samples/sec/chip",
            "vs_baseline": 1234.56,
            "device": "TPU v5 lite",
            "detail": "BENCH_full.json",
            "models": compact,
        },
        separators=(",", ":"),
    )
    # 1500 leaves ~500 chars of slack inside the driver's 2000-char tail
    # for stray stderr/warning lines sharing the capture
    assert len(line) <= 1500, f"{len(line)} bytes: {line}"
    # every config survives compaction with its headline number
    for name in models:
        assert name in compact
    assert compact["resnet50_cifar10"]["r"] == 142900  # 4 sig digits
    assert compact["resnet50_cifar10"]["mfu"] == 0.271
    assert compact["resnet50_cifar10"]["deg"] == 1
    assert compact["mnist_e2e"]["roof"] == 0.831
    assert compact["mnist_e2e"]["vs"] == 0.211
    assert compact["mnist_e2e"]["bind"] == "d"
    # measured anatomy ratios: prefetch ON is roofm, OFF is roofm0
    assert compact["mnist_e2e"]["roofm"] == 0.912
    assert compact["mnist_e2e"]["roofm0"] == 0.695
    # the between-task idle share rides in both windows' compact keys
    assert compact["mnist_e2e"]["bst"] == 0.0312
    assert compact["mnist_e2e"]["bst0"] == 0.0312
    assert compact["transformer_seq8192"]["tok"] == 137000
    assert compact["accuracy"]["mnist"] == [0.9712, 1]
    assert compact["elastic_reform"]["ok"] == 1
    assert compact["accuracy_under_preemption"]["ok"] == 1
    assert compact["runtime_ratios"] == {
        "ts_vs_local": 0.915,
        "lockstep_vs_local": 0.496,
    }


def test_compact_marks_failed_configs(bench):
    compact = bench._compact_models(
        {"mnist": {"error": "tunnel reset mid-compile " * 8}}
    )
    assert compact["mnist"] == {"err": 1}
    # a failed accuracy SUB-config stays visible too (silent truncation
    # of gate failures is the r4 artifact bug class)
    compact = bench._compact_models(
        {
            "accuracy": {
                "mnist": {"error": "boom"},
                "census": {"accuracy": 0.81, "pass": True,
                           "threshold": 0.8},
            }
        }
    )
    assert compact["accuracy"]["mnist"] == {"err": 1}
    assert compact["accuracy"]["census"] == [0.81, 1]


def test_every_compact_key_is_in_the_legend(bench):
    compact = bench._compact_models(_fully_populated_models())
    for name, entry in compact.items():
        if name == "accuracy":
            continue  # values are [acc, pass] pairs keyed by config
        for key in entry:
            assert (
                key in bench.COMPACT_KEY_LEGEND
                or key == "lockstep_vs_local"
            ), f"{name}.{key} missing from COMPACT_KEY_LEGEND"


def test_typical_rates_derive_from_committed_history(bench, tmp_path):
    hist = tmp_path / "BENCH_full.json"
    hist.write_text(
        json.dumps(
            {
                "device": "TPU v5 lite",
                "models": {
                    "mnist": {"samples_per_sec_per_chip": 60000.0},
                    "mnist_e2e": {
                        "e2e_samples_per_sec_per_chip": 30000.0
                    },
                    "accuracy": {"mnist": {"accuracy": 0.97}},
                    "broken": {"error": "x"},
                },
            }
        )
    )
    out = bench._typical_rates("TPU v5 lite", str(hist))
    assert out == {"mnist": 60000.0, "mnist_e2e": 30000.0}
    # a degraded-window measurement must never become "typical": it
    # would gate the retry at the degraded level forever
    hist.write_text(
        json.dumps(
            {
                "device": "TPU v5 lite",
                "models": {
                    "mnist": {
                        "samples_per_sec_per_chip": 9200.0,
                        "link_degraded": True,
                    },
                    "deepfm": {
                        "samples_per_sec_per_chip": 1e6,
                        "link_degraded_retry": True,
                    },
                },
            }
        )
    )
    assert bench._typical_rates("TPU v5 lite", str(hist)) == {}
    # history from different hardware must NOT gate this run's retries
    assert bench._typical_rates("TPU v4", str(hist)) == {}
    # no history at all: no retries, not a crash
    assert bench._typical_rates("TPU v5 lite", str(tmp_path / "nope")) == {}


def test_e2e_typical_prefers_in_run_roofline(bench):
    result = {
        "e2e_samples_per_sec_per_chip": 10000.0,
        "budget": {
            "host_pipeline_records_per_sec": 1650000,
            "device_path_records_per_sec": 282000,
        },
    }
    # roofline (282k) beats a stale lower history
    assert bench._e2e_typical(result, 30000.0) == 282000
    # history wins when the whole run's link is degraded (low floors)
    degraded = {
        "budget": {
            "host_pipeline_records_per_sec": 20000,
            "device_path_records_per_sec": 15000,
        }
    }
    assert bench._e2e_typical(degraded, 300000.0) == 300000.0
    # no budget and no history: no typical, no retry
    assert bench._e2e_typical({}, None) is None


def test_device_preflight_detects_hang_and_failure(bench, monkeypatch):
    """A hung TPU tunnel must fail the bench FAST with a structured
    ``device_unreachable`` payload (stamped into BENCH_full.json by
    main()), not hang the driver's whole bench window (observed: a
    multi-hour outage where jax.devices() blocked indefinitely) — and
    BENCH_r05-style transient failures get a bounded retry first."""
    import sys as _sys

    # ambient kill-switches/overrides on the dev box must not leak in
    monkeypatch.delenv("EDL_BENCH_PREFLIGHT_SECS", raising=False)
    monkeypatch.delenv("EDL_BENCH_PREFLIGHT_ATTEMPTS", raising=False)
    # healthy device: no error
    ok = bench._device_preflight(
        timeout_secs=30, probe_argv=[_sys.executable, "-c", "print('v5')"]
    )
    assert ok is None
    # hang: subprocess exceeds the timeout -> structured payload
    err = bench._device_preflight(
        timeout_secs=0.5,
        probe_argv=[_sys.executable, "-c", "import time; time.sleep(30)"],
        attempts=1,
    )
    assert "did not answer" in err["reason"]
    assert err["timeout_secs"] == 0.5 and err["attempts"] == 1
    # hard failure: nonzero exit propagates the stderr tail
    err = bench._device_preflight(
        timeout_secs=30,
        probe_argv=[
            _sys.executable,
            "-c",
            "import sys; sys.stderr.write('tunnel exploded'); sys.exit(3)",
        ],
        attempts=1,
    )
    assert "tunnel exploded" in err["reason"]
    # env kill-switch
    monkeypatch.setenv("EDL_BENCH_PREFLIGHT_SECS", "0")
    assert bench._device_preflight(probe_argv=["/bin/false"]) is None
    # a malformed override must not crash the bench before its artifact
    monkeypatch.setenv("EDL_BENCH_PREFLIGHT_SECS", "off")
    assert (
        bench._device_preflight(
            timeout_secs=30,
            probe_argv=[_sys.executable, "-c", "print('v5')"],
        )
        is None
    )


def test_device_preflight_retries_transient_failures(
    bench, monkeypatch, tmp_path
):
    """A flapping tunnel that answers on the second try must not cost
    the run (BENCH_r05 died on one transient init timeout)."""
    import sys as _sys

    monkeypatch.delenv("EDL_BENCH_PREFLIGHT_SECS", raising=False)
    monkeypatch.delenv("EDL_BENCH_PREFLIGHT_ATTEMPTS", raising=False)
    flag = tmp_path / "second_try"
    probe = (
        "import os, sys\n"
        f"p = {str(flag)!r}\n"
        "if os.path.exists(p):\n"
        "    print('v5')\n"
        "else:\n"
        "    open(p, 'w').close()\n"
        "    sys.stderr.write('first try down')\n"
        "    sys.exit(3)\n"
    )
    assert (
        bench._device_preflight(
            timeout_secs=30,
            probe_argv=[_sys.executable, "-c", probe],
            attempts=2,
            backoff_secs=0.01,
        )
        is None
    )
    # the env can widen the budget without code changes
    flag.unlink()
    monkeypatch.setenv("EDL_BENCH_PREFLIGHT_ATTEMPTS", "2")
    assert (
        bench._device_preflight(
            timeout_secs=30,
            probe_argv=[_sys.executable, "-c", probe],
            attempts=1,
            backoff_secs=0.01,
        )
        is None
    )


def test_no_hardcoded_per_config_rate_tables(bench):
    """The r4 TYPICAL_RATE / TYPICAL_E2E_RATE constants must stay gone
    (VERDICT r4 #5): 'typical' comes from _typical_rates/_e2e_typical."""
    assert not hasattr(bench, "TYPICAL_RATE")
    assert not hasattr(bench, "TYPICAL_E2E_RATE")
    src = open(_BENCH_PATH).read()
    assert "TYPICAL_RATE" not in src
    assert "TYPICAL_E2E_RATE" not in src
