"""Pipeline parallelism (GPipe schedule over pp): forward and gradient
equivalence against the sequential stage composition, on the virtual
8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.pipeline import pipeline_apply
from elasticdl_tpu.parallel.mesh import MeshConfig

STAGES = 4
DIM = 8


def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _stacked_params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(
            rng.randn(STAGES, DIM, DIM) / np.sqrt(DIM), jnp.float32
        ),
        "b": jnp.asarray(rng.randn(STAGES, DIM) * 0.1, jnp.float32),
    }


def _sequential(params, x):
    for s in range(STAGES):
        x = _stage_fn(
            jax.tree_util.tree_map(lambda p: p[s], params), x
        )
    return x


@pytest.mark.parametrize("mesh_shape", ["pp=4", "dp=2,pp=4"])
@pytest.mark.parametrize("num_microbatches", [2, 4])
def test_pipeline_forward_matches_sequential(mesh_shape, num_microbatches):
    mesh = MeshConfig.from_string(mesh_shape).create()
    params = _stacked_params()
    x = jnp.asarray(
        np.random.RandomState(1).randn(8, DIM), jnp.float32
    )
    out = pipeline_apply(
        _stage_fn, params, x, mesh, num_microbatches=num_microbatches
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), atol=1e-5
    )


def test_pipeline_gradients_match_sequential():
    """AD through the ppermute schedule IS the backward pipeline; its
    gradients must equal differentiating the plain composition."""
    mesh = MeshConfig.from_string("pp=4").create()
    params = _stacked_params()
    x = jnp.asarray(
        np.random.RandomState(2).randn(8, DIM), jnp.float32
    )

    def loss_pipe(p):
        return (
            pipeline_apply(_stage_fn, p, x, mesh, num_microbatches=4) ** 2
        ).sum()

    def loss_seq(p):
        return (_sequential(p, x) ** 2).sum()

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(g_pipe[key]),
            np.asarray(g_seq[key]),
            atol=1e-4,
            rtol=1e-4,
        )


def test_pipeline_degenerate_single_stage_mesh():
    mesh = MeshConfig.from_string("dp=8").create()  # pp = 1
    params = _stacked_params()
    x = jnp.asarray(
        np.random.RandomState(3).randn(8, DIM), jnp.float32
    )
    out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), atol=1e-5
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = MeshConfig.from_string("pp=4").create()
    x = jnp.zeros((6, DIM), jnp.float32)
    with pytest.raises(ValueError):
        pipeline_apply(
            _stage_fn, _stacked_params(), x, mesh, num_microbatches=4
        )
