"""SLO watchdog tests (ISSUE 17).

Covers the burn-rate detector math (window edges, hysteresis no-flap,
auto-baseline), the merge-discipline property (reordered / duplicated /
batched-replayed heartbeats converge to identical SLO state through
utils/merge.py), the shared percentile tracker pin (autoscaler decision
stream byte-identical to the historical private window), the off-path
contracts (argv byte-identity, clock-poison on the disabled accessor),
incident grouping + cause classification + artifact round-trip, the
report CLI's incidents/summary surfaces, and the fleetsim
``mute_slo`` falsification gate.
"""

from __future__ import annotations

import itertools
import json
import logging
import os

import pytest

from elasticdl_tpu.telemetry import slo as slo_mod
from elasticdl_tpu.telemetry.incident import (
    CAUSE_COMPUTE_BOUND,
    CAUSE_CONTROL_PLANE,
    CAUSE_INPUT_BOUND,
    CAUSE_MEMORY_PRESSURE,
    CAUSE_NETWORK_DEGRADED,
    IncidentManager,
    classify_cause,
    read_incidents,
)
from elasticdl_tpu.telemetry.slo import (
    SIGNAL_E2E_VS_ROOFLINE,
    SIGNAL_LAST_STEP_AGE_SECS,
    SIGNAL_MEMORY_HEADROOM_SHARE,
    SIGNAL_RPC_OUTAGE_RISE,
    SIGNAL_STEP_TIME_P95_MS,
    SLOEngine,
    StepTimePercentileTracker,
    _ObjectiveState,
    parse_slo_config,
    signals_from_phase_totals,
)
from elasticdl_tpu.utils.merge import (
    max_merge_counters,
    max_merge_phase_stats,
)


class FakeClock:
    def __init__(self, start: float = 1000.0):
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, secs: float) -> float:
        self.t += secs
        return self.t


def _objective(
    threshold=100.0,
    comparator="above",
    fast_secs=30.0,
    slow_secs=300.0,
    min_evals=3,
    **overrides,
) -> _ObjectiveState:
    spec = {
        "name": "t",
        "signal": "s",
        "comparator": comparator,
        "threshold": threshold,
        "windows": {
            "fast_secs": fast_secs,
            "slow_secs": slow_secs,
            "min_evals": min_evals,
        },
        "hysteresis": dict(slo_mod.DEFAULT_HYSTERESIS),
    }
    spec.update(overrides)
    return _ObjectiveState(spec)


# ---- detector math ----------------------------------------------------------


def test_transient_spike_never_fires():
    state = _objective()
    t = 0.0
    for _ in range(10):
        assert state.observe(50.0, t) is None
        t += 5.0
    # one spike among healthy evals: fast window is not all-bad
    assert state.observe(500.0, t) is None
    t += 5.0
    for _ in range(10):
        assert state.observe(50.0, t) is None
        t += 5.0
    assert not state.fired
    assert state.violations == 0


def test_sustained_burn_fires_exactly_once_then_recovers_once():
    state = _objective()
    t = 0.0
    transitions = []
    for _ in range(20):
        kind = state.observe(500.0, t)
        if kind:
            transitions.append(kind)
        t += 5.0
    assert transitions == ["violation"]
    for _ in range(20):
        kind = state.observe(50.0, t)
        if kind:
            transitions.append(kind)
        t += 5.0
    assert transitions == ["violation", "recovery"]


def test_hysteresis_band_prevents_flapping():
    """While fired, a mixed good/bad stream neither re-fires nor
    recovers: clear needs an ALL-GOOD fast window (clear_share 0.0),
    fire needs an all-bad one (fire_share 1.0) — the gap is the band."""
    state = _objective()
    t = 0.0
    for _ in range(10):
        state.observe(500.0, t)
        t += 5.0
    assert state.fired and state.violations == 1
    for value in itertools.islice(itertools.cycle([500.0, 50.0]), 40):
        assert state.observe(value, t) is None
        t += 5.0
    assert state.fired  # latched — no flap
    assert state.violations == 1


def test_fast_window_boundary_is_inclusive():
    # three samples exactly spanning fast_secs: the oldest sits at
    # exactly now - fast_secs and must still count (closed interval)
    state = _objective(fast_secs=30.0, min_evals=3)
    assert state.observe(500.0, 0.0) is None
    assert state.observe(500.0, 15.0) is None
    kind = state.observe(500.0, 30.0)
    assert kind == "violation"
    assert state.burn_fast == 1.0


def test_slow_window_evicts_only_strictly_older_samples():
    state = _objective(slow_secs=300.0)
    state.observe(500.0, 0.0)
    state.observe(50.0, 300.0)  # boundary sample from t=0 survives
    assert len(state.samples) == 2
    state.observe(50.0, 301.0)  # now t=0 is strictly past the window
    assert len(state.samples) == 2
    assert state.samples[0][0] == 300.0


def test_min_evals_gate_before_firing():
    state = _objective(min_evals=3)
    assert state.observe(500.0, 0.0) is None
    assert state.observe(500.0, 1.0) is None
    assert state.observe(500.0, 2.0) == "violation"


def test_auto_baseline_learns_median_then_judges_factor():
    state = _objective(threshold=None, baseline_factor=2.0)
    for i, value in enumerate([100.0, 120.0, 80.0, 110.0, 90.0]):
        assert state.observe(value, float(i)) is None
    assert state.baseline == 100.0  # median of the learning evals
    assert state.snapshot()["threshold"] == 200.0
    t = 10.0
    fired = []
    for _ in range(8):
        kind = state.observe(250.0, t)
        if kind:
            fired.append(kind)
        t += 5.0
    assert fired == ["violation"]


def test_below_comparator_fires_on_floor_violation():
    state = _objective(threshold=0.3, comparator="below")
    t = 0.0
    kinds = []
    for _ in range(6):
        kind = state.observe(0.1, t)
        if kind:
            kinds.append(kind)
        t += 5.0
    assert kinds == ["violation"]


# ---- config parsing ---------------------------------------------------------


def test_parse_slo_config_shapes():
    assert parse_slo_config(None) is None
    assert parse_slo_config("") is None
    config = parse_slo_config("default")
    assert len(config["objectives"]) == len(slo_mod.DEFAULT_OBJECTIVES)
    inline = parse_slo_config(
        '{"objectives": [{"name": "x", "signal": "s", "threshold": 5}],'
        ' "windows": {"fast_secs": 10}}'
    )
    assert inline["objectives"][0]["windows"]["fast_secs"] == 10
    assert inline["objectives"][0]["windows"]["slow_secs"] == 300.0


def test_parse_slo_config_rejects_bad_specs():
    with pytest.raises(ValueError):
        parse_slo_config('{"objectives": [{"signal": "s", "threshold": 1}]}')
    with pytest.raises(ValueError):
        parse_slo_config(
            '{"objectives": [{"name": "x", "signal": "s", '
            '"threshold": 1, "comparator": "sideways"}]}'
        )
    with pytest.raises(ValueError):
        parse_slo_config('{"objectives": [{"name": "x", "signal": "s"}]}')


def test_parse_slo_config_from_file(tmp_path):
    path = tmp_path / "slo.json"
    path.write_text(
        json.dumps(
            {"objectives": [{"name": "f", "signal": "s", "threshold": 1}]}
        )
    )
    config = parse_slo_config(str(path))
    assert config["objectives"][0]["name"] == "f"


# ---- merge discipline: delivery order cannot change SLO state ---------------


def _beat_schedules(beats: list) -> list[list]:
    """The delivery shapes the servicer's fan-in can produce: in-order,
    reversed, and duplicated-plus-replayed (every beat twice, then the
    whole stream replayed once more, master-restart style)."""
    return [
        list(beats),
        list(reversed(beats)),
        [b for b in beats for _ in (0, 1)] + list(beats),
    ]


def test_rpc_merge_property_identical_slo_transitions():
    """Outage counters ride max-merge: any delivery order / duplication
    / batch-replay of a round's beats converges to the same fleet
    totals, so the engine sees the same rise sequence and produces the
    SAME transitions.  This is the whole heartbeat->merge->signal->
    detector chain, property-tested."""
    # per-round, per-worker monotone counter snapshots; round 2 onward
    # carries a genuine outage-class rise on two workers
    rounds = [
        [(0, {"ok": 10}), (1, {"ok": 12}), (2, {"ok": 9})],
        [(0, {"ok": 20, "deadline_exceeded": 1}), (1, {"ok": 22}),
         (2, {"ok": 19, "unavailable": 2})],
        [(0, {"ok": 30, "deadline_exceeded": 3}), (1, {"ok": 31}),
         (2, {"ok": 29, "unavailable": 4})],
        [(0, {"ok": 40, "deadline_exceeded": 5}), (1, {"ok": 41}),
         (2, {"ok": 39, "unavailable": 6})],
        [(0, {"ok": 50, "deadline_exceeded": 7}), (1, {"ok": 51}),
         (2, {"ok": 49, "unavailable": 8})],
    ]
    results = []
    for schedule_idx in range(3):
        merged: dict[int, dict] = {}
        totals: dict = {}
        engine = SLOEngine(parse_slo_config("default"), clock=FakeClock())
        now = 0.0
        for round_beats in rounds:
            for worker_id, counters in _beat_schedules(round_beats)[
                schedule_idx
            ]:
                max_merge_counters(
                    merged.setdefault(worker_id, {}),
                    counters,
                    totals=totals,
                )
            now += 10.0
            engine.evaluate(
                {
                    SIGNAL_RPC_OUTAGE_RISE: engine.ingest_rpc_totals(
                        totals
                    )
                },
                now=now,
            )
        results.append(
            (
                dict(totals),
                [
                    (t["kind"], t["objective"], t["at"])
                    for t in engine.transitions
                ],
                engine.health_block()["objectives"]["rpc_outage"],
            )
        )
    assert results[0] == results[1] == results[2]
    # and the property is not vacuous: the outage objective fired
    assert any(t[1] == "rpc_outage" for t in results[0][1])


def test_phase_merge_property_identical_goodput_signal():
    """Anatomy phase totals ride max_merge_phase_stats: any delivery
    shape converges to the same fleet totals, hence the same
    e2e_vs_roofline signal and the same goodput_floor state."""
    rounds = [
        [
            (0, {"host_fetch": {"ms": 100.0 * n, "count": n},
                 "device_compute": {"ms": 400.0 * n, "count": n},
                 "assemble": {"ms": 50.0 * n, "count": n},
                 "h2d_transfer": {"ms": 50.0 * n, "count": n},
                 "untracked": {"ms": 1400.0 * n, "count": n}}),
            (1, {"host_fetch": {"ms": 120.0 * n, "count": n},
                 "device_compute": {"ms": 380.0 * n, "count": n},
                 "assemble": {"ms": 60.0 * n, "count": n},
                 "h2d_transfer": {"ms": 40.0 * n, "count": n},
                 "untracked": {"ms": 1500.0 * n, "count": n}}),
        ]
        for n in range(1, 7)
    ]
    results = []
    for schedule_idx in range(3):
        merged: dict[int, dict] = {}
        totals: dict = {}
        engine = SLOEngine(parse_slo_config("default"), clock=FakeClock())
        now = 0.0
        signal_stream = []
        for round_beats in rounds:
            for worker_id, phases in _beat_schedules(round_beats)[
                schedule_idx
            ]:
                max_merge_phase_stats(
                    merged.setdefault(worker_id, {}),
                    phases,
                    totals=totals,
                )
            signals = signals_from_phase_totals(totals)
            signal_stream.append(round(signals[SIGNAL_E2E_VS_ROOFLINE], 9))
            now += 10.0
            engine.evaluate(signals, now=now)
        results.append(
            (
                signal_stream,
                [(t["kind"], t["objective"]) for t in engine.transitions],
            )
        )
    assert results[0] == results[1] == results[2]
    # device path sits well under the wall: the goodput floor fired
    assert ("violation", "goodput_floor") in results[0][1]


# ---- shared percentile tracker: the autoscaler pin --------------------------


class _ReferenceTracker:
    """The historical master/autoscaler.py private window, reimplemented
    verbatim as the pin oracle (wall-clock reads replaced by the
    injected now — the only delta, since the original read
    time.monotonic() inline)."""

    def __init__(self, window: int = 128):
        self._window = window
        self._samples_ms: list[float] = []
        self._last: tuple[float, int] | None = None

    def note_version(self, now: float, version: int):
        last = self._last
        if last is not None and version > last[1]:
            per_step_ms = (now - last[0]) * 1000.0 / (version - last[1])
            self._samples_ms.append(per_step_ms)
            if len(self._samples_ms) > self._window:
                del self._samples_ms[: -self._window]
        if last is None or version >= last[1]:
            self._last = (now, version)

    def p95_ms(self) -> float | None:
        samples = sorted(self._samples_ms)
        if len(samples) < 4:
            return None
        idx = min(
            len(samples) - 1, int(round(95.0 / 100.0 * (len(samples) - 1)))
        )
        return samples[idx]


def _version_stream():
    """A gnarly version-report stream: stalls, duplicate reports,
    out-of-order stale versions, bursts."""
    reports = []
    version = 0
    t = 0.0
    deltas = [0.5, 0.5, 2.0, 0.1, 0.1, 0.1, 3.0, 0.5, 0.5, 0.5] * 20
    for i, dt in enumerate(deltas):
        t += dt
        if i % 7 == 3:
            reports.append((t, version))  # duplicate (no advance)
        elif i % 11 == 5:
            reports.append((t, max(0, version - 2)))  # stale re-report
        else:
            version += 1 + (i % 3)
            reports.append((t, version))
    return reports


def test_tracker_semantics_pinned_to_historical_window():
    clock = FakeClock(0.0)
    shared = StepTimePercentileTracker(clock=clock)
    reference = _ReferenceTracker()
    for t, version in _version_stream():
        clock.t = t
        shared.note_version(0, version)
        reference.note_version(t, version)
        assert shared.p95_ms() == reference.p95_ms()


def test_autoscaler_decision_stream_pinned():
    """The autoscaler fed by the SHARED tracker produces the same
    decision stream the historical private window produced."""
    from elasticdl_tpu.master.autoscaler import Autoscaler

    clock = FakeClock(0.0)
    shared = StepTimePercentileTracker(clock=clock)
    scaler = Autoscaler(
        p95_step_ms=400.0,
        cooldown_secs=5.0,
        shrink=True,
        min_slices=1,
        max_slices=4,
        tracker=shared,
    )
    reference = _ReferenceTracker()
    reference_decisions = []
    ref_last_decision = None
    slices = 1
    for t, version in _version_stream():
        clock.t = t
        shared.note_version(0, version)
        reference.note_version(t, version)
        decision = scaler.evaluate(0, slices, now=t)
        # reference decision logic: the same thresholds over the
        # reference p95
        ref_decision = None
        if ref_last_decision is None or t - ref_last_decision >= 5.0:
            p95 = reference.p95_ms()
            if p95 is not None and p95 >= 400.0 and slices < 4:
                ref_decision = ("grow", slices, slices + 1)
                ref_last_decision = t
            elif p95 is not None and p95 <= 0.25 * 400.0 and slices > 1:
                ref_decision = ("shrink", slices, slices - 1)
                ref_last_decision = t
        if ref_decision:
            reference_decisions.append(ref_decision)
        if decision:
            slices = decision["to_slices"]
    assert [
        (d["action"], d["from_slices"], d["to_slices"])
        for d in scaler.decisions
    ] == reference_decisions
    assert reference_decisions  # the stream actually decided things


def test_autoscaler_exports_shared_tracker_type():
    from elasticdl_tpu.master import autoscaler

    assert autoscaler.StepTimeTracker is StepTimePercentileTracker
    assert isinstance(
        Autoscaler_default_tracker(), StepTimePercentileTracker
    )


def Autoscaler_default_tracker():
    from elasticdl_tpu.master.autoscaler import Autoscaler

    return Autoscaler(p95_step_ms=1.0).tracker


# ---- off-path contracts -----------------------------------------------------

_BASE_ARGS = [
    "--model_def",
    "mnist_functional_api.mnist_functional_api.custom_model",
    "--training_data",
    "/tmp/x",
]


def test_slo_config_never_reaches_worker_argv():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    off = parse_master_args(_BASE_ARGS)
    on = parse_master_args(_BASE_ARGS + ["--slo_config", "default"])
    assert off.slo_config is None
    argv_off = build_worker_arguments(off, 0, "localhost:1")
    argv_on = build_worker_arguments(on, 0, "localhost:1")
    # master-only: even when SET it travels by env, never worker argv —
    # and the off argv is byte-identical to a build without the flag
    assert "--slo_config" not in argv_on
    assert argv_on == argv_off


def test_master_forwards_slo_config_by_env():
    from elasticdl_tpu.utils.args import parse_master_args

    args = parse_master_args(
        _BASE_ARGS + ["--num_workers", "1", "--slo_config", "default"]
    )
    captured = {}

    class _FakeLIM:
        def __init__(self, master, num_workers, build_argv, envs=None, **kw):
            captured["envs"] = dict(envs or {})
            captured["argv"] = build_argv(0, "localhost:1")

    class _FactoryHolder:
        def __init__(self, args, instance_manager_factory=None):
            self.factory = instance_manager_factory

    import elasticdl_tpu.master.main as master_main

    real_lim = master_main.LocalInstanceManager
    real_master = master_main.Master
    master_main.LocalInstanceManager = _FakeLIM
    master_main.Master = _FactoryHolder
    try:
        holder = master_main.build_master(args)
        holder.factory(object())
    finally:
        master_main.LocalInstanceManager = real_lim
        master_main.Master = real_master
    assert captured["envs"][slo_mod.SLO_CONFIG_ENV] == "default"
    assert "--slo_config" not in captured["argv"]


def test_disabled_accessor_reads_no_clock(monkeypatch):
    """Clock-poison contract: the disabled-path gate is one global load
    — it must not touch any clock (the fleetsim digest and the
    disabled-overhead budget both depend on this)."""
    slo_mod.uninstall()

    def _poisoned():
        raise AssertionError("disabled SLO path read a clock")

    monkeypatch.setattr(slo_mod.time, "monotonic", _poisoned)
    assert slo_mod.get_engine() is None


def test_install_if_enabled_lifecycle():
    engine = slo_mod.install_if_enabled("default", clock=FakeClock())
    assert engine is slo_mod.get_engine()
    assert slo_mod.install_if_enabled(None) is None
    assert slo_mod.get_engine() is None
    engine = slo_mod.install_from_env(clock=FakeClock())
    assert engine is None  # env unset
    os.environ[slo_mod.SLO_CONFIG_ENV] = "default"
    try:
        engine = slo_mod.install_from_env(clock=FakeClock())
        assert engine is not None
    finally:
        del os.environ[slo_mod.SLO_CONFIG_ENV]
        slo_mod.uninstall()


def test_healthz_block_absent_without_engine():
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    telemetry = MasterTelemetry()
    health_fn = telemetry.build_health_fn("training")
    assert "slo" not in health_fn()
    engine = SLOEngine(parse_slo_config("default"), clock=FakeClock())
    telemetry.set_slo_engine(engine)
    assert health_fn()["slo"]["ok"] is True


# ---- engine side effects: events, metrics, profiler, incidents --------------


def _drive_regression(engine, clock, healthy=12, bad=12, recover=12):
    for _ in range(healthy):
        clock.advance(10.0)
        engine.evaluate({SIGNAL_STEP_TIME_P95_MS: 100.0})
    for _ in range(bad):
        clock.advance(10.0)
        engine.evaluate({SIGNAL_STEP_TIME_P95_MS: 500.0})
    for _ in range(recover):
        clock.advance(10.0)
        engine.evaluate({SIGNAL_STEP_TIME_P95_MS: 100.0})


def test_regression_opens_exactly_one_incident_and_arms_profiler(tmp_path):
    clock = FakeClock()
    events = []
    arms = []
    incidents = IncidentManager(
        telemetry_dir=str(tmp_path),
        emit=lambda event, **fields: events.append((event, fields)),
        clock=clock,
    )
    engine = SLOEngine(
        parse_slo_config("default"),
        clock=clock,
        emit=lambda event, **fields: events.append((event, fields)),
        arm_profiler=arms.append,
        incidents=incidents,
    )
    _drive_regression(engine, clock)
    names = [e for e, _f in events]
    assert names.count("slo_violation") == 1
    assert names.count("slo_recovered") == 1
    assert names.count("incident_open") == 1
    assert names.count("incident_close") == 1
    assert arms == [slo_mod.DEFAULT_PROFILE_STEPS]
    assert incidents.total_count == 1 and incidents.open_count == 0
    loaded = read_incidents(str(tmp_path))
    assert len(loaded) == 1
    record = loaded[0]
    assert record["objectives"] == ["step_time_p95"]
    assert record["suspected_cause"]
    assert any(
        entry["name"] == "slo_violation" for entry in record["timeline"]
    )
    # artifact is strict JSON (already parsed) and self-describing
    assert record["duration_secs"] > 0


def test_second_objective_joins_open_incident():
    clock = FakeClock()
    incidents = IncidentManager(clock=clock)
    engine = SLOEngine(
        parse_slo_config("default"), clock=clock, incidents=incidents
    )
    for _ in range(8):
        clock.advance(10.0)
        engine.evaluate(
            {
                SIGNAL_STEP_TIME_P95_MS: 100.0,
                SIGNAL_LAST_STEP_AGE_SECS: 1.0,
            }
        )
    for _ in range(8):
        clock.advance(10.0)
        engine.evaluate(
            {
                SIGNAL_STEP_TIME_P95_MS: 500.0,
                SIGNAL_LAST_STEP_AGE_SECS: 500.0,
            }
        )
    assert len(engine.active_violations()) == 2
    assert incidents.total_count == 1  # joined, not a second incident
    # one objective recovers: the incident stays open
    for _ in range(8):
        clock.advance(10.0)
        engine.evaluate(
            {
                SIGNAL_STEP_TIME_P95_MS: 100.0,
                SIGNAL_LAST_STEP_AGE_SECS: 500.0,
            }
        )
    assert incidents.open_count == 1
    for _ in range(8):
        clock.advance(10.0)
        engine.evaluate(
            {
                SIGNAL_STEP_TIME_P95_MS: 100.0,
                SIGNAL_LAST_STEP_AGE_SECS: 1.0,
            }
        )
    assert incidents.open_count == 0 and incidents.total_count == 1


def test_mirror_metrics_families(tmp_path):
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    clock = FakeClock()
    engine = SLOEngine(
        parse_slo_config("default"),
        clock=clock,
        incidents=IncidentManager(clock=clock),
    )
    _drive_regression(engine, clock, healthy=6, bad=8, recover=0)
    registry = MetricsRegistry()
    engine.mirror_metrics(registry)
    text = registry.exposition()
    assert 'elasticdl_slo_violations_total{objective="step_time_p95"} 1' in text
    assert 'elasticdl_slo_objective_ok{objective="step_time_p95"} 0' in text
    assert "elasticdl_slo_burn_rate" in text
    assert "elasticdl_slo_incidents_total 1" in text


def test_dormant_signals_never_advance_windows():
    clock = FakeClock()
    engine = SLOEngine(parse_slo_config("default"), clock=clock)
    for _ in range(20):
        clock.advance(10.0)
        engine.evaluate({})
    block = engine.health_block()
    # no signal measured: only reform_downtime auto-injects (healthy 0)
    assert block["objectives"]["memory_headroom"]["evaluations"] == 0
    assert block["objectives"]["goodput_floor"]["evaluations"] == 0
    assert block["ok"]


def test_reform_downtime_signal_accumulates_and_expires():
    clock = FakeClock()
    engine = SLOEngine(parse_slo_config("default"), clock=clock)
    engine.note_reform_downtime(40.0)
    engine.note_reform_downtime(30.0)
    transitions = []
    for _ in range(6):
        clock.advance(10.0)
        transitions += engine.evaluate({})
    assert [(t["kind"], t["objective"]) for t in transitions] == [
        ("violation", "reform_downtime_budget")
    ]
    # past the slow window the ledger drains and the budget recovers
    clock.advance(400.0)
    for _ in range(6):
        clock.advance(10.0)
        transitions += engine.evaluate({})
    assert transitions[-1]["kind"] == "recovery"


# ---- cause classification ---------------------------------------------------


def _violation(signal):
    return [{"objective": "x", "signal": signal}]


def test_classify_cause_priorities():
    assert classify_cause(
        _violation(SIGNAL_MEMORY_HEADROOM_SHARE), None, None
    )[0] == CAUSE_MEMORY_PRESSURE
    assert classify_cause(
        _violation(SIGNAL_STEP_TIME_P95_MS),
        None,
        None,
        [{"event": "memory_pressure"}],
    )[0] == CAUSE_MEMORY_PRESSURE
    assert classify_cause(
        _violation(SIGNAL_RPC_OUTAGE_RISE), None, None
    )[0] == CAUSE_NETWORK_DEGRADED
    assert classify_cause(
        _violation(SIGNAL_STEP_TIME_P95_MS),
        {"rpc": {"deadline_exceeded": 1}},
        {"rpc": {"deadline_exceeded": 5}},
    )[0] == CAUSE_NETWORK_DEGRADED
    assert classify_cause(
        _violation(SIGNAL_STEP_TIME_P95_MS),
        None,
        None,
        [{"event": "reform_start"}],
    )[0] == CAUSE_CONTROL_PLANE
    assert classify_cause(
        _violation(SIGNAL_LAST_STEP_AGE_SECS), None, None
    )[0] == CAUSE_CONTROL_PLANE


def test_classify_cause_anatomy_split():
    open_ctx = {
        "anatomy": {
            "host_fetch": {"ms": 100.0},
            "device_compute": {"ms": 400.0},
        }
    }
    input_bound = {
        "anatomy": {
            "host_fetch": {"ms": 900.0},
            "device_compute": {"ms": 450.0},
        }
    }
    compute_bound = {
        "anatomy": {
            "host_fetch": {"ms": 120.0},
            "device_compute": {"ms": 1400.0},
        }
    }
    cause, rationale = classify_cause(
        _violation(SIGNAL_STEP_TIME_P95_MS), open_ctx, input_bound
    )
    assert cause == CAUSE_INPUT_BOUND and "host_fetch" in rationale
    cause, _rationale = classify_cause(
        _violation(SIGNAL_E2E_VS_ROOFLINE), open_ctx, compute_bound
    )
    assert cause == CAUSE_COMPUTE_BOUND


# ---- report CLI surfaces ----------------------------------------------------


def test_report_summary_json_verdicts(tmp_path):
    from elasticdl_tpu.telemetry import report as report_cli

    run_dir = tmp_path / "run"
    telemetry_dir = run_dir / "telemetry"
    telemetry_dir.mkdir(parents=True)
    with open(telemetry_dir / "events.jsonl", "w", encoding="utf-8") as f:
        for event in [
            {"event": "step", "monotonic": 1.0, "duration_secs": 0.1,
             "records": 32, "generation": 0, "worker_id": 0, "time": 1.0},
            {"event": "slo_violation", "monotonic": 2.0,
             "objective": "step_time_p95", "signal": "step_time_p95_ms",
             "value": 500.0, "threshold": 200.0, "time": 2.0},
            {"event": "incident_open", "monotonic": 2.0, "incident": 1,
             "objective": "step_time_p95", "time": 2.0},
            {"event": "slo_recovered", "monotonic": 9.0,
             "objective": "step_time_p95", "time": 9.0},
            {"event": "incident_close", "monotonic": 9.0, "incident": 1,
             "suspected_cause": "input-bound", "time": 9.0},
        ]:
            f.write(json.dumps(event) + "\n")
    incidents_dir = telemetry_dir / "incidents"
    incidents_dir.mkdir()
    with open(
        incidents_dir / "incident_1.json", "w", encoding="utf-8"
    ) as f:
        json.dump(
            {
                "incident": 1,
                "duration_secs": 7.0,
                "objectives": ["step_time_p95"],
                "violations": [{"objective": "step_time_p95"}],
                "recoveries": [{}],
                "suspected_cause": "input-bound",
                "rationale": "host_fetch grew",
                "profile_windows": [{"window_id": 3}],
                "timeline": [],
            },
            f,
        )
    summary_path = tmp_path / "summary.json"
    rc = report_cli.main(
        [str(run_dir), "--summary-json", str(summary_path)]
    )
    assert rc == 0
    summary = json.loads(summary_path.read_text())
    assert summary["verdict"] == "degraded"
    assert summary["incidents"]["total"] == 1
    assert summary["incidents"]["causes"] == {"input-bound": 1}
    assert summary["slo"] == {
        "violations": 1,
        "recoveries": 1,
        "still_firing": [],
    }
    report = report_cli.build_report(str(run_dir))
    text = report_cli._format_text(report)
    assert "incident 1: input-bound" in text
    assert "slo: 1 violation(s), 1 recovery(ies)" in text


def test_report_summary_fail_on_still_open_incident(tmp_path):
    from elasticdl_tpu.telemetry import report as report_cli

    telemetry_dir = tmp_path / "telemetry"
    telemetry_dir.mkdir()
    with open(telemetry_dir / "events.jsonl", "w", encoding="utf-8") as f:
        for event in [
            {"event": "slo_violation", "monotonic": 2.0,
             "objective": "progress_stall",
             "signal": "last_step_age_secs", "value": 500.0,
             "threshold": 120.0, "time": 2.0},
            {"event": "incident_open", "monotonic": 2.0, "incident": 1,
             "objective": "progress_stall", "time": 2.0},
        ]:
            f.write(json.dumps(event) + "\n")
    summary = report_cli.summarize_report(
        report_cli.build_report(str(tmp_path))
    )
    assert summary["verdict"] == "fail"
    assert summary["slo"]["still_firing"] == ["progress_stall"]
    assert summary["incidents"]["open"] == 1


def test_report_summary_no_data(tmp_path):
    from elasticdl_tpu.telemetry import report as report_cli

    summary = report_cli.summarize_report(
        report_cli.build_report(str(tmp_path))
    )
    assert summary["verdict"] == "no_data"


# ---- fleetsim: virtual-clock watchdog + mute_slo falsification --------------


def _small_fleet(corrupt=""):
    from elasticdl_tpu.fleetsim.plans import named_fleet_plan
    from elasticdl_tpu.fleetsim.sim import FleetConfig, FleetSimulator

    logging.disable(logging.CRITICAL)
    try:
        config = FleetConfig(
            num_workers=48, seed=11, num_tasks=120, corrupt=corrupt
        )
        sim = FleetSimulator(
            named_fleet_plan("fleet_mass_preemption"), config
        )
        return sim.run()
    finally:
        logging.disable(logging.NOTSET)


def test_fleetsim_slo_detection_invariant_passes():
    result = _small_fleet()
    by_name = {i["name"]: i for i in result["invariants"]}
    assert by_name["slo_detection"]["status"] == "PASS"
    assert result["rc"] == 0
    slo = result["scale"]["slo"]
    assert slo["evaluations"] > 0
    # the virtual tracker measured real samples (the >=4-sample p95
    # gate itself runs at 1000 workers in scripts/fleetsim_smoke.py)
    assert slo["p95_samples"] >= 1


def test_fleetsim_mute_slo_trips_invariant_rc1():
    result = _small_fleet(corrupt="mute_slo")
    by_name = {i["name"]: i for i in result["invariants"]}
    assert by_name["slo_detection"]["status"] == "FAIL"
    assert result["rc"] == 1


def test_fleetsim_digest_invariant_under_watchdog():
    first = _small_fleet()
    second = _small_fleet()
    assert first["event_log_digest"] == second["event_log_digest"]
