"""XLA profiler window: --profile_dir captures a step-window trace
(TensorBoard 'profile' plugin artifacts) during local training."""

import glob
import os

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.trainer.local_executor import LocalExecutor
from elasticdl_tpu.utils.args import parse_master_args
from elasticdl_tpu.utils.profiling import StepProfiler


def test_local_training_writes_profile(tmp_path):
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=192, num_shards=1, seed=0
    )
    profile_dir = str(tmp_path / "prof")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "32",
            "--records_per_task",
            "96",
            "--profile_dir",
            profile_dir,
            "--profile_steps",
            "2",
        ]
    )
    LocalExecutor(args).run()
    traces = glob.glob(
        os.path.join(profile_dir, "**", "*.trace.json*"), recursive=True
    ) + glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"), recursive=True)
    assert traces, f"no trace artifacts under {profile_dir}"


def test_step_profiler_inactive_without_dir():
    prof = StepProfiler("", num_steps=3)
    for step in range(10):
        prof.on_step(step)  # must be a no-op, not a crash
    prof.stop()


def test_step_profiler_window_bounds(monkeypatch, tmp_path):
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    out = str(tmp_path / "p")
    prof = StepProfiler(out, start_step=2, num_steps=3)
    for step in range(10):
        prof.on_step(step)
    prof.stop()  # idempotent after the window closed
    assert calls == [("start", out), ("stop",)]
