"""Regression tests for two jit hazards in the model-zoo path:

1. learning_rate_scheduler is evaluated on a TRACED step inside the jitted
   train step (optax schedule), so it must be branch-free;
2. train-mode dropout requires the step builder to thread a 'dropout' rng.
"""

import jax
import numpy as np

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.models import cifar10_functional_api as c10
from elasticdl_tpu.trainer.local_executor import build_optimizer
from elasticdl_tpu.trainer.state import TrainState, init_model
from elasticdl_tpu.trainer.step import build_train_step
from elasticdl_tpu.utils.model_utils import get_model_spec


def test_cifar10_scheduler_under_jit():
    """The production path that used to crash: build_optimizer wires the
    model's learning_rate_scheduler as an optax schedule evaluated on a
    tracer (local_executor.build_optimizer)."""
    spec = get_model_spec(
        "", "cifar10_functional_api.cifar10_functional_api.custom_model"
    )
    assert spec.learning_rate_scheduler is not None
    model = spec.build_model()
    rng = np.random.RandomState(0)
    feats = {"image": rng.rand(4, 32, 32, 3).astype(np.float32)}
    labels = rng.randint(0, 10, 4).astype(np.int32)
    params, mstate = init_model(model, feats)
    tx = build_optimizer(spec)  # schedule path
    state = TrainState.create(model.apply, params, tx, mstate)
    train_step = build_train_step(spec.loss, compute_dtype=None)
    state, metrics = train_step(state, feats, labels)  # jitted: step traced
    assert np.isfinite(float(metrics["loss"]))

    # schedule values match the reference's milestones
    sch = spec.learning_rate_scheduler
    np.testing.assert_allclose(float(sch(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sch(5000)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sch(15000)), 0.001, rtol=1e-6)


def test_dropout_active_in_training():
    """Same inputs, two different steps -> dropout rng differs by step, and
    training forward differs from deterministic eval forward."""
    model = c10.custom_model()
    rng = np.random.RandomState(0)
    feats = {"image": rng.rand(4, 32, 32, 3).astype(np.float32)}
    params, mstate = init_model(model, feats)

    out_eval = model.apply({"params": params, **mstate}, feats, training=False)

    def train_out(step):
        return model.apply(
            {"params": params, **mstate},
            feats,
            training=True,
            mutable=["batch_stats"],
            rngs={"dropout": jax.random.fold_in(jax.random.PRNGKey(0), step)},
        )[0]

    out_t0, out_t1 = train_out(0), train_out(1)
    assert not np.allclose(np.asarray(out_t0), np.asarray(out_eval))
    assert not np.allclose(np.asarray(out_t0), np.asarray(out_t1))
