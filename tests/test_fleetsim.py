"""Fleet-scale control plane: merge-under-batching contract, coalesced
heartbeat fan-in, incremental dead-worker sweep, /metrics cardinality
cap, and the deterministic fleet simulator (ISSUE 14)."""

from __future__ import annotations

import json
import os
import random
import threading

import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.merge import (
    max_merge_counters,
    max_merge_phase_stats,
)


class FakeClock:
    def __init__(self, start: float = 0.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, secs: float):
        self.now += secs


def make_servicer(clock=None, tasks: int = 4):
    dispatcher = TaskDispatcher(
        {"shard": (0, 64 * tasks)}, records_per_task=64, num_epochs=1
    )
    kwargs = {} if clock is None else {"clock": clock}
    return MasterServicer(32, dispatcher, **kwargs), dispatcher


# ---- utils/merge.py: the batched/coalesced heartbeat contract ---------------
#
# The PR-8 dedup contract extended to BATCHES: reordered, duplicated,
# and batched-then-replayed monotone counter sets must all merge to the
# same totals the ordered per-beat application produces.


def synth_beats(seed: int, workers: int = 8, beats: int = 40):
    """Deterministic per-worker monotone counter timelines."""
    rng = random.Random(seed)
    timelines = []
    counters = {w: {"retries": 0, "unavailable": 0} for w in range(workers)}
    for _ in range(beats):
        w = rng.randrange(workers)
        key = rng.choice(["retries", "unavailable"])
        counters[w][key] += rng.randint(1, 3)
        timelines.append((w, dict(counters[w])))
    final = counters
    return timelines, final


def apply_beats(beats, totals=None):
    merged: dict[int, dict] = {}
    for w, update in beats:
        max_merge_counters(
            merged.setdefault(w, {}), update, totals=totals
        )
    return merged


class TestMaxMergeUnderBatching:
    def test_ordered_vs_reordered_vs_duplicated(self):
        beats, final = synth_beats(7)
        ordered = apply_beats(beats)
        rng = random.Random(99)
        shuffled = list(beats)
        rng.shuffle(shuffled)
        reordered = apply_beats(shuffled)
        duplicated = apply_beats(beats + beats[::3] + shuffled[::5])
        assert ordered == reordered == duplicated
        for w, expect in final.items():
            assert ordered[w] == expect

    def test_batched_then_replayed_totals_identical(self):
        """Coalesced drains apply beats in arbitrary batch boundaries
        and a master restart replays whole batches: the aggregate
        (sum-of-per-worker-maxima) must be invariant to all of it."""
        beats, final = synth_beats(11)
        expected_totals: dict[str, int] = {}
        apply_beats(beats, totals=expected_totals)

        rng = random.Random(3)
        batched = list(beats)
        rng.shuffle(batched)
        batches = []
        i = 0
        while i < len(batched):
            size = rng.randint(1, 7)
            batches.append(batched[i : i + size])
            i += size
        replayed = batches + [batches[0], batches[-1]]  # replay batches
        totals: dict[str, int] = {}
        merged: dict[int, dict] = {}
        for batch in replayed:
            for w, update in batch:
                max_merge_counters(
                    merged.setdefault(w, {}), update, totals=totals
                )
        assert totals == expected_totals
        assert totals == {
            key: sum(final[w][key] for w in final)
            for key in ("retries", "unavailable")
        }

    def test_totals_never_walk_backward(self):
        totals: dict[str, int] = {}
        merged: dict[str, int] = {}
        max_merge_counters(merged, {"retries": 10}, totals=totals)
        max_merge_counters(merged, {"retries": 4}, totals=totals)  # stale
        assert merged == {"retries": 10}
        assert totals == {"retries": 10}

    def test_malformed_values_skipped(self):
        totals: dict[str, int] = {}
        merged: dict[str, int] = {}
        rose = max_merge_counters(
            merged,
            {"retries": "nope", "unavailable": 2},
            watch=frozenset({"unavailable"}),
            totals=totals,
        )
        assert rose
        assert merged == {"unavailable": 2}
        assert totals == {"unavailable": 2}

    def test_phase_stats_batched_aggregate(self):
        updates = [
            {"train": {"ms": 10.0, "count": 2, "buckets": {"0.1": 2}}},
            {"train": {"ms": 25.0, "count": 5, "buckets": {"0.1": 5}}},
            {"train": {"ms": 25.0, "count": 5, "buckets": {"0.1": 5}}},
            {"train": {"ms": 15.0, "count": 3, "buckets": {"0.1": 3}}},
        ]
        for order in (updates, updates[::-1]):
            merged: dict = {}
            totals: dict = {}
            for update in order:
                max_merge_phase_stats(merged, update, totals=totals)
            assert merged["train"]["ms"] == 25.0
            assert totals["train"]["ms"] == 25.0
            assert totals["train"]["count"] == 5
            assert totals["train"]["buckets"] == {"0.1": 5}

    def test_phase_stats_malformed_entry_tolerated(self):
        merged: dict = {}
        totals: dict = {}
        max_merge_phase_stats(
            merged,
            {"bad": "not-a-dict", "ok": {"ms": 5.0, "count": 1}},
            totals=totals,
        )
        assert "bad" not in merged
        assert merged["ok"]["ms"] == 5.0
        assert totals["ok"]["ms"] == 5.0


# ---- servicer: coalesced fan-in + incremental sweep -------------------------


class TestCoalescedHeartbeat:
    def test_immediate_visibility_single_threaded(self):
        servicer, _ = make_servicer()
        servicer.heartbeat(
            msg.HeartbeatRequest(worker_id=1, rpc={"retries": 3})
        )
        assert servicer.rpc_stats_totals() == {"retries": 3}
        assert servicer.live_workers() == [1]

    def test_batched_drain_applies_whole_backlog(self):
        """Concurrent arrivals enqueue; ONE drain applies them all
        under one lock acquisition — max-merge keeps totals exact."""
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        for wid in range(50):
            servicer._hb_pending.append(
                (
                    msg.HeartbeatRequest(
                        worker_id=wid, rpc={"retries": wid}
                    ),
                    clock(),
                )
            )
        servicer._drain_heartbeats(block=True)
        stats = servicer.heartbeat_stats()
        assert stats["beats"] == 50
        assert stats["max_batch"] == 50
        assert stats["batches"] == 1
        assert servicer.rpc_stats_totals() == {
            "retries": sum(range(50))
        }
        assert len(servicer.live_workers()) == 50

    def test_sequence_equivalence_shuffled_duplicated(self):
        beats, final = synth_beats(21, workers=6, beats=60)
        sequential, _ = make_servicer()
        for w, update in beats:
            sequential.heartbeat(
                msg.HeartbeatRequest(worker_id=w, rpc=update)
            )
        rng = random.Random(5)
        chaosed = beats + beats[::4]
        rng.shuffle(chaosed)
        shuffled, _ = make_servicer()
        for w, update in chaosed:
            shuffled.heartbeat(
                msg.HeartbeatRequest(worker_id=w, rpc=update)
            )
        assert (
            sequential.rpc_stats_totals() == shuffled.rpc_stats_totals()
        )

    def test_concurrent_hammer_totals_exact(self):
        servicer, _ = make_servicer()
        per_thread_beats = 200
        threads = []

        def worker(wid: int):
            for i in range(1, per_thread_beats + 1):
                servicer.heartbeat(
                    msg.HeartbeatRequest(worker_id=wid, rpc={"retries": i})
                )

        for wid in range(8):
            t = threading.Thread(target=worker, args=(wid,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        assert servicer.rpc_stats_totals() == {
            "retries": 8 * per_thread_beats
        }
        stats = servicer.heartbeat_stats()
        assert stats["beats"] == 8 * per_thread_beats
        # coalescing must actually engage under contention: strictly
        # fewer lock acquisitions than beats would be flaky to assert
        # on a single-core runner, but batches can never exceed beats
        assert stats["batches"] <= stats["beats"]

    def test_phase_and_prefetch_totals_ride_batches(self):
        servicer, _ = make_servicer()
        servicer.heartbeat(
            msg.HeartbeatRequest(
                worker_id=0,
                phases={
                    "device_compute": {
                        "ms": 12.0,
                        "count": 3,
                        "buckets": {"0.25": 3},
                    }
                },
                prefetch={"groups": 2, "stall_ms": 5},
            )
        )
        servicer.heartbeat(
            msg.HeartbeatRequest(
                worker_id=1,
                phases={
                    "device_compute": {
                        "ms": 8.0,
                        "count": 2,
                        "buckets": {"0.25": 2},
                    }
                },
                prefetch={"groups": 1, "stall_ms": 1},
            )
        )
        totals = servicer.phase_stats_totals()
        assert totals["device_compute"]["ms"] == 20.0
        assert totals["device_compute"]["count"] == 5
        assert totals["device_compute"]["buckets"] == {"0.25": 5}
        assert servicer.prefetch_stats_totals() == {
            "groups": 3,
            "stall_ms": 6,
        }


class TestIncrementalSweep:
    def test_expired_reported_until_forgotten(self):
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        servicer.heartbeat(msg.HeartbeatRequest(worker_id=1))
        servicer.heartbeat(msg.HeartbeatRequest(worker_id=2))
        assert servicer.dead_workers(10.0) == []
        clock.advance(11.0)
        assert servicer.dead_workers(10.0) == [1, 2]
        # repeated sweeps keep reporting (the run loop may take ticks
        # to act) — the heap re-push contract
        assert servicer.dead_workers(10.0) == [1, 2]
        servicer.forget_worker(1)
        assert servicer.dead_workers(10.0) == [2]

    def test_fresh_beat_revives(self):
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        servicer.heartbeat(msg.HeartbeatRequest(worker_id=7))
        clock.advance(11.0)
        assert servicer.dead_workers(10.0) == [7]
        servicer.heartbeat(msg.HeartbeatRequest(worker_id=7))
        assert servicer.dead_workers(10.0) == []

    def test_matches_full_scan_semantics_at_scale(self):
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        rng = random.Random(13)
        last_beat = {}
        for wid in range(300):
            clock.advance(rng.uniform(0.0, 0.1))
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
            last_beat[wid] = clock()
        clock.advance(5.0)
        # a third of the fleet beats again
        for wid in range(0, 300, 3):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
            last_beat[wid] = clock()
        clock.advance(3.0)
        timeout = 6.0
        expected = sorted(
            wid
            for wid, at in last_beat.items()
            if clock() - at > timeout
        )
        assert servicer.dead_workers(timeout) == expected

    def test_heap_bounded_without_timeout_detection(self):
        """A deployment on external failure events alone never runs the
        timeout sweep — the heap must self-compact, not leak one entry
        per beat forever."""
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        for beat in range(200):
            clock.advance(1.0)
            for wid in range(10):
                servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        assert len(servicer._hb_heap) <= max(64, 4 * 10) + 10
        # compaction preserved sweep semantics
        clock.advance(20.0)
        assert servicer.dead_workers(10.0) == list(range(10))

    def test_blocking_drain_synchronizes_with_inflight_drainer(self):
        """A reader must see a beat whose handler already popped it off
        the deque but has not yet applied it: the blocking drain always
        takes the drain lock (never returns early on an empty deque)."""
        servicer, _ = make_servicer()
        started = threading.Event()
        proceed = threading.Event()
        original = servicer._apply_heartbeat_batch

        def stalled_apply(batch):
            started.set()
            proceed.wait(5.0)
            original(batch)

        servicer._apply_heartbeat_batch = stalled_apply
        handler = threading.Thread(
            target=servicer.heartbeat,
            args=(msg.HeartbeatRequest(worker_id=9, rpc={"retries": 4}),),
        )
        handler.start()
        assert started.wait(5.0)
        # deque is empty, the batch is in-flight; restore the real
        # apply for the reader's own drain and release the handler
        servicer._apply_heartbeat_batch = original
        assert not servicer._hb_pending
        results: list = []
        reader = threading.Thread(
            target=lambda: results.append(servicer.rpc_stats_totals())
        )
        reader.start()
        proceed.set()
        reader.join(5.0)
        handler.join(5.0)
        assert results == [{"retries": 4}]

    def test_heap_does_not_leak_forgotten_workers(self):
        clock = FakeClock()
        servicer, _ = make_servicer(clock=clock)
        for wid in range(100):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        clock.advance(20.0)
        assert len(servicer.dead_workers(10.0)) == 100
        for wid in range(100):
            servicer.forget_worker(wid)
        clock.advance(1.0)
        assert servicer.dead_workers(10.0) == []
        # the lazily-invalidated entries were popped, not re-pushed
        assert len(servicer._hb_heap) == 0

    def test_sweep_stats_accumulate(self):
        servicer, _ = make_servicer()
        servicer.dead_workers(10.0)
        servicer.dead_workers(10.0)
        stats = servicer.sweep_stats()
        assert stats["count"] == 2
        assert stats["ms"] >= 0.0
        assert stats["max_ms"] >= 0.0


# ---- /metrics: per-worker series cardinality cap ----------------------------


class TestWorkerSeriesCardinality:
    def _wired(self, clock=None):
        from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

        servicer, dispatcher = make_servicer(clock=clock)
        telemetry = MasterTelemetry("")
        telemetry.attach(dispatcher, servicer)
        return telemetry, servicer

    @staticmethod
    def _age_series(text: str) -> list[str]:
        return [
            line
            for line in text.splitlines()
            if line.startswith("elasticdl_worker_heartbeat_age_secs{")
        ]

    def test_small_fleet_gets_per_worker_series(self):
        telemetry, servicer = self._wired()
        for wid in range(5):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        series = self._age_series(telemetry.registry.exposition())
        assert len(series) == 5
        assert any('worker="3"' in line for line in series)

    def test_large_fleet_collapses_to_aggregates(self):
        telemetry, servicer = self._wired()
        for wid in range(200):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        series = self._age_series(telemetry.registry.exposition())
        assert len(series) == 2
        labels = "".join(series)
        assert 'worker="max"' in labels and 'worker="p50"' in labels

    def test_crossing_the_budget_prunes_individual_series(self):
        telemetry, servicer = self._wired()
        for wid in range(5):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        assert len(self._age_series(telemetry.registry.exposition())) == 5
        for wid in range(5, 200):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        series = self._age_series(telemetry.registry.exposition())
        # the 5 individual children must be GONE, not frozen forever
        assert len(series) == 2

    def test_env_override_raises_budget(self, monkeypatch):
        from elasticdl_tpu.telemetry.master_hooks import (
            WORKER_SERIES_MAX_ENV,
        )

        monkeypatch.setenv(WORKER_SERIES_MAX_ENV, "500")
        telemetry, servicer = self._wired()
        for wid in range(200):
            servicer.heartbeat(msg.HeartbeatRequest(worker_id=wid))
        series = self._age_series(telemetry.registry.exposition())
        assert len(series) == 200

    def test_heartbeat_and_sweep_counters_exposed(self):
        telemetry, servicer = self._wired()
        servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
        servicer.dead_workers(10.0)
        text = telemetry.registry.exposition()
        assert "elasticdl_heartbeats_total 1" in text
        assert "elasticdl_heartbeat_batches_total 1" in text
        assert "elasticdl_dead_worker_sweeps_total 1" in text
        assert "elasticdl_dead_worker_sweep_ms_total" in text


# ---- the fleet simulator ----------------------------------------------------


def run_sim(plan_name: str, workdir: str, **kwargs):
    from elasticdl_tpu.fleetsim.runner import run_plan

    defaults = dict(workers=120, num_tasks=180, seed=4321)
    defaults.update(kwargs)
    return run_plan(plan_name, workdir, **defaults)


class TestFleetSimulator:
    def test_mass_preemption_passes_and_is_deterministic(self, tmp_path):
        first = run_sim("fleet_mass_preemption", str(tmp_path / "a"))
        second = run_sim("fleet_mass_preemption", str(tmp_path / "b"))
        assert first["invariants_ok"] and second["invariants_ok"]
        assert first["event_log_digest"] == second["event_log_digest"]
        assert first["scale"]["dead_detected"] >= 30
        # duplicate heartbeat storm applied more beats than calls
        assert (
            first["scale"]["heartbeats"]["total"]
            > first["scale"]["master_cpu_ms"]["heartbeat"]["calls"]
        )

    def test_seed_changes_digest(self, tmp_path):
        first = run_sim("fleet_mass_preemption", str(tmp_path / "a"))
        second = run_sim(
            "fleet_mass_preemption", str(tmp_path / "b"), seed=999
        )
        assert first["event_log_digest"] != second["event_log_digest"]

    def test_rolling_slice_loss(self, tmp_path):
        result = run_sim("fleet_rolling_slice_loss", str(tmp_path))
        assert result["invariants_ok"], result["invariants"]
        # three of eight slices died
        assert result["scale"]["dead_detected"] == 3 * (120 // 8)

    def test_master_kill_rehomes_and_journals(self, tmp_path):
        result = run_sim("fleet_master_kill_fanin", str(tmp_path))
        assert result["invariants_ok"], result["invariants"]
        assert result["scale"]["rehomes"] == 120
        assert result["budgets"]["journal_bytes_per_event"]["ok"]
        assert os.path.exists(tmp_path / "journal" / "journal.jsonl")

    def test_lost_task_corruption_trips_exactly_once(self, tmp_path):
        result = run_sim(
            "fleet_mass_preemption", str(tmp_path), corrupt="lost_task"
        )
        assert result["rc"] == 1
        failed = {
            i["name"]
            for i in result["invariants"]
            if i["status"] == "FAIL"
        }
        assert "exactly_once" in failed
        assert "records_accounted" in failed

    def test_series_flood_corruption_trips_cardinality_budget(
        self, tmp_path
    ):
        """The /metrics cardinality gate is falsifiable: lifting the
        per-worker series cap at a fleet past the budget must render
        one series per worker and fail scrape_worker_series."""
        result = run_sim(
            "fleet_mass_preemption", str(tmp_path), corrupt="series_flood"
        )
        assert result["rc"] == 1
        budget = result["budgets"]["scrape_worker_series"]
        assert not budget["ok"]
        assert budget["value"] > budget["budget"]

    def test_budget_override_trips_compliance(self, tmp_path):
        result = run_sim(
            "fleet_mass_preemption",
            str(tmp_path),
            budgets={"heartbeat_cpu_ms": 1e-9},
        )
        assert result["rc"] == 1
        failed = {
            i["name"]
            for i in result["invariants"]
            if i["status"] == "FAIL"
        }
        assert failed == {"budget_compliance"}

    def test_result_schema_matches_chaos_result_core(self, tmp_path):
        """Satellite contract: one verdict schema across chaos and
        fleetsim artifacts — CI reads both with the same code."""
        result = run_sim("fleet_mass_preemption", str(tmp_path))
        path = tmp_path / "fleetsim_result.json"
        assert path.exists()
        artifact = json.loads(path.read_text())
        for key in ("plan", "seed", "corrupt", "invariants",
                    "invariants_ok", "rc"):
            assert key in artifact, key
        for invariant in artifact["invariants"]:
            assert set(invariant) >= {"name", "status"}
        assert artifact["event_log_digest"] == result["event_log_digest"]

    def test_report_control_plane_section(self, tmp_path):
        from elasticdl_tpu.telemetry.report import control_plane_section

        run_sim("fleet_mass_preemption", str(tmp_path))
        section = control_plane_section(str(tmp_path))
        assert section is not None
        run = section["runs"][0]
        assert run["plan"] == "fleet_mass_preemption"
        assert run["scale"]["heartbeats"]["total"] > 0
        assert "sweep_ms" in run["scale"]

    def test_runner_cli_list(self, capsys):
        from elasticdl_tpu.fleetsim.runner import main

        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        assert "fleet_mass_preemption" in out
        assert "fleet_master_kill_fanin" in out
        assert "budget_compliance" in out

    def test_uses_unmodified_production_servicer(self, tmp_path):
        """The no-forked-control-plane contract: the simulator's master
        objects ARE the production classes, not subclasses."""
        from elasticdl_tpu.fleetsim.plans import named_fleet_plan
        from elasticdl_tpu.fleetsim.sim import FleetConfig, FleetSimulator
        from elasticdl_tpu.master.autoscaler import Autoscaler

        sim = FleetSimulator(
            named_fleet_plan("fleet_mass_preemption"),
            FleetConfig(num_workers=10, num_tasks=10),
        )
        assert type(sim.servicer) is MasterServicer
        assert type(sim.task_d) is TaskDispatcher
        assert type(sim.autoscaler) is Autoscaler

    def test_autoscaler_in_loop_fires_on_backlog(self, tmp_path):
        """The REAL autoscaler rides the simulated tick: a mass
        preemption's requeue spike crosses the backlog SLO and the
        decision lands in the scale section — deterministically (the
        p95 tracker is deliberately unwired)."""
        from elasticdl_tpu.fleetsim.plans import named_fleet_plan
        from elasticdl_tpu.fleetsim.sim import FleetConfig, FleetSimulator

        plan = named_fleet_plan("fleet_mass_preemption")
        plan.seed = 77
        sim = FleetSimulator(
            plan,
            FleetConfig(
                num_workers=60,
                num_tasks=200,
                seed=77,
                autoscale_backlog_tasks=20,
            ),
        )
        result = sim.run()
        decisions = result["scale"]["autoscale_decisions"]
        assert decisions, "backlog spike never crossed the SLO"
        assert decisions[0]["action"] == "grow"
        assert result["invariants_ok"], result["invariants"]

    def test_no_nondaemon_threads_leak(self, tmp_path):
        before = {
            t
            for t in threading.enumerate()
            if not t.daemon
        }
        run_sim("fleet_master_kill_fanin", str(tmp_path), workers=40,
                num_tasks=60)
        after = {
            t
            for t in threading.enumerate()
            if not t.daemon
        }
        assert after <= before


class TestFleetPlans:
    def test_plans_serialize_roundtrip(self, tmp_path):
        from elasticdl_tpu.chaos.plan import FaultPlan
        from elasticdl_tpu.fleetsim.plans import builtin_fleet_plans

        for name, plan in builtin_fleet_plans().items():
            restored = FaultPlan.from_json(plan.to_json())
            assert restored.name == name
            assert [f.fault_id for f in restored.faults] == [
                f.fault_id for f in plan.faults
            ]
            # the mass-fault fraction survives the JSON round trip
            assert [f.fraction for f in restored.faults] == [
                f.fraction for f in plan.faults
            ]

    def test_old_plan_json_still_loads(self):
        """The new Fault.fraction field must default for pre-existing
        plan JSONs (wire compatibility, the PR-4 discipline)."""
        from elasticdl_tpu.chaos.plan import FaultPlan

        raw = json.dumps(
            {
                "name": "legacy",
                "faults": [
                    {
                        "kind": "preempt_worker",
                        "fault_id": "old",
                        "at_step": 3,
                    }
                ],
            }
        )
        plan = FaultPlan.from_json(raw)
        assert plan.faults[0].fraction == 0.0

    def test_chaos_runner_list_includes_fleet_plans(self, capsys):
        from elasticdl_tpu.chaos.runner import main

        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        assert "fleet_mass_preemption" in out
        assert "fleet_rolling_slice_loss" in out
        assert "heartbeat_merge_monotone" in out
        assert "fleet_recovery" in out
