"""The vectorized data plane: chunk scanner -> fused decode -> windowed
shuffle -> minibatches (data/fast_pipeline.py), and the cross-task
prefetcher (trainer/host_pipeline.py)."""

import threading

import numpy as np
import pytest

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.dataset import Dataset, batched_model_pipeline
from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.data.fast_pipeline import (
    FallbackNeeded,
    _vectorized_task_batches,
    build_task_batches,
)
from elasticdl_tpu.data.reader import (
    decode_concat_batch,
    decode_example,
    encode_example,
)
from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.trainer.host_pipeline import TaskPrefetcher
from elasticdl_tpu.trainer.state import Modes
from elasticdl_tpu.utils.model_utils import get_model_spec


def _frappe_setup(tmp_path, num_records=12000, records_per_task=6000):
    data_dir = synthetic.gen_frappe(
        str(tmp_path / "data"), num_records=num_records, num_shards=2, seed=0
    )
    reader = create_data_reader(data_dir, records_per_task=records_per_task)
    spec = get_model_spec(
        "", "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    )
    disp = TaskDispatcher(
        reader.create_shards(),
        records_per_task=records_per_task,
        num_epochs=1,
    )
    return reader, spec, disp


# ---- chunk API ------------------------------------------------------------


def test_scanner_next_chunk_roundtrip(tmp_path):
    path = str(tmp_path / "c.edlio")
    recs = [b"a" * 10, b"bb" * 20, b"xyz"]
    with recordio.Writer(path) as w:
        for r in recs:
            w.write(r)
    with recordio.Scanner(path) as sc:
        buf, lengths = sc.next_chunk()
        assert [int(x) for x in lengths] == [len(r) for r in recs]
        joined = bytes(memoryview(buf))
        assert joined == b"".join(recs)
        assert sc.next_chunk() is None


def test_pyimpl_scanner_next_chunk_matches(tmp_path):
    path = str(tmp_path / "p.edlio")
    recs = [b"one", b"two2", b"three33"]
    with recordio._pyimpl.Writer(path) as w:
        for r in recs:
            w.write(r)
    with recordio._pyimpl.Scanner(path) as sc:
        buf, lengths = sc.next_chunk(max_records=2)
        assert bytes(memoryview(buf)) == b"onetwo2"
        assert [int(x) for x in lengths] == [3, 4]
        buf2, lengths2 = sc.next_chunk(max_records=2)
        assert bytes(memoryview(buf2)) == b"three33"
        assert sc.next_chunk() is None


@pytest.mark.skipif(
    not recordio.native_available(), reason="native codec not built"
)
def test_decode_concat_batch_matches_per_record():
    rng = np.random.RandomState(0)
    examples = [
        {
            "feature": rng.randint(0, 100, 10).astype(np.int64),
            "label": np.int64(i % 2),
        }
        for i in range(17)
    ]
    payloads = [encode_example(e) for e in examples]
    buf = np.frombuffer(b"".join(payloads), dtype=np.uint8)
    lengths = np.array([len(p) for p in payloads], dtype=np.uint64)
    template = decode_example(payloads[0])
    out = decode_concat_batch(buf, lengths, template)
    assert out is not None
    for i, e in enumerate(examples):
        np.testing.assert_array_equal(out["feature"][i], e["feature"])
        assert out["label"][i] == e["label"]


# ---- vectorized task pipeline --------------------------------------------


def test_fast_path_covers_all_records_with_classic_batch_count(tmp_path):
    reader, spec, disp = _frappe_setup(tmp_path)
    _tid, task = disp.get(0)

    fast = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.TRAINING,
            reader.metadata,
            512,
            shuffle_records=True,
        )
    )
    classic = list(
        batched_model_pipeline(
            Dataset.from_generator(lambda: reader.read_records(task)),
            spec,
            Modes.TRAINING,
            reader.metadata,
            512,
            shuffle_records=True,
        )
    )
    # lockstep invariant: identical batch count and total records
    assert len(fast) == len(classic)
    assert sum(b[1].shape[0] for b in fast) == sum(
        b[1].shape[0] for b in classic
    )
    # same multiset of labels: every record exactly once
    fast_labels = np.sort(np.concatenate([b[1] for b in fast]))
    classic_labels = np.sort(np.concatenate([b[1] for b in classic]))
    np.testing.assert_array_equal(fast_labels, classic_labels)


def test_fast_path_deterministic_reiteration(tmp_path):
    reader, spec, disp = _frappe_setup(tmp_path)
    _tid, task = disp.get(0)
    ds = build_task_batches(
        reader,
        task,
        spec,
        Modes.TRAINING,
        reader.metadata,
        512,
        shuffle_records=True,
    )
    a = list(ds)
    b = list(ds)
    assert len(a) == len(b)
    for (fa, la), (fb, lb) in zip(a, b):
        np.testing.assert_array_equal(fa["feature"], fb["feature"])
        np.testing.assert_array_equal(la, lb)


def test_fast_path_eval_preserves_record_order(tmp_path):
    reader, spec, disp = _frappe_setup(tmp_path)
    _tid, task = disp.get(0)
    fast = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.EVALUATION,
            reader.metadata,
            512,
            shuffle_records=False,
        )
    )
    classic = list(
        batched_model_pipeline(
            Dataset.from_generator(lambda: reader.read_records(task)),
            spec,
            Modes.EVALUATION,
            reader.metadata,
            512,
        )
    )
    for (fa, la), (fb, lb) in zip(fast, classic):
        np.testing.assert_array_equal(fa["feature"], fb["feature"])
        np.testing.assert_array_equal(la, lb)


def test_fast_path_windowed_flush_emits_exact_batches(tmp_path):
    """A window smaller than the task still yields ceil(n/batch) batches
    with every record exactly once (full batches from every flush, one
    final partial)."""
    reader, spec, disp = _frappe_setup(
        tmp_path, num_records=5000, records_per_task=2500
    )
    _tid, task = disp.get(0)
    batches = list(
        _vectorized_task_batches(
            reader,
            task,
            spec.batch_parse,
            Modes.TRAINING,
            batch_size=400,
            shuffle_seed=0,
            window_bytes=900 * 100,  # ~ a few batches per window
        )
    )
    sizes = [b[1].shape[0] for b in batches]
    assert sum(sizes) == 2500
    assert len(batches) == -(-2500 // 400)
    assert all(s == 400 for s in sizes[:-1])
    assert sizes[-1] == 2500 % 400


def test_fallback_on_schema_the_native_decoder_rejects(tmp_path):
    """Records the fused decoder cannot batch (a string-keyed object
    column is fine — but sparse/mixed schemas are not) fall back to the
    classic path before the first yield."""
    path = str(tmp_path / "mixed")
    import os

    os.makedirs(path)
    with recordio.Writer(os.path.join(path, "s-000.edlio")) as w:
        # schema varies per record: vectorized decode must refuse
        for i in range(100):
            shape = (10,) if i % 2 == 0 else (11,)
            w.write(
                encode_example(
                    {
                        "feature": np.zeros(shape, dtype=np.int64),
                        "label": np.int64(0),
                    }
                )
            )
    reader = create_data_reader(path, records_per_task=100)
    disp = TaskDispatcher(
        reader.create_shards(), records_per_task=100, num_epochs=1
    )
    _tid, task = disp.get(0)

    calls = []

    def batch_parse(example_batch, mode):
        calls.append(len(example_batch))
        return example_batch, np.zeros(1)

    with pytest.raises(FallbackNeeded):
        list(
            _vectorized_task_batches(
                reader, task, batch_parse, Modes.TRAINING, 32, None
            )
        )


# ---- cross-task prefetcher ------------------------------------------------


def _fake_task_stream(n_tasks, batches_per_task):
    tasks = [(i, f"task{i}") for i in range(n_tasks)] + [(None, None)]
    it = iter(tasks)

    def next_task():
        return next(it)

    def make_batches(task):
        return [f"{task}-b{j}" for j in range(batches_per_task)]

    return next_task, make_batches


def test_prefetcher_preserves_task_and_batch_order():
    next_task, make_batches = _fake_task_stream(5, 3)
    out = []
    pf = TaskPrefetcher(next_task, make_batches, max_buffered_batches=4)
    for tid, task, batches in pf:
        out.append((tid, task, list(batches)))
    pf.close()
    assert [t[0] for t in out] == [0, 1, 2, 3, 4]
    assert out[2] == (2, "task2", ["task2-b0", "task2-b1", "task2-b2"])


def test_prefetcher_decodes_ahead_while_consumer_holds_a_task():
    """While the consumer sits inside task 0, the producer fills the
    buffer with upcoming batches (the whole point: decode overlaps the
    device dispatch)."""
    produced = []
    gate = threading.Event()

    def next_task():
        if len(produced) >= 3:
            return None, None
        tid = len(produced)
        produced.append(tid)
        return tid, f"t{tid}"

    def make_batches(task):
        for j in range(2):
            yield f"{task}-b{j}"

    pf = TaskPrefetcher(next_task, make_batches, max_buffered_batches=16)
    it = iter(pf)
    _tid, _task, batches = next(it)
    first = next(iter(batches))
    assert first == "t0-b0"
    # give the producer a moment: it should have pulled MORE tasks than
    # the one the consumer is holding
    for _ in range(100):
        if len(produced) >= 3:
            break
        gate.wait(0.05)
    assert len(produced) >= 2
    # drain cleanly
    list(batches)
    for _tid, _task, bs in it:
        list(bs)
    pf.close()


def test_prefetcher_propagates_producer_error():
    def next_task():
        return 0, "t0"

    def make_batches(task):
        yield "b0"
        raise RuntimeError("decode exploded")

    pf = TaskPrefetcher(next_task, make_batches)
    with pytest.raises(RuntimeError, match="decode exploded"):
        for _tid, _task, batches in pf:
            list(batches)
    pf.close()


def test_prefetcher_close_releases_blocked_producer():
    def next_task():
        return 0, "t0"

    def make_batches(task):
        for j in range(1000):
            yield j

    pf = TaskPrefetcher(next_task, make_batches, max_buffered_batches=2)
    it = iter(pf)
    next(it)  # start the producer; it will fill the queue and block
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetcher_byte_budget_blocks_producer():
    """Decode-ahead is bounded by bytes, not just batch count: two 1MB
    batches exhaust a 2MB budget even with a generous count bound."""
    import time as _time

    produced = []

    def next_task():
        if produced:
            return None, None
        produced.append(0)
        return 0, "t0"

    def make_batches(task):
        for j in range(50):
            yield np.zeros((256, 1024), np.float32)  # ~1MB each

    pf = TaskPrefetcher(
        next_task,
        make_batches,
        max_buffered_batches=1000,
        max_buffered_bytes=2 << 20,
    )
    it = iter(pf)
    _tid, _task, batches = next(it)
    _time.sleep(0.5)
    # ~2 batches fit the byte budget (+1 may be mid-put)
    assert pf._buffered_batches <= 3
    n = sum(1 for _ in batches)
    assert n == 50  # consuming releases credit; all batches arrive
    pf.close()


def test_deepfm_wire_dtype_narrows_and_widens():
    """deepfm ids ship int16 while the model's vocab fits, int32 when a
    user overrides input_dim past int16 range; the model output is
    identical either way (ids are cast to int32 on device)."""
    from elasticdl_tpu.models import deepfm_functional_api as dfm

    rng = np.random.RandomState(0)
    batch = {
        "feature": rng.randint(0, 5383, (8, 10)).astype(np.int64),
        "label": rng.randint(0, 2, 8).astype(np.int64),
    }
    dfm.custom_model()
    feats, labels = dfm.batch_parse(batch, Modes.TRAINING)
    assert feats["feature"].dtype == np.int16
    assert labels.dtype == np.int32

    dfm.custom_model(input_dim=40000)
    feats, _ = dfm.batch_parse(batch, Modes.TRAINING)
    assert feats["feature"].dtype == np.int32

    # the wire dtype is a pure function of the BUILT model, never of
    # batch history (a history-dependent dtype would flip int16<->int32
    # — one step recompile per flip — and diverge between lockstep
    # processes with different histories).  An id past int16 range
    # under an int16-resolved wire is >= 2^15 > input_dim, outside the
    # embedding vocab: corrupt data, raise rather than widen
    dfm.custom_model()  # resolves int16
    with pytest.raises(ValueError, match="exceeds int16 range"):
        dfm.batch_parse(
            dict(batch, feature=np.full((8, 10), 40000, np.int64)),
            Modes.TRAINING,
        )
    feats, _ = dfm.batch_parse(batch, Modes.TRAINING)
    assert feats["feature"].dtype == np.int16  # unchanged by the reject

    # negative ids are corrupt data (astype would wrap silently): raise
    with pytest.raises(ValueError, match="negative feature id"):
        dfm.batch_parse(
            dict(batch, feature=np.full((2, 10), -1, np.int64)),
            Modes.TRAINING,
        )

    # restore the default for other tests (module-level state)
    dfm.custom_model()
    # int16 ids drive the model fine (device-side widening)
    import jax

    model = dfm.custom_model()
    feats16, _ = dfm.batch_parse(batch, Modes.TRAINING)
    params = model.init(jax.random.PRNGKey(0), feats16, training=False)
    out = model.apply(params, feats16, training=False)
    assert np.asarray(out["logits"]).shape == (8,)


def test_device_parse_step_equivalence():
    """A train step fed uint8 wire batches through device_parse computes
    the same update as one fed host-normalized f32 batches (the classic
    path) — the wire format changes transfer bytes, not math."""
    import jax
    import optax

    from elasticdl_tpu.models import mnist_functional_api as mnist
    from elasticdl_tpu.trainer.state import TrainState
    from elasticdl_tpu.trainer.step import build_train_step

    rng = np.random.RandomState(0)
    raw = {"image": rng.randint(0, 255, (8, 28, 28)).astype(np.uint8)}
    labels = rng.randint(0, 10, 8).astype(np.int32)
    f32 = {"image": raw["image"].astype(np.float32) / 255.0}

    model = mnist.custom_model()

    def make_state():
        variables = model.init(
            jax.random.PRNGKey(0), f32, training=False
        )
        return TrainState.create(
            model.apply,
            variables.get("params", {}),
            optax.sgd(0.1),
            {k: v for k, v in variables.items() if k != "params"},
        )

    step_wire = build_train_step(
        mnist.loss, device_parse=mnist.device_parse
    )
    step_classic = build_train_step(mnist.loss)
    s1, m1 = step_wire(make_state(), raw, labels)
    s2, m2 = step_classic(make_state(), f32, labels)
    # same math, different programs: XLA fuses the in-step /255 with the
    # first conv, so values round differently in the last ulps — tight
    # tolerance, not bitwise (applies to the loss too)
    np.testing.assert_allclose(
        float(m1["loss"]), float(m2["loss"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params),
        jax.tree_util.tree_leaves(s2.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_prestacked_groups_match_plain_batches(tmp_path):
    """stack_k emits PreStacked groups whose (k, B, ...) contents equal
    the plain path's batches exactly (same permutation, same rows), with
    leftover batches plain."""
    from elasticdl_tpu.trainer.stacking import PreStacked

    reader, spec, disp = _frappe_setup(
        tmp_path, num_records=12000, records_per_task=6000
    )
    _tid, task = disp.get(0)
    plain = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.TRAINING,
            reader.metadata,
            512,
            shuffle_records=True,
        )
    )
    stacked = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.TRAINING,
            reader.metadata,
            512,
            shuffle_records=True,
            stack_k=4,
        )
    )
    # 6000 records / 512 = 11 full batches + tail -> 2 groups of 4,
    # then 3 plain full batches, then the partial tail
    assert isinstance(stacked[0], PreStacked)
    assert isinstance(stacked[1], PreStacked)
    assert all(not isinstance(x, PreStacked) for x in stacked[2:])
    assert len(stacked) == 2 + 3 + 1

    rebuilt = []
    for item in stacked:
        if isinstance(item, PreStacked):
            for i in range(item.num_steps):
                rebuilt.append(
                    (
                        {
                            k: v[i]
                            for k, v in item.features.items()
                        },
                        item.labels[i],
                    )
                )
        else:
            rebuilt.append(item)
    assert len(rebuilt) == len(plain)
    for (fa, la), (fb, lb) in zip(rebuilt, plain):
        np.testing.assert_array_equal(fa["feature"], fb["feature"])
        np.testing.assert_array_equal(la, lb)


def test_run_stacked_steps_dispatches_prestacked():
    """PreStacked items dispatch directly (one stacked call, no
    grouping), counting records and firing hooks per group."""
    from elasticdl_tpu.trainer import stacking

    class FakeTrainer:
        def __init__(self):
            self.stacked = []
            self.single = 0

        def place_stacked(self, tree):
            return tree

        def place_padded(self, tree):
            return tree

        def pad_batch(self, tree):
            return tree, 1

        def train_step(self, f, l):
            self.single += 1

        def train_steps_stacked(self, f, l):
            import jax

            self.stacked.append(
                jax.tree_util.tree_leaves(f)[0].shape[:2]
            )

    feats = {"x": np.zeros((4, 8, 3), np.float32)}
    labels = np.zeros((4, 8), np.int32)
    group = stacking.PreStacked(
        feats, labels, 32, {"x": feats["x"][0]}
    )
    tail = ({"x": np.zeros((5, 3), np.float32)}, np.zeros(5, np.int32))
    pre, post = [], []
    trainer = FakeTrainer()
    n = stacking.run_stacked_steps(
        lambda: trainer,
        iter([group, tail]),
        4,
        pre_batch=lambda f: pre.append(1),
        post_group=lambda: post.append(1),
    )
    assert n == 32 + 5
    assert trainer.stacked == [(4, 8)]
    assert trainer.single == 1  # the tail dispatches as a single step
    assert len(pre) == 4 + 1  # one hook call per step
    assert len(post) == 2  # one per dispatch group


def test_prestacked_caps_group_to_window(tmp_path):
    """stack_k larger than the task's full-batch count still groups:
    one PreStacked of however many full batches exist (auto k=36 over a
    32-batch task must not silently fall back to per-batch grouping)."""
    from elasticdl_tpu.trainer.stacking import PreStacked

    reader, spec, disp = _frappe_setup(
        tmp_path, num_records=4096, records_per_task=2048
    )
    _tid, task = disp.get(0)
    items = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.TRAINING,
            reader.metadata,
            512,
            shuffle_records=True,
            stack_k=36,
        )
    )
    # 2048/512 = 4 full batches -> one PreStacked(4), no tail
    assert len(items) == 1
    assert isinstance(items[0], PreStacked)
    assert items[0].num_steps == 4
    assert items[0].num_records == 2048


def test_prestacked_disabled_for_prediction_parse(tmp_path):
    """An explicit int stack_k with a prediction-shaped batch_parse
    (no labels) downgrades to plain batches instead of crashing."""
    from elasticdl_tpu.trainer.stacking import PreStacked

    reader, spec, disp = _frappe_setup(
        tmp_path, num_records=4096, records_per_task=2048
    )
    _tid, task = disp.get(0)
    items = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.PREDICTION,
            reader.metadata,
            512,
            shuffle_records=False,
            stack_k=4,
        )
    )
    assert all(not isinstance(x, PreStacked) for x in items)
    assert sum(x["feature"].shape[0] for x in items) == 2048


def test_prefetcher_charges_prestacked_groups_their_step_count():
    """A PreStacked group counts its k steps against the decode-ahead
    batch budget, so 'two dispatch groups ahead' means two GROUPS, not
    2*k of them."""
    import time as _time

    from elasticdl_tpu.trainer.stacking import PreStacked

    def next_task():
        return 0, "t0"

    def make_batches(task):
        while True:
            feats = {"x": np.zeros((8, 4, 2), np.float32)}
            yield PreStacked(
                feats, np.zeros((8, 4), np.int32), 32, feats["x"][0]
            )

    pf = TaskPrefetcher(
        next_task,
        make_batches,
        max_buffered_batches=16,  # two 8-step groups
        max_buffered_bytes=1 << 30,
    )
    it = iter(pf)
    next(it)
    _time.sleep(0.5)
    # the QUEUE must hold only ~2 groups (a regression charging groups
    # 1 instead of num_steps would admit ~16 of them before blocking;
    # the budget counter itself can never exceed the cap by much, so
    # asserting on it alone would be vacuous)
    assert pf._q.qsize() <= 4, pf._q.qsize()
    assert pf._buffered_batches >= 16  # the admitted groups charged 8 each
    pf.close()


def test_census_batch_parse_matches_dataset_fn(tmp_path):
    """The feature-column model's vectorized parse equals the per-record
    dataset_fn path batch for batch (same shuffle stream policy)."""
    data_dir = synthetic.gen_census(
        str(tmp_path / "c"), num_records=1200, num_shards=1, seed=0
    )
    reader = create_data_reader(data_dir, records_per_task=1200)
    spec = get_model_spec(
        "", "census_dnn_model.census_functional_api.custom_model"
    )
    assert spec.batch_parse is not None
    disp = TaskDispatcher(
        reader.create_shards(), records_per_task=1200, num_epochs=1
    )
    _tid, task = disp.get(0)
    fast = list(
        build_task_batches(
            reader,
            task,
            spec,
            Modes.EVALUATION,  # no shuffle: order-comparable
            reader.metadata,
            256,
        )
    )
    # force the TRUE per-record dataset_fn path for the comparison side
    # (otherwise batched_model_pipeline would prefer batch_parse and the
    # test would compare batch_parse with itself)
    spec.batch_parse = None
    classic = list(
        batched_model_pipeline(
            Dataset.from_generator(lambda: reader.read_records(task)),
            spec,
            Modes.EVALUATION,
            reader.metadata,
            256,
        )
    )
    assert len(fast) == len(classic) == 5
    for (fa, la), (fb, lb) in zip(fast, classic):
        assert set(fa) == set(fb)
        for k in fa:
            np.testing.assert_array_equal(fa[k], fb[k])
        np.testing.assert_array_equal(la, lb)
