"""Telemetry subsystem tests (ISSUE 2).

Covers the four acceptance surfaces: registry exposition round-trip,
event-log schema for a scripted preempt -> reform sequence, ``/healthz``
during quiesce (plus ``/metrics`` family count), and the report CLI on a
canned run dir — plus the overhead contract (disabled per-step path is a
single early-return) and the satellite fixes (TensorboardService
shutdown, Timing routing, chaos_result.json, naming lint).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.telemetry import report as report_cli
from elasticdl_tpu.telemetry import worker_hooks
from elasticdl_tpu.telemetry.events import EventLog, read_events
from elasticdl_tpu.telemetry.httpd import TelemetryHTTPServer
from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry
from elasticdl_tpu.telemetry.registry import (
    STEP_LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_worker_hooks():
    worker_hooks.uninstall()
    yield
    worker_hooks.uninstall()


# ---- registry / exposition --------------------------------------------------


def _parse_exposition(text: str) -> dict[str, float]:
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        samples[name] = float(value)
    return samples


def test_registry_exposition_round_trip():
    r = MetricsRegistry()
    r.counter("demo_total", "a counter").inc(3)
    r.gauge("demo_gauge", "a gauge").set(1.5)
    h = r.histogram("demo_seconds", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.exposition()
    samples = _parse_exposition(text)
    assert samples["demo_total"] == 3
    assert samples["demo_gauge"] == 1.5
    # cumulative buckets: 0.05 <= 0.1; 0.5 <= 1.0; 5.0 -> +Inf
    assert samples['demo_seconds_bucket{le="0.1"}'] == 1
    assert samples['demo_seconds_bucket{le="1"}'] == 2
    assert samples['demo_seconds_bucket{le="+Inf"}'] == 3
    assert samples["demo_seconds_count"] == 3
    assert abs(samples["demo_seconds_sum"] - 5.55) < 1e-9
    assert "# TYPE demo_seconds histogram" in text
    assert "# HELP demo_total a counter" in text


def test_registry_labels_and_reregistration():
    r = MetricsRegistry()
    a = r.counter("family_total", labels={"type": "a"})
    b = r.counter("family_total", labels={"type": "b"})
    assert a is not b
    assert r.counter("family_total", labels={"type": "a"}) is a
    a.inc()
    samples = _parse_exposition(r.exposition())
    assert samples['family_total{type="a"}'] == 1
    assert samples['family_total{type="b"}'] == 0
    with pytest.raises(ValueError):
        r.gauge("family_total")  # kind conflict
    with pytest.raises(ValueError):
        r.counter("NotSnakeCase")


def test_counter_set_total_is_monotone():
    r = MetricsRegistry()
    c = r.counter("mirrored_total")
    c.set_total(10)
    c.set_total(4)  # must never go down
    assert c.value == 10
    with pytest.raises(ValueError):
        c.inc(-1)


def test_histogram_default_buckets_are_log_spaced_step_range():
    h = Histogram()
    assert h.bounds == STEP_LATENCY_BUCKETS
    assert h.bounds[0] == 0.001 and h.bounds[-1] == 60.0
    h.observe(0.004)
    snap = h.snapshot()
    assert snap["buckets"][0.005] == 1
    assert snap["buckets"][0.0025] == 0


def test_collect_callback_runs_per_scrape():
    r = MetricsRegistry()
    g = r.gauge("fresh_gauge")
    calls = []
    r.add_collect_callback(lambda reg: (calls.append(1), g.set(len(calls))))
    r.exposition()
    samples = _parse_exposition(r.exposition())
    assert samples["fresh_gauge"] == 2


def test_percentile_nearest_rank():
    samples = [float(i) for i in range(1, 101)]
    assert report_cli.percentile(samples, 50) == 50.0
    assert report_cli.percentile(samples, 95) == 95.0
    assert report_cli.percentile(samples, 99) == 99.0
    assert report_cli.percentile([7.0], 99) == 7.0


# ---- event log schema: scripted preempt -> reform ---------------------------


def _scripted_preempt_reform(tmp_path):
    """Drive master-side telemetry through a full preempt -> reform:
    lease + complete a task, kill a worker, recover its task, re-form."""
    telemetry = MasterTelemetry(str(tmp_path))
    task_d = TaskDispatcher(
        {"s": (0, 128)}, records_per_task=64, shuffle_seed=1
    )
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)

    telemetry.job_start("training_only", 2)
    tid0, _ = task_d.get(worker_id=0)
    task_d.report(tid0, True, exec_counters={"time_batch_process_ms": 21})
    servicer.report_version(
        type("R", (), {"worker_id": 0, "model_version": 2})()
    )
    tid1, _ = task_d.get(worker_id=1)
    # worker 1 dies: master marks it, recovers its lease, re-forms
    telemetry.worker_dead([1], generation=0)
    new_gen = servicer.bump_cluster_version()
    telemetry.reform_start(new_gen, [1], "worker_failure", old_world_size=2)
    task_d.recover_tasks(1)
    telemetry.reform_complete(new_gen, old_world_size=2, new_world_size=2)
    telemetry.reform_latency(new_gen, 1.25)
    telemetry.job_end(0)
    return os.path.join(str(tmp_path), "events.jsonl")


def test_event_log_schema_preempt_reform(tmp_path):
    path = _scripted_preempt_reform(tmp_path)
    events = read_events(path)
    for record in events:
        assert {"time", "monotonic", "event"} <= set(record)
        assert isinstance(record["time"], float)
    names = [e["event"] for e in events]
    assert names[0] == "job_start"
    assert names[-1] == "job_end"
    for expected in (
        "task_dispatch",
        "task_done",
        "worker_dead",
        "reform_start",
        "task_recovered",
        "reform_complete",
        "reform_latency",
    ):
        assert expected in names, f"missing {expected} in {names}"
    # recovery happens INSIDE the reform window
    assert names.index("reform_start") < names.index("task_recovered")
    assert names.index("task_recovered") < names.index("reform_complete")
    done = next(e for e in events if e["event"] == "task_done")
    assert done["worker_id"] == 0
    assert done["records"] == 64
    assert done["time_batch_process_ms"] == 21  # exec counters ride along
    start = next(e for e in events if e["event"] == "reform_start")
    assert start["generation"] == 1
    assert start["dead_workers"] == [1]
    assert start["old_world_size"] == 2
    complete = next(e for e in events if e["event"] == "reform_complete")
    assert complete["new_world_size"] == 2
    recovered = next(e for e in events if e["event"] == "task_recovered")
    assert recovered["reason"] == "report_failed"


def test_quiesce_events_via_servicer_sink(tmp_path):
    telemetry = MasterTelemetry(str(tmp_path))
    task_d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)
    servicer.begin_quiesce()
    assert servicer.is_quiescing
    servicer.end_quiesce()
    telemetry.events.flush()  # master event log writes asynchronously
    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    names = [e["event"] for e in events]
    assert names == ["quiesce_begin", "quiesce_end"]
    assert events[0]["generation"] == 0
    assert events[1]["generation"] == 1  # end_quiesce bumps the generation


# ---- HTTP endpoint ----------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.headers.get("Content-Type", ""), resp.read().decode()


def test_metrics_endpoint_and_healthz_during_quiesce(tmp_path):
    telemetry = MasterTelemetry()
    task_d = TaskDispatcher({"s": (0, 128)}, records_per_task=64)
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)
    server = TelemetryHTTPServer(
        telemetry.registry,
        health_fn=telemetry.build_health_fn("training_only"),
        port=0,
    )
    server.start()
    try:
        ctype, text = _get(server.port, "/metrics")
        assert "text/plain" in ctype and "version=0.0.4" in ctype
        families = [
            line.split()[2]
            for line in text.splitlines()
            if line.startswith("# TYPE")
        ]
        assert len(families) >= 8, families
        assert len(set(families)) == len(families)
        # acceptance: valid exposition — every sample line parses
        _parse_exposition(text)

        _, body = _get(server.port, "/healthz")
        health = json.loads(body)
        assert health["status"] == "ok" and health["quiescing"] is False
        servicer.begin_quiesce()
        _, body = _get(server.port, "/healthz")
        health = json.loads(body)
        assert health["status"] == "quiescing"
        assert health["quiescing"] is True
        assert health["generation"] == 0
        assert "model_version" in health and "live_workers" in health
        with pytest.raises(urllib.error.HTTPError):
            _get(server.port, "/nope")
    finally:
        server.stop()


# ---- worker hooks / overhead contract ---------------------------------------


def test_record_step_disabled_is_single_early_return(monkeypatch):
    """With telemetry not installed the per-step path must not even read
    the clock: poison every timer the module could reach and call the
    hook — any work beyond the None check would raise."""
    assert worker_hooks.get_recorder() is None

    def boom(*_a, **_k):
        raise AssertionError("disabled path touched the clock")

    monkeypatch.setattr(worker_hooks.time, "monotonic", boom)
    monkeypatch.setattr(worker_hooks.time, "time", boom, raising=False)
    worker_hooks.record_step(5, 32)
    worker_hooks.emit_event("anything_here")
    worker_hooks.publish_timing(None)  # would explode on .totals_ms()


def test_step_recorder_samples_and_generation_stamp(tmp_path):
    worker_hooks.install(
        str(tmp_path), worker_id=3, process_id=1, generation=2
    )
    worker_hooks.record_step(10, 32)
    worker_hooks.record_step(11, 32)
    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    assert [e["step"] for e in events] == [10, 11]
    assert all(e["generation"] == 2 for e in events)
    assert all(e["worker_id"] == 3 for e in events)
    assert "duration_secs" not in events[0]  # no interval yet
    assert events[1]["duration_secs"] >= 0


def test_publish_timing_routes_buckets(tmp_path):
    from elasticdl_tpu.utils.timing_utils import Timing

    timing = Timing(enabled=True)
    with timing.record("batch_process"):
        time.sleep(0.002)
    worker_hooks.install(str(tmp_path), worker_id=0)
    worker_hooks.publish_timing(timing)
    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    assert events[-1]["event"] == "worker_timing"
    assert events[-1]["time_batch_process_ms"] >= 1
    assert timing.totals_ms()["time_batch_process_ms"] >= 1


def test_exec_counters_mirrored_to_metrics():
    telemetry = MasterTelemetry()
    task_d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    servicer = MasterServicer(32, task_d)
    telemetry.attach(task_d, servicer)
    tid, _ = task_d.get(0)
    task_d.report(tid, True, exec_counters={"time_device_step_ms": 42})
    samples = _parse_exposition(telemetry.registry.exposition())
    assert (
        samples['elasticdl_worker_time_ms_total{bucket="device_step"}'] == 42
    )
    assert samples['elasticdl_tasks_completed_total{type="training"}'] == 1
    assert samples["elasticdl_records_processed_total"] == 64


def test_dispatcher_on_task_done_observer():
    calls = []

    class Observer:
        def on_task_done(self, task_id, task, worker_id, success, counters):
            calls.append((task_id, worker_id, success, counters))

    task_d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    task_d.add_observer(Observer())
    tid, _ = task_d.get(worker_id=7)
    task_d.report(tid, True, exec_counters={"time_x_ms": 1})
    task_d.report(999, True)  # stale: must NOT reach on_task_done
    assert calls == [(tid, 7, True, {"time_x_ms": 1})]


# ---- report CLI on a canned run dir -----------------------------------------


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record) + "\n")


def _canned_run_dir(tmp_path) -> str:
    """Two generations of step samples separated by a 4s gap caused by a
    preemption, with a recovered task inside the gap."""
    run = tmp_path / "run"
    t0 = 1000.0
    events = []
    for i in range(10):
        events.append(
            {
                "time": 1.7e9 + t0 + i * 0.1,
                "monotonic": t0 + i * 0.1,
                "event": "step",
                "step": i,
                "generation": 0,
                "worker_id": 0,
                "records": 32,
                **({"duration_secs": 0.1} if i else {}),
            }
        )
    gap_start = t0 + 0.9
    events.append(
        {
            "time": 1.7e9 + gap_start + 4.0,
            "monotonic": gap_start + 4.0,
            "event": "task_recovered",
            "task_id": 5,
            "reason": "report_failed",
        }
    )
    for i in range(5):
        events.append(
            {
                "time": 1.7e9 + gap_start + 5.0 + i * 0.2,
                "monotonic": gap_start + 5.0 + i * 0.2,
                "event": "step",
                "step": 8 + i,
                "generation": 1,
                "worker_id": 2,
                "records": 32,
                **({"duration_secs": 0.2} if i else {}),
            }
        )
    events.append(
        {
            "time": 1.7e9,
            "monotonic": t0 + 12.0,
            "event": "task_done",
            "task_id": 9,
            "worker_id": 2,
            "records": 64,
            "time_batch_process_ms": 30,
        }
    )
    _write_jsonl(str(run / "telemetry" / "events.jsonl"), events)
    _write_jsonl(
        str(run / "chaos_events.jsonl"),
        [
            {
                "fault_id": "f0",
                "kind": "preempt",
                "process_id": 1,
                "step": 8,
                "time": 1.7e9 + gap_start + 0.05,
                "monotonic": gap_start + 0.05,
            }
        ],
    )
    with open(str(run / "chaos_result.json"), "w", encoding="utf-8") as f:
        json.dump(
            {
                "plan": "preempt_one_worker",
                "seed": 0,
                "invariants": [
                    {"name": "exactly_once", "status": "PASS"},
                    {"name": "version_monotonic", "status": "PASS"},
                ],
                "invariants_ok": True,
            },
            f,
        )
    return str(run)


def test_report_cli_on_canned_run_dir(tmp_path, capsys):
    run_dir = _canned_run_dir(tmp_path)
    out_path = str(tmp_path / "report.json")
    rc = report_cli.main([run_dir, "--output", out_path])
    assert rc == 0
    text = capsys.readouterr().out
    assert "p50=" in text and "p95=" in text and "p99=" in text
    assert "downtime 5.00s" in text  # the injected 5s gap, attributed
    assert "cause: f0 (preempt" in text
    assert "plan=preempt_one_worker" in text
    assert "exactly_once=PASS" in text

    with open(out_path, encoding="utf-8") as f:
        report = json.load(f)
    run = report["runs"][os.path.join("telemetry", "events.jsonl")]
    gen0 = run["generations"]["0"]
    assert gen0["steps"] == 10
    assert abs(gen0["step_time_p50_ms"] - 100.0) < 1e-6
    downtime = run["reform_downtime"][0]
    assert downtime["downtime_secs"] > 0
    assert downtime["cause"]["fault_id"] == "f0"
    assert downtime["tasks_recovered"] == 1
    assert run["records_per_sec_by_worker"]["0"] > 0
    assert run["worker_time_ms"]["batch_process"] == 30
    assert report["chaos_result"]["invariants_ok"] is True


def test_report_cli_empty_dir(tmp_path):
    # a run dir with no telemetry yet is a VALID state reported as
    # "no data" (ISSUE 10 satellite) — only a non-directory is misuse
    assert report_cli.main([str(tmp_path)]) == 0
    assert report_cli.main([str(tmp_path / "missing")]) == 2


def test_report_handles_torn_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"event": "step", "monotonic": 1.0}) + "\n")
        f.write('{"event": "step", "monoto')  # killed writer
    assert len(read_events(path)) == 1


# ---- satellite: TensorboardService shutdown ---------------------------------


def _sleeper():
    return subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])


def test_tensorboard_close_reaps_subprocess(tmp_path):
    from elasticdl_tpu.master.tensorboard_service import TensorboardService

    service = TensorboardService(str(tmp_path))
    service.tb_process = _sleeper()
    service.close()
    assert service.tb_process is None  # terminated AND reaped, no zombie


def test_tensorboard_keep_running_exits_promptly_on_check_fn(tmp_path):
    from elasticdl_tpu.master.tensorboard_service import TensorboardService

    service = TensorboardService(str(tmp_path))
    service.tb_process = _sleeper()
    try:
        flips = {"n": 0}

        def check_fn():
            flips["n"] += 1
            return flips["n"] < 3

        started = time.monotonic()
        service.keep_running(check_fn=check_fn, poll_secs=30.0)
        assert time.monotonic() - started < 5.0  # not a full poll window
    finally:
        service.close()


# ---- satellite: chaos_result.json + naming lint -----------------------------


def test_chaos_runner_writes_result_json(tmp_path):
    from elasticdl_tpu.chaos.runner import write_result_json

    report = {
        "plan": "preempt_one_worker",
        "seed": 7,
        "corrupt": "",
        "invariants": [
            {"name": "exactly_once", "status": "PASS", "violations": []}
        ],
        "invariants_ok": True,
        "rc": 0,
        "reform_latency_secs": 2.5,
    }
    path = write_result_json(report, str(tmp_path))
    with open(path, encoding="utf-8") as f:
        result = json.load(f)
    assert result["plan"] == "preempt_one_worker"
    assert result["seed"] == 7
    assert result["invariants"] == [
        {"name": "exactly_once", "status": "PASS"}
    ]
    assert result["invariants_ok"] is True


def test_telemetry_naming_lint_passes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "check_telemetry_names.py")],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---- master wiring (in-process, no workers) ---------------------------------


def test_master_serves_metrics_and_events(tmp_path):
    """A real Master (instance_backend=none) exposes /metrics with ≥8
    families and writes job lifecycle events to --telemetry_dir."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.master.main import build_master
    from elasticdl_tpu.utils.args import parse_master_args

    train = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=64, num_shards=1, seed=1
    )
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--records_per_task",
            "32",
            "--minibatch_size",
            "32",
            "--num_workers",
            "0",
            "--port",
            "0",
            "--telemetry_dir",
            str(tmp_path / "telemetry"),
        ]
    )
    master = build_master(args)
    master.prepare()
    try:
        assert master.metrics_port is not None
        _, text = _get(master.metrics_port, "/metrics")
        families = [
            line.split()[2]
            for line in text.splitlines()
            if line.startswith("# TYPE")
        ]
        assert len(families) >= 8
        _, body = _get(master.metrics_port, "/healthz")
        assert json.loads(body)["status"] == "ok"
    finally:
        master.request_stop()
        master.stop()
    events = read_events(str(tmp_path / "telemetry" / "events.jsonl"))
    names = [e["event"] for e in events]
    assert "job_start" in names and "job_end" in names
