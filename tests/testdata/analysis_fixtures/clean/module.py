"""A clean fixture: every checker passes here (rc 0)."""

import threading


class Quiet:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}  # guarded-by: _lock

    def set(self, key, value):
        with self._lock:
            self._state[key] = value


def spawn(fn):
    threading.Thread(target=fn, daemon=True).start()


def emit(registry):
    registry.counter("clean_total")
