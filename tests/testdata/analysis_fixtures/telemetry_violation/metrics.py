"""Seeded telemetry-names violations: non-snake-case + two-site name."""


def register(registry):
    registry.counter("BadCamelName")  # VIOLATION: not snake_case
    registry.counter("twice_registered")


def register_again(registry):
    registry.counter("twice_registered")  # VIOLATION: second site
