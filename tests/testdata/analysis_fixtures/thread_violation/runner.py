"""Seeded thread-discipline violation: non-daemon, never-joined thread."""

import threading


def fire_and_forget(fn):
    # VIOLATION: not daemon, never joined — hangs interpreter exit
    orphan = threading.Thread(target=fn)
    orphan.start()
    return orphan


def joined(fn):
    worker = threading.Thread(target=fn)
    worker.start()
    worker.join()
    return worker


def daemonized(fn):
    threading.Thread(target=fn, daemon=True).start()
