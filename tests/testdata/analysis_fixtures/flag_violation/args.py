"""Seeded flag-hygiene violations in a miniature flag module."""

import argparse


def _add_train_params(parser):
    parser.add_argument("--minibatch_size", type=int, default=64)
    # VIOLATION (FH3): optional shared flag whose default is not None —
    # when unset it still lands in every reconstructed worker argv
    parser.add_argument(
        "--new_feature", type=int, default=0, required=False
    )


def _add_master_params(parser):
    parser.add_argument("--port", type=int, default=0)
    # VIOLATION (FH1): master-group flag missing from _MASTER_ONLY_FLAGS
    parser.add_argument("--leaky_master_knob", default="")


_MASTER_GROUPS = (_add_train_params, _add_master_params)
_WORKER_GROUPS = (_add_train_params,)

_MASTER_ONLY_FLAGS = frozenset(
    {
        "port",
        # VIOLATION (FH2): stale entry no add_argument defines
        "removed_long_ago",
    }
)


def build_arguments_from_parsed_result(args, filter_args=frozenset()):
    argv = []
    for key, value in sorted(vars(args).items()):
        if key in filter_args or value is None:
            continue
        argv.extend([f"--{key}", str(value)])
    return argv
