"""Seeded hot-path violations: a clock read on the disabled fast path
and a stray print outside the CLI allowlist."""

import time

_active = None


def record_step(step):  # elastic-lint: hot-path
    t0 = time.monotonic()  # VIOLATION: clock read before the gate
    recorder = _active
    if recorder is None:
        return
    recorder.record(step, t0)


def helper():
    print("debugging")  # VIOLATION: print outside CLI modules


def _decorator(fn):
    return fn


@_decorator
def decorated_gate():  # elastic-lint: hot-path
    items = [1, 2, 3]  # VIOLATION: allocation on a decorated hot gate
    recorder = _active
    if recorder is None:
        return None
    return recorder.use(items)
