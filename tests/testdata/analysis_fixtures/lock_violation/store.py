"""Seeded lock-discipline violation: unlocked write of a guarded attr."""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock (writes)

    def put(self, key, value):
        with self._lock:
            self._items[key] = value

    def drop(self, key):
        # VIOLATION: guarded write outside the lock
        self._items.pop(key, None)

    def peek_count(self):
        return self._count  # fine: writes-only guard, GIL-atomic read

    def bump(self):
        # VIOLATION: writes-guarded attr written unlocked
        self._count += 1

    # lock-holding: _other_lock — callers: __init__ (single-threaded
    # construction); the prose above must NOT exempt this method
    def sneaky(self, key):
        # VIOLATION: _items is _lock-guarded, and the lock-holding
        # annotation names a DIFFERENT lock; the "(single-threaded)"
        # prose inside it must not disable analysis either
        self._items.pop(key, None)
