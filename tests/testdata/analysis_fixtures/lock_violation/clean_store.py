"""The same shape, clean: every access under the lock or documented."""

import threading


class CleanStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock

    def put(self, key, value):
        with self._lock:
            self._items[key] = value

    # lock-holding: _lock
    def _drop_locked(self, key):
        self._items.pop(key, None)

    def drop(self, key):
        with self._lock:
            self._drop_locked(key)
