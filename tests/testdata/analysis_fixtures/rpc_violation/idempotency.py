"""Miniature retry-safety registry for the fixture tree."""

IDEMPOTENCY = {
    "classified_call": ("read-only", "fixture: no server-side effect"),
    "forbidden_call": ("not-retryable", "fixture: duplicates double"),
}

# deliberately the computed frozenset(<name>) shape the real repo uses
# (MASTER_RETRYABLE_METHODS = frozenset(_METHODS)): the checker must
# resolve the reference, or the not-retryable rule goes vacuous exactly
# where the master's retryable set lives
_ALL_CALLS = (
    "classified_call",
    "forbidden_call",  # VIOLATION: not-retryable in a retryable set
)
RETRYABLE_METHODS = frozenset(_ALL_CALLS)
