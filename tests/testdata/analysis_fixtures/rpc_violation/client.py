"""Seeded rpc-contract violations: a deadline-less client construction
and a server method table naming a method the registry never classified."""


class RpcClient:
    def __init__(self, addr, deadlines=None):
        self._addr = addr
        self._deadlines = deadlines

    def _call(self, name, request, timeout=None):
        if timeout is None and self._deadlines is not None:
            timeout = self._deadlines.deadline_for(name)
        return None


class FixtureClient(RpcClient):
    pass


_METHODS = (
    "classified_call",
    "brand_new_unclassified_call",  # VIOLATION: not in IDEMPOTENCY
)


def connect(addr):
    # VIOLATION: no deadlines= — this client can hang forever
    return FixtureClient(addr)


def connect_properly(addr, policy):
    return FixtureClient(addr, deadlines=policy)
