"""Export/load round-trip parity per zoo model family (satellite of the
serving PR: the load path previously had no direct coverage).

For each family: build the live model, PERTURB its initialized params
(so an injection bug that silently keeps fresh-init weights cannot
pass), export, then ``load_exported_model`` -> ``rebuild_variables``
and require bitwise-close output parity between the live perturbed
model and the reloaded one on a real decoded batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_reader import RecordIODataReader
from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
from elasticdl_tpu.trainer.step import resolve_optimizer
from elasticdl_tpu.utils.export_utils import (
    export_model,
    load_exported_model,
    read_manifest,
    rebuild_variables,
)
from elasticdl_tpu.utils.model_utils import get_model_spec

# one representative per dataset family (the full per-model sweep lives
# in test_model_zoo; the round-trip contract is per feature/variable
# SHAPE family, which these cover: image tensor, CTR id+value dict,
# hashed-categorical dict, tabular dict, plain float features)
FAMILIES = [
    ("mnist_functional_api.mnist_functional_api.custom_model", "mnist"),
    ("deepfm_functional_api.deepfm_functional_api.custom_model", "frappe"),
    ("census_dnn_model.census_functional_api.custom_model", "census"),
    ("heart_functional_api.heart_functional_api.custom_model", "heart"),
    ("odps_iris_dnn_model.odps_iris_dnn_model.custom_model", "iris"),
]


def _first_batch(spec, data_dir, batch_size=8):
    reader = RecordIODataReader(data_dir=data_dir)
    shards = reader.create_shards()
    name, (start, count) = next(iter(shards.items()))

    class _Task:
        shard_name = name

    _Task.start, _Task.end = start, start + count
    ds = Dataset.from_generator(lambda: reader.read_records(_Task))
    ds = spec.dataset_fn(ds, Modes.TRAINING, reader.metadata)
    for features, _labels in ds.batch(batch_size):
        return features
    raise AssertionError("no batch decoded")


class _Args:
    model_zoo = ""
    model_params_dict: dict = {}

    def __init__(self, model_def):
        self.model_def = model_def


@pytest.mark.parametrize("model_def,gen", FAMILIES)
def test_export_load_rebuild_parity(model_def, gen, tmp_path):
    data_dir = synthetic.GENERATORS[gen](
        str(tmp_path / gen), num_records=32, num_shards=1, seed=0
    )
    spec = get_model_spec("", model_def)
    model = spec.build_model()
    features = _first_batch(spec, data_dir)

    params, model_state = init_model(model, features)
    # perturb: exported weights must be distinguishable from fresh init
    params = jax.tree_util.tree_map(lambda x: x * 1.5 + 0.05, params)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    state = state.replace(step=jnp.asarray(17, jnp.int32))
    live_out = model.apply(
        {"params": params, **model_state}, features, training=False
    )

    export_dir = export_model(
        str(tmp_path / "export"), state, spec, _Args(model_def)
    )
    assert read_manifest(export_dir)["model_version"] == 17

    model2, flat_params, flat_state = load_exported_model(export_dir)
    sample = jax.tree_util.tree_map(
        lambda x: np.asarray(x)[:1], features
    )
    params2, model_state2 = rebuild_variables(
        model2, sample, flat_params, flat_state
    )
    reload_out = model2.apply(
        {"params": params2, **model_state2}, features, training=False
    )
    _assert_trees_close(live_out, reload_out)

    # falsification: fresh-init (unperturbed) weights must NOT match —
    # otherwise this parity check would be vacuous
    fresh_params, fresh_state = init_model(model2, sample)
    fresh_out = model2.apply(
        {"params": fresh_params, **fresh_state}, features, training=False
    )
    assert not _trees_close(live_out, fresh_out)


def _assert_trees_close(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=1e-6
        )


def _trees_close(a, b) -> bool:
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    return all(
        np.allclose(np.asarray(x), np.asarray(y), atol=1e-6)
        for x, y in zip(la, lb)
    )
