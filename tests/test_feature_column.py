"""Feature columns: host transform + in-jit DenseFeatures.

Covers the census-model column recipe (reference
census_feature_columns.py:24-40: numeric + hash-bucket -> embedding(16))."""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu import feature_column as fc


RAW = {
    "age": np.array([25.0, 52.0]),
    "workclass": np.array(["Private", "Self-emp"]),
    "hours": np.array([40.0, 12.0]),
    "cls": np.array([1, 7]),
}


def test_numeric_and_hash_transform():
    cols = [
        fc.numeric_column("age"),
        fc.categorical_column_with_hash_bucket("workclass", 64),
    ]
    out = fc.transform_features(cols, RAW)
    assert out["age"].dtype == np.float32
    assert out["workclass"].dtype == np.int32
    assert np.all((out["workclass"] >= 0) & (out["workclass"] < 64))
    # deterministic (sha256, hash_utils.string_to_id)
    again = fc.transform_features(cols, RAW)
    np.testing.assert_array_equal(out["workclass"], again["workclass"])


def test_vocab_and_identity_oov_to_absent():
    vocab = fc.categorical_column_with_vocabulary_list(
        "workclass", ["Private", "Gov"]
    )
    ids = vocab.transform(RAW)
    np.testing.assert_array_equal(ids, [0, -1])  # OOV -> -1

    ident = fc.categorical_column_with_identity("cls", num_buckets=4)
    np.testing.assert_array_equal(ident.transform(RAW), [1, -1])


def test_bucketized():
    col = fc.bucketized_column(fc.numeric_column("age"), [30.0, 50.0])
    np.testing.assert_array_equal(col.transform(RAW), [0, 2])
    assert col.num_buckets == 3


def test_dense_features_census_recipe():
    cols = [
        fc.numeric_column("age"),
        fc.numeric_column("hours"),
        fc.embedding_column(
            fc.categorical_column_with_hash_bucket("workclass", 64),
            dimension=16,
        ),
    ]
    feats = fc.transform_features(cols, RAW)
    layer = fc.DenseFeatures(columns=tuple(cols))
    params = layer.init(jax.random.PRNGKey(0), feats)
    out = layer.apply(params, feats)
    assert out.shape == (2, 1 + 1 + 16)
    # numeric passthrough in column order
    np.testing.assert_allclose(np.asarray(out)[:, 0], RAW["age"])
    np.testing.assert_allclose(np.asarray(out)[:, 1], RAW["hours"])
    # embedding params named after the column -> policy-visible
    assert "workclass_embedding" in params["params"]


def test_dense_features_indicator_and_bucketized():
    cols = [
        fc.indicator_column(
            fc.categorical_column_with_identity("cls", num_buckets=8)
        ),
        fc.bucketized_column(fc.numeric_column("age"), [30.0]),
    ]
    feats = fc.transform_features(cols, RAW)
    layer = fc.DenseFeatures(columns=tuple(cols))
    params = layer.init(jax.random.PRNGKey(0), feats)
    out = np.asarray(layer.apply(params, feats))
    assert out.shape == (2, 8 + 2)
    assert out[0, 1] == 1.0  # cls=1 one-hot
    assert out[1, 7] == 1.0  # cls=7 one-hot (valid under 8 buckets)
    # bucketized one-hot occupies the trailing 2 slots
    np.testing.assert_array_equal(out[:, 8:], [[1.0, 0.0], [0.0, 1.0]])


def test_dense_features_under_jit():
    cols = (
        fc.numeric_column("age"),
        fc.embedding_column(
            fc.categorical_column_with_hash_bucket("workclass", 32), 4
        ),
    )
    feats = fc.transform_features(cols, RAW)
    layer = fc.DenseFeatures(columns=cols)
    params = layer.init(jax.random.PRNGKey(0), feats)
    jit_apply = jax.jit(lambda p, f: layer.apply(p, f))
    out = jit_apply(params, feats)
    assert out.shape == (2, 5)
    assert np.all(np.isfinite(np.asarray(out)))
