"""Execute the shipped cluster smoke harness (VERDICT r3 #8).

The reference's CI actually RUNS ``scripts/client_test.sh``
(``/root/reference/.travis.yml:52-80``); a shipped-but-never-executed
port is documentation, not verification.  This test runs the harness's
always-available ``local`` mode end to end in a subprocess — arg
plumbing, synthetic data generation, the real ``elasticdl train`` CLI,
exit codes — on every suite run.  (The k8s modes self-skip without a
cluster; their golden manifests are covered in test_k8s.py.)
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_client_test_sh_local_mode_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["EDL_TEST_DATA"] = str(tmp_path / "smoke-data")
    # the harness invokes bare `python`: make sure it resolves to this
    # interpreter and that the repo is importable from the script's cwd
    env["PATH"] = (
        os.path.dirname(sys.executable) + os.pathsep + env.get("PATH", "")
    )
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "client_test.sh"), "local"],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"client_test.sh local failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
    )
    assert "Local smoke test succeeded." in proc.stdout


def test_client_test_sh_k8s_mode_self_skips_without_cluster():
    """Without a reachable cluster the k8s modes exit 0 with a SKIP
    message (the contract that keeps clusterless CI green)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("EDL_TEST_IMAGE", None)
    # ensure kubectl (if present at all) cannot reach a cluster
    env["KUBECONFIG"] = "/nonexistent/kubeconfig"
    env["PATH"] = (
        os.path.dirname(sys.executable) + os.pathsep + env.get("PATH", "")
    )
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "client_test.sh"), "train"],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "SKIP" in proc.stdout
