"""Slice-granular elastic reform: the mesh seams, the slice-aware
replica ring, the autoscaler policy, the master's shrink/park logic,
and the cross_slice_replica_coverage checker's falsifiability.

End-to-end (subprocess worlds, mesh resize, hot restore) lives in
``scripts/multislice_smoke.py`` (tier-1) and the slow chaos acceptance
tests; everything here is process-local and fast.
"""

from __future__ import annotations

import pytest

from elasticdl_tpu.parallel.mesh import (
    detect_num_slices,
    plan_dcn_axes,
    process_slice_index_fn,
    slice_assignments,
)


class _FakeDevice:
    def __init__(self, process_index=0, slice_index=None):
        self.process_index = process_index
        if slice_index is not None:
            self.slice_index = slice_index


# ---- mesh seams the tentpole leans on ---------------------------------------


class TestDetectNumSlices:
    def test_empty_devices_is_one_slice(self):
        assert detect_num_slices([]) == 1

    def test_devices_without_slice_index_are_one_slice(self):
        devices = [_FakeDevice(process_index=i) for i in range(4)]
        assert detect_num_slices(devices) == 1

    def test_mixed_none_slice_index_is_one_slice(self):
        devices = [
            _FakeDevice(process_index=0, slice_index=0),
            _FakeDevice(process_index=1),
        ]
        assert detect_num_slices(devices) == 1

    def test_real_slice_index_counted(self):
        devices = [
            _FakeDevice(process_index=i, slice_index=i // 2)
            for i in range(4)
        ]
        assert detect_num_slices(devices) == 2

    def test_slice_index_fn_override_forces_layout(self):
        """The multichip-dryrun CPU path: host-platform devices carry no
        slice_index; the fn imposes one."""
        devices = [_FakeDevice(process_index=i) for i in range(4)]
        assert (
            detect_num_slices(
                devices, slice_index_fn=lambda d: d.process_index % 2
            )
            == 2
        )

    def test_slice_index_fn_with_empty_devices(self):
        assert detect_num_slices([], slice_index_fn=lambda d: 0) == 1


class TestPlanDcnAxes:
    def test_explicit_product_mismatch_is_clear_error(self):
        with pytest.raises(ValueError, match="product 4 != number of slices 2"):
            plan_dcn_axes({"dp": 8}, 2, {"dp": 4})

    def test_non_divisible_dp_is_clear_error(self):
        with pytest.raises(ValueError, match="not divisible"):
            plan_dcn_axes({"dp": 3}, 2, None)

    def test_default_puts_all_slices_on_dp(self):
        assert plan_dcn_axes({"dp": 8}, 2, None) == {"dp": 2}

    def test_single_slice_is_empty_plan(self):
        assert plan_dcn_axes({"dp": 8}, 1, {"dp": 8}) == {}

    def test_dcn_axis_must_divide_mesh_axis(self):
        with pytest.raises(ValueError, match="does not divide"):
            plan_dcn_axes({"dp": 4, "fsdp": 3}, 2, {"fsdp": 2})


class TestSliceAssignments:
    def test_even_split(self):
        assert slice_assignments(4, 2) == [0, 0, 1, 1]

    def test_uneven_split_front_loads(self):
        assert slice_assignments(5, 2) == [0, 0, 0, 1, 1]
        assert slice_assignments(7, 3) == [0, 0, 0, 1, 1, 2, 2]

    def test_single_slice(self):
        assert slice_assignments(3, 1) == [0, 0, 0]

    def test_clamps_slices_to_processes(self):
        assert slice_assignments(2, 5) == [0, 1]

    def test_empty(self):
        assert slice_assignments(0, 2) == []

    def test_process_slice_index_fn_uses_canonical_map(self):
        fn = process_slice_index_fn(4, 2)
        devices = [_FakeDevice(process_index=i) for i in range(4)]
        assert [fn(d) for d in devices] == [0, 0, 1, 1]

    def test_process_slice_index_fn_ignores_degenerate_slice_index(self):
        """Multi-process CPU worlds expose a CONSTANT slice_index=0 on
        every device; the forced map must win or the layout collapses
        back to one slice (caught by the CLI drive, PR 7)."""
        fn = process_slice_index_fn(4, 2)
        devices = [
            _FakeDevice(process_index=i, slice_index=0) for i in range(4)
        ]
        assert [fn(d) for d in devices] == [0, 0, 1, 1]

    def test_resolved_fn_defers_to_real_multislice_hardware(self):
        from elasticdl_tpu.parallel.mesh import resolved_slice_index_fn

        real = [
            _FakeDevice(process_index=i, slice_index=i // 2)
            for i in range(4)
        ]
        assert resolved_slice_index_fn(real, 4, 2) is None

    def test_resolved_fn_forces_on_degenerate_backends(self):
        from elasticdl_tpu.parallel.mesh import resolved_slice_index_fn

        for devices in (
            [_FakeDevice(process_index=i) for i in range(4)],  # no attr
            [
                _FakeDevice(process_index=i, slice_index=0)  # constant
                for i in range(4)
            ],
        ):
            fn = resolved_slice_index_fn(devices, 4, 2)
            assert fn is not None
            assert [fn(d) for d in devices] == [0, 0, 1, 1]
        assert resolved_slice_index_fn(devices, 4, 1) is None


# ---- slice-aware replica ring ----------------------------------------------


class TestRingNeighbor:
    def _map(self, n, k):
        return slice_assignments(n, k)

    def test_single_slice_keeps_classic_ring(self):
        from elasticdl_tpu.replication.replicator import ring_neighbor

        for n in (2, 3, 4):
            for i in range(n):
                assert ring_neighbor(i, n, self._map(n, 1)) == (i + 1) % n

    @pytest.mark.parametrize(
        "n,k",
        [
            (2, 2),
            (4, 2),
            (6, 2),
            (6, 3),
            (3, 3),
            # uneven processes-per-slice
            (5, 2),
            (5, 3),
            (7, 3),
        ],
    )
    def test_replica_never_on_owner_slice(self, n, k):
        """The pin: for n_slices in {1,2,3} and uneven splits, a shard's
        only ring replica NEVER lands on its owner's slice (a slice loss
        would otherwise take state and replica together)."""
        from elasticdl_tpu.replication.replicator import ring_neighbor

        slice_map = self._map(n, k)
        for i in range(n):
            j = ring_neighbor(i, n, slice_map)
            assert j != i
            assert slice_map[j] != slice_map[i], (
                f"process {i} (slice {slice_map[i]}) replicates onto its "
                f"own slice via neighbor {j}"
            )

    def test_classic_ring_violates_on_shared_slice(self):
        """Why the repin exists: with 2 procs per slice, (i+1)%n puts
        p0's replica on p1 — the SAME slice."""
        slice_map = self._map(4, 2)
        assert slice_map[(0 + 1) % 4] == slice_map[0]

    def test_same_slice_ring_env_restores_classic_ring(self, monkeypatch):
        from elasticdl_tpu.replication.replicator import (
            SAME_SLICE_RING_ENV,
            PeerReplicator,
        )
        from elasticdl_tpu.replication.store import ReplicaStore

        monkeypatch.setenv(SAME_SLICE_RING_ENV, "1")
        rep = PeerReplicator(
            ReplicaStore(),
            process_id=0,
            num_processes=4,
            generation=0,
            addr="127.0.0.1:1",
            num_slices=2,
        )
        assert rep.neighbor == 1  # slice-blind: p1 shares slice 0
        monkeypatch.delenv(SAME_SLICE_RING_ENV)
        rep = PeerReplicator(
            ReplicaStore(),
            process_id=0,
            num_processes=4,
            generation=0,
            addr="127.0.0.1:1",
            num_slices=2,
        )
        assert rep.neighbor == 2  # slice-aware: first off-slice process
        assert rep.advertisement()["slice_id"] == 0


    def test_replicator_prefers_mesh_derived_slice_map(self):
        """On hardware whose slice_index grouping diverges from the
        canonical assignment, the ring must follow the PHYSICAL map."""
        from elasticdl_tpu.replication.replicator import PeerReplicator
        from elasticdl_tpu.replication.store import ReplicaStore

        # physical: slice 0 = {p0, p2}, slice 1 = {p1, p3} — interleaved,
        # unlike the canonical contiguous [0, 0, 1, 1]
        rep = PeerReplicator(
            ReplicaStore(),
            process_id=0,
            num_processes=4,
            generation=0,
            addr="127.0.0.1:1",
            num_slices=2,
            slice_map=[0, 1, 0, 1],
        )
        assert rep.neighbor == 1  # p1 IS off-slice physically
        assert rep.advertisement()["slice_id"] == 0

    def test_mesh_process_slice_map_reads_devices(self):
        from elasticdl_tpu.parallel.mesh import mesh_process_slice_map

        class _FakeMesh:
            class devices:
                flat = [
                    _FakeDevice(process_index=0, slice_index=1),
                    _FakeDevice(process_index=1, slice_index=0),
                ]

        assert mesh_process_slice_map(_FakeMesh()) == [1, 0]
        forced = mesh_process_slice_map(
            _FakeMesh(), slice_index_fn=lambda d: d.process_index
        )
        assert forced == [0, 1]


# ---- cross_slice_replica_coverage: falsifiable ------------------------------


class TestCrossSliceCoverage:
    def _push(self, src, dst, src_slice, dst_slice, step=2, slices=2):
        return {
            "event": "replica_push",
            "step": step,
            "source": src,
            "target": dst,
            "source_slice": src_slice,
            "target_slice": dst_slice,
            "num_slices": slices,
            "ok": True,
        }

    def test_cross_slice_pushes_pass(self):
        from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

        events = [self._push(0, 2, 0, 1), self._push(2, 0, 1, 0)]
        assert check_cross_slice_coverage(events, 2) == []

    def test_same_slice_push_is_flagged(self):
        """The --corrupt same_slice_ring trip: a push landing on its
        owner's slice MUST fail the invariant."""
        from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

        events = [self._push(0, 1, 0, 0), self._push(2, 0, 1, 0)]
        violations = check_cross_slice_coverage(events, 2)
        assert len(violations) == 1
        assert "OWN slice" in violations[0]

    def test_no_pushes_is_unproven_coverage(self):
        from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

        violations = check_cross_slice_coverage([], 2)
        assert violations and "unproven" in violations[0]

    def test_single_slice_pushes_exempt(self):
        """A post-shrink single-slice world legitimately pushes
        on-slice (there is no other slice); only multi-slice pushes are
        in contract."""
        from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

        events = [
            self._push(0, 1, 0, 0, slices=1),
            self._push(0, 2, 0, 1, slices=2),
        ]
        assert check_cross_slice_coverage(events, 2) == []

    def test_missing_slice_fields_flagged(self):
        from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

        events = [
            {
                "event": "replica_push",
                "step": 4,
                "num_slices": 2,
                "source": 0,
                "target": 1,
            }
        ]
        violations = check_cross_slice_coverage(events, 2)
        assert violations and "no slice placement" in violations[0]


# ---- chaos plumbing ---------------------------------------------------------


class TestSliceLossFault:
    def test_plan_registered(self):
        from elasticdl_tpu.chaos.plan import FaultKind, builtin_plans
        from elasticdl_tpu.chaos.runner import MULTISLICE_PLANS

        plans = builtin_plans(2)
        fault = plans["slice_loss_mid_epoch"].faults[0]
        assert fault.kind == FaultKind.SLICE_LOSS
        assert fault.slice_id == 1
        assert fault.process_id is None
        assert plans["grow_under_load"].faults[0].kind == (
            FaultKind.RESTORE_CAPACITY
        )
        assert set(MULTISLICE_PLANS) <= set(plans)

    def test_injector_arms_only_matching_slice(self, tmp_path):
        from elasticdl_tpu.chaos.hooks import ChaosInjector
        from elasticdl_tpu.chaos.plan import Fault, FaultKind, FaultPlan

        plan = FaultPlan(
            name="t",
            faults=[
                Fault(
                    kind=FaultKind.SLICE_LOSS,
                    fault_id="sl",
                    at_step=4,
                    slice_id=1,
                )
            ],
        )
        on_slice = ChaosInjector(
            plan, process_id=2, cluster_version=0, worker_id=2, slice_id=1
        )
        off_slice = ChaosInjector(
            plan, process_id=0, cluster_version=0, worker_id=0, slice_id=0
        )
        assert len(on_slice._pending) == 1
        assert off_slice._pending == []

    def test_slice_loss_roundtrips_json(self):
        from elasticdl_tpu.chaos.plan import FaultPlan, named_plan

        plan = named_plan("slice_loss_mid_epoch", 2)
        again = FaultPlan.from_json(plan.to_json())
        assert again.faults[0].slice_id == 1

    def test_harness_refuses_slice_plan_without_slices(self, tmp_path):
        from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
        from elasticdl_tpu.chaos.plan import named_plan

        with pytest.raises(ValueError, match="SLICE_LOSS"):
            run_chaos_job(
                ChaosJobConfig(
                    plan=named_plan("slice_loss_mid_epoch", 2),
                    workdir=str(tmp_path / "w"),
                    num_slices=1,
                )
            )

    def test_harness_refuses_same_slice_ring_without_replication(
        self, tmp_path
    ):
        from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
        from elasticdl_tpu.chaos.plan import named_plan

        with pytest.raises(ValueError, match="same_slice_ring"):
            run_chaos_job(
                ChaosJobConfig(
                    plan=named_plan("slice_loss_mid_epoch", 2),
                    workdir=str(tmp_path / "w"),
                    num_slices=2,
                    replication=False,
                    corrupt="same_slice_ring",
                )
            )

    def test_runner_list_prints_plans_and_invariants(self, capsys):
        from elasticdl_tpu.chaos import runner

        assert runner.main(["--list"]) == 0
        out = capsys.readouterr().out
        assert "slice_loss_mid_epoch" in out
        assert "grow_under_load" in out
        assert "cross_slice_replica_coverage" in out
        assert "exactly_once" in out


# ---- autoscaler policy ------------------------------------------------------


class TestAutoscaler:
    def _scaler(self, **kw):
        from elasticdl_tpu.master.autoscaler import Autoscaler

        kw.setdefault("cooldown_secs", 0.0)
        kw.setdefault("max_slices", 4)
        return Autoscaler(**kw)

    def test_build_returns_none_with_no_slos(self):
        from argparse import Namespace

        from elasticdl_tpu.master.autoscaler import build_autoscaler

        args = Namespace(
            autoscale_p95_step_ms=None, autoscale_backlog_tasks=None
        )
        assert build_autoscaler(args, 4) is None

    def test_grow_on_backlog(self):
        scaler = self._scaler(backlog_tasks=10)
        decision = scaler.evaluate(backlog=12, current_slices=2, now=100.0)
        assert decision["action"] == "grow"
        assert decision["to_slices"] == 3

    def test_no_grow_under_backlog_slo(self):
        scaler = self._scaler(backlog_tasks=10)
        assert scaler.evaluate(backlog=3, current_slices=2, now=100.0) is None

    def test_grow_clamped_at_max_slices(self):
        scaler = self._scaler(backlog_tasks=10, max_slices=2)
        assert (
            scaler.evaluate(backlog=50, current_slices=2, now=100.0) is None
        )

    def test_grow_on_p95(self):
        scaler = self._scaler(p95_step_ms=100.0)
        for i in range(20):
            # 2 steps per second -> 500ms/step, way over the 100ms SLO
            scaler.tracker._samples_ms.append(500.0)
        decision = scaler.evaluate(backlog=0, current_slices=1, now=100.0)
        assert decision["action"] == "grow"
        assert decision["p95_step_ms"] == 500.0

    def test_cooldown_blocks_consecutive_decisions(self):
        scaler = self._scaler(backlog_tasks=10, cooldown_secs=30.0)
        assert scaler.evaluate(10, 1, now=100.0)["action"] == "grow"
        assert scaler.evaluate(10, 2, now=110.0) is None  # cooling down
        assert scaler.evaluate(10, 2, now=140.0)["action"] == "grow"

    def test_reform_restarts_cooldown_and_baseline(self):
        scaler = self._scaler(backlog_tasks=10, cooldown_secs=1e6)
        scaler.tracker._samples_ms.extend([100.0] * 8)
        scaler.note_reform()
        assert scaler.tracker.p95_ms() is None
        assert scaler.evaluate(50, 1) is None  # cooldown holds

    def test_shrink_gated_and_bounded(self):
        scaler = self._scaler(
            p95_step_ms=100.0, shrink=True, min_slices=1, max_slices=4
        )
        # measured p95 well under a quarter of the SLO: over-provisioned
        scaler.tracker._samples_ms.extend([10.0] * 8)
        decision = scaler.evaluate(backlog=0, current_slices=2, now=100.0)
        assert decision["action"] == "shrink"
        assert decision["to_slices"] == 1
        # at the floor: no further shrink
        assert scaler.evaluate(backlog=0, current_slices=1, now=200.0) is None

    def test_no_shrink_on_empty_backlog_alone(self):
        """pending counts only UNLEASED tasks — it reads 0 while every
        worker is busy mid-lease, so an empty backlog must never be
        shrink evidence by itself (a shrink would requeue the leases,
        spike the backlog, and flap grow/shrink every cooldown)."""
        scaler = self._scaler(
            backlog_tasks=10, shrink=True, min_slices=1, max_slices=4
        )
        assert scaler.evaluate(backlog=0, current_slices=2, now=100.0) is None

    def test_no_shrink_without_flag(self):
        scaler = self._scaler(p95_step_ms=100.0)
        scaler.tracker._samples_ms.extend([10.0] * 8)
        assert scaler.evaluate(backlog=0, current_slices=2, now=100.0) is None

    def test_step_time_tracker_p95(self):
        from elasticdl_tpu.master.autoscaler import StepTimeTracker

        tracker = StepTimeTracker()
        assert tracker.p95_ms() is None  # too few samples
        tracker._samples_ms.extend(float(i) for i in range(1, 101))
        assert tracker.p95_ms() == pytest.approx(96.0, abs=1.0)

    def test_step_time_tracker_derives_per_step_interval(self):
        from elasticdl_tpu.master.autoscaler import StepTimeTracker

        tracker = StepTimeTracker()
        import time as _time

        t0 = _time.monotonic()
        tracker._last = (t0 - 1.0, 10)  # 1s ago at version 10
        tracker.note_version(0, 20)  # 10 steps in ~1s -> ~100ms/step
        assert tracker._samples_ms[-1] == pytest.approx(100.0, rel=0.2)


# ---- instance-manager slice math -------------------------------------------


class TestInstanceManagerSlices:
    def _im(self, num_workers=4, num_slices=2):
        from elasticdl_tpu.master.master import LocalInstanceManager

        return LocalInstanceManager(
            master=None,
            num_workers=num_workers,
            build_argv=lambda *a, **k: [],
            lockstep=True,
            num_slices=num_slices,
        )

    def test_fleet_must_divide(self):
        with pytest.raises(ValueError, match="not divisible"):
            self._im(num_workers=3, num_slices=2)

    def test_set_world_slices(self):
        im = self._im(4, 2)
        assert im.world_size == 4 and im.world_num_slices == 2
        im.set_world_slices(1)
        assert im.world_size == 2 and im.world_num_slices == 1
        im.set_world_slices(99)  # clamped to the fleet
        assert im.world_size == 4 and im.world_num_slices == 2

    def test_set_world_size_snaps_to_slice_units(self):
        im = self._im(4, 2)
        im.set_world_size(3)  # not a whole number of slices
        assert im.world_size == 2 and im.world_num_slices == 1
        im.set_world_size(4)
        assert im.world_size == 4 and im.world_num_slices == 2

    def test_max_world_size_is_fleet(self):
        im = self._im(4, 2)
        im.set_world_slices(1)
        assert im.max_world_size == 4

    def test_single_slice_ignores_slice_snap(self):
        im = self._im(4, 1)
        im.set_world_size(3)
        assert im.world_size == 3
        assert im.world_num_slices == 1

    def test_restore_worker_slices(self):
        im = self._im(4, 2)
        im.restore_worker_slices({"7": 0, "8": 1})
        assert im.worker_slices() == {7: 0, 8: 1}


# ---- master slice reform: shrink / park / unpark ----------------------------


class _FakeSliceIM:
    """LocalInstanceManager's slice surface without subprocesses."""

    lockstep = True

    def __init__(self, num_workers=4, num_slices=2):
        self._num_workers = num_workers
        self.fleet_slices = num_slices
        self._pps = num_workers // num_slices
        self.world_num_slices = num_slices
        self.world_size = num_workers
        from elasticdl_tpu.parallel.mesh import slice_assignments

        assign = slice_assignments(num_workers, num_slices)
        self._workers = {wid: assign[wid] for wid in range(num_workers)}
        self.reformed_with: list[int] = []
        self.torn_down = 0
        self.pending_world_trace = None

    @property
    def max_world_size(self):
        return self._num_workers

    def worker_ids(self):
        return list(self._workers)

    def worker_slices(self):
        return dict(self._workers)

    def set_world_slices(self, n):
        n = max(1, min(self.fleet_slices, int(n)))
        self.world_num_slices = n
        self.world_size = n * self._pps

    def set_world_size(self, n):
        self.set_world_slices(max(1, int(n) // self._pps))

    def reform_world(self, cluster_version, count_against_budget=True):
        self.reformed_with.append(self.world_size)
        from elasticdl_tpu.parallel.mesh import slice_assignments

        assign = slice_assignments(self.world_size, self.world_num_slices)
        self._workers = {
            100 * (len(self.reformed_with) + 1) + i: assign[i]
            for i in range(self.world_size)
        }

    def teardown_world(self, budget=False):
        self.torn_down += 1
        self._workers = {}

    def start_workers(self):
        self.started = True

    def stop_workers(self, grace_secs=0.0):
        pass


def _make_master(tmp_path, extra_args=(), num_workers=4, fake_im=None):
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.utils.args import parse_master_args

    train = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=64, num_shards=1, seed=3
    )
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "16",
            "--records_per_task",
            "32",
            "--num_workers",
            str(num_workers),
            "--distribution_strategy",
            "AllreduceStrategy",
            *extra_args,
        ]
    )
    return Master(
        args,
        instance_manager_factory=(lambda m: fake_im) if fake_im else None,
    )


class TestSliceReform:
    def test_whole_slice_death_shrinks_next_world(self, tmp_path):
        im = _FakeSliceIM(4, 2)
        master = _make_master(tmp_path, fake_im=im)
        # slice 1 = workers {2, 3}: both dead -> shrink to 1 slice
        master._reform_lockstep([2, 3], reason="worker_failure")
        assert im.reformed_with == [2]
        assert im.world_num_slices == 1
        assert not master._parked

    def test_partial_slice_death_keeps_size(self, tmp_path):
        im = _FakeSliceIM(4, 2)
        master = _make_master(tmp_path, fake_im=im)
        master._reform_lockstep([3], reason="worker_failure")
        assert im.reformed_with == [4]  # full-size relaunch
        assert im.world_num_slices == 2

    def test_all_slices_dead_is_whole_world_crash(self, tmp_path):
        im = _FakeSliceIM(4, 2)
        master = _make_master(tmp_path, fake_im=im)
        master._reform_lockstep([0, 1, 2, 3], reason="worker_failure")
        assert im.reformed_with == [4]  # ambiguous evidence: full size
        assert im.world_num_slices == 2

    def test_shrink_below_min_slices_parks_then_grant_unparks(
        self, tmp_path
    ):
        im = _FakeSliceIM(4, 2)
        master = _make_master(
            tmp_path, extra_args=["--min_slices", "2"], fake_im=im
        )
        master._reform_lockstep([2, 3], reason="worker_failure")
        assert master._parked
        assert im.torn_down == 1
        assert im.reformed_with == []  # no relaunch below the floor
        assert master.servicer.is_quiescing
        # a stray elective request below the floor stays parked
        im.set_world_slices(1)
        master._reform_lockstep([], reason="stray")
        assert master._parked and im.reformed_with == []
        # the capacity grant restores the fleet and unparks
        im.set_world_slices(2)
        master._reform_lockstep([], reason="capacity_grant")
        assert not master._parked
        assert im.reformed_with == [4]
        assert not master.servicer.is_quiescing

    def test_master_restart_while_parked_stays_parked(self, tmp_path):
        """The journal world record carries the parked flag: a master
        relaunched from it must NOT start a fleet the capacity cannot
        run — it waits quiesced for a grant."""
        journal_dir = str(tmp_path / "journal")
        im1 = _FakeSliceIM(4, 2)
        master1 = _make_master(
            tmp_path,
            extra_args=[
                "--min_slices", "2", "--master_journal_dir", journal_dir,
            ],
            fake_im=im1,
        )
        master1._reform_lockstep([2, 3], reason="worker_failure")
        assert master1._parked
        # relaunch a master from the journal (the parked one "died")
        im2 = _FakeSliceIM(4, 2)
        master2 = _make_master(
            tmp_path,
            extra_args=[
                "--min_slices", "2", "--master_journal_dir", journal_dir,
            ],
            fake_im=im2,
        )
        assert master2._parked
        master2.prepare(port=0)
        try:
            assert not getattr(im2, "started", False)
            assert master2.servicer.is_quiescing
        finally:
            master2.stop()
            master1.journal.close()

    def test_slice_loss_emits_mesh_resize_event(self, tmp_path):
        im = _FakeSliceIM(4, 2)
        master = _make_master(tmp_path, fake_im=im)
        emitted = []
        master.telemetry.events.emit = lambda name, **kw: emitted.append(
            (name, kw)
        )
        master._reform_lockstep([2, 3], reason="worker_failure")
        names = [n for n, _ in emitted]
        assert "slice_loss" in names
        assert "mesh_resize" in names
        resize = dict(emitted)[("mesh_resize")]
        assert resize["old_slices"] == 2 and resize["new_slices"] == 1
        assert resize["old_world_size"] == 4
        assert resize["new_world_size"] == 2
        loss = dict(emitted)[("slice_loss")]
        assert loss["lost_slices"] == [1] and not loss["parked"]

    def test_autoscale_tick_requests_grow_on_backlog(self, tmp_path):
        im = _FakeSliceIM(4, 2)
        im.set_world_slices(1)
        master = _make_master(
            tmp_path,
            extra_args=[
                "--autoscale_backlog_tasks",
                "1",
                "--autoscale_cooldown_secs",
                "0",
            ],
            fake_im=im,
        )
        assert master.autoscaler is not None
        master._autoscale_tick()
        assert im.world_num_slices == 2
        assert master._reform_requested == "autoscale:grow"

    def test_no_autoscaler_without_flags(self, tmp_path):
        master = _make_master(tmp_path, fake_im=_FakeSliceIM(4, 2))
        assert master.autoscaler is None


# ---- argv / golden coupling -------------------------------------------------


class TestArgvAudit:
    def test_new_flags_default_none_and_absent_from_worker_argv(self):
        from elasticdl_tpu.utils.args import (
            build_worker_arguments,
            parse_master_args,
        )

        base = [
            "--model_def",
            "m.custom_model",
            "--training_data",
            "/tmp/t",
        ]
        plain = parse_master_args(base)
        for flag in (
            "num_slices",
            "min_slices",
            "autoscale_p95_step_ms",
            "autoscale_backlog_tasks",
            "autoscale_cooldown_secs",
            "autoscale_shrink",
        ):
            assert getattr(plain, flag) is None, flag
        sliced = parse_master_args(
            base
            + [
                "--num_slices",
                "2",
                "--min_slices",
                "1",
                "--autoscale_backlog_tasks",
                "5",
                "--autoscale_p95_step_ms",
                "200",
                "--autoscale_cooldown_secs",
                "10",
                "--autoscale_shrink",
                "true",
            ]
        )
        # byte-identical worker argv whether the master flags are set
        # or not (they are master-only and filtered)
        assert build_worker_arguments(
            sliced, 0, "localhost:1"
        ) == build_worker_arguments(plain, 0, "localhost:1")
        assert not any(
            "autoscale" in a or "slices" in a
            for a in build_worker_arguments(plain, 0, "localhost:1")
        )

    def test_worker_slice_args_parse(self):
        from elasticdl_tpu.utils.args import parse_worker_args

        args = parse_worker_args(
            [
                "--model_def",
                "m.custom_model",
                "--worker_id",
                "0",
                "--master_addr",
                "localhost:1",
                "--slice_id",
                "1",
                "--num_slices",
                "2",
            ]
        )
        assert args.slice_id == 1 and args.num_slices == 2


# ---- end to end (multi-process; slow) --------------------------------------


@pytest.mark.slow
def test_slice_loss_chaos_end_to_end(tmp_path):
    """Acceptance: slice_loss_mid_epoch with replication — invariants
    all PASS (incl. cross_slice_replica_coverage), the world shrank."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("slice_loss_mid_epoch", 2),
            workdir=str(tmp_path / "chaos"),
            num_records=256,
            num_epochs=2,
            num_workers=2,
            num_slices=2,
            checkpoint_steps=4,
            replication=True,
            run_timeout_secs=300.0,
        )
    )
    assert report["invariants_ok"], report["invariants"]
    names = {i["name"] for i in report["invariants"]}
    assert "cross_slice_replica_coverage" in names
    resizes = report["multislice"]["mesh_resizes"]
    assert any(r["new_slices"] < r["old_slices"] for r in resizes)
    assert report["multislice"]["slice_losses"][0]["lost_slices"] == [1]


@pytest.mark.slow
def test_grow_under_load_chaos_end_to_end(tmp_path):
    """Acceptance: the job starts on 1 of 2 slices; a capacity grant
    grows the world mid-training with exactly-once accounting."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("grow_under_load", 2),
            workdir=str(tmp_path / "chaos"),
            num_records=512,
            num_epochs=2,
            num_workers=2,
            num_slices=2,
            initial_slices=1,
            run_timeout_secs=300.0,
        )
    )
    assert report["invariants_ok"], report["invariants"]
    resizes = report["multislice"]["mesh_resizes"]
    assert any(r["new_slices"] > r["old_slices"] for r in resizes)
    assert any(
        "capacity-grant" in r.get("reason", "") for r in report["reforms"]
    )


# ---- journal world record carries slice topology ----------------------------


class TestJournalSlices:
    def test_world_replay_roundtrips_slices(self):
        from elasticdl_tpu.master.journal import replay

        records = [
            {
                "kind": "snapshot",
                "state": {
                    "dispatcher": {
                        "pending": [],
                        "pending_eval": [],
                        "active": [],
                        "epoch": 0,
                    },
                    "servicer": {
                        "cluster_version": 0,
                        "model_version": 0,
                        "stream": {},
                    },
                    "callbacks_invoked": 0,
                    "world": None,
                },
            },
            {
                "kind": "world",
                "cluster_version": 1,
                "worker_ids": [4, 5],
                "world_size": 2,
                "num_slices": 2,
                "slices": {"4": 0, "5": 1},
            },
        ]
        state = replay(records)
        assert state["world"]["num_slices"] == 2
        assert state["world"]["slices"] == {"4": 0, "5": 1}

    def test_pre_multislice_world_record_defaults(self):
        from elasticdl_tpu.master.journal import replay

        records = [
            {
                "kind": "snapshot",
                "state": {
                    "dispatcher": {
                        "pending": [],
                        "pending_eval": [],
                        "active": [],
                        "epoch": 0,
                    },
                    "servicer": {},
                    "callbacks_invoked": 0,
                },
            },
            {
                "kind": "world",
                "cluster_version": 0,
                "worker_ids": [0, 1],
                "world_size": 2,
            },
        ]
        state = replay(records)
        assert state["world"]["num_slices"] == 1
        assert state["world"]["slices"] == {}
