"""Task dispatcher + master servicer tests.

Reference counterparts: ``task_dispatcher_test.py``, ``servicer_test.py``
(SURVEY §4 tier 1/2).
"""

import time

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    FAIL_COUNT,
    Task,
    TaskDispatcher,
)
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.constants import TaskType


def make_dispatcher(**kw):
    defaults = dict(
        training_shards={"f1": (0, 100), "f2": (0, 50)},
        records_per_task=30,
        num_epochs=1,
        shuffle_seed=42,
    )
    defaults.update(kw)
    return TaskDispatcher(**defaults)


class TestTaskDispatcher:
    def test_task_slicing_covers_all_records(self):
        d = make_dispatcher()
        seen = []
        while True:
            tid, task = d.get(worker_id=0)
            if task is None:
                break
            seen.append(task)
            d.report(tid, success=True)
        # f1: 0-30,30-60,60-90,90-100  f2: 0-30,30-50
        assert len(seen) == 6
        total = sum(t.num_records for t in seen)
        assert total == 150
        assert d.finished()

    def test_epochs_lazily_created(self):
        d = make_dispatcher(num_epochs=3)
        count = 0
        while True:
            tid, task = d.get(0)
            if task is None:
                break
            count += 1
            d.report(tid, success=True)
        assert count == 6 * 3
        assert d.epoch == 2

    def test_failed_task_requeued(self):
        d = make_dispatcher(training_shards={"f": (0, 10)}, records_per_task=10)
        tid, task = d.get(0)
        assert task is not None
        d.report(tid, success=False)
        assert not d.finished()
        tid2, task2 = d.get(1)
        assert (task2.shard_name, task2.start, task2.end) == (
            task.shard_name,
            task.start,
            task.end,
        )
        assert tid2 != tid

    def test_recover_tasks_requeues_only_dead_workers(self):
        d = make_dispatcher()
        t1, _ = d.get(worker_id=1)
        t2, _ = d.get(worker_id=2)
        t3, _ = d.get(worker_id=1)
        before = d.snapshot()
        assert len(before["active"]) == 3
        d.recover_tasks(worker_id=1)
        after = d.snapshot()
        assert set(after["active"]) == {t2}
        assert after["pending"] == before["pending"] + 2

    def test_fail_count_accumulates(self):
        d = make_dispatcher(training_shards={"f": (0, 10)}, records_per_task=5)
        tid, _ = d.get(0)
        d.report(tid, success=True, exec_counters={FAIL_COUNT: 3})
        assert d.counters(TaskType.TRAINING).failed_records == 3

    def test_timing_exec_counters_are_deltas(self):
        """Each exec_counters() call reports only time accrued since the
        last call (a batch finishing several tasks must not multiply its
        wall clock), and zero deltas are omitted."""
        import time as time_mod

        from elasticdl_tpu.utils.timing_utils import Timing

        timing = Timing(enabled=True)
        with timing.record("batch_process"):
            time_mod.sleep(0.02)
        first = timing.exec_counters()
        assert first.get("time_batch_process_ms", 0) >= 10
        # nothing new accrued -> empty, not a duplicate of the total
        assert timing.exec_counters() == {}
        with timing.record("batch_process"):
            time_mod.sleep(0.02)
        second = timing.exec_counters()
        # only the delta, never the cumulative total again (no upper
        # wall-clock bound — shared CI hosts stall unpredictably)
        assert second["time_batch_process_ms"] > 0
        assert timing.exec_counters() == {}

    def test_exec_metrics_aggregate_across_tasks(self):
        """Worker-reported timing buckets sum per job (VERDICT r1 #10:
        per-task timing rides the task reports)."""
        d = make_dispatcher(training_shards={"f": (0, 10)}, records_per_task=5)
        t1, _ = d.get(0)
        d.report(t1, success=True, exec_counters={"time_batch_process_ms": 40})
        t2, _ = d.get(0)
        d.report(
            t2,
            success=True,
            exec_counters={"time_batch_process_ms": 25, FAIL_COUNT: 1},
        )
        counters = d.counters(TaskType.TRAINING)
        assert counters.exec_metrics == {"time_batch_process_ms": 65}
        assert counters.failed_records == 1

    def test_eval_tasks_separate_queue(self):
        d = TaskDispatcher(
            training_shards={"t": (0, 10)},
            evaluation_shards=None,
            records_per_task=10,
        )
        d.create_tasks(TaskType.EVALUATION, model_version=5)
        # no eval shards configured -> no tasks
        tid, task = d.get_eval_task(0)
        assert task is None
        d2 = TaskDispatcher(
            training_shards=None,
            evaluation_shards={"e": (0, 20)},
            records_per_task=10,
        )
        tid, task = d2.get_eval_task(0)
        assert task is not None and task.type == TaskType.EVALUATION

    def test_lease_timeout_reclaims(self):
        d = make_dispatcher(
            training_shards={"f": (0, 10)},
            records_per_task=10,
            task_timeout_secs=0.05,
        )
        tid, task = d.get(0)
        assert task is not None
        time.sleep(0.08)
        # next get() reclaims the expired lease and hands the task out again
        tid2, task2 = d.get(1)
        assert task2 is not None
        assert task2.start == task.start
        # the original lease is gone: reporting it warns but doesn't crash
        d.report(tid, success=True)
        d.report(tid2, success=True)
        assert d.finished()

    def test_save_model_deferred_callback(self):
        d = make_dispatcher(training_shards={"f": (0, 10)}, records_per_task=4)
        d.add_deferred_callback_create_save_model_task("/out/model")
        while True:
            tid, task = d.get(0)
            if task is None:
                break
            d.report(tid, success=True)
        assert d.invoke_deferred_callback()
        tid, task = d.get(0)
        assert task.type == TaskType.SAVE_MODEL
        assert task.extended["saved_model_path"] == "/out/model"
        assert not d.invoke_deferred_callback()

    def test_shuffle_is_seeded(self):
        order1 = []
        d1 = make_dispatcher(shuffle_seed=7)
        while True:
            tid, t = d1.get(0)
            if t is None:
                break
            order1.append((t.shard_name, t.start))
            d1.report(tid, True)
        d2 = make_dispatcher(shuffle_seed=7)
        order2 = []
        while True:
            tid, t = d2.get(0)
            if t is None:
                break
            order2.append((t.shard_name, t.start))
            d2.report(tid, True)
        assert order1 == order2


class TestMasterServicer:
    def _servicer(self, **kw):
        d = make_dispatcher(**kw)
        return MasterServicer(32, d), d

    def test_get_task_and_report(self):
        s, d = self._servicer()
        resp = s.get_task(msg.GetTaskRequest(worker_id=0))
        assert resp.task_id > 0
        assert resp.minibatch_size == 32
        assert resp.type == int(TaskType.TRAINING)
        s.report_task_result(msg.ReportTaskResultRequest(task_id=resp.task_id))
        assert resp.end > resp.start

    def test_wait_sentinel_while_tasks_in_flight(self):
        s, d = self._servicer(
            training_shards={"f": (0, 10)}, records_per_task=10
        )
        first = s.get_task(msg.GetTaskRequest(worker_id=0))
        # queue drained but the leased task may still fail: WAIT
        second = s.get_task(msg.GetTaskRequest(worker_id=1))
        assert second.is_wait
        s.report_task_result(
            msg.ReportTaskResultRequest(task_id=first.task_id)
        )
        third = s.get_task(msg.GetTaskRequest(worker_id=1))
        assert third.is_empty

    def test_error_report_requeues(self):
        s, d = self._servicer(
            training_shards={"f": (0, 10)}, records_per_task=10
        )
        resp = s.get_task(msg.GetTaskRequest(worker_id=0))
        s.report_task_result(
            msg.ReportTaskResultRequest(task_id=resp.task_id, err_message="boom")
        )
        resp2 = s.get_task(msg.GetTaskRequest(worker_id=0))
        assert resp2.task_id > 0 and resp2.start == resp.start

    def test_report_version_monotonic(self):
        s, _ = self._servicer()
        s.report_version(msg.ReportVersionRequest(model_version=10))
        s.report_version(msg.ReportVersionRequest(model_version=7))
        assert s.get_model_version() == 10

    def test_heartbeat_failure_detection(self):
        s, _ = self._servicer()
        s.heartbeat(msg.HeartbeatRequest(worker_id=1))
        s.heartbeat(msg.HeartbeatRequest(worker_id=2))
        assert s.dead_workers(timeout_secs=10) == []
        time.sleep(0.05)
        dead = s.dead_workers(timeout_secs=0.01)
        assert set(dead) == {1, 2}
        s.forget_worker(1)
        assert s.dead_workers(timeout_secs=0.01) == [2]

    def test_quiesce_signaling(self):
        s, _ = self._servicer()
        r = s.heartbeat(msg.HeartbeatRequest(worker_id=0))
        assert not r.should_quiesce
        s.begin_quiesce()
        r = s.heartbeat(msg.HeartbeatRequest(worker_id=0))
        assert r.should_quiesce
        s.end_quiesce()
        r = s.heartbeat(msg.HeartbeatRequest(worker_id=0))
        assert not r.should_quiesce and r.cluster_version == 1


class TestMessages:
    def test_simple_roundtrip(self):
        for m in [
            msg.GetTaskRequest(worker_id=3, task_type=1),
            msg.TaskResponse(task_id=9, shard_name="s", start=5, end=10, type=0),
            msg.ReportTaskResultRequest(task_id=1, err_message="e"),
            msg.ReportVersionRequest(model_version=12),
            msg.HeartbeatRequest(worker_id=1, step=100, timestamp=1.5),
        ]:
            assert msg.decode(msg.encode(m)) == m

    def test_eval_metrics_roundtrip(self):
        import numpy as np

        from elasticdl_tpu.utils.tensor import Tensor

        req = msg.ReportEvaluationMetricsRequest(
            model_outputs={
                "logits": Tensor("logits", np.ones((4, 3), np.float32))
            },
            labels=Tensor("labels", np.arange(4, dtype=np.int64)),
            model_version=8,
        )
        out = msg.decode(msg.encode(req))
        assert out.model_version == 8
        np.testing.assert_array_equal(
            out.model_outputs["logits"].values, req.model_outputs["logits"].values
        )
        np.testing.assert_array_equal(out.labels.values, [0, 1, 2, 3])


def test_lease_refresh_on_report_protects_ahead_leases():
    """Prefetching workers lease tasks ahead of consumption; a task
    report refreshes the reporter's other leases (progress proof), so
    ahead-leased tasks survive ``task_timeout_secs`` sized for
    lease-then-train — while a worker that stops reporting still loses
    its leases to the reclaim."""
    disp = TaskDispatcher(
        {"s0": (0, 64)},
        records_per_task=16,
        num_epochs=1,
        task_timeout_secs=2.0,
    )
    t1, _ = disp.get(0)
    t2, _ = disp.get(0)  # leased ahead by the prefetcher
    time.sleep(1.2)
    disp.report(t1, True)  # progress: refreshes t2's lease clock
    time.sleep(1.2)  # t2 now 2.4s old by lease, 1.2s by refresh
    t3, _ = disp.get(0)  # get() runs the reclaim
    assert t3 not in (t1, t2)  # t2 was NOT re-queued and re-served
    assert disp.is_active(t2)
    # no more reports: both remaining leases expire for real
    time.sleep(2.2)
    disp.get(0)
    assert not disp.is_active(t2)


class TestServicerConcurrency:
    """The reference serves RPCs from a 64-thread gRPC pool
    (master.py:301-324); every dispatcher/servicer mutation is guarded by
    hand-rolled locks (SURVEY §5).  Hammer the in-process servicer from
    many threads and assert the exactly-once invariants hold."""

    def test_threaded_workers_exactly_once(self):
        import threading

        from elasticdl_tpu.master.servicer import MasterServicer
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.rpc import messages as msg

        num_workers, records, rpt = 16, 4096, 16
        dispatcher = TaskDispatcher(
            {"s0": (0, records // 2), "s1": (0, records // 2)},
            records_per_task=rpt,
            num_epochs=2,
            shuffle_seed=3,
        )
        servicer = MasterServicer(8, dispatcher)

        leases: list = []
        errors: list = []
        barrier = threading.Barrier(num_workers)
        # hard deadline: with 16 threads on a loaded 1-core host a bare
        # busy-spin on WAIT can GIL-starve the thread holding the last
        # re-queued task for tens of minutes (observed: a 27-minute
        # stall under full-suite load).  Threads back off on WAIT per
        # the servicer contract and abort loudly past the deadline
        # instead of letting join() report an opaque hang.
        deadline = time.monotonic() + 60

        def worker(worker_id):
            try:
                barrier.wait()
                while True:
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"worker {worker_id} passed the 60s deadline; "
                            f"dispatcher finished={dispatcher.finished()} "
                            f"leases so far={len(leases)}"
                        )
                    resp = servicer.get_task(
                        msg.GetTaskRequest(worker_id=worker_id)
                    )
                    if resp.task_id < 0 and resp.type == int(TaskType.WAIT):
                        # the get_task contract: WAIT means "poll later",
                        # not "spin" — yield the GIL so the lease-holding
                        # thread can run
                        time.sleep(0.005)
                        continue
                    if resp.task_id < 0:
                        return  # job complete
                    leases.append(
                        (resp.task_id, resp.shard_name, resp.start, resp.end)
                    )
                    if (resp.task_id + worker_id) % 7 == 0:
                        # fail some tasks: they must re-queue, not vanish
                        servicer.report_task_result(
                            msg.ReportTaskResultRequest(
                                task_id=resp.task_id, err_message="boom"
                            )
                        )
                    else:
                        servicer.report_task_result(
                            msg.ReportTaskResultRequest(task_id=resp.task_id)
                        )
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(num_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert all(not t.is_alive() for t in threads), "worker thread hung"
        assert dispatcher.finished()

        counters = dispatcher.counters(TaskType.TRAINING)
        # exactly-once: 2 epochs x records, regardless of who leased what
        # or how many times a failing task bounced between threads
        assert counters.total_records == 2 * records
        # every lease id handed out was unique (no double-lease of one id)
        ids = [lease[0] for lease in leases]
        assert len(ids) == len(set(ids))
