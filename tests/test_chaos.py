"""Chaos subsystem tests.

The checker is correctness tooling, so the core tests here are
falsification tests: a lost task, a double-reported task and a version
rollback must each be DETECTED (a checker that cannot fail proves
nothing).  Plan model tests pin the replayability contract; hook tests
pin the generation/process fencing that keeps injected faults
deterministic; the end-to-end kill-and-reform path is exercised by the
slow marker test (and by ``benchmarks/reform_bench.py``, now a harness
consumer).
"""

from __future__ import annotations

import json
import os
import time
import types

import pytest

from elasticdl_tpu.chaos.harness import _install_corruption, _read_events
from elasticdl_tpu.chaos.hooks import ChaosInjector
from elasticdl_tpu.chaos.invariants import InvariantChecker
from elasticdl_tpu.chaos.plan import (
    Fault,
    FaultKind,
    FaultPlan,
    builtin_plans,
    random_plan,
    resolve_plan,
)
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.utils.constants import TaskType


# ---- fault plan model -------------------------------------------------------


def test_plan_json_round_trip(tmp_path):
    plan = resolve_plan("preempt_one_worker")
    path = str(tmp_path / "plan.json")
    plan.save(path)
    loaded = FaultPlan.load(path)
    assert loaded.name == plan.name
    assert loaded.faults == plan.faults


def test_random_plan_is_replayable_by_seed():
    a, b = random_plan(1234), random_plan(1234)
    assert a.faults == b.faults
    assert a.faults != random_plan(1235).faults or a.seed != 1235


def test_random_plan_generations_follow_reforms():
    """A fault scheduled after k re-formation-causing faults targets
    generation k — otherwise it could never fire (the world it names is
    gone).  Heartbeat drops count: their window outlasts the harness
    timeout, so the frozen worker is declared dead and the world
    re-forms just like after a kill."""
    reforming = (
        FaultKind.PREEMPT,
        FaultKind.KILL_COORDINATOR,
        FaultKind.DROP_HEARTBEAT,
    )
    for seed in range(20):
        plan = random_plan(seed)
        reforms = 0
        for fault in plan.faults:
            assert fault.cluster_version == reforms
            if fault.kind in reforming:
                reforms += 1


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError):
        Fault(kind="meteor_strike", fault_id="x")


def test_builtin_plans_parse_and_target_valid_processes():
    plans = builtin_plans(num_workers=2)
    assert {"none", "preempt_one_worker", "preempt_coordinator"} <= set(plans)
    for plan in plans.values():
        for fault in plan.faults:
            if fault.process_id is not None:
                assert 0 <= fault.process_id < 2
    assert not plans["none"].faults


def test_resolve_plan_random_spelling():
    plan = resolve_plan("random:7")
    assert plan.seed == 7
    with pytest.raises(KeyError):
        resolve_plan("no_such_plan")


# ---- invariant checker: must catch what it claims to catch -----------------


def _drive_clean_job(checker, shards=None, num_epochs=1):
    d = TaskDispatcher(
        shards or {"s": (0, 256)},
        records_per_task=64,
        num_epochs=num_epochs,
        shuffle_seed=3,
    )
    d.add_observer(checker)
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        d.report(tid, success=True)
    return d


def test_checker_passes_clean_run():
    checker = InvariantChecker(expected_records=256)
    d = _drive_clean_job(checker)
    assert checker.check(d.counters(TaskType.TRAINING)) == []
    summary = checker.summary()
    assert summary["ok"]
    assert all(i["status"] == "PASS" for i in summary["invariants"])


def test_checker_detects_lost_task():
    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher(
        {"s": (0, 256)}, records_per_task=64, shuffle_seed=3
    )
    d.add_observer(checker)
    leases = []
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        leases.append(tid)
    # complete all but one; the last lease is never reported (lost)
    for tid in leases[:-1]:
        d.report(tid, success=True)
    violations = checker.check()
    assert any(v.invariant == "exactly_once" for v in violations)
    assert any("never successfully trained" in v.detail for v in violations)
    # records_accounted must flag the shortfall too
    assert any(v.invariant == "records_accounted" for v in violations)


def test_checker_detects_double_reported_task():
    checker = InvariantChecker(expected_records=256)
    d = _drive_clean_job(checker)
    # simulate a dispatcher double-count: the same completion is
    # delivered to observers twice
    rec = next(iter(checker._tasks.values()))
    checker.on_task_reported(99, rec.task, True, True)
    violations = checker.check(d.counters(TaskType.TRAINING))
    assert any(
        v.invariant == "exactly_once" and "double-counted" in v.detail
        for v in violations
    )


def test_checker_ignores_uncounted_reports():
    """A report the dispatcher correctly DROPPED (stale lease) must not
    count as a completion — dropping is the fix, not the bug."""
    checker = InvariantChecker(expected_records=256)
    d = _drive_clean_job(checker)
    rec = next(iter(checker._tasks.values()))
    checker.on_task_reported(99, rec.task, True, False)  # counted=False
    assert checker.check(d.counters(TaskType.TRAINING)) == []


def test_checker_detects_version_rollback():
    checker = InvariantChecker()
    checker.on_version_report(0, 3)
    checker.on_version_report(0, 5)
    checker.on_version_report(0, 4)  # rollback within one generation
    violations = checker.check()
    assert any(v.invariant == "version_monotonic" for v in violations)


def test_checker_allows_rewind_across_reform_but_requires_progress():
    checker = InvariantChecker()
    checker.on_version_report(0, 6)
    checker.on_reform(1, dead_workers=[1], reason="worker_failure")
    # restored from the version-4 checkpoint: a legitimate rewind
    checker.on_version_report(2, 4)
    assert not any(
        v.invariant == "version_monotonic" for v in checker.check()
    )
    # ...but stalling at the pre-reform high-water mark is a violation
    assert any(v.invariant == "reform_progress" for v in checker.check())
    checker.on_version_report(2, 8)
    assert not any(
        v.invariant == "reform_progress" for v in checker.check()
    )


def test_checker_epoch_tasks_are_distinct_identities():
    """Each epoch re-slices the shards into fresh Task objects: the same
    record range trained once per epoch is exactly-once, not double."""
    checker = InvariantChecker(expected_records=512)
    d = _drive_clean_job(checker, num_epochs=2)
    assert checker.check(d.counters(TaskType.TRAINING)) == []
    assert checker.summary()["tasks_tracked"] == 8  # 4 tasks x 2 epochs


def test_checker_retried_task_counts_once():
    """A task that fails, re-queues and then succeeds is exactly-once."""
    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher(
        {"s": (0, 256)}, records_per_task=64, shuffle_seed=3
    )
    d.add_observer(checker)
    tid, task = d.get(worker_id=0)
    d.report(tid, success=False)  # fails; re-queued
    while True:
        tid, task = d.get(worker_id=1)
        if task is None:
            break
        d.report(tid, success=True)
    assert checker.check(d.counters(TaskType.TRAINING)) == []


def test_checker_observer_replay_on_attach():
    """Attaching after construction (the harness does) still sees the
    epoch-0 tasks the dispatcher constructor created."""
    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=3)
    checker = InvariantChecker(expected_records=256)
    d.add_observer(checker)
    assert checker.summary()["tasks_tracked"] == 4


# ---- worker-side injector fencing ------------------------------------------


def _plan_with(*faults):
    return FaultPlan(name="t", faults=list(faults))


def test_injector_arms_only_matching_process_and_generation():
    fault = Fault(
        kind=FaultKind.PREEMPT, fault_id="k", at_step=5, process_id=1
    )
    gen1 = Fault(
        kind=FaultKind.PREEMPT,
        fault_id="k2",
        at_step=5,
        process_id=0,
        cluster_version=1,
    )
    # wrong process, wrong generation: nothing armed
    inj = ChaosInjector(
        _plan_with(fault, gen1), process_id=0, cluster_version=0,
        worker_id=0,
    )
    assert inj._pending == []
    # right process + generation
    inj = ChaosInjector(
        _plan_with(fault, gen1), process_id=1, cluster_version=0,
        worker_id=3,
    )
    assert [f.fault_id for f in inj._pending] == ["k"]
    inj = ChaosInjector(
        _plan_with(fault, gen1), process_id=0, cluster_version=1,
        worker_id=5,
    )
    assert [f.fault_id for f in inj._pending] == ["k2"]


def test_injector_heartbeat_drop_freezes_whole_process(tmp_path):
    """DROP_HEARTBEAT models a frozen process: the training thread
    stalls for the window (step-task pulls are implicit heartbeats — a
    worker that keeps pulling is correctly never declared dead) and the
    beat thread is suppressed throughout it."""
    events = str(tmp_path / "events.jsonl")
    fault = Fault(
        kind=FaultKind.DROP_HEARTBEAT,
        fault_id="hb",
        at_step=3,
        process_id=0,
        duration_secs=0.2,
    )
    inj = ChaosInjector(
        _plan_with(fault), process_id=0, cluster_version=0, worker_id=0,
        events_path=events,
    )
    assert not inj.heartbeat_suppressed()
    inj.on_step(2)
    assert not inj.heartbeat_suppressed()  # not armed yet
    t0 = time.monotonic()
    suppressed_during: list[bool] = []
    timer = __import__("threading").Timer(
        0.1, lambda: suppressed_during.append(inj.heartbeat_suppressed())
    )
    timer.start()
    inj.on_step(3)
    assert time.monotonic() - t0 >= 0.2  # training thread stalled
    timer.join()
    assert suppressed_during == [True]  # beats suppressed mid-window
    assert not inj.heartbeat_suppressed()  # window closed with the stall
    inj.on_step(4)  # fire-once: must not re-freeze
    assert not inj.heartbeat_suppressed()
    faults, _ = _read_events(events)
    assert [e["fault_id"] for e in faults] == ["hb"]
    assert faults[0]["step"] == 3
    assert "monotonic" in faults[0] and "time" in faults[0]


def test_injector_batch_delay_preserves_stream(tmp_path):
    fault = Fault(
        kind=FaultKind.DELAY_BATCHES,
        fault_id="slow",
        at_step=0,
        delay_ms=1.0,
        duration_secs=5.0,
    )
    inj = ChaosInjector(
        _plan_with(fault), process_id=0, cluster_version=0, worker_id=0,
        events_path=str(tmp_path / "e.jsonl"),
    )
    inj.on_step(0)
    # the shim only delays — every batch passes through, in order
    assert list(inj.wrap_batches(iter(range(5)))) == [0, 1, 2, 3, 4]


def test_injector_kill_in_checkpoint_arms_via_save_hook(tmp_path, monkeypatch):
    killed = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: killed.append(sig))
    fault = Fault(
        kind=FaultKind.KILL_IN_CHECKPOINT,
        fault_id="ck",
        at_step=4,
        process_id=0,
    )
    inj = ChaosInjector(
        _plan_with(fault), process_id=0, cluster_version=0, worker_id=0,
        events_path=str(tmp_path / "e.jsonl"),
    )
    inj.on_step(4)  # arms (does not fire at a step boundary)
    assert not killed
    inj.on_checkpoint_save(2)  # below at_step: survives
    assert not killed
    inj.on_checkpoint_save(4)
    assert killed  # died entering the save
    faults, _ = _read_events(str(tmp_path / "e.jsonl"))
    assert faults[0]["phase"] == "checkpoint_save"


def test_events_log_skips_torn_lines(tmp_path):
    path = str(tmp_path / "e.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"fault_id": "a", "kind": "preempt_worker"}) + "\n")
        f.write('{"fault_id": "b", "ki')  # torn write from a killed proc
    faults, _ = _read_events(path)
    assert [e["fault_id"] for e in faults] == ["a"]


# ---- deliberate corruption must trip the checker ---------------------------


def _fake_master(dispatcher):
    servicer = types.SimpleNamespace(
        _observers=[], add_version_observer=lambda cb: None
    )
    return types.SimpleNamespace(task_d=dispatcher, servicer=servicer)


def test_corruption_double_report_is_detected():
    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    _install_corruption(_fake_master(d), checker, "double_report")
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        d.report(tid, success=True)
    assert d.finished()  # the JOB completes fine — the ACCOUNTING is corrupt
    violations = checker.check(d.counters(TaskType.TRAINING))
    assert any(
        v.invariant == "exactly_once" and "double-counted" in v.detail
        for v in violations
    )


def test_corruption_lose_task_is_detected():
    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    _install_corruption(_fake_master(d), checker, "lose_task")
    while True:
        tid, task = d.get(worker_id=0)
        if task is None:
            break
        d.report(tid, success=True)
    violations = checker.check(d.counters(TaskType.TRAINING))
    assert any(
        v.invariant == "exactly_once"
        and "never successfully trained" in v.detail
        for v in violations
    )


def test_corruption_rejects_unknown_mode():
    d = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    with pytest.raises(ValueError):
        _install_corruption(
            _fake_master(d), InvariantChecker(), "cosmic_rays"
        )


# ---- master-side plumbing ---------------------------------------------------


def test_instance_manager_world_size_clamped():
    from elasticdl_tpu.master.master import LocalInstanceManager

    im = LocalInstanceManager.__new__(LocalInstanceManager)
    im._num_workers = 4
    im._world_size = 4
    im.set_world_size(2)
    assert im.world_size == 2
    im.set_world_size(0)
    assert im.world_size == 1  # never below one process
    im.set_world_size(99)
    assert im.world_size == 4  # never beyond the configured fleet


# ---- end to end (multi-process; slow) --------------------------------------


@pytest.mark.slow
def test_chaos_runner_preempt_end_to_end(tmp_path):
    """The acceptance path: a preempt_one_worker chaos job completes,
    all invariants PASS, and the report carries the injected fault."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("preempt_one_worker", num_workers=2),
            workdir=str(tmp_path),
            num_records=512,
            num_epochs=2,
        )
    )
    assert report["invariants_ok"], report
    assert report["records_ok"]
    assert [e["kind"] for e in report["faults_injected"]] == [
        "preempt_worker"
    ]
    assert report["reforms"], "the kill never re-formed the world"
    assert report["reform_latency_secs"] > 0
