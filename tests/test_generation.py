"""KV-cached autoregressive generation for the transformer LM.

Correctness bar: cached one-token-at-a-time decoding must produce the
EXACT same greedy continuation as re-running the full forward pass per
step (the O(seq^2)-per-step oracle); and a model trained on the Markov
sequence data must generate its transition chain.
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.models import long_seq_transformer as lm


def _init_params(model, batch=2, seq=8, seed=0):
    feats = {"tokens": jnp.zeros((batch, seq), jnp.int32)}
    return model.init(jax.random.PRNGKey(seed), feats)["params"]


def _greedy_full_forward(model, params, prompt, num_steps):
    """Oracle: recompute the whole sequence every step."""
    tokens = jnp.asarray(prompt, jnp.int32)
    for _ in range(num_steps):
        logits = model.apply({"params": params}, {"tokens": tokens})
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


def test_cached_generation_matches_full_forward():
    kwargs = dict(
        vocab_size=64, num_layers=2, embed_dim=32, num_heads=4
    )
    model = lm.custom_model(**kwargs)
    params = _init_params(model)
    prompt = jnp.asarray([[3, 7, 1], [10, 2, 5]], jnp.int32)

    cached = lm.generate(params, prompt, num_steps=6, **kwargs)
    oracle = _greedy_full_forward(model, params, prompt, num_steps=6)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))


def test_cached_generation_matches_full_forward_gqa():
    kwargs = dict(
        vocab_size=64,
        num_layers=1,
        embed_dim=32,
        num_heads=4,
        num_kv_heads=2,  # the cache shrinks by the group factor
    )
    model = lm.custom_model(**kwargs)
    params = _init_params(model, seed=1)
    prompt = jnp.asarray([[9, 4], [0, 31]], jnp.int32)
    cached = lm.generate(params, prompt, num_steps=5, **kwargs)
    oracle = _greedy_full_forward(model, params, prompt, num_steps=5)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))


def test_sampling_modes():
    """temperature=0 is greedy; near-zero temperature sampling matches
    greedy (the distribution collapses onto the argmax); same seed is
    reproducible and sampling needs a key."""
    import pytest

    kwargs = dict(vocab_size=32, num_layers=1, embed_dim=32, num_heads=2)
    model = lm.custom_model(**kwargs)
    params = _init_params(model)
    prompt = jnp.asarray([[1, 2]], jnp.int32)

    greedy = lm.generate(params, prompt, num_steps=5, **kwargs)
    cold = lm.generate(
        params,
        prompt,
        num_steps=5,
        temperature=1e-4,
        rng=jax.random.PRNGKey(7),
        **kwargs,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(cold))

    hot_a = lm.generate(
        params,
        prompt,
        num_steps=5,
        temperature=5.0,
        top_k=8,
        rng=jax.random.PRNGKey(1),
        **kwargs,
    )
    hot_b = lm.generate(
        params,
        prompt,
        num_steps=5,
        temperature=5.0,
        top_k=8,
        rng=jax.random.PRNGKey(1),
        **kwargs,
    )
    np.testing.assert_array_equal(np.asarray(hot_a), np.asarray(hot_b))

    with pytest.raises(ValueError):
        lm.generate(params, prompt, num_steps=2, temperature=1.0, **kwargs)


def test_trained_model_generates_the_markov_chain(tmp_path):
    """Train briefly on gen_sequence's permutation chain, then generate:
    most continuations should follow next = perm[cur] (noise rate 5%)."""
    import optax

    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
    from elasticdl_tpu.trainer.step import build_train_step

    data_dir = synthetic.gen_sequence(
        str(tmp_path / "seq"),
        num_records=256,
        num_shards=1,
        seq_len=32,
        seed=0,
    )
    reader = RecordIODataReader(data_dir=data_dir)
    name, (start, count) = next(iter(reader.create_shards().items()))
    task = type(
        "T", (), {"shard_name": name, "start": start, "end": start + count}
    )
    ds = lm.dataset_fn(
        Dataset.from_generator(lambda: reader.read_records(task)),
        Modes.TRAINING,
        reader.metadata,
    )
    batches = list(ds.batch(32))

    kwargs = dict(num_layers=1, embed_dim=64, num_heads=2)
    model = lm.custom_model(**kwargs)
    feats, _ = batches[0]
    params, model_state = init_model(model, feats)
    state = TrainState.create(
        model.apply, params, optax.adam(3e-3), model_state
    )
    train_step = build_train_step(lm.loss, compute_dtype=None)
    for _ in range(8):
        for f, l in batches:
            state, _m = train_step(state, f, l)

    perm = np.random.RandomState(1234).permutation(lm.VOCAB)
    prompt = np.array([[5, int(perm[5])], [40, int(perm[40])]])
    out = np.asarray(
        lm.generate(state.params, prompt, num_steps=10, **kwargs)
    )
    correct = sum(
        int(out[b, t + 1] == perm[out[b, t]])
        for b in range(out.shape[0])
        for t in range(1, out.shape[1] - 1)
    )
    total = out.shape[0] * (out.shape[1] - 2)
    # the data itself carries 5% routing noise; 0.7 leaves margin for a
    # short training run while still proving the chain was learned
    assert correct / total > 0.7, (correct, total, out)
