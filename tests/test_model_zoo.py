"""Model-zoo parity: every reference model module exists, resolves through
the spec contract, and trains (one-plus jitted steps, finite loss) on its
synthetic dataset.

This is the analogue of the reference's ``example_test.py:15-60`` which
runs every model-zoo model through the distributed harness; here the tier-1
check is per-model spec + train-step soundness (the distributed run is
covered by the worker/master tests).
"""

import jax
import numpy as np
import pytest

from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_reader import RecordIODataReader
from elasticdl_tpu.trainer.metrics import (
    metric_tree_results,
    update_metric_tree,
)
from elasticdl_tpu.trainer.state import Modes, TrainState, init_model
from elasticdl_tpu.trainer.step import (
    build_eval_step,
    build_train_step,
    resolve_optimizer,
)
from elasticdl_tpu.utils.model_utils import get_model_spec

# (model_def, synthetic generator, records, batch)
ZOO = [
    ("mnist_functional_api.mnist_functional_api.custom_model", "mnist", 64, 16),
    ("mnist_subclass.mnist_subclass.custom_model", "mnist", 64, 16),
    (
        "cifar10_functional_api.cifar10_functional_api.custom_model",
        "cifar10",
        32,
        8,
    ),
    ("cifar10_subclass.cifar10_subclass.custom_model", "cifar10", 32, 8),
    ("deepfm_functional_api.deepfm_functional_api.custom_model", "frappe", 64, 16),
    ("deepfm_edl_embedding.deepfm_edl_embedding.custom_model", "frappe", 64, 16),
    (
        "census_dnn_model.census_functional_api.custom_model",
        "census",
        64,
        16,
    ),
    ("census_dnn_model.census_sequential.custom_model", "census", 64, 16),
    ("census_dnn_model.census_subclass.custom_model", "census", 64, 16),
    ("heart_functional_api.heart_functional_api.custom_model", "heart", 64, 16),
    ("odps_iris_dnn_model.odps_iris_dnn_model.custom_model", "iris", 64, 16),
    # TPU-build additions (no reference counterpart): long-context
    # transformer (flash attention on the single-device path) and the
    # pipeline-parallel transformer (sequential-scan path here)
    (
        "long_seq_transformer.long_seq_transformer.custom_model",
        "sequence",
        32,
        8,
    ),
    (
        "pipelined_transformer.pipelined_transformer.custom_model",
        "sequence",
        32,
        8,
    ),
]


def _first_batches(spec, data_dir, batch_size, n=2, mode=Modes.TRAINING):
    reader = RecordIODataReader(data_dir=data_dir)
    shards = reader.create_shards()
    name, (start, count) = next(iter(shards.items()))

    class _Task:
        shard_name = name

    _Task.start, _Task.end = start, start + count
    ds = Dataset.from_generator(lambda: reader.read_records(_Task))
    ds = spec.dataset_fn(ds, mode, reader.metadata)
    out = []
    for el in ds.batch(batch_size):
        out.append(el)
        if len(out) >= n:
            break
    return out


@pytest.mark.parametrize("model_def,gen,records,batch", ZOO)
def test_zoo_model_trains(model_def, gen, records, batch, tmp_path):
    data_dir = synthetic.GENERATORS[gen](
        str(tmp_path / gen), num_records=records, num_shards=1, seed=0
    )
    spec = get_model_spec("", model_def)
    model = spec.build_model()
    batches = _first_batches(spec, data_dir, batch)
    features, labels = batches[0]

    params, model_state = init_model(model, features)
    tx = resolve_optimizer(spec.optimizer)
    state = TrainState.create(model.apply, params, tx, model_state)
    train_step = build_train_step(spec.loss, compute_dtype=None)

    losses = []
    for feats, labs in batches * 3:
        state, metrics = train_step(state, feats, labs)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert int(state.step) == len(losses)

    # eval path + metrics contract
    eval_step = build_eval_step(spec.loss)
    outputs, eval_loss = eval_step(state, features, labels)
    assert np.isfinite(float(eval_loss))
    if spec.eval_metrics_fn is not None:
        tree = spec.eval_metrics_fn()
        update_metric_tree(tree, np.asarray(labels), jax.device_get(outputs))
        results = metric_tree_results(tree)
        assert results and all(np.isfinite(v) for v in results.values())


def test_resnet50_builds_and_steps(tmp_path):
    """ResNet-50 is too heavy for the per-model sweep on CPU; one tiny
    train step proves the full block stack + decayed-weights optimizer."""
    data_dir = synthetic.gen_cifar10(
        str(tmp_path / "c10"), num_records=4, num_shards=1, seed=0
    )
    spec = get_model_spec(
        "", "resnet50_subclass.resnet50_subclass.custom_model"
    )
    model = spec.build_model()
    (features, labels), = _first_batches(spec, data_dir, 2, n=1)
    params, model_state = init_model(model, features)
    n_kernels = len(
        [1 for k in jax.tree_util.tree_leaves(params) if k.ndim == 4]
    )
    assert n_kernels == 1 + 16 * 3 + 4  # stem + 16 blocks x3 + 4 shortcuts
    # softmax-probability output contract (the loss consumes probabilities)
    probs = model.apply({"params": params, **model_state}, features)
    np.testing.assert_allclose(
        np.asarray(probs).sum(-1), np.ones(2), rtol=1e-5
    )
    tx = resolve_optimizer(spec.optimizer)
    state = TrainState.create(model.apply, params, tx, model_state)
    train_step = build_train_step(spec.loss, compute_dtype=None)
    state, metrics = train_step(state, features, labels)
    assert np.isfinite(float(metrics["loss"]))


def test_imagenet_prep_and_model():
    import io

    import pytest as _pytest

    from elasticdl_tpu.data.reader import decode_example
    from elasticdl_tpu.models import imagenet_resnet50

    m = imagenet_resnet50.custom_model(num_classes=12)
    assert m.num_classes == 12

    # real image bytes -> (224, 224, 3) record
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(
        np.zeros((8, 8, 3), np.uint8)
    ).save(buf, format="PNG")
    rec = imagenet_resnet50.prepare_data_for_a_single_file(
        io.BytesIO(buf.getvalue()), "n02/7_sample.JPEG"
    )
    ex = decode_example(rec)
    assert int(ex["label"]) == 7
    assert ex["image"].shape == (224, 224, 3)

    # garbage bytes must fail loudly at prep time, not corrupt the dataset
    with _pytest.raises(ValueError, match="not a decodable image"):
        imagenet_resnet50.prepare_data_for_a_single_file(
            io.BytesIO(b"\x01\x02\x03"), "n02/7_sample.JPEG"
        )


def test_deepfm_edl_sharding_rules():
    """The rules must actually APPLY on a mesh (odd 5383 vocab is padded to
    /128 so ep=4 divides), not just regex-match — and the spec loader must
    surface the hook."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.models import deepfm_edl_embedding
    from elasticdl_tpu.parallel.mesh import MeshConfig
    from elasticdl_tpu.parallel.sharding import infer_param_specs

    mesh = MeshConfig.from_string("dp=2,ep=4").create(jax.devices("cpu")[:8])
    rules = deepfm_edl_embedding.sharding_rules(mesh)
    assert len(rules) == 2
    assert rules[0].matches("embedding/embedding")
    assert not rules[0].matches("my_embedding/embedding")

    spec = get_model_spec(
        "", "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    )
    assert spec.sharding_rules is deepfm_edl_embedding.sharding_rules
    model = spec.build_model()
    ids = np.zeros((2, 10), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    assert params["embedding"]["embedding"].shape[0] % 128 == 0  # padded
    specs = infer_param_specs(params, mesh, rules)
    assert specs["embedding"]["embedding"] == P("ep", None)
    assert specs["id_bias"]["embedding"] == P("ep", None)
