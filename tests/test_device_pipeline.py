"""Device-path pipelining (ISSUE 12): double-buffered h2d staging,
batch-buffer donation, async retire-behind — and the three load-bearing
contracts it must preserve: bit-exact masked parity with the serial
path, read-after-retire of a donated buffer is caught, and
stream-order/error-propagation through the staging thread.
"""

from __future__ import annotations

import threading
import time

import flax.linen as nn
import jax
import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel.distributed import SPMDTrainer
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.trainer import device_pipeline
from elasticdl_tpu.trainer.device_pipeline import (
    DEVICE_PREFETCH_ENV,
    DeviceStager,
    RetiredBufferError,
    StagedGroup,
    resolve_device_prefetch,
    resolve_donate_state,
    run_pipelined_steps,
    stage_depth,
)
from elasticdl_tpu.trainer.stacking import PreStacked, run_stacked_steps


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(DEVICE_PREFETCH_ENV, raising=False)
    device_pipeline._reset_totals_for_tests()
    yield


# ---- flag / helper resolution ----------------------------------------------


def test_resolve_device_prefetch_flag_wins_and_env_falls_back(monkeypatch):
    assert resolve_device_prefetch(None) is False
    assert resolve_device_prefetch(True) is True
    assert resolve_device_prefetch(False) is False
    monkeypatch.setenv(DEVICE_PREFETCH_ENV, "1")
    assert resolve_device_prefetch(None) is True
    # an explicit flag still beats the env (bench on/off overrides)
    assert resolve_device_prefetch(False) is False
    # the env parses like parse_bool: falsey spellings mean OFF — a
    # truthy-string read would let "=0" build a donated step program on
    # some hosts only (the mixed-world hazard the uniformity contract
    # forbids)
    for falsey in ("0", "false", "FALSE", "no", "off", " "):
        monkeypatch.setenv(DEVICE_PREFETCH_ENV, falsey)
        assert resolve_device_prefetch(None) is False
    monkeypatch.setenv(DEVICE_PREFETCH_ENV, "true")
    assert resolve_device_prefetch(None) is True
    # an unrecognized spelling (typo) fails SAFE: off, never silently on
    monkeypatch.setenv(DEVICE_PREFETCH_ENV, "flase")
    assert resolve_device_prefetch(None) is False


def test_resolve_donate_state_is_the_one_definition_site():
    class A:
        donate_state = False

    class B:
        pass

    assert resolve_donate_state(A()) is False
    assert resolve_donate_state(B()) is True
    # the three runtimes now resolve through this helper, not their own
    # getattr copies
    import inspect

    from elasticdl_tpu.trainer import local_executor
    from elasticdl_tpu.worker import lockstep, worker

    for module in (local_executor, worker, lockstep):
        source = inspect.getsource(module)
        assert 'getattr(self._args, "donate_state"' not in source
        assert "resolve_donate_state" in source


def test_device_prefetch_flag_never_reaches_worker_argv():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data",
        "/tmp/x",
    ]
    off = parse_master_args(base)
    on = parse_master_args(base + ["--device_prefetch", "true"])
    argv_off = build_worker_arguments(off, 0, "localhost:1")
    argv_on = build_worker_arguments(on, 0, "localhost:1")
    # even when SET it travels by env, never worker argv — and the off
    # argv is byte-identical to a build without the flag
    assert "--device_prefetch" not in argv_on
    assert argv_on == argv_off


def test_stage_depth_collapses_to_barrier_under_anatomy():
    assert stage_depth(None) == device_pipeline.RETIRE_WINDOW
    assert stage_depth(object()) == 1


def test_disabled_gates_take_no_clock_reads(monkeypatch):
    def boom():
        raise AssertionError("clock read on the disabled path")

    monkeypatch.setattr("time.monotonic", boom)
    assert device_pipeline.heartbeat_snapshot() == {}
    assert stage_depth(None) == device_pipeline.RETIRE_WINDOW


# ---- real-trainer parity ----------------------------------------------------


class _Dense(nn.Module):
    """Deterministic per-row model (no batch stats, no dropout), so
    masked parity is exact — the test_compile_canonical idiom."""

    @nn.compact
    def __call__(self, x, training=False):
        return nn.Dense(3)(x)


def _loss(labels, predictions):
    labels = labels.reshape(-1)
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels
    ).mean()


def _mesh():
    return MeshConfig.from_string("dp=1").create()


def _trainer(mesh, donate_batch=False):
    feats = np.zeros((1, 4), np.float32)
    return SPMDTrainer(
        mesh,
        _Dense(),
        _loss,
        optax.sgd(0.1, momentum=0.9),
        feats,
        embedding_threshold=None,
        donate_batch=donate_batch,
    )


def _batches(sizes, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (
            rng.randn(n, 4).astype(np.float32),
            rng.randint(0, 3, size=(n,)).astype(np.int32),
        )
        for n in sizes
    ]


def _assert_params_bitexact(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(jax.device_get(a.state.params)),
        jax.tree_util.tree_leaves(jax.device_get(b.state.params)),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestPipelinedParity:
    def test_train_parity_full_groups_and_masked_tail(self):
        mesh = _mesh()
        batches = _batches([8, 8, 8, 8, 5])
        serial = _trainer(mesh)
        n1 = run_stacked_steps(
            lambda: serial, iter(batches), 2, canonical_rows=8
        )
        piped = _trainer(mesh, donate_batch=True)
        n2 = run_stacked_steps(
            lambda: piped,
            iter(batches),
            2,
            canonical_rows=8,
            device_prefetch=True,
        )
        assert n1 == n2 == 37
        assert serial.step == piped.step == 5
        _assert_params_bitexact(serial, piped)

    def test_train_parity_prestacked_and_trailing_singles(self):
        mesh = _mesh()
        plain = _batches([8, 8, 8, 5], seed=3)
        feats = np.stack([plain[0][0], plain[1][0]])
        labels = np.stack([plain[0][1], plain[1][1]])
        stream = [
            PreStacked(feats, labels, 16, feats[0]),
            plain[2],
            plain[3],
        ]
        serial = _trainer(mesh)
        n1 = run_stacked_steps(
            lambda: serial, iter(stream), 2, canonical_rows=8
        )
        piped = _trainer(mesh, donate_batch=True)
        n2 = run_stacked_steps(
            lambda: piped,
            iter(stream),
            2,
            canonical_rows=8,
            device_prefetch=True,
        )
        assert n1 == n2 == 29
        _assert_params_bitexact(serial, piped)

    def test_eval_parity_with_donating_trainer(self):
        """Donation covers the TRAIN step only: the eval step of a
        donate_batch trainer returns the same masked loss as the
        serial trainer's, and its inputs stay readable."""
        mesh = _mesh()
        batches = _batches([8, 8], seed=5)
        serial = _trainer(mesh)
        piped = _trainer(mesh, donate_batch=True)
        run_stacked_steps(lambda: serial, iter(batches), 2, canonical_rows=8)
        run_stacked_steps(
            lambda: piped,
            iter(batches),
            2,
            canonical_rows=8,
            device_prefetch=True,
        )
        feats, labels = _batches([5], seed=9)[0]
        results = []
        for tr in (serial, piped):
            pf = tr.place_canonical(feats, 8)
            pl = tr.place_canonical(labels, 8)
            outputs, loss = tr.eval_step(pf, pl, tr.place_mask(5, 8))
            jax.block_until_ready(outputs)
            np.asarray(pf)  # eval inputs are NOT donated: still readable
            results.append(float(jax.device_get(loss)))
        assert results[0] == results[1]

    def test_hook_cadence_matches_serial(self):
        mesh = _mesh()
        batches = _batches([8, 8, 8], seed=7)
        calls_serial, calls_piped = [], []
        posts_serial, posts_piped = [], []
        serial = _trainer(mesh)
        run_stacked_steps(
            lambda: serial,
            iter(batches),
            2,
            pre_batch=lambda f: calls_serial.append(f.shape),
            post_group=lambda: posts_serial.append(1),
            canonical_rows=8,
        )
        piped = _trainer(mesh, donate_batch=True)
        run_stacked_steps(
            lambda: piped,
            iter(batches),
            2,
            pre_batch=lambda f: calls_piped.append(f.shape),
            post_group=lambda: posts_piped.append(1),
            canonical_rows=8,
            device_prefetch=True,
        )
        # one pre_batch per STEP, one post_group per dispatch group
        assert calls_serial == calls_piped
        assert len(posts_serial) == len(posts_piped) == 2


def test_local_executor_e2e_parity_bitexact(tmp_path):
    """The whole executor path (reader -> decode -> TaskPrefetcher ->
    grouping -> dispatch) with --device_prefetch on is bit-identical to
    off: same step program, same k, same pinned shuffle — only the
    execution discipline differs."""
    import jax as _jax

    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    train_dir = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=256, num_shards=2, seed=0
    )

    def run(prefetch: str):
        args = parse_master_args(
            [
                "--model_def",
                "mnist_functional_api.mnist_functional_api.custom_model",
                "--training_data",
                train_dir,
                "--minibatch_size",
                "32",
                "--records_per_task",
                "64",
                "--num_epochs",
                "1",
                "--compute_dtype",
                "float32",
                "--steps_per_dispatch",
                "2",
                "--shuffle_seed",
                "7",
                "--device_prefetch",
                prefetch,
            ]
        )
        ex = LocalExecutor(args)
        ex.run()
        return _jax.device_get(ex.state.params), int(ex.state.step)

    params_off, steps_off = run("false")
    params_on, steps_on = run("true")
    assert steps_off == steps_on == 8
    for x, y in zip(
        _jax.tree_util.tree_leaves(params_off),
        _jax.tree_util.tree_leaves(params_on),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---- donation falsification -------------------------------------------------


class TestDonationFalsification:
    def test_staged_group_take_twice_is_caught(self):
        staged = StagedGroup(
            StagedGroup.KIND_STACKED,
            ("placed",),
            steps=1,
            records=8,
            hook_features=(),
        )
        assert staged.take() == ("placed",)
        with pytest.raises(RetiredBufferError):
            staged.take()

    def test_jax_read_after_donate_raises_on_aliased_buffer(self):
        """The backend-level half of the contract: where XLA does alias
        a donated buffer, a read-after-retire raises on the deleted
        Array (the staging layer's single-take discipline exists so the
        runtimes never reach this error)."""
        f = jax.jit(lambda x: x * 2, donate_argnums=(0,))
        x = jax.device_put(np.ones(8, np.float32))
        jax.block_until_ready(f(x))
        if not x.is_deleted():
            pytest.skip("backend did not consume the donation")
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(x)

    def test_donated_train_batch_is_dead_when_aliased(self):
        """If the backend aliases the train batch, a retired buffer
        must be unreadable; if it cannot alias (tiny models), the
        buffer survives — either way the dispatch math is unchanged
        (parity tests above)."""
        mesh = _mesh()
        tr = _trainer(mesh, donate_batch=True)
        feats, labels = _batches([8], seed=11)[0]
        pf = tr.place_batch(feats)
        pl = tr.place_batch(labels)
        pm = tr.place_batch(np.ones(8, np.float32))
        jax.block_until_ready(tr.train_step(pf, pl, pm))
        if pf.is_deleted():
            with pytest.raises(RuntimeError, match="deleted"):
                np.asarray(pf)


# ---- stager: order, errors, lifecycle ---------------------------------------


class _FakeTrainer:
    """Host-only trainer double: real padding, identity placement."""

    step = 0

    def pad_to(self, tree, rows):
        def _pad(x):
            x = np.asarray(x)
            if x.shape[0] == rows:
                return x
            return np.concatenate(
                [x, np.repeat(x[-1:], rows - x.shape[0], axis=0)]
            )

        return jax.tree_util.tree_map(_pad, tree)

    def row_mask(self, n, rows):
        mask = np.zeros(rows, np.float32)
        mask[:n] = 1.0
        return mask

    def place_batch(self, tree):
        return tree

    def place_stacked(self, tree):
        return tree

    def train_step(self, f, l, w=None):
        return np.float32(0.0)

    def train_steps_stacked(self, f, l, w=None):
        return np.float32(0.0)


def test_stager_preserves_stream_order_and_group_policy():
    batches = _batches([8, 8, 8, 8, 5], seed=1)
    stager = DeviceStager(
        lambda: _FakeTrainer(), iter(batches), 2, canonical_rows=8
    )
    try:
        groups = list(stager)
    finally:
        stager.close()
    # [8,8] [8,8] stacked + [5] trailing singles — in stream order
    assert [g.kind for g in groups] == [
        StagedGroup.KIND_STACKED,
        StagedGroup.KIND_STACKED,
        StagedGroup.KIND_SINGLES,
    ]
    assert [g.records for g in groups] == [16, 16, 5]
    first = groups[0].take()
    np.testing.assert_array_equal(first[0][0], batches[0][0])
    np.testing.assert_array_equal(first[0][1], batches[1][0])


def test_stager_propagates_upstream_error_in_stream_position():
    good = _batches([8, 8], seed=2)

    def stream():
        yield good[0]
        yield good[1]
        raise ValueError("decode exploded")

    stager = DeviceStager(
        lambda: _FakeTrainer(), stream(), 2, canonical_rows=8
    )
    try:
        first = stager.next_staged()
        assert first is not None and first.records == 16
        with pytest.raises(ValueError, match="decode exploded"):
            while True:
                if stager.next_staged() is None:
                    raise AssertionError("stream ended without the error")
    finally:
        stager.close()
    stager._thread.join(timeout=5)
    assert not stager._thread.is_alive()


def test_stager_degrades_staging_failures_to_error_groups():
    """A pad/place failure during STAGING must not poison the stream:
    the group arrives carrying the error + its host batches (the
    task-stream worker falls back to its serial retry path; the grouped
    runtimes re-raise, matching their serial behavior)."""

    class _BadPad(_FakeTrainer):
        def pad_to(self, tree, rows):
            raise ValueError("batch exceeds the canonical shape")

    batches = _batches([8, 8], seed=21)
    stager = DeviceStager(
        lambda: _BadPad(), iter(batches), 2, canonical_rows=8
    )
    try:
        staged = stager.next_staged()
        assert staged is not None and staged.error is not None
        assert "canonical shape" in str(staged.error)
        # the host group survives for the serial fallback
        assert len(staged.host) == 2
        np.testing.assert_array_equal(staged.host[0][0], batches[0][0])
        # the stream then ends cleanly (no crash contract for staging)
        assert stager.next_staged() is None
    finally:
        stager.close()


def test_run_pipelined_reraises_staging_failures_like_serial():
    class _BadPadAfterWarmup(_FakeTrainer):
        calls = 0

        def pad_to(self, tree, rows):
            type(self).calls += 1
            if type(self).calls > 2:  # warmup group pads fine
                raise ValueError("bad batch")
            return super().pad_to(tree, rows)

    trainer = _BadPadAfterWarmup()
    with pytest.raises(ValueError, match="bad batch"):
        run_pipelined_steps(
            lambda: trainer,
            iter(_batches([8] * 4, seed=22)),
            2,
            canonical_rows=8,
        )


def test_stager_close_releases_a_blocked_producer():
    many = _batches([8] * 32, seed=4)
    stager = DeviceStager(
        lambda: _FakeTrainer(), iter(many), 1, canonical_rows=8
    )
    time.sleep(0.05)  # let the producer fill the bounded queue
    stager.close()
    stager._thread.join(timeout=5)
    assert not stager._thread.is_alive()


def test_task_prefetcher_feeds_stager_errors_and_order():
    """The three-deep pipeline seam: a decode error raised on the
    TaskPrefetcher's producer thread crosses BOTH queues and surfaces
    on the consumer, and batches keep task order on the way."""
    from elasticdl_tpu.trainer.host_pipeline import TaskPrefetcher

    tasks = [(1, "t1"), (2, "t2")]

    def next_task():
        return tasks.pop(0) if tasks else (0, None)

    def make_batches(task):
        if task == "t2":
            raise ValueError("shard corrupt")
        return _batches([8, 8], seed=6)

    prefetcher = TaskPrefetcher(next_task, make_batches)
    seen = []
    with pytest.raises(ValueError, match="shard corrupt"):
        for _tid, _task, batches in prefetcher:
            stager = DeviceStager(
                lambda: _FakeTrainer(), iter(batches), 2, canonical_rows=8
            )
            try:
                for staged in stager:
                    seen.append(staged.records)
            finally:
                stager.close()
    prefetcher.close()
    assert seen == [16]


# ---- retire-behind window ---------------------------------------------------


def test_retire_window_bounds_inflight_and_drains_at_end(monkeypatch):
    retired = []
    dispatched = []

    real_block = jax.block_until_ready
    monkeypatch.setattr(
        device_pipeline.jax,
        "block_until_ready",
        lambda out: retired.append(len(dispatched)) or real_block(out),
    )

    class _Tracking(_FakeTrainer):
        def train_steps_stacked(self, f, l, w=None):
            dispatched.append(1)
            return np.float32(0.0)

        def train_step(self, f, l, w=None):
            dispatched.append(1)
            return np.float32(0.0)

    trainer = _Tracking()
    n = run_pipelined_steps(
        lambda: trainer,
        iter(_batches([8] * 10, seed=8)),
        2,
        canonical_rows=8,
    )
    assert n == 80
    assert len(dispatched) == 5
    # a retire only ever happens once the window (2) is exceeded: the
    # first block came after the third dispatch, and every dispatched
    # group was retired by the time the function returned (the task-
    # boundary barrier)
    assert retired[0] == 3
    assert len(retired) == 5


def test_post_group_runs_per_dispatch_not_per_retire():
    posts = []
    trainer = _FakeTrainer()
    run_pipelined_steps(
        lambda: trainer,
        iter(_batches([8] * 6, seed=10)),
        2,
        post_group=lambda: posts.append(1),
        canonical_rows=8,
    )
    assert len(posts) == 3


# ---- anatomy under pipelining -----------------------------------------------


def test_anatomy_commits_sum_exact_under_pipelined_path(tmp_path):
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.telemetry.anatomy import ALL_PHASES, AnatomyRecorder
    from elasticdl_tpu.telemetry.events import read_events

    worker_hooks.install(str(tmp_path), worker_id=1, generation=0)
    try:
        rec = AnatomyRecorder()
        trainer = _FakeTrainer()
        n = run_pipelined_steps(
            lambda: trainer,
            iter(_batches([8, 8, 8, 5], seed=12)),
            2,
            canonical_rows=8,
            anatomy=rec,
        )
        assert n == 29
        # [8,8] warmup + [8,5] staged (the masked tail joins its group)
        assert rec.dispatches == 2
        events = [
            e
            for e in read_events(str(tmp_path / "events.jsonl"))
            if e["event"] == "step_anatomy"
        ]
        assert len(events) == 2
        for event in events:
            tracked = sum(
                event.get(f"{p}_ms", 0.0) for p in ALL_PHASES
            )
            assert abs(event["wall_ms"] - tracked) < 1e-6
            split = event.get("enqueue_ms", 0.0) + event.get(
                "ready_wait_ms", 0.0
            )
            assert abs(split - event["device_compute_ms"]) < 1e-6
    finally:
        worker_hooks.uninstall()


# ---- heartbeat totals: worker -> servicer -> /metrics -----------------------


def test_heartbeat_snapshot_monotone_after_staging():
    assert device_pipeline.heartbeat_snapshot() == {}
    stager = DeviceStager(
        lambda: _FakeTrainer(),
        iter(_batches([8, 8], seed=13)),
        2,
        canonical_rows=8,
    )
    try:
        assert list(stager)  # drain
    finally:
        stager.close()
    snap = device_pipeline.heartbeat_snapshot()
    assert snap["groups"] == 1
    assert snap["stall_ms"] >= 0 and snap["stage_ms"] >= 0


def _servicer():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    shards = {"s": (0, 8)}
    return MasterServicer(4, TaskDispatcher(shards, records_per_task=4))


def test_servicer_prefetch_merge_is_monotone_and_summed():
    from elasticdl_tpu.rpc import messages as msg

    servicer = _servicer()
    beat = {"groups": 10, "stall_ms": 5, "stage_ms": 40}
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=0, step=1, prefetch=beat)
    )
    # a REORDERED (older) beat can't walk anything backward
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=1,
            prefetch={"groups": 4, "stall_ms": 2, "stage_ms": 11},
        )
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=1, step=1, prefetch=beat)
    )
    totals = servicer.prefetch_stats_totals()
    assert totals == {"groups": 20, "stall_ms": 10, "stage_ms": 80}


def test_master_telemetry_mirrors_prefetch_counters():
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    servicer = _servicer()
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=1,
            prefetch={"groups": 7, "stall_ms": 3, "stage_ms": 29},
        )
    )
    telemetry = MasterTelemetry()
    telemetry._servicer = servicer
    text = telemetry.registry.exposition()
    assert "elasticdl_device_prefetch_groups_total 7" in text
    assert "elasticdl_device_prefetch_stall_ms_total 3" in text
    assert "elasticdl_device_prefetch_stage_ms_total 29" in text
