"""Memory observability plane: the component ledger, timestamped
last-writer-wins merging, the /metrics mirror + cardinality cap, the
report's memory and serving sections (with their no_data discipline),
and the on-demand request_profile round trip.

The merge pins mirror tests/test_fleetsim.py's max-merge properties:
reordered, duplicated and batched-then-replayed heartbeat sets must
produce IDENTICAL merged state — with the extra, defining property that
current values go DOWN when a newer-stamped sample says so, while peak
watermarks never decrease.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading

import numpy as np
import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.telemetry import memory as memory_mod
from elasticdl_tpu.telemetry.memory import (
    COMPONENT_MODEL_STATE,
    MemoryLedger,
    pytree_bytes,
    register_component,
    unregister_component,
)
from elasticdl_tpu.utils.merge import last_merge_counters, max_merge_counters


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test starts from an empty component registry and no
    installed ledger (module-global state)."""
    with memory_mod._components_lock:
        saved = dict(memory_mod._components)
        memory_mod._components.clear()
    memory_mod.uninstall()
    yield
    with memory_mod._components_lock:
        memory_mod._components.clear()
        memory_mod._components.update(saved)
    memory_mod.uninstall()


def _dispatcher():
    return TaskDispatcher(
        {"shard": (0, 64)}, records_per_task=64, num_epochs=1
    )


# ---- last_merge_counters properties -----------------------------------------


def test_last_merge_newest_stamp_wins_and_goes_down():
    merged, stamps, totals = {}, {}, {}
    last_merge_counters(merged, {"m": 100}, 1.0, stamps, totals=totals)
    assert merged == {"m": 100} and totals == {"m": 100}
    # newer stamp, LOWER value: applied (the release a max-merge
    # ratchet could never report)
    last_merge_counters(merged, {"m": 40}, 2.0, stamps, totals=totals)
    assert merged == {"m": 40} and totals == {"m": 40}
    # older stamp, higher value: dropped
    last_merge_counters(merged, {"m": 999}, 1.5, stamps, totals=totals)
    assert merged == {"m": 40} and totals == {"m": 40}


def test_last_merge_malformed_values_skipped():
    merged, stamps = {}, {}
    last_merge_counters(
        merged, {"ok": 5, "bad": "not-an-int", "none": None}, 1.0, stamps
    )
    assert merged == {"ok": 5}


def test_last_merge_order_insensitive_permutations():
    """Every delivery order of the same sample set converges to the
    same merged state and the same aggregate."""
    samples = [
        (1.0, {"a": 10, "b": 5}),
        (2.0, {"a": 7}),
        (3.0, {"a": 12, "b": 2}),
    ]
    expected_state = None
    for perm in itertools.permutations(samples):
        merged, stamps, totals = {}, {}, {}
        for at, update in perm:
            last_merge_counters(merged, update, at, stamps, totals=totals)
        if expected_state is None:
            expected_state = (dict(merged), dict(totals))
        assert (merged, totals) == (
            expected_state[0],
            expected_state[1],
        ), f"order {perm} diverged"
    assert expected_state[0] == {"a": 12, "b": 2}


def test_last_merge_duplicated_and_batch_replayed_sets_identical():
    rng = random.Random(7)
    samples = [
        (float(i), {"x": rng.randrange(1000), "y": rng.randrange(1000)})
        for i in range(20)
    ]
    # reference: in-order, once each
    ref_m, ref_s, ref_t = {}, {}, {}
    for at, update in samples:
        last_merge_counters(ref_m, update, at, ref_s, totals=ref_t)
    # duplicated + shuffled + whole-set replayed afterwards
    stream = samples * 2
    rng.shuffle(stream)
    stream += samples
    got_m, got_s, got_t = {}, {}, {}
    for at, update in stream:
        last_merge_counters(got_m, update, at, got_s, totals=got_t)
    assert got_m == ref_m
    assert got_t == ref_t


def test_last_merge_equal_stamp_ties_are_deterministic():
    a = ({"k": 3}, {"k": 9})
    for first, second in (a, a[::-1]):
        merged, stamps = {}, {}
        last_merge_counters(merged, first, 5.0, stamps)
        last_merge_counters(merged, second, 5.0, stamps)
        assert merged == {"k": 9}


def test_last_merge_complete_snapshot_deletes_absent_keys():
    """complete=True declares the update a WHOLE snapshot: a key the
    newest snapshot no longer carries was released at the source (its
    owner unregistered) and must leave the merged view — and its total
    — instead of ratcheting at its last nonzero reading."""
    merged, stamps, totals = {}, {}, {}
    last_merge_counters(
        merged, {"q": 50, "m": 10}, 1.0, stamps, totals=totals,
        complete=True,
    )
    last_merge_counters(
        merged, {"m": 12}, 2.0, stamps, totals=totals, complete=True
    )
    assert merged == {"m": 12}
    assert totals == {"m": 12}


def test_last_merge_complete_snapshot_stale_cannot_readd():
    """A reordered STALE snapshot must not re-add a key a newer
    snapshot deleted — the newest complete stamp is a floor, so every
    delivery order of the same snapshot set converges."""
    snapshots = [
        (1.0, {"q": 50}),
        (2.0, {}),  # q's owner unregistered
        (3.0, {"m": 7}),
    ]
    reference = None
    for perm in itertools.permutations(snapshots):
        merged, stamps, totals = {}, {}, {}
        for at, update in perm:
            last_merge_counters(
                merged, update, at, stamps, totals=totals, complete=True
            )
        if reference is None:
            reference = (dict(merged), dict(totals))
        assert (merged, totals) == reference, f"order {perm} diverged"
    assert reference[0] == {"m": 7}
    assert reference[1] == {"m": 7}


def test_peaks_never_decrease_under_any_order():
    rng = random.Random(3)
    samples = [{"p": rng.randrange(100)} for _ in range(30)]
    expected = max(s["p"] for s in samples)
    for _ in range(5):
        rng.shuffle(samples)
        merged: dict = {}
        running_max = 0
        for update in samples:
            max_merge_counters(merged, update)
            assert merged["p"] >= running_max
            running_max = merged["p"]
        assert merged["p"] == expected


# ---- the ledger --------------------------------------------------------------


def test_pytree_bytes_counts_leaves():
    tree = {
        "a": np.zeros((4, 4), np.float32),
        "b": [np.zeros(10, np.int64), None, 3],
    }
    assert pytree_bytes(tree) == 4 * 4 * 4 + 10 * 8


def test_ledger_samples_components_and_peaks():
    register_component("thing", lambda: 100)
    ledger = MemoryLedger()
    snap = ledger.sample("test")
    assert snap["components"]["thing"] == 100
    register_component("thing", lambda: 40)  # replace: memory released
    ledger.sample("test")
    state = ledger.snapshot()
    assert state["current"]["thing"] == 40
    assert state["peak"]["thing"] == 100  # the watermark survives


def test_ledger_broken_callback_skipped():
    register_component("ok", lambda: 7)
    register_component("broken", lambda: 1 / 0)
    ledger = MemoryLedger()
    snap = ledger.sample()
    assert snap["components"] == {"ok": 7}


def test_ledger_heartbeat_snapshot_shape_and_empty_before_sample():
    ledger = MemoryLedger(clock=lambda: 42.0)
    assert ledger.heartbeat_snapshot() == {}
    register_component("c", lambda: 5)
    ledger.sample()
    snap = ledger.heartbeat_snapshot()
    assert snap["at"] == 42.0
    assert snap["current"]["c"] == 5
    assert snap["peak"]["c"] == 5
    # host RSS rides as a pseudo-component on Linux
    if memory_mod.read_host_rss() is not None:
        assert snap["current"][memory_mod.KEY_HOST_RSS] > 0


def test_ledger_emits_sample_events():
    events = []
    register_component("c", lambda: 11)
    ledger = MemoryLedger(emit=lambda name, **f: events.append((name, f)))
    ledger.sample("swap_test")
    assert events and events[0][0] == "memory_sample"
    assert events[0][1]["phase"] == "swap_test"
    assert events[0][1]["components"] == {"c": 11}
    assert events[0][1]["tracked_bytes"] == 11


def test_module_gates_are_noops_when_uninstalled():
    assert memory_mod.sample() is None
    assert memory_mod.heartbeat_snapshot() == {}
    assert memory_mod.get_ledger() is None


def test_unregister_component_identity_guard():
    """An owner torn down AFTER a replacement registered the same name
    must not drop the newer registration (bench and the in-process
    harnesses build several owners per process); an unguarded
    unregister still removes unconditionally."""
    old_cb, new_cb = (lambda: 1), (lambda: 2)
    register_component("x", old_cb)
    register_component("x", new_cb)  # replacement
    unregister_component("x", old_cb)  # stale owner's teardown
    with memory_mod._components_lock:
        assert memory_mod._components["x"] is new_cb
    unregister_component("x")  # unguarded: removes whatever is there
    with memory_mod._components_lock:
        assert "x" not in memory_mod._components


def test_serving_entrypoint_installs_ledger(tmp_path):
    """The serving CLI's telemetry install must include the memory
    ledger: without it every engine/batcher sample site is a no-op and
    the swap double-residency instrumentation is inert in the real
    serving path (the smoke installs in-process, which masked this)."""
    import types

    from elasticdl_tpu.serving.main import _install_telemetry
    from elasticdl_tpu.telemetry import tracing, worker_hooks

    args = types.SimpleNamespace(telemetry_dir=str(tmp_path))
    try:
        _install_telemetry(args)
        assert memory_mod.get_ledger() is not None
    finally:
        worker_hooks.uninstall()
        tracing.uninstall()
        memory_mod.uninstall()
    # and a telemetry-less serving process installs nothing
    args = types.SimpleNamespace(telemetry_dir="")
    os.environ.pop(worker_hooks.TELEMETRY_DIR_ENV, None)
    try:
        _install_telemetry(args)
        assert memory_mod.get_ledger() is None
    finally:
        worker_hooks.uninstall()
        tracing.uninstall()
        memory_mod.uninstall()


def test_register_trainer_state_none_safe():
    memory_mod.register_trainer_state(lambda: None)
    ledger = memory_mod.install()
    assert ledger.sample()["components"][COMPONENT_MODEL_STATE] == 0


# ---- servicer merge end to end ----------------------------------------------


def _beat(wid, at, current, peak):
    return msg.HeartbeatRequest(
        worker_id=wid,
        memory={"at": at, "current": current, "peak": peak},
    )


def test_servicer_memory_merge_order_insensitive_and_non_monotone():
    beats = [
        _beat(1, 1.0, {"model_state": 100}, {"model_state": 100}),
        _beat(1, 2.0, {"model_state": 250}, {"model_state": 250}),
        _beat(1, 3.0, {"model_state": 80}, {"model_state": 250}),
        _beat(2, 1.5, {"model_state": 60}, {"model_state": 60}),
    ]
    reference = None
    for perm in itertools.permutations(beats):
        servicer = MasterServicer(64, _dispatcher())
        for beat in perm:
            servicer.heartbeat(beat)
            # duplicate delivery too
            servicer.heartbeat(beat)
        totals = servicer.memory_stats_totals()
        if reference is None:
            reference = totals
        assert totals == reference
    # worker 1's newest sample says 80 (released from its 250 peak):
    # current reflects the release, peak keeps the watermark
    assert reference["current"]["model_state"] == 80 + 60
    assert reference["peak"]["model_state"] == 250 + 60


def test_servicer_memory_release_by_absence():
    """A component the newest beat no longer ships (its owner
    unregistered — a closed stager, a drained queue) leaves the fleet
    CURRENT gauge; its peak watermark stays."""
    servicer = MasterServicer(64, _dispatcher())
    servicer.heartbeat(
        _beat(
            1,
            1.0,
            {"model_state": 100, "device_stager": 30},
            {"model_state": 100, "device_stager": 30},
        )
    )
    servicer.heartbeat(
        _beat(1, 2.0, {"model_state": 90}, {"model_state": 100})
    )
    totals = servicer.memory_stats_totals()
    assert totals["current"] == {"model_state": 90}
    assert totals["peak"] == {
        "model_state": 100,
        "device_stager": 30,
    }


def test_servicer_memory_malformed_payload_tolerated():
    servicer = MasterServicer(64, _dispatcher())
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=1, memory={"at": "nope"})
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=1, memory={"at": 1.0, "current": "bad", "peak": []}
        )
    )
    assert servicer.memory_stats_totals() == {"current": {}, "peak": {}}


def test_heartbeat_memory_field_wire_roundtrip():
    request = _beat(3, 9.5, {"a": 1}, {"a": 2})
    decoded = msg.decode(msg.encode(request))
    assert decoded.memory == {
        "at": 9.5,
        "current": {"a": 1},
        "peak": {"a": 2},
    }
    # old payloads (no memory key) decode to the default
    old = msg.decode(msg.encode(msg.HeartbeatRequest(worker_id=1)))
    assert old.memory == {}


def test_forget_worker_retires_current_bytes_keeps_peaks():
    """An evicted worker's RAM died with its process: the CURRENT fleet
    gauge must drop its contribution (else preemption churn ratchets the
    gauge upward forever), while the peak watermark — which happened —
    survives, and a REUSED worker id re-contributes without
    double-counting."""
    servicer = MasterServicer(64, _dispatcher())
    servicer.heartbeat(_beat(1, 1.0, {"model_state": 100}, {"model_state": 100}))
    servicer.heartbeat(_beat(2, 1.0, {"model_state": 40}, {"model_state": 40}))
    servicer.forget_worker(1)
    totals = servicer.memory_stats_totals()
    assert totals["current"] == {"model_state": 40}
    assert totals["peak"] == {"model_state": 140}
    # the reform-replacement worker reuses id 1: its fresh beat
    # re-contributes current; its (smaller) peak is absorbed by the
    # retained per-worker watermark — no double count
    servicer.heartbeat(_beat(1, 2.0, {"model_state": 70}, {"model_state": 70}))
    totals = servicer.memory_stats_totals()
    assert totals["current"] == {"model_state": 110}
    assert totals["peak"] == {"model_state": 140}


def test_healthz_fleet_tracked_excludes_pseudo_components(tmp_path):
    """host_rss/device pseudo-keys ride the wire maps but are NOT
    tracked components: summing them into fleet_tracked_bytes would
    double-count each worker's whole RSS."""
    servicer = MasterServicer(64, _dispatcher())
    telemetry = _master_telemetry(tmp_path, servicer)
    servicer.heartbeat(
        _beat(
            1,
            1.0,
            {
                "model_state": 64,
                memory_mod.KEY_HOST_RSS: 10_000,
                memory_mod.KEY_DEVICE_IN_USE: 5_000,
            },
            {},
        )
    )
    health = telemetry.build_health_fn("training")()
    assert health["memory"]["fleet_tracked_bytes"] == 64


# ---- registry: prune + gauge semantics (the satellite fix pins) -------------


def test_prune_then_reseen_child_reregisters_cleanly():
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    registry = MetricsRegistry()
    gauge = registry.gauge("g_family", "help", labels={"x": "1"})
    gauge.set(5)
    dropped = registry.prune_children("g_family", [])
    assert dropped == 1
    assert 'g_family{x="1"}' not in registry.exposition()
    # re-seen after the prune: a FRESH child, registered cleanly
    again = registry.gauge("g_family", "help", labels={"x": "1"})
    assert again is not gauge
    again.set(9)
    assert 'g_family{x="1"} 9' in registry.exposition()


def test_gauge_is_exempt_from_monotone_mirroring():
    """Gauges are non-monotone by design: set() lowers the exposed
    value — exactly what the memory ledger's current series needs —
    while Counter.set_total stays a monotone mirror (never lowers)."""
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    registry = MetricsRegistry()
    gauge = registry.gauge("mem_g", "")
    gauge.set(100)
    gauge.set(40)
    assert gauge.value == 40
    counter = registry.counter("mem_c_total", "")
    counter.set_total(100)
    counter.set_total(40)
    assert counter.value == 100


def test_gauge_family_kind_conflict_still_raises():
    from elasticdl_tpu.telemetry.registry import MetricsRegistry

    registry = MetricsRegistry()
    registry.gauge("fam", "")
    with pytest.raises(ValueError):
        registry.counter("fam", "")


# ---- /metrics mirror + cardinality cap --------------------------------------


def _master_telemetry(tmp_path, servicer):
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    telemetry = MasterTelemetry(telemetry_dir=str(tmp_path / "tel"))
    telemetry.attach(_dispatcher(), servicer)
    return telemetry


def test_metrics_mirror_renders_memory_bytes_and_release(tmp_path):
    servicer = MasterServicer(64, _dispatcher())
    telemetry = _master_telemetry(tmp_path, servicer)
    servicer.heartbeat(
        _beat(1, 1.0, {"model_state": 500}, {"model_state": 500})
    )
    text = telemetry.registry.exposition()
    assert (
        'elasticdl_memory_bytes{component="model_state",kind="current"} 500'
        in text
    )
    assert (
        'elasticdl_memory_bytes{component="model_state",kind="peak"} 500'
        in text
    )
    # a newer-stamped LOWER sample lowers the current gauge (the
    # non-monotone path end to end) while the peak holds
    servicer.heartbeat(
        _beat(1, 2.0, {"model_state": 120}, {"model_state": 500})
    )
    text = telemetry.registry.exposition()
    assert (
        'elasticdl_memory_bytes{component="model_state",kind="current"} 120'
        in text
    )
    assert (
        'elasticdl_memory_bytes{component="model_state",kind="peak"} 500'
        in text
    )


def test_metrics_mirror_cardinality_cap_and_prune(tmp_path, monkeypatch):
    from elasticdl_tpu.telemetry import master_hooks

    monkeypatch.setenv(master_hooks.WORKER_SERIES_MAX_ENV, "4")
    servicer = MasterServicer(64, _dispatcher())
    telemetry = _master_telemetry(tmp_path, servicer)
    flood = {f"component_{i:03d}": 1000 - i for i in range(32)}
    servicer.heartbeat(_beat(1, 1.0, flood, flood))
    text = telemetry.registry.exposition()
    lines = [
        line
        for line in text.splitlines()
        if line.startswith("elasticdl_memory_bytes{")
    ]
    # at most budget series per kind (3 kept + 1 "other"), both kinds
    assert len(lines) <= 8, lines
    assert 'component="other"' in text
    # the biggest components survive individually
    assert 'component="component_000"' in text
    # a later scrape with a small honest set prunes the flood children
    servicer2 = MasterServicer(64, _dispatcher())
    telemetry2 = _master_telemetry(tmp_path, servicer2)
    servicer2.heartbeat(_beat(1, 1.0, {"model_state": 5}, {"model_state": 5}))
    text2 = telemetry2.registry.exposition()
    assert 'component="model_state"' in text2


def test_healthz_memory_headroom_block(tmp_path):
    servicer = MasterServicer(64, _dispatcher())
    telemetry = _master_telemetry(tmp_path, servicer)
    servicer.heartbeat(_beat(1, 1.0, {"model_state": 64}, {"model_state": 64}))
    health = telemetry.build_health_fn("training")()
    assert "memory" in health
    memory = health["memory"]
    assert memory["fleet_tracked_bytes"] == 64
    if memory_mod.read_host_rss() is not None:
        assert memory["host_rss_bytes"] > 0
        assert 0.0 <= memory["headroom_share"] <= 1.0


# ---- report sections ---------------------------------------------------------


def _event(name, monotonic, **fields):
    return {"event": name, "monotonic": monotonic, **fields}


def test_memory_section_aggregates_and_budget():
    events = [
        _event(
            "memory_sample",
            1.0,
            components={"model_state": 100, "replica_store": 10},
            host_rss_bytes=1000,
        ),
        _event(
            "memory_sample",
            2.0,
            components={"model_state": 60, "replica_store": 30},
            host_rss_bytes=900,
        ),
        _event("memory_pressure", 2.5, entered=True,
               host_available_bytes=123),
    ]
    from elasticdl_tpu.telemetry.report import memory_section

    section = memory_section(events)
    model = section["components"]["model_state"]
    assert model["current_bytes"] == 60  # last sample wins
    assert model["peak_bytes"] == 100  # watermark survives
    assert section["tracked_bytes"] == 90
    assert section["host_rss_bytes"] == 900
    assert section["host_rss_peak_bytes"] == 1000
    assert section["unaccounted_bytes"] == 810
    assert section["unaccounted_over_budget"] is False
    assert section["pressure_events"][0]["entered"] is True
    # per-component peak >= current always
    for slot in section["components"].values():
        assert slot["peak_bytes"] >= slot["current_bytes"]


def test_memory_section_groups_by_emitting_process():
    """Multi-worker runs write memory_sample events from several
    processes into one log; ``monotonic`` restarts per process, so the
    section must aggregate per (worker_id, process_id) group — each
    group's LAST sample, summed across groups — never interleave the
    incomparable clocks into one arbitrary worker's reading."""
    from elasticdl_tpu.telemetry.report import memory_section

    events = [
        # worker 0: its clock happens to read HIGHER than worker 1's
        _event(
            "memory_sample",
            900.0,
            worker_id=0,
            process_id=0,
            components={"model_state": 100},
            host_rss_bytes=1000,
        ),
        _event(
            "memory_sample",
            901.0,
            worker_id=0,
            process_id=0,
            components={"model_state": 80},
            host_rss_bytes=950,
        ),
        # worker 1: fresh process, clock restarted near zero — a global
        # monotonic sort would make ITS samples look oldest
        _event(
            "memory_sample",
            1.0,
            worker_id=1,
            process_id=1,
            components={"model_state": 70},
            host_rss_bytes=800,
        ),
        _event(
            "memory_sample",
            2.0,
            worker_id=1,
            process_id=1,
            components={"model_state": 60},
            host_rss_bytes=780,
        ),
    ]
    section = memory_section(events)
    model = section["components"]["model_state"]
    assert model["current_bytes"] == 80 + 60  # each group's last, summed
    assert model["peak_bytes"] == 100 + 70
    assert section["tracked_bytes"] == 140
    assert section["host_rss_bytes"] == 950 + 780
    assert section["host_rss_peak_bytes"] == 1000 + 800
    assert section["samples"] == 4


def test_memory_section_absent_without_samples():
    from elasticdl_tpu.telemetry.report import memory_section

    assert memory_section([]) is None
    assert memory_section([_event("step", 1.0)]) is None


def test_serving_section_aggregates_percentiles_sheds_and_swaps():
    from elasticdl_tpu.telemetry.report import serving_section

    events = []
    for i in range(10):
        events.append(
            _event(
                "serving_request",
                float(i),
                rows=2,
                dispatches=1,
                total_ms=float(i + 1),
                queue_wait_ms=0.1,
                device_compute_ms=float(i),
                untracked_ms=0.0,
            )
        )
    events.append(
        _event("serving_request", 11.0, rows=4, error="overload", shed=True)
    )
    events.append(
        _event("serving_request", 12.0, rows=1, error="ShapeMismatchError")
    )
    events.append(
        _event(
            "model_swap",
            13.0,
            old_version=3,
            model_version=7,
            swap_ms=2.5,
            source="in-memory",
        )
    )
    section = serving_section(events)
    assert section["requests"] == 10
    assert section["rows"] == 20
    assert section["sheds"] == 1
    assert section["errors"] == 1
    assert section["errors_by_kind"] == {
        "overload": 1,
        "ShapeMismatchError": 1,
    }
    assert section["latency_p50_ms"] == 5.0
    assert section["phases"]["device_compute"]["p99_ms"] == 9.0
    assert section["swaps"][0]["model_version"] == 7
    assert section["swaps"][0]["old_version"] == 3


def test_serving_section_absent_without_serving_events():
    from elasticdl_tpu.telemetry.report import serving_section

    assert serving_section([_event("step", 1.0)]) is None


def test_report_no_data_discipline_memory_and_serving(tmp_path):
    """Empty events file / rotated-shards-only dirs: rc 0 with an
    explicit no_data marker, the memory/serving sections absent — the
    PR-9 section discipline extended."""
    from elasticdl_tpu.telemetry.report import analyze_events, main

    run = analyze_events([], [])
    assert "no_data" in run
    assert "memory" not in run and "serving" not in run

    # an empty events.jsonl on disk: rc 0, report renders
    empty_dir = tmp_path / "empty"
    empty_dir.mkdir()
    (empty_dir / "events.jsonl").write_text("")
    assert main([str(empty_dir)]) == 0

    # only a rotated shard (the active file rotated away): the reader
    # walks shards, rc stays 0
    rotated_dir = tmp_path / "rotated"
    rotated_dir.mkdir()
    (rotated_dir / "events.jsonl.1").write_text(
        json.dumps({"event": "memory_sample", "monotonic": 1.0,
                    "components": {"model_state": 5}}) + "\n"
    )
    (rotated_dir / "events.jsonl").write_text("")
    assert main([str(rotated_dir), "--json"]) == 0
    from elasticdl_tpu.telemetry.events import read_events

    events = read_events(str(rotated_dir / "events.jsonl"))
    from elasticdl_tpu.telemetry.report import memory_section

    assert memory_section(events)["components"]["model_state"][
        "current_bytes"
    ] == 5


# ---- on-demand profiler ------------------------------------------------------


class _FakeJaxProfiler:
    def __init__(self, monkeypatch):
        import jax

        self.calls = []
        monkeypatch.setattr(
            jax.profiler,
            "start_trace",
            lambda d: self.calls.append(("start", d)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: self.calls.append(("stop",))
        )


def test_profiler_flag_window_unchanged(monkeypatch, tmp_path):
    """The launch-flag path keeps its exact open/close call indices."""
    from elasticdl_tpu.utils.profiling import StepProfiler

    fake = _FakeJaxProfiler(monkeypatch)
    out = str(tmp_path / "p")
    profiler = StepProfiler(out, start_step=2, num_steps=3)
    opens = []
    for step in range(1, 11):
        profiler.on_step(step)
        if fake.calls and fake.calls[-1][0] == "start" and len(opens) == 0:
            opens.append(step)
    assert fake.calls[0] == ("start", out)
    assert opens == [3]  # opened at call 3 (past start_step=2)
    assert ("stop",) in fake.calls  # closed when seen > 5 (call 6)
    profiler.stop()
    assert fake.calls.count(("stop",)) == 1  # idempotent


def test_profiler_arm_opens_next_step_and_dedupes(monkeypatch, tmp_path):
    from elasticdl_tpu.utils.profiling import StepProfiler

    fake = _FakeJaxProfiler(monkeypatch)
    profiler = StepProfiler("")  # no flag window
    for _ in range(5):
        profiler.on_step()
    assert fake.calls == []  # idle: truly off
    out = str(tmp_path / "w1")
    assert profiler.arm(out, num_steps=2, window_id=1) is True
    # replayed command (the master re-sends every beat): absorbed
    assert profiler.arm(out, num_steps=2, window_id=1) is False
    profiler.on_step()  # opens
    assert fake.calls == [("start", out)]
    # arming DURING a window is refused without consuming the id
    assert profiler.arm(str(tmp_path / "w2"), window_id=2) is False
    profiler.on_step()  # second in-window step
    profiler.on_step()  # seen > stop_at: closes
    assert fake.calls[-1] == ("stop",)
    # window 2 retries after the close and now arms
    assert profiler.arm(str(tmp_path / "w2"), window_id=2) is True
    profiler.on_step()
    assert fake.calls[-1] == ("start", str(tmp_path / "w2"))
    profiler.stop()


def test_profiler_emits_window_events(monkeypatch, tmp_path):
    from elasticdl_tpu.telemetry import worker_hooks
    from elasticdl_tpu.utils.profiling import StepProfiler

    _FakeJaxProfiler(monkeypatch)
    worker_hooks.install(str(tmp_path / "tel"))
    try:
        profiler = StepProfiler("")
        profiler.arm(str(tmp_path / "w"), num_steps=1, window_id=5)
        profiler.on_step()
        profiler.on_step()
        from elasticdl_tpu.telemetry.events import read_events

        events = read_events(str(tmp_path / "tel" / "events.jsonl"))
        names = [e["event"] for e in events]
        assert "profile_window_open" in names
        assert "profile_window_close" in names
        closed = next(
            e for e in events if e["event"] == "profile_window_close"
        )
        assert closed["window_id"] == 5
        assert closed["steps"] == 1
    finally:
        worker_hooks.uninstall()


def test_apply_profile_command_paths(monkeypatch, tmp_path):
    from elasticdl_tpu.utils.profiling import (
        StepProfiler,
        apply_profile_command,
    )

    _FakeJaxProfiler(monkeypatch)
    profiler = StepProfiler("")
    telemetry_dir = str(tmp_path / "tel")
    command = {"window_id": 1, "num_steps": 2, "out_dir": ""}
    assert apply_profile_command(
        profiler, command, telemetry_dir=telemetry_dir, tag="w0"
    )
    # replay: absorbed
    assert not apply_profile_command(
        profiler, command, telemetry_dir=telemetry_dir, tag="w0"
    )
    # no out_dir anywhere: refused
    assert not apply_profile_command(
        StepProfiler(""), {"window_id": 2, "num_steps": 1}
    )
    # malformed: refused, never raises
    assert not apply_profile_command(profiler, {})
    assert not apply_profile_command(profiler, {"window_id": "x"})


def test_servicer_request_profile_absorbed_and_ttl():
    clock = [100.0]
    servicer = MasterServicer(64, _dispatcher(), clock=lambda: clock[0])
    first = servicer.request_profile(
        msg.RequestProfileRequest(num_steps=3)
    )
    assert first.accepted and first.window_id == 1
    # a re-delivered arm while the command distributes: same window
    dup = servicer.request_profile(msg.RequestProfileRequest(num_steps=3))
    assert dup.accepted and dup.window_id == 1
    # the command rides the heartbeat response
    resp = servicer.heartbeat(msg.HeartbeatRequest(worker_id=0))
    assert resp.profile == {
        "window_id": 1,
        "num_steps": 3,
        "out_dir": "",
    }
    # after the TTL the command stops riding and a new arm advances
    clock[0] += MasterServicer.PROFILE_COMMAND_TTL_SECS + 1
    assert servicer.heartbeat(msg.HeartbeatRequest(worker_id=0)).profile == {}
    nxt = servicer.request_profile(msg.RequestProfileRequest())
    assert nxt.window_id == 2


def test_request_profile_wire_roundtrip_and_method_table():
    decoded = msg.decode(
        msg.encode(msg.RequestProfileRequest(num_steps=7, out_dir="/d"))
    )
    assert decoded.num_steps == 7 and decoded.out_dir == "/d"
    response = msg.decode(
        msg.encode(msg.RequestProfileResponse(accepted=True, window_id=4))
    )
    assert response.accepted and response.window_id == 4
    from elasticdl_tpu.rpc.idempotency import classification
    from elasticdl_tpu.rpc.service import _METHODS

    assert "request_profile" in _METHODS
    assert classification("request_profile") == "deduped"
    # old heartbeat responses decode without the profile field
    old = msg.decode(msg.encode(msg.HeartbeatResponse()))
    assert old.profile == {}


# ---- serving engine double residency ----------------------------------------


def test_engine_swap_records_double_residency(tmp_path):
    """A hot swap's ledger peak covers old + new leaves resident at
    once; after the swap the current drops back to one copy."""
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.serving.engine import ServingEngine
    from elasticdl_tpu.trainer.state import TrainState, init_model
    from elasticdl_tpu.trainer.step import resolve_optimizer
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.utils.model_utils import get_model_spec

    iris_def = "odps_iris_dnn_model.odps_iris_dnn_model.custom_model"
    spec = get_model_spec("", iris_def)
    model = spec.build_model()
    sample = {"features": np.zeros((1, 4), np.float32)}
    params, model_state = init_model(model, sample)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    state = state.replace(step=jnp.asarray(3, jnp.int32))
    import argparse

    export_dir = export_model(
        str(tmp_path / "export"),
        state,
        spec,
        argparse.Namespace(
            model_zoo="", model_def=iris_def, model_params_dict={}
        ),
    )
    ledger = memory_mod.install()
    engine = ServingEngine(export_dir, canonical_rows=8)
    feats = {"features": np.zeros((2, 4), np.float32)}
    engine.predict_rows(feats)  # builds
    built = ledger.snapshot()["current"]["serving_model"]
    assert built > 0
    from elasticdl_tpu.trainer.state import state_to_checkpoint

    flat = state_to_checkpoint(state)
    flat_params = {
        k[len("params/"):]: np.asarray(v)
        for k, v in flat.items()
        if k.startswith("params/")
    }
    accepted, version, _reason = engine.swap_state_dicts(
        flat_params, {}, version=9
    )
    assert accepted and version == 9
    snap = ledger.snapshot()
    # the swap sample caught both copies resident; afterwards current
    # settles back to ~one copy (the release, observable)
    assert snap["peak"]["serving_model"] >= int(1.8 * built)
    assert snap["current"]["serving_model"] < snap["peak"]["serving_model"]
    jax.clear_caches()
