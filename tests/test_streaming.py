"""Streaming subsystem: watermark-lease dispatch over an unbounded
source, checkpoint-free durability via journal replay, the bounded-lag
and freshness invariants (and their falsifiability), the live
train->serve push, the lag-driven autoscaler trigger, and flag hygiene.

The stream record contract is load-bearing for everything here: record
``i`` of ``stream://<dataset>?seed=S`` is a pure function of ``(S, i)``,
so any worker can serve any leased window and a replayed window re-reads
identical bytes.
"""

from __future__ import annotations

import numpy as np
import pytest

from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.streaming.source import (
    QueueStreamSource,
    build_stream_source,
    is_stream_origin,
    parse_stream_origin,
)
from elasticdl_tpu.utils.constants import TaskType

ORIGIN = "stream://mnist?seed=7&total=256&rate=0&initial=256"
WINDOW = 64


def _dispatcher(source, records_per_task: int = WINDOW) -> TaskDispatcher:
    return TaskDispatcher(
        {},
        records_per_task=records_per_task,
        num_epochs=1,
        stream_source=source,
        stream_origin=ORIGIN,
    )


# ---- source + origin parsing ------------------------------------------------


def test_parse_stream_origin():
    assert is_stream_origin("stream://mnist?seed=1")
    assert not is_stream_origin("/data/train")
    spec = parse_stream_origin(ORIGIN)
    assert spec.dataset == "mnist"
    assert spec.seed == 7 and spec.total == 256 and spec.rate == 0.0
    assert spec.params == {"initial": "256"}
    with pytest.raises(ValueError):
        parse_stream_origin("file:///nope")


def test_queue_source_watermark_monotone_and_close():
    source = QueueStreamSource(total=128, rate_per_sec=0.0, initial=32)
    assert source.watermark() == 32 and not source.closed()
    assert source.advance(64) == 96
    # advance_to is a FLOOR: a lower target never regresses the watermark
    assert source.advance_to(50) == 96
    # the cap: a bounded prefix closes at total and stays there
    assert source.advance(1000) == 128
    assert source.closed()


def test_build_stream_source_reads_initial():
    source = build_stream_source(ORIGIN)
    assert source.watermark() == 256 and source.closed()


def test_stream_record_deterministic():
    from elasticdl_tpu.streaming.reader import StreamDataReader, stream_record

    a = stream_record("mnist", 7, 41)
    b = stream_record("mnist", 7, 41)
    assert np.array_equal(a["image"], b["image"]) and a["label"] == b["label"]
    c = stream_record("mnist", 7, 42)
    assert not np.array_equal(a["image"], c["image"]) or a["label"] != c["label"]

    # two independent readers over the same leased window: identical bytes
    class _Win:
        start, end = 40, 44

    r1 = list(StreamDataReader(data_origin=ORIGIN).read_records(_Win))
    r2 = list(StreamDataReader(data_origin=ORIGIN).read_records(_Win))
    assert len(r1) == 4 and r1 == r2
    assert StreamDataReader(data_origin=ORIGIN).create_shards() == {}


# ---- watermark-lease dispatcher semantics -----------------------------------


class TestWatermarkLease:
    def test_windows_mint_fifo_up_to_watermark(self):
        d = _dispatcher(QueueStreamSource(total=0, initial=160))
        tid1, t1 = d.get(0)
        tid2, t2 = d.get(1)
        assert (t1.start, t1.end) == (0, 64)
        assert (t2.start, t2.end) == (64, 128)
        # [128, 160) is a partial window and the source is OPEN: held
        # back until the watermark reaches a full window (or close)
        tid3, t3 = d.get(0)
        assert tid3 == -1 and t3 is None

    def test_partial_window_minted_on_close(self):
        d = _dispatcher(QueueStreamSource(total=96, initial=96))
        _, t1 = d.get(0)
        _, t2 = d.get(0)
        assert (t1.start, t1.end) == (0, 64)
        assert (t2.start, t2.end) == (64, 96)  # closed: the tail flushes

    def test_out_of_order_completion_gap_free_prefix(self):
        d = _dispatcher(QueueStreamSource(total=256, initial=256))
        leases = [d.get(0) for _ in range(4)]
        # completing [64,128) first: the trained watermark must NOT
        # advance over the [0,64) hole
        d.report(leases[1][0], True)
        assert d.stream_status()["trained_watermark"] == 0
        d.report(leases[0][0], True)
        assert d.stream_status()["trained_watermark"] == 128
        d.report(leases[3][0], True)
        d.report(leases[2][0], True)
        status = d.stream_status()
        assert status["trained_watermark"] == 256 and status["lag"] == 0

    def test_failed_window_requeues_and_leases_first(self):
        d = _dispatcher(QueueStreamSource(total=256, initial=256))
        tid1, t1 = d.get(0)
        d.report(tid1, False)  # failure: the window goes back
        tid1b, t1b = d.get(1)
        assert (t1b.start, t1b.end) == (t1.start, t1.end)
        assert tid1b != tid1  # a fresh lease id — the old one is dead

    def test_duplicate_report_is_dropped(self):
        d = _dispatcher(QueueStreamSource(total=256, initial=256))
        tid, _ = d.get(0)
        d.report(tid, True)
        before = d.stream_status()["trained_watermark"]
        d.report(tid, True)  # duplicate delivery: absorbed
        assert d.stream_status()["trained_watermark"] == before
        counters = d.counters(TaskType.TRAINING)
        assert counters.total_records == 256  # counted at mint, once

    def test_finished_gates_on_source_close(self):
        source = QueueStreamSource(total=128, initial=64)
        d = _dispatcher(source)
        tid, _ = d.get(0)
        d.report(tid, True)
        # drained NOW, but the source is open: more records will come,
        # so the job must not finish
        assert not d.finished()
        source.advance(64)  # reaches total=128: the source closes
        tid, task = d.get(0)
        assert (task.start, task.end) == (64, 128)
        assert not d.finished()  # window in flight
        d.report(tid, True)
        assert d.finished()
        assert d.stream_status()["closed"]

    def test_stream_status_lag(self):
        d = _dispatcher(QueueStreamSource(total=0, initial=192))
        assert d.stream_status()["lag"] == 192
        tid, _ = d.get(0)
        d.report(tid, True)
        status = d.stream_status()
        assert status["trained_watermark"] == 64 and status["lag"] == 128

    def test_epoch_mode_has_no_stream_status(self):
        d = TaskDispatcher({"f": (0, 10)}, records_per_task=10, num_epochs=1)
        assert not d.streaming and d.stream_status() is None


# ---- journal replay: checkpoint-free durability -----------------------------


def test_stream_state_snapshot_replay_equivalence():
    """A restarted master restores the dispatcher at the exact stream
    cursor: same trained watermark, same out-of-order completion set,
    same next offset — and the fresh source is re-floored at the
    journaled watermark so it can never regress."""
    source_a = QueueStreamSource(total=256, initial=256)
    a = _dispatcher(source_a)
    leases = [a.get(0) for _ in range(3)]
    a.report(leases[1][0], True)  # out-of-order: [64,128) done, [0,64) not
    snap = a.state_snapshot()

    # the restarted master's source starts cold (watermark 0) — replay
    # must re-floor it
    b = _dispatcher(QueueStreamSource(total=256, initial=0))
    b.restore_state(snap)
    assert b.stream_status() == a.stream_status()
    assert b.stream_status()["source_watermark"] == 256

    # the restored lease ids stay live: completing them advances the
    # trained watermark over the gap exactly as in the original life
    b.report(leases[0][0], True)
    assert b.stream_status()["trained_watermark"] == 128
    # and minting continues where the cursor left off
    _, t4 = b.get(2)
    assert (t4.start, t4.end) == (192, 256)


# ---- invariant checkers: bounded_lag + freshness_monotone -------------------


def _stream_config(tmp_path, **overrides):
    from elasticdl_tpu.chaos.harness import ChaosJobConfig
    from elasticdl_tpu.chaos.plan import resolve_plan

    kwargs = dict(
        plan=resolve_plan("none", 2),
        workdir=str(tmp_path),
        streaming=True,
        stream_total=256,
    )
    kwargs.update(overrides)
    return ChaosJobConfig(**kwargs)


class TestBoundedLag:
    def test_pass_within_bound(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_bounded_lag

        result = _check_bounded_lag(
            _stream_config(tmp_path),
            [{"event": "stream_lag", "lag_records": 300}],
            {"trained_watermark": 256},
        )
        # auto bound: max(256, 6 * records_per_task=64) = 384
        assert result["status"] == "PASS"
        assert result["lag_limit_records"] == 384

    def test_fails_on_lag_over_bound(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_bounded_lag

        result = _check_bounded_lag(
            _stream_config(tmp_path, stream_lag_limit=100),
            [{"event": "stream_lag", "lag_records": 101}],
            {"trained_watermark": 256},
        )
        assert result["status"] == "FAIL"
        assert "101" in result["violations"][0]

    def test_fails_on_incomplete_drain(self, tmp_path):
        """The drop_stream_window corruption's signature: a lost window
        leaves a hole the trained watermark can never cross."""
        from elasticdl_tpu.chaos.harness import _check_bounded_lag

        result = _check_bounded_lag(
            _stream_config(tmp_path),
            [{"event": "stream_lag", "lag_records": 10}],
            {"trained_watermark": 192},
        )
        assert result["status"] == "FAIL"
        assert "drain incomplete" in result["violations"][0]

    def test_fails_on_missing_telemetry(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_bounded_lag

        result = _check_bounded_lag(
            _stream_config(tmp_path), [], {"trained_watermark": 256}
        )
        assert result["status"] == "FAIL"

    def test_none_on_epoch_mode(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_bounded_lag

        assert (
            _check_bounded_lag(
                _stream_config(tmp_path, streaming=False, stream_total=0),
                [],
                None,
            )
            is None
        )


class TestFreshnessMonotone:
    @staticmethod
    def _push(version, trained, mono, accepted=True):
        return {
            "event": "live_push",
            "model_version": version,
            "trained_watermark": trained,
            "monotonic": mono,
            "accepted": accepted,
        }

    def test_pass_on_monotone_pushes(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_freshness_monotone

        result = _check_freshness_monotone(
            _stream_config(tmp_path),
            [self._push(2, 64, 1.0), self._push(4, 128, 2.0)],
        )
        assert result["status"] == "PASS" and result["pushes"] == 2

    def test_fails_on_regressed_watermark(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_freshness_monotone

        result = _check_freshness_monotone(
            _stream_config(tmp_path),
            [self._push(4, 128, 1.0), self._push(6, 64, 2.0)],
        )
        assert result["status"] == "FAIL"
        assert "regressed" in result["violations"][0]

    def test_refused_pushes_do_not_count(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_freshness_monotone

        result = _check_freshness_monotone(
            _stream_config(tmp_path),
            [
                self._push(4, 128, 1.0),
                self._push(6, 64, 2.0, accepted=False),
            ],
        )
        assert result["status"] == "PASS" and result["pushes"] == 1

    def test_vacuous_pass_without_pushes(self, tmp_path):
        from elasticdl_tpu.chaos.harness import _check_freshness_monotone

        result = _check_freshness_monotone(_stream_config(tmp_path), [])
        assert result["status"] == "PASS" and result["pushes"] == 0


# ---- live pusher: tick gating + push/absorb ---------------------------------


class _FakeDirectory:
    def __init__(self):
        self.calls = 0
        self.stage = None

    def harvest(self, **kwargs):
        self.calls += 1
        return self.stage


class _FakeTelemetry:
    def __init__(self):
        self.rows = []

    def live_push(self, **kwargs):
        self.rows.append(kwargs)


class _FakeServingClient:
    """Stands in for ServingClient; scripted swap responses."""

    responses: list = []
    sent: list = []

    def __init__(self, addr, deadlines=None):
        pass

    def swap_model(self, request):
        _FakeServingClient.sent.append(request)
        return _FakeServingClient.responses.pop(0)

    def close(self):
        pass


class TestLivePusher:
    def _pusher(self, directory, telemetry=None, now=None):
        from elasticdl_tpu.streaming.live_push import LivePusher

        now = now if now is not None else [0.0]
        pusher = LivePusher(
            "localhost:1",
            directory,
            telemetry=telemetry,
            clock=lambda: now[0],
        )
        return pusher, now

    def test_no_harvest_before_first_step(self):
        directory = _FakeDirectory()
        pusher, _now = self._pusher(directory)
        assert not pusher.tick(
            model_version=0,
            generation=0,
            num_sources=2,
            live_worker_ids=[0, 1],
        )
        assert directory.calls == 0  # nothing trained -> nothing staged

    def test_interval_gate_and_harvest_skip(self):
        directory = _FakeDirectory()
        pusher, now = self._pusher(directory)
        tick = dict(
            model_version=2,
            generation=0,
            num_sources=2,
            live_worker_ids=[0, 1],
        )
        assert not pusher.tick(**tick)
        assert directory.calls == 1 and pusher.harvest_skips == 1
        # within the min interval: no probe hammering while the ring
        # catches up
        now[0] += 0.5
        assert not pusher.tick(**tick)
        assert directory.calls == 1
        now[0] += 1.0
        assert not pusher.tick(**tick)
        assert directory.calls == 2

    def test_push_accept_then_replay_absorbed(self, monkeypatch):
        from elasticdl_tpu.rpc import messages as msg
        from elasticdl_tpu.serving import replica as replica_mod

        monkeypatch.setattr(
            replica_mod, "ServingClient", _FakeServingClient
        )
        _FakeServingClient.sent = []
        _FakeServingClient.responses = [
            msg.SwapModelResponse(accepted=True, model_version=2),
            # a replayed/raced push refused as STALE is convergence
            msg.SwapModelResponse(
                accepted=False,
                model_version=4,
                reason="stale swap: serving 4",
                stale=True,
            ),
        ]
        directory = _FakeDirectory()
        telemetry = _FakeTelemetry()
        pusher, now = self._pusher(directory, telemetry)

        directory.stage = {
            "generation": 0,
            "version": 2,
            "checksum": "x",
            "payload": b"blob-v2",
            "sources": 2,
        }
        status = {"source_watermark": 192, "trained_watermark": 128}
        assert pusher.tick(
            model_version=2,
            generation=0,
            num_sources=2,
            live_worker_ids=[0, 1],
            stream_status=status,
        )
        assert pusher.last_pushed_version == 2
        assert pusher.pushes_accepted == 1
        sent = _FakeServingClient.sent[0]
        assert sent.payload == b"blob-v2" and sent.version == 2
        assert sent.trained_watermark == 128 and sent.source_watermark == 192
        row = telemetry.rows[0]
        assert row["accepted"] and row["trained_watermark"] == 128

        # version gate: same version never re-pushes
        now[0] += 2.0
        assert not pusher.tick(
            model_version=2,
            generation=0,
            num_sources=2,
            live_worker_ids=[0, 1],
        )
        assert len(_FakeServingClient.sent) == 1

        # the stale refusal: converged (serving already at/past 4), the
        # ledger records it as not-accepted
        directory.stage = dict(directory.stage, version=4, payload=b"blob-v4")
        assert pusher.tick(
            model_version=4,
            generation=0,
            num_sources=2,
            live_worker_ids=[0, 1],
            stream_status=status,
        )
        assert pusher.last_pushed_version == 4
        assert not telemetry.rows[1]["accepted"]


# ---- live-push parity: payload swap == export of the same state -------------


def test_live_push_payload_parity(tmp_path):
    """The served outputs after an inline-payload swap are IDENTICAL to
    serving a disk export of the same trainer state — the payload path
    (flat_state_arrays -> encode_snapshot -> swap_model) loses nothing,
    with the compile counter flat and a replayed payload absorbed as
    stale."""
    import argparse

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.parallel.elastic import flat_state_arrays
    from elasticdl_tpu.replication.blob import encode_snapshot
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.serving.batcher import MicroBatcher
    from elasticdl_tpu.serving.engine import ServingEngine
    from elasticdl_tpu.serving.replica import ServingReplicaServicer
    from elasticdl_tpu.telemetry import compile_tracker
    from elasticdl_tpu.trainer.state import TrainState, init_model
    from elasticdl_tpu.trainer.step import resolve_optimizer
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.utils.model_utils import get_model_spec

    rows = 8
    iris_def = "odps_iris_dnn_model.odps_iris_dnn_model.custom_model"
    ns = argparse.Namespace(
        model_zoo="", model_def=iris_def, model_params_dict={}
    )
    spec = get_model_spec("", iris_def)
    model = spec.build_model()
    sample = {"features": np.zeros((1, 4), np.float32)}
    params, model_state = init_model(model, sample)

    def mk_state(scale, step):
        scaled = jax.tree_util.tree_map(lambda x: x * scale + 0.01, params)
        state = TrainState.create(
            model.apply, scaled, resolve_optimizer(spec.optimizer), model_state
        )
        return state.replace(step=jnp.asarray(step, jnp.int32))

    export_v1 = export_model(
        str(tmp_path / "export_v1"), mk_state(1.0, 3), spec, ns
    )
    engine = ServingEngine(export_v1, rows)
    servicer = ServingReplicaServicer(
        engine, MicroBatcher(rows, max_wait_secs=0.0)
    )
    feats = {
        "features": np.random.RandomState(0).rand(5, 4).astype(np.float32)
    }
    before = engine.predict_rows(feats)

    # the trainer at "watermark 128": version 9, perturbed weights —
    # the snapshot encoded EXACTLY as replication/live-push wires it
    state_v2 = mk_state(3.0, 9)
    flat = {
        k: np.asarray(v) for k, v in flat_state_arrays(state_v2).items()
    }
    payload = encode_snapshot(flat, {})

    compile_tracker.install()
    flat0 = compile_tracker.compile_count()
    resp = servicer.swap_model(
        msg.SwapModelRequest(
            payload=payload,
            version=9,
            source="live-push@128",
            trained_watermark=128,
            source_watermark=192,
        )
    )
    assert resp.accepted and resp.model_version == 9, resp.reason
    after = engine.predict_rows(feats)
    assert not np.allclose(before, after)
    assert compile_tracker.compile_count() == flat0  # program reused

    # reference: a full disk export of the same state served fresh
    export_v2 = export_model(str(tmp_path / "export_v2"), state_v2, spec, ns)
    reference = ServingEngine(export_v2, rows).predict_rows(feats)
    np.testing.assert_allclose(after, reference, atol=1e-6)

    # replay: the identical push is refused as stale, state untouched
    resp2 = servicer.swap_model(
        msg.SwapModelRequest(payload=payload, version=9)
    )
    assert not resp2.accepted and resp2.stale
    np.testing.assert_array_equal(after, engine.predict_rows(feats))


# ---- autoscaler: grow on stream lag -----------------------------------------


class TestStreamAutoscaler:
    def _args(self, **overrides):
        import argparse

        ns = argparse.Namespace(
            streaming=True,
            stream_lag_tasks=None,
            autoscale_p95_step_ms=None,
            autoscale_backlog_tasks=None,
            autoscale_cooldown_secs=0.0,
            autoscale_shrink=None,
            min_slices=None,
        )
        for key, value in overrides.items():
            setattr(ns, key, value)
        return ns

    def test_stream_lag_tasks_alone_builds_autoscaler(self):
        from elasticdl_tpu.master.autoscaler import build_autoscaler

        scaler = build_autoscaler(self._args(stream_lag_tasks=4), 2)
        assert scaler is not None and scaler.backlog_tasks == 4
        assert build_autoscaler(self._args(), 2) is None

    def test_grow_on_lag_threshold(self):
        from elasticdl_tpu.master.autoscaler import build_autoscaler

        scaler = build_autoscaler(self._args(stream_lag_tasks=4), 2)
        # lag 3 windows: below threshold, no decision
        assert scaler.evaluate(3, current_slices=1, now=100.0) is None
        decision = scaler.evaluate(4, current_slices=1, now=200.0)
        assert decision["action"] == "grow"
        assert decision["to_slices"] == 2
        assert "backlog 4" in decision["reason"]

    def test_epoch_mode_ignores_stream_lag_tasks(self):
        from elasticdl_tpu.master.autoscaler import build_autoscaler

        args = self._args(streaming=False, stream_lag_tasks=4)
        assert build_autoscaler(args, 2) is None


# ---- flag hygiene: master-only, argv byte-identical -------------------------


def test_streaming_flags_master_only_argv_byte_identical():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def",
        "m.custom_model",
        "--training_data",
        ORIGIN,
    ]
    plain = parse_master_args(base)
    for flag in ("streaming", "stream_lag_tasks", "live_push_addr"):
        assert getattr(plain, flag) is None, flag
    streaming = parse_master_args(
        base
        + [
            "--streaming",
            "true",
            "--stream_lag_tasks",
            "4",
            "--live_push_addr",
            "localhost:9999",
        ]
    )
    assert streaming.streaming is True
    assert streaming.stream_lag_tasks == 4
    # byte-identical worker argv whether the master flags are set or
    # not: streaming is master business end to end, workers only see
    # the stream:// origin through --training_data
    assert build_worker_arguments(
        streaming, 0, "localhost:1"
    ) == build_worker_arguments(plain, 0, "localhost:1")
    argv = build_worker_arguments(streaming, 0, "localhost:1")
    assert not any(
        "stream_lag" in a or "live_push" in a or a == "--streaming"
        for a in argv
    )
    assert ORIGIN in argv  # the origin itself DOES ride --training_data
