"""Trainer tests: metrics, state/step, and the end-to-end Local slice
(SURVEY §7 step 4: CLI args -> model zoo -> data -> jit loop).
"""

import numpy as np
import pytest

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.trainer import metrics as metrics_lib
from elasticdl_tpu.trainer.local_executor import LocalExecutor
from elasticdl_tpu.utils.args import parse_master_args


class TestMetrics:
    def test_accuracy_from_logits(self):
        m = metrics_lib.Accuracy()
        m.update([0, 1, 2], np.eye(3))
        assert m.result() == 1.0
        m.update([0], [[0.0, 9.0, 0.0]])
        assert m.result() == 0.75

    def test_binary_accuracy(self):
        m = metrics_lib.BinaryAccuracy()
        m.update([1, 0, 1, 0], [0.9, 0.2, 0.4, 0.6])
        assert m.result() == 0.5

    def test_auc_perfect_and_random(self):
        m = metrics_lib.AUC()
        m.update([0, 0, 1, 1], [0.1, 0.2, 0.8, 0.9])
        assert m.result() == 1.0
        m.reset()
        m.update([0, 1], [0.5, 0.5])
        assert m.result() == 0.5  # tie -> 0.5 via rank averaging

    def test_mse(self):
        m = metrics_lib.MeanSquaredError()
        m.update([1.0, 2.0], [1.0, 4.0])
        assert m.result() == 2.0

    def test_metric_tree_nested(self):
        tree = {"accuracy": {"logits": metrics_lib.Accuracy()}}
        metrics_lib.update_metric_tree(
            tree, np.array([1]), {"logits": np.array([[0.0, 5.0]])}
        )
        assert metrics_lib.metric_tree_results(tree) == {
            "accuracy_logits": 1.0
        }
        metrics_lib.reset_metric_tree(tree)
        assert metrics_lib.metric_tree_results(tree) == {
            "accuracy_logits": 0.0
        }


def _local_args(tmp_path, extra=()):
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=512, num_shards=2, seed=0
    )
    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "eval"), num_records=128, num_shards=1, seed=1
    )
    return parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--validation_data",
            eval_dir,
            "--minibatch_size",
            "64",
            "--records_per_task",
            "128",
            "--num_epochs",
            "4",
            "--compute_dtype",
            "float32",
            *extra,
        ]
    )


class TestLocalExecutor:
    def test_mnist_trains_to_accuracy(self, tmp_path):
        """The reference's quality bar: trained accuracy far above chance
        (worker_ps_interaction_test.py asserts > 0.8 on real MNIST; our
        synthetic templates are easier, so demand >= 0.7)."""
        args = _local_args(tmp_path)
        executor = LocalExecutor(args)
        results = executor.run()
        assert results["accuracy"] >= 0.7, results
        assert int(executor.state.step) == 32  # 512*4 epochs / 64 batch

    def test_checkpoint_save_restore_continues(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        args = _local_args(tmp_path, ["--checkpoint_dir", ckpt])
        executor = LocalExecutor(args)
        executor.run()
        from elasticdl_tpu.utils import save_utils

        version = save_utils.latest_version(ckpt)
        assert version == 32

        # warm-start run: restore and evaluate without training
        args2 = _local_args(
            tmp_path, ["--checkpoint_dir_for_init", ckpt]
        )
        executor2 = LocalExecutor(args2)
        # build state from one batch then evaluate with restored params
        executor2._init_from_eval_data()
        results = executor2.evaluate()
        assert results["accuracy"] >= 0.7

    def test_prediction(self, tmp_path):
        args = _local_args(tmp_path)
        args.prediction_data = args.validation_data
        executor = LocalExecutor(args)
        executor.run()
        outputs = executor.predict()
        assert outputs
        total = sum(o.shape[0] for o in outputs)
        assert total == 128
        assert outputs[0].shape[-1] == 10

    def test_export_and_reload(self, tmp_path):
        out = str(tmp_path / "export")
        args = _local_args(tmp_path, ["--output", out])
        executor = LocalExecutor(args)
        results = executor.run()
        from elasticdl_tpu.utils.export_utils import (
            load_exported_model,
            rebuild_variables,
        )

        model, flat_params, flat_state = load_exported_model(out)
        sample = {
            "image": np.zeros((1, 28, 28), np.float32)
        }
        params, model_state = rebuild_variables(
            model, sample, flat_params, flat_state
        )
        out_logits = model.apply(
            {"params": params, **model_state}, sample, training=False
        )
        assert np.asarray(out_logits).shape == (1, 10)

    def test_learning_rate_override(self, tmp_path):
        args = _local_args(tmp_path)
        args.learning_rate = 1e-9  # effectively frozen
        executor = LocalExecutor(args)
        results = executor.run()
        # frozen model should be near chance (10 classes)
        assert results["accuracy"] < 0.5


def test_steps_per_dispatch_equivalent(tmp_path):
    """--steps_per_dispatch k runs k sequential optimizer steps inside
    one scanned dispatch over the same shuffled task stream
    (shuffle_seed pins the order).  The math is the same step function,
    but the scanned program fuses differently than the per-step one, so
    params match to float tolerance, not bitwise — and only over a SHORT
    horizon: per-step rounding (~1e-6) amplifies chaotically through
    ReLU/dropout boundary flips (observed 7e-3 after just 8 steps of
    early mnist training at lr 0.1), so the param check runs on a
    2-step task and the long run asserts the step-count/record
    invariants instead."""
    import jax

    def run(extra):
        args = _local_args(tmp_path, ["--shuffle_seed", "7", *extra])
        ex = LocalExecutor(args)
        ex.run()
        return jax.device_get(ex.state.params), int(ex.state.step)

    # long run: identical step count either way
    _params_1, steps_1 = run([])
    _params_k, steps_k = run(["--steps_per_dispatch", "4"])
    assert steps_1 == steps_k

    # short horizon (one 128-record task = 2 steps): params equivalent
    # before chaotic amplification sets in
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "short"), num_records=128, num_shards=1, seed=0
    )

    def run_short(extra):
        args = parse_master_args(
            [
                "--model_def",
                "mnist_functional_api.mnist_functional_api.custom_model",
                "--training_data",
                train_dir,
                "--minibatch_size",
                "64",
                "--records_per_task",
                "128",
                "--num_epochs",
                "1",
                "--compute_dtype",
                "float32",
                *extra,
            ]
        )
        ex = LocalExecutor(args)
        ex.run()
        return jax.device_get(ex.state.params)

    params_1 = run_short([])
    params_k = run_short(["--steps_per_dispatch", "4"])
    leaves_1 = jax.tree_util.tree_leaves(params_1)
    leaves_k = jax.tree_util.tree_leaves(params_k)
    for a, b in zip(leaves_1, leaves_k):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_steps_per_dispatch_ragged_tail(tmp_path):
    """A record count that leaves ragged tail batches (and a group
    shorter than k) still trains every record exactly once."""
    train_dir = synthetic.gen_mnist(
        str(tmp_path / "t2"), num_records=300, num_shards=1, seed=0
    )
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--minibatch_size",
            "64",
            "--records_per_task",
            "150",  # tasks of 150 -> batches 64,64,22 per task
            "--steps_per_dispatch",
            "4",
            "--compute_dtype",
            "float32",
        ]
    )
    ex = LocalExecutor(args)
    ex.run()
    assert int(ex.state.step) == 6  # 2 tasks x 3 batches
