"""End-to-end failure paths (VERDICT r1 #8): recovery driven by REAL
failure modes — a stalled (not killed) worker whose heartbeats stop, and
an eval lease reclaimed through the actual gRPC transport.

Reference analogues: heartbeat detection stands in for the k8s watch
(``k8s_instance_manager.py:198-281``); the lease-reclaim double-count
guard hardens the reference's exactly-once eval accounting
(``evaluation_service.py:69-124``).
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.utils.args import parse_master_args
from elasticdl_tpu.utils.constants import TaskType

_WORKER_ENVS = "JAX_PLATFORMS=cpu,XLA_FLAGS= "


def _master_args(train_dir, extra):
    return parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--minibatch_size",
            "32",
            "--compute_dtype",
            "float32",
            "--shuffle_seed",
            "11",
            "--jax_platform",
            "cpu",
            "--envs",
            _WORKER_ENVS,
            "--port",
            "0",
            *extra,
        ]
    )


def _wait_for_checkpoint(ckpt_dir, timeout=300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.isdir(ckpt_dir) and any(
            name.startswith("version-") for name in os.listdir(ckpt_dir)
        ):
            return True
        time.sleep(0.5)
    return False


def _run_stall_recovery(tmp_path, extra, num_workers, victim_index=-1):
    """Start a master, SIGSTOP one worker after real progress, assert the
    job completes with every record accounted; returns the master."""
    from elasticdl_tpu.master.main import build_master

    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=256, num_shards=2, seed=7
    )
    ckpt = str(tmp_path / "ckpt")
    args = _master_args(
        train,
        [
            "--num_workers",
            str(num_workers),
            "--records_per_task",
            "64",
            "--num_epochs",
            "2",
            "--checkpoint_dir",
            ckpt,
            "--checkpoint_steps",
            "2",
            "--heartbeat_timeout_secs",
            "5",
            *extra,
        ],
    )
    master = build_master(args)
    master.prepare()
    rc: list[int] = []
    runner = threading.Thread(target=lambda: rc.append(master.run()))
    runner.start()
    stalled_pid = None
    try:
        assert _wait_for_checkpoint(ckpt), "job never progressed"
        victims = master.instance_manager.worker_ids()
        assert len(victims) == num_workers
        victim_proc = master.instance_manager._procs[
            sorted(victims)[victim_index]
        ]
        stalled_pid = victim_proc.pid
        # STALL, don't kill: the process stays alive but its heartbeat
        # thread freezes with it — the failure k8s cannot see but a
        # heartbeat timeout must
        os.kill(stalled_pid, signal.SIGSTOP)

        runner.join(timeout=600)
        assert not runner.is_alive(), "master never finished after stall"
    finally:
        master.request_stop()
        runner.join(timeout=30)
        if stalled_pid is not None:
            try:  # reap the frozen victim if recovery didn't
                os.kill(stalled_pid, signal.SIGKILL)
            except ProcessLookupError:
                pass

    assert rc == [0]
    assert master.task_d.finished()
    counters = master.task_d.counters(TaskType.TRAINING)
    assert counters.total_records == 2 * 256
    return master


@pytest.mark.slow
def test_stalled_lockstep_worker_triggers_reform(tmp_path):
    """A frozen lockstep process stalls the whole world's collectives;
    the master must detect the silent heartbeat and re-form."""
    master = _run_stall_recovery(
        tmp_path,
        ["--distribution_strategy", "AllreduceStrategy"],
        num_workers=2,
    )
    assert master.reform_events, "stall never triggered a re-formation"
    assert master.reform_events[0]["latency_secs"] > 0
    # the new world must come from the hot-standby pool, not a cold start
    assert master.instance_manager.standby_activations == 2


@pytest.mark.slow
def test_stalled_coordinator_process_triggers_reform(tmp_path):
    """Process 0 hosts the jax.distributed coordination service: losing
    IT is the worst lockstep failure (survivors lose both their peer and
    the coordinator).  The world must still re-form and finish."""
    master = _run_stall_recovery(
        tmp_path,
        ["--distribution_strategy", "AllreduceStrategy"],
        num_workers=2,
        victim_index=0,  # worker 0 == process 0 == coordinator host
    )
    assert master.reform_events, "coordinator stall never triggered reform"
    assert master.instance_manager.standby_activations == 2


@pytest.mark.slow
def test_stalled_taskstream_worker_restarted_with_new_id(tmp_path):
    """Task-stream mode (one worker, no lockstep world): the stalled
    worker's tasks are re-queued and a NEW worker id is launched
    (reference k8s_instance_manager.py:266-275)."""
    master = _run_stall_recovery(tmp_path, [], num_workers=1)
    assert not master.reform_events  # no world to re-form
    # the replacement got a fresh id: worker 0 stalled, worker 1 finished
    assert master.instance_manager._next_worker_id >= 2


def test_standby_activation_skips_dead_processes():
    """_activate_standby must skip standbys that died while waiting and
    report False on an empty pool (caller then cold-starts)."""
    from elasticdl_tpu.master.master import LocalInstanceManager

    class _FakeProc:
        def __init__(self, alive=True, broken_pipe=False):
            self._alive = alive
            self._broken = broken_pipe
            self.killed = False
            self.stdin = self
            self.written = b""
            self.pid = 999

        def poll(self):
            return None if self._alive else 1

        def write(self, data):
            if self._broken:
                raise OSError("broken pipe")
            self.written += data

        def flush(self):
            pass

        def kill(self):
            self.killed = True

    im = LocalInstanceManager.__new__(LocalInstanceManager)
    im._lock = threading.Lock()
    im._procs = {}
    im.standby_activations = 0
    dead = _FakeProc(alive=False)
    broken = _FakeProc(broken_pipe=True)
    good = _FakeProc()
    im._standbys = [dead, broken, good]

    world = dict(
        coordinator_addr="localhost:1", num_processes=2,
        process_id=0, cluster_version=1,
    )
    assert im._activate_standby(7, world)
    assert im._procs == {7: good}
    assert broken.killed  # unwritable standby is reaped, not leaked
    assert im.standby_activations == 1
    assert b'"worker_id": 7' in good.written

    # pool exhausted -> False (caller cold-starts)
    assert not im._activate_standby(8, world)


def test_eval_lease_reclaim_over_grpc(tmp_path):
    """Exactly-once eval accounting through the REAL wire: worker A
    leases an eval task, stalls past the lease timeout; the dispatcher
    re-queues it; worker B completes it.  A's late metric report and
    completion must both be dropped (in-process version:
    test_master_eval.test_inactive_lease_metrics_dropped)."""
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.rpc.service import MasterClient, create_server
    from elasticdl_tpu.utils.tensor import ndarray_to_tensor

    eval_dir = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    args = _master_args(
        "",
        [
            "--validation_data",
            eval_dir,
            "--records_per_task",
            "32",
            "--task_timeout_secs",
            "1",
        ],
    )
    master = Master(args)
    server = create_server(master.servicer, port=0)
    server.start()
    client_a = MasterClient(f"localhost:{server._edl_bound_port}")
    client_b = MasterClient(f"localhost:{server._edl_bound_port}")
    try:
        task_a = client_a.get_task(
            msg.GetTaskRequest(worker_id=1, task_type=int(TaskType.EVALUATION))
        )
        assert task_a.type == int(TaskType.EVALUATION)

        time.sleep(1.2)  # expire A's lease
        task_b = client_b.get_task(
            msg.GetTaskRequest(worker_id=2, task_type=int(TaskType.EVALUATION))
        )
        # the same shard is re-leased under a FRESH lease id (lease
        # identity is what the double-count guard keys on)
        assert (task_b.shard_name, task_b.start, task_b.end) == (
            task_a.shard_name,
            task_a.start,
            task_a.end,
        )
        assert task_b.task_id != task_a.task_id

        perfect = np.eye(10, dtype=np.float32)[
            np.arange(32) % 10
        ]  # 100%-accurate outputs
        labels = ndarray_to_tensor("labels", (np.arange(32) % 10))

        # A's late report through the wire: inactive lease -> dropped
        client_a.report_evaluation_metrics(
            msg.ReportEvaluationMetricsRequest(
                model_outputs={
                    "output": ndarray_to_tensor(
                        "output", np.zeros((32, 10), np.float32)
                    )
                },
                labels=labels,
                task_id=task_a.task_id,
            )
        )
        job = master.evaluation_service._eval_job
        assert job.get_evaluation_summary()["accuracy"] == 0.0

        # B's report for the SAME task id (active lease) is counted
        client_b.report_evaluation_metrics(
            msg.ReportEvaluationMetricsRequest(
                model_outputs={
                    "output": ndarray_to_tensor("output", perfect)
                },
                labels=labels,
                task_id=task_b.task_id,
            )
        )
        client_b.report_task_result(
            msg.ReportTaskResultRequest(task_id=task_b.task_id)
        )
        assert job.get_evaluation_summary()["accuracy"] == 1.0
        # exactly-once completion: B's single report finished the job
        assert job.finished()
        assert master.task_d.finished()
    finally:
        client_a.close()
        client_b.close()
        server.stop(grace=None)
