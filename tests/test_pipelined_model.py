"""Pipeline-parallel transformer model: the pp schedule and the
sequential scan are two execution plans for ONE parameter layout — their
outputs must match, the stacked params must shard over pp, and the model
must train through SPMDTrainer on a dp x pp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models import pipelined_transformer as ppt
from elasticdl_tpu.ops.attention import (
    attention_mesh_scope,
    set_attention_mesh,
)
from elasticdl_tpu.parallel.distributed import SPMDTrainer
from elasticdl_tpu.parallel.mesh import MeshConfig

KW = dict(
    vocab_size=64, embed_dim=32, num_heads=2, num_stages=4,
    num_microbatches=2,
)


def _data(batch=4, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    feats = {"tokens": rng.randint(0, 64, (batch, seq)).astype(np.int32)}
    labels = rng.randint(0, 64, (batch, seq)).astype(np.int32)
    return feats, labels


def test_pipelined_forward_matches_sequential_scan():
    feats, _ = _data()
    model = ppt.custom_model(**KW)
    set_attention_mesh(None)
    params = model.init(jax.random.PRNGKey(0), feats)["params"]
    seq_out = model.apply({"params": params}, feats)  # scan path

    mesh = MeshConfig.from_string("dp=2,pp=4").create()
    with attention_mesh_scope(mesh):
        pipe_out = jax.jit(
            lambda p, f: model.apply({"params": p}, f)
        )(params, feats)
    np.testing.assert_allclose(
        np.asarray(pipe_out), np.asarray(seq_out), atol=2e-4, rtol=2e-4
    )
    set_attention_mesh(None)


def test_pipelined_model_trains_on_pp_mesh():
    feats, labels = _data()
    mesh = MeshConfig.from_string("dp=2,pp=4").create()
    model = ppt.custom_model(**KW)
    trainer = SPMDTrainer(
        mesh,
        model,
        ppt.loss,
        optax.adam(3e-3),
        feats,
        rules=tuple(ppt.sharding_rules(mesh)),
    )
    wq = trainer.state.params["stages_wq"]
    assert "pp" in str(wq.sharding.spec), wq.sharding.spec

    losses = [
        float(
            trainer.train_step(
                trainer.place_batch(feats), trainer.place_batch(labels)
            )["loss"]
        )
        for _ in range(5)
    ]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_pipelined_model_rejects_stage_mesh_mismatch():
    import pytest

    feats, _ = _data()
    mesh = MeshConfig.from_string("dp=4,pp=2").create()  # pp=2 != stages=4
    model = ppt.custom_model(**KW)
    params = model.init(jax.random.PRNGKey(0), feats)["params"]
    with attention_mesh_scope(mesh):
        with pytest.raises(ValueError):
            model.apply({"params": params}, feats)
    set_attention_mesh(None)


def test_pipelined_spec_contract():
    from elasticdl_tpu.utils.model_utils import get_model_spec

    spec = get_model_spec(
        "", "pipelined_transformer.pipelined_transformer.custom_model"
    )
    assert spec.build_model() is not None
    assert spec.loss is not None and spec.dataset_fn is not None
    assert spec.sharding_rules is not None
