"""Master high availability (ISSUE 6): journaled control-plane
recovery, worker re-homing, and the RPC retry unit.

Covers, per the issue's satellites:

- ``rpc/retry.py`` as its own reviewed unit: bounded attempts,
  full-jitter backoff, wall budget, idempotent-only defaults, and the
  flaky-server / re-resolve loop on ``RpcClient``;
- msgpack ``strict_map_key`` pinning for the new wire payloads
  (re-homing handshake, boot id) and journal str-key discipline;
- journal replay equivalence against ``state_snapshot()`` as a property
  test over randomized recorded transitions;
- the PR 4 ``finished()`` bug shape replayed: a master killed at an
  epoch's LAST task must restart into a dispatcher that still owes the
  remaining epochs;
- argv/golden byte-compat: HA flags default to None and never reach
  worker argv;
- the gloo fast-fail linger generalization (a crashed lockstep process
  lingers when master HA is on, even without a replica server);
- the ``master_recovery`` invariant and its journal_rollback
  falsification;
- master-downtime attribution: report section + trace-analyze phases
  summing exactly to the measured gap.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

import pytest

from elasticdl_tpu.master.journal import (
    MASTER_ADDR_FILE_ENV,
    MasterJournal,
    addr_file_path,
    journal_path,
    load_state,
    read_master_addr,
    replay,
    write_master_addr,
)
from elasticdl_tpu.master.task_dispatcher import Task, TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.rpc.retry import (
    DEFAULT_IDEMPOTENT,
    RetryPolicy,
    call_with_retry,
)
from elasticdl_tpu.utils.constants import TaskType

# ---- retry policy (pure math, no channel) -----------------------------------


def test_delay_cap_grows_exponentially_and_is_bounded():
    policy = RetryPolicy(base_delay_secs=0.1, max_delay_secs=2.0)
    assert policy.delay_cap(1) == pytest.approx(0.1)
    assert policy.delay_cap(2) == pytest.approx(0.2)
    assert policy.delay_cap(3) == pytest.approx(0.4)
    # bounded: attempt 30 would overflow 0.1 * 2**29 without the cap
    assert policy.delay_cap(30) == 2.0


def test_call_with_retry_succeeds_after_transient_failures():
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("down")
        return "ok"

    sleeps = []
    out = call_with_retry(
        flaky,
        RetryPolicy(max_attempts=5, base_delay_secs=0.01),
        sleep=sleeps.append,
    )
    assert out == "ok"
    assert len(attempts) == 3
    assert len(sleeps) == 2  # one backoff per failed attempt
    # full jitter: every delay within the attempt's cap
    policy = RetryPolicy(max_attempts=5, base_delay_secs=0.01)
    for i, delay in enumerate(sleeps, start=1):
        assert 0.0 <= delay <= policy.delay_cap(i)


def test_call_with_retry_exhausts_attempts_and_reraises():
    def always_down():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retry(
            always_down,
            RetryPolicy(max_attempts=3, base_delay_secs=0.0),
            sleep=lambda _s: None,
        )


def test_call_with_retry_nonretryable_raises_immediately():
    attempts = []

    def fails():
        attempts.append(1)
        raise ValueError("bug, not outage")

    with pytest.raises(ValueError):
        call_with_retry(
            fails,
            RetryPolicy(max_attempts=10),
            is_retryable=lambda ex: isinstance(ex, ConnectionError),
            sleep=lambda _s: None,
        )
    assert len(attempts) == 1


def test_call_with_retry_honors_wall_budget():
    clock = [0.0]

    def tick_sleep(secs):
        clock[0] += max(secs, 0.05)

    def always_down():
        clock[0] += 0.1
        raise ConnectionError("down")

    attempts_seen = []
    with pytest.raises(ConnectionError):
        call_with_retry(
            always_down,
            RetryPolicy.from_budget(1.0),
            on_retry=lambda attempt, _ex: attempts_seen.append(attempt),
            sleep=tick_sleep,
            clock=lambda: clock[0],
        )
    # the budget, not max_attempts (10_000), ended the loop
    assert 2 <= len(attempts_seen) < 100
    assert clock[0] >= 1.0


def test_default_idempotent_is_the_read_only_subset():
    assert "report_task_result" not in DEFAULT_IDEMPOTENT
    assert "get_task" not in DEFAULT_IDEMPOTENT
    assert {"heartbeat", "get_step_task"} <= DEFAULT_IDEMPOTENT


# ---- RpcClient retry + re-resolve (flaky fake server) -----------------------


class _FakeGrpcError(Exception):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


def _make_client(retry, retryable, resolve_addr=None):
    from elasticdl_tpu.rpc.service import RpcClient

    return RpcClient(
        "localhost:1",
        methods=("heartbeat", "report_task_result"),
        retry=retry,
        retryable_methods=retryable,
        resolve_addr=resolve_addr,
    )


def test_rpc_client_retries_only_retryable_methods():
    import grpc

    client = _make_client(
        RetryPolicy(max_attempts=5, base_delay_secs=0.0),
        {"heartbeat"},
    )
    calls = {"heartbeat": 0, "report_task_result": 0}

    def flaky(name):
        def call(_payload, timeout=None):
            calls[name] += 1
            if calls[name] < 3:
                raise _FakeGrpcError(grpc.StatusCode.UNAVAILABLE)
            return msg.encode(msg.HeartbeatResponse(boot_id="b1"))

        return call

    client._calls = {n: flaky(n) for n in client._calls}
    out = client._call("heartbeat", msg.HeartbeatRequest(worker_id=0))
    assert out.boot_id == "b1"
    assert calls["heartbeat"] == 3
    # a non-retryable method fails fast on the same error
    with pytest.raises(_FakeGrpcError):
        client._call(
            "report_task_result",
            msg.ReportTaskResultRequest(task_id=1, err_message=""),
        )
    assert calls["report_task_result"] == 1
    client.close()


def test_rpc_client_does_not_retry_non_outage_codes():
    import grpc

    client = _make_client(
        RetryPolicy(max_attempts=5, base_delay_secs=0.0), {"heartbeat"}
    )
    calls = []

    def broken(_payload, timeout=None):
        calls.append(1)
        raise _FakeGrpcError(grpc.StatusCode.INVALID_ARGUMENT)

    client._calls = {n: broken for n in client._calls}
    with pytest.raises(_FakeGrpcError):
        client._call("heartbeat", msg.HeartbeatRequest(worker_id=0))
    assert len(calls) == 1  # a bug is not an outage: no backoff loop
    client.close()


def test_rpc_client_reresolves_address_and_rebuilds_channel():
    import grpc

    moved = {"addr": "localhost:1"}
    client = _make_client(
        RetryPolicy(max_attempts=8, base_delay_secs=0.0),
        {"heartbeat"},
        resolve_addr=lambda: moved["addr"],
    )
    connects = []
    real_connect = client._connect

    def tracking_connect(addr):
        connects.append(addr)
        real_connect(addr)
        # the rebuilt channel serves: the relaunched master is up
        client._calls = {
            n: (
                lambda _p, timeout=None: msg.encode(
                    msg.HeartbeatResponse(boot_id="new-master")
                )
            )
            for n in client._calls
        }

    client._connect = tracking_connect

    def down(_payload, timeout=None):
        raise _FakeGrpcError(grpc.StatusCode.UNAVAILABLE)

    client._calls = {n: down for n in client._calls}
    moved["addr"] = "localhost:2"  # the addr file now names the new master
    out = client._call("heartbeat", msg.HeartbeatRequest(worker_id=0))
    assert out.boot_id == "new-master"
    assert connects == ["localhost:2"]
    assert client._addr == "localhost:2"
    client.close()


def test_reresolve_parks_old_channel_until_client_close():
    """A re-resolve must NOT close the superseded channel: another
    thread's retry attempt may be invoking on it, and grpc turns that
    into a non-retryable ValueError that escapes the retry loop.  The
    old channel is parked and only closed with the client."""

    class FakeChannel:
        closed = False

        def close(self):
            self.closed = True

    moved = {"addr": "localhost:2"}
    client = _make_client(
        RetryPolicy(max_attempts=2, base_delay_secs=0.0),
        {"heartbeat"},
        resolve_addr=lambda: moved["addr"],
    )
    old = FakeChannel()
    client._channel = old
    client._maybe_reresolve(2, None)  # attempt multiple of _RERESOLVE_EVERY
    assert client._addr == "localhost:2"
    assert not old.closed  # parked, not closed
    assert old in client._stale_channels
    client.close()
    assert old.closed


# ---- wire payloads (msgpack strict_map_key discipline) ----------------------


def test_rehome_messages_round_trip():
    req = msg.decode(
        msg.encode(
            msg.RehomeRequest(
                worker_id=3,
                cluster_version=2,
                pid=4242,
                lease_ids=[7, 9],
            )
        )
    )
    assert (req.worker_id, req.cluster_version, req.pid) == (3, 2, 4242)
    assert req.lease_ids == [7, 9]
    resp = msg.decode(
        msg.encode(
            msg.RehomeResponse(
                accepted=True,
                cluster_version=2,
                boot_id="abc",
                accepted_leases=[7],
            )
        )
    )
    assert resp.accepted and resp.accepted_leases == [7]
    assert resp.boot_id == "abc"


def test_old_heartbeat_payload_decodes_without_boot_id():
    """Wire-compat: a pre-HA master's HeartbeatResponse has no boot_id
    field — decode must fill the empty default (workers then never
    re-home, exactly the HA-off behavior)."""
    import msgpack

    body = {"should_quiesce": False, "cluster_version": 0}
    buf = msgpack.packb(
        {"kind": "HeartbeatResponse", "body": body}, use_bin_type=True
    )
    decoded = msg.decode(buf)
    assert decoded.boot_id == ""


def test_journal_records_and_snapshots_use_string_keys_only():
    """The journal is JSONL: non-str dict keys would be silently
    coerced on write and mismatch on replay — pin str keys end to end
    (the PR 4 peer-map rule, applied to the control plane)."""
    d = TaskDispatcher(
        {"s": (0, 128)}, records_per_task=64, shuffle_seed=1
    )
    d.get(worker_id=0)
    snap = d.state_snapshot()

    def assert_str_keys(obj, path="$"):
        if isinstance(obj, dict):
            for key, value in obj.items():
                assert isinstance(key, str), f"non-str key at {path}: {key!r}"
                assert_str_keys(value, f"{path}.{key}")
        elif isinstance(obj, list):
            for i, item in enumerate(obj):
                assert_str_keys(item, f"{path}[{i}]")

    assert_str_keys(snap)
    # and the round trip through JSON is the identity (what replay sees)
    assert json.loads(json.dumps(snap)) == snap


# ---- journal replay equivalence (property test) -----------------------------


def _journal_for(d: TaskDispatcher, tmp_path, cv=0, **kw) -> MasterJournal:
    journal = MasterJournal(str(tmp_path), **kw)
    d.add_observer(journal)
    # the master's provider(append) contract: the dispatcher capture and
    # the snapshot append share the dispatcher transition lock
    journal.set_snapshot_provider(
        lambda append: d.atomic_state_snapshot(
            lambda dispatcher_state: append(
                {
                    "dispatcher": dispatcher_state,
                    "servicer": {
                        "cluster_version": cv,
                        "model_version": 0,
                        "stream": {},
                    },
                    "callbacks_invoked": journal.callbacks_invoked,
                    "world": None,
                }
            )
        )
    )
    journal.start()
    return journal


def _drive_random(d: TaskDispatcher, journal, rng, ops=60):
    """Random but valid transition stream: lease / succeed / fail /
    recover a worker / occasional re-snapshot."""
    active: list[int] = []
    for _ in range(ops):
        op = rng.random()
        if op < 0.45:
            tid, task = d.get(worker_id=rng.randrange(3))
            if task is not None:
                active.append(tid)
        elif op < 0.75 and active:
            tid = active.pop(rng.randrange(len(active)))
            d.report(
                tid,
                success=rng.random() < 0.8,
                exec_counters={"fail_count": rng.randrange(2),
                               "batch_count": rng.randrange(5)},
            )
        elif op < 0.85 and active:
            worker = rng.randrange(3)
            d.recover_tasks(worker)
            still_active = set(d.state_snapshot()["active"])
            active = [t for t in active if str(t) in still_active]
        elif op < 0.9:
            journal.write_snapshot()


@pytest.mark.parametrize("seed", [1, 7, 23, 57])
def test_journal_replay_reconstructs_snapshot_equivalent_state(
    tmp_path, seed
):
    """THE replay-equivalence property: for a random recorded
    transition stream, last-snapshot-plus-deltas == the live
    dispatcher's own state_snapshot()."""
    rng = random.Random(seed)
    d = TaskDispatcher(
        {"a": (0, 256), "b": (256, 192)},
        records_per_task=64,
        num_epochs=3,
        shuffle_seed=seed,
    )
    journal = _journal_for(d, tmp_path, snapshot_every=10_000)
    _drive_random(d, journal, rng)
    journal.flush()
    restored = load_state(str(tmp_path))
    assert restored is not None
    assert not restored["clean_shutdown"]
    assert restored["dispatcher"] == d.state_snapshot()


def test_restored_dispatcher_continues_equivalently(tmp_path):
    """restore_state() installs the replayed state into a dispatcher
    that then finishes the job with exactly-once accounting."""
    rng = random.Random(11)
    d = TaskDispatcher(
        {"a": (0, 256)}, records_per_task=64, num_epochs=2, shuffle_seed=11
    )
    journal = _journal_for(d, tmp_path, snapshot_every=10_000)
    _drive_random(d, journal, rng, ops=25)
    journal.flush()
    restored = load_state(str(tmp_path))

    d2 = TaskDispatcher(
        {"a": (0, 256)}, records_per_task=64, num_epochs=2, shuffle_seed=99
    )
    d2.restore_state(restored["dispatcher"])
    assert d2.state_snapshot() == d.state_snapshot()
    # finish the restored job: leases held at the "kill" are presented
    # by nobody, so reconcile requeues them, then drain everything
    for tid in list(restored["dispatcher"]["active"]):
        d2.reconcile_leases(
            restored["dispatcher"]["active"][tid]["worker_id"], set()
        )
    seen_uids = set()
    while True:
        tid, task = d2.get(worker_id=0)
        if task is None:
            break
        assert task.uid not in seen_uids
        seen_uids.add(task.uid)
        d2.report(tid, success=True)
    assert d2.finished()


def test_replay_kill_at_epochs_last_task_runs_remaining_epochs(tmp_path):
    """The PR 4 finished() bug shape, replayed through the journal: the
    master dies right after the LAST task of epoch 0 completes (epoch 1
    never opened — epochs open lazily in get()).  The restored
    dispatcher must still owe epoch 1."""
    d = TaskDispatcher(
        {"s": (0, 128)}, records_per_task=64, num_epochs=2, shuffle_seed=5
    )
    journal = _journal_for(d, tmp_path, snapshot_every=10_000)
    # lease every epoch-0 task FIRST (get() with an empty queue would
    # lazily open epoch 1 — the kill must land before that), then
    # complete them all: epoch 0 drained, epoch 1 unopened
    leases = []
    while d.state_snapshot()["pending"]:
        tid, _task = d.get(worker_id=0)
        leases.append(tid)
    for tid in leases:
        d.report(tid, success=True)
    epoch0_tasks = len(leases)
    snap = d.state_snapshot()
    assert snap["epoch"] == 0 and not snap["pending"] and not snap["active"]
    journal.flush()
    restored = load_state(str(tmp_path))
    d2 = TaskDispatcher(
        {"s": (0, 128)}, records_per_task=64, num_epochs=2, shuffle_seed=5
    )
    d2.restore_state(restored["dispatcher"])
    # the restored master must NOT declare the job done one epoch early
    assert not d2.finished()
    remaining = 0
    while True:
        tid, task = d2.get(worker_id=1)
        if task is None:
            break
        d2.report(tid, success=True)
        remaining += 1
    assert remaining == epoch0_tasks  # epoch 1 is the same slice count
    assert d2.finished()
    assert (
        d2.counters(TaskType.TRAINING).total_records == 2 * 128
    )


def test_replay_generation_bump_resets_stream_and_is_monotone():
    records = [
        {
            "kind": "snapshot",
            "state": {
                "dispatcher": {
                    "epoch": 0,
                    "next_task_id": 0,
                    "next_task_uid": 0,
                    "pending": [],
                    "pending_eval": [],
                    "active": {},
                    "counters": {},
                },
                "servicer": {
                    "cluster_version": 0,
                    "model_version": 0,
                    "stream": {},
                },
            },
        },
        {"kind": "stream", "stream_seq": 4, "response": {"task_id": 9}},
        {"kind": "generation", "cluster_version": 2},
        {"kind": "stream", "stream_seq": 0, "response": {"task_id": 11}},
        # forged/corrupt rollback: the monotone guard must hold the fence
        {"kind": "generation", "cluster_version": 1},
    ]
    state = replay(records)
    assert state["servicer"]["cluster_version"] == 2
    # the bump reset the old generation's memos; post-bump memo retained
    assert state["servicer"]["stream"] == {"0": {"task_id": 11}}


def test_replay_drops_stream_records_stamped_for_another_world():
    """``get_step_task``'s fence check and its memoization run under
    different locks: a stale request racing a reform can journal its
    ``stream`` record AFTER the reform's ``generation`` record, where
    the live master's ``reset_step_stream`` has no replay analogue.  The
    generation stamp closes the hole; unstamped (legacy) records keep
    the old always-apply behavior."""
    records = [
        {
            "kind": "snapshot",
            "state": {
                "dispatcher": {
                    "epoch": 0,
                    "next_task_id": 0,
                    "next_task_uid": 0,
                    "pending": [],
                    "pending_eval": [],
                    "active": {},
                    "counters": {},
                },
                "servicer": {
                    "cluster_version": 0,
                    "model_version": 0,
                    "stream": {},
                },
            },
        },
        {
            "kind": "stream",
            "stream_seq": 4,
            "response": {"task_id": 9},
            "cluster_version": 0,
        },
        {"kind": "generation", "cluster_version": 1},
        # the stale racer: resolved FOR generation 0, record landed
        # after the fence — replay must drop it
        {
            "kind": "stream",
            "stream_seq": 5,
            "response": {"task_id": 10},
            "cluster_version": 0,
        },
        # an unstamped legacy record still always applies
        {"kind": "stream", "stream_seq": 6, "response": {"task_id": 12}},
        # the new world's resolution applies
        {
            "kind": "stream",
            "stream_seq": 0,
            "response": {"task_id": 11},
            "cluster_version": 1,
        },
    ]
    state = replay(records)
    assert state["servicer"]["stream"] == {
        "0": {"task_id": 11},
        "6": {"task_id": 12},
    }


def test_step_task_memo_journals_with_its_generation_stamp():
    """The servicer stamps every journaled stream resolution with the
    fence the request passed, so replay can tell a pre-reform racer from
    a new-world memo."""
    from elasticdl_tpu.master.servicer import MasterServicer

    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=4)
    servicer = MasterServicer(32, d)
    recorded: list = []

    class _Journal:
        def record_stream(self, seq, response, cluster_version=-1):
            recorded.append((seq, cluster_version))

    servicer.set_journal(_Journal())
    resp = servicer.get_step_task(
        msg.GetStepTaskRequest(worker_id=0, seq=0, cluster_version=0)
    )
    assert resp.task_id != -1
    assert recorded == [(0, 0)]
    # a stale world is fenced before it can lease or memoize
    stale = servicer.get_step_task(
        msg.GetStepTaskRequest(worker_id=1, seq=0, cluster_version=7)
    )
    assert stale.task_id == -1
    assert recorded == [(0, 0)]


def test_replay_stream_snapshot_supersedes_earlier_memos():
    """The servicer journals a full stream capture (under its stream
    lock) right after each main snapshot; on replay it must REPLACE
    whatever the main snapshot + earlier deltas built — a memo resolved
    between the main snapshot's capture and its append only survives via
    this record — while later deltas still apply on top."""
    records = [
        {
            "kind": "snapshot",
            "state": {
                "dispatcher": {
                    "epoch": 0,
                    "next_task_id": 0,
                    "next_task_uid": 0,
                    "pending": [],
                    "pending_eval": [],
                    "active": {},
                    "counters": {},
                },
                # captured BEFORE the snapshot's append: stale
                "servicer": {
                    "cluster_version": 0,
                    "model_version": 0,
                    "stream": {"0": {"task_id": 7}},
                },
            },
        },
        {
            "kind": "stream_snapshot",
            "stream": {"0": {"task_id": 7}, "1": {"task_id": 8}},
        },
        {"kind": "stream", "stream_seq": 2, "response": {"task_id": 9}},
    ]
    state = replay(records)
    assert state["servicer"]["stream"] == {
        "0": {"task_id": 7},
        "1": {"task_id": 8},
        "2": {"task_id": 9},
    }


def test_journal_abort_drops_the_unflushed_tail(tmp_path):
    """SIGKILL semantics: abort() loses the buffered batch window — the
    journal must replay to the last durable state, not the lost tail."""
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=2)
    journal = _journal_for(
        d, tmp_path, fsync_batch=10_000, fsync_interval_secs=3600.0
    )
    d.get(worker_id=0)  # a lease rides the batch window
    journal.abort()
    restored = load_state(str(tmp_path))
    # only the initial snapshot survived: no leases, full pending queue
    assert restored["dispatcher"]["active"] == {}
    assert len(restored["dispatcher"]["pending"]) == 2
    # the journal refuses writes after abort
    journal.on_epoch_opened(1)
    journal.flush()
    assert load_state(str(tmp_path))["dispatcher"] == restored["dispatcher"]


def test_journal_success_reports_survive_the_abort_tail(tmp_path):
    """The one loss re-homing cannot reconcile: a COUNTED completion.
    Success reports flush inline (critical), so a master killed inside
    the batch window still replays the task as done — never re-trained,
    never double-counted."""
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=2)
    journal = _journal_for(
        d, tmp_path, fsync_batch=10_000, fsync_interval_secs=3600.0
    )
    tid, _task = d.get(worker_id=0)
    d.report(tid, success=True)
    journal.abort()
    restored = load_state(str(tmp_path))
    # the inline flush carried the buffered lease down with it, and the
    # completion itself is durable: the done task is in NEITHER queue —
    # nothing to re-train (contrast the lease-only abort test above)
    assert restored["dispatcher"]["active"] == {}
    assert len(restored["dispatcher"]["pending"]) == 1
    assert restored["dispatcher"]["counters"]["TRAINING"][
        "total_records"
    ] == 128


def test_master_addr_file_round_trip(tmp_path):
    write_master_addr(str(tmp_path), "localhost:4711")
    assert read_master_addr(addr_file_path(str(tmp_path))) == "localhost:4711"
    assert read_master_addr(str(tmp_path / "missing")) is None


# ---- lease reconciliation (the re-homing handshake) -------------------------


def _leased_dispatcher():
    d = TaskDispatcher(
        {"s": (0, 256)}, records_per_task=64, shuffle_seed=4
    )
    leases = {}
    for worker in (1, 1, 2):
        tid, task = d.get(worker_id=worker)
        leases.setdefault(worker, []).append(tid)
    return d, leases


def test_reconcile_leases_keeps_presented_and_requeues_the_rest():
    d, leases = _leased_dispatcher()
    present = leases[1][0]
    dropped = leases[1][1]
    kept, requeued = d.reconcile_leases(1, {present})
    assert kept == [present]
    assert requeued == [dropped]
    # worker 2's lease is untouched
    assert d.is_active(leases[2][0])
    assert d.is_active(present)
    assert not d.is_active(dropped)


def test_reconcile_leases_ignores_unknown_presented_ids():
    d, leases = _leased_dispatcher()
    kept, requeued = d.reconcile_leases(1, {9999, *leases[1]})
    assert sorted(kept) == sorted(leases[1])
    assert requeued == []
    # the unknown id was NOT accepted: its eventual report is dropped
    assert 9999 not in kept


def test_servicer_rehome_fences_stale_generations():
    from elasticdl_tpu.master.servicer import MasterServicer

    d, leases = _leased_dispatcher()
    servicer = MasterServicer(32, d)
    servicer.set_boot_id("boot-2")
    servicer.bump_cluster_version()  # generation 1: world 0 is fenced
    stale = servicer.rehome_worker(
        msg.RehomeRequest(worker_id=1, cluster_version=0, lease_ids=leases[1])
    )
    assert not stale.accepted
    assert stale.cluster_version == 1
    # the fenced worker's leases were NOT touched
    assert all(d.is_active(t) for t in leases[1])
    current = servicer.rehome_worker(
        msg.RehomeRequest(worker_id=1, cluster_version=1, lease_ids=leases[1])
    )
    assert current.accepted
    assert current.boot_id == "boot-2"
    assert sorted(current.accepted_leases) == sorted(leases[1])


def test_servicer_rehome_sink_receives_reconciliation_outcome():
    from elasticdl_tpu.master.servicer import MasterServicer

    d, leases = _leased_dispatcher()
    servicer = MasterServicer(32, d)
    servicer.set_boot_id("b")
    sunk = []
    servicer.set_rehome_sink(
        lambda worker_id, pid, kept, requeued, started_at: sunk.append(
            (worker_id, pid, sorted(kept), sorted(requeued), started_at)
        )
    )
    before = time.monotonic()
    servicer.rehome_worker(
        msg.RehomeRequest(
            worker_id=1, cluster_version=0, pid=77,
            lease_ids=[leases[1][0]],
        )
    )
    assert [s[:4] for s in sunk] == [(1, 77, [leases[1][0]], [leases[1][1]])]
    # started_at is the servicer's handshake ENTRY time, so the
    # worker_rehome span covers fence + reconciliation, not just the
    # adoption tail
    assert before <= sunk[0][4] <= time.monotonic()


# ---- invariant checker across a master restart ------------------------------


def test_checker_identity_spans_master_restart():
    """Task identity is the journaled uid: a restored dispatcher's
    backlog replay must dedup onto pre-outage records, and completions
    on either side of the outage count toward ONE identity."""
    from elasticdl_tpu.chaos.invariants import InvariantChecker

    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    tid, _ = d.get(worker_id=0)
    d.report(tid, success=True)

    # "master restart": an equivalent dispatcher from the snapshot,
    # same checker re-attached (backlog replay fires on attach)
    d2 = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=8)
    d2.restore_state(d.state_snapshot())
    d2.add_observer(checker)
    while True:
        tid, task = d2.get(worker_id=1)
        if task is None:
            break
        d2.report(tid, success=True)
    assert checker.check(d2.counters(TaskType.TRAINING)) == []
    summary = checker.summary()
    assert summary["ok"] and summary["tasks_tracked"] == 4


def test_checker_detects_double_training_across_restart():
    """If a restored master re-runs a task its previous life already
    counted (journal tamper / replay bug), exactly_once must flag it."""
    from elasticdl_tpu.chaos.invariants import InvariantChecker

    checker = InvariantChecker(expected_records=256)
    d = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=3)
    d.add_observer(checker)
    tid, task = d.get(worker_id=0)
    done_uid = task.uid
    d.report(tid, success=True)

    snap = d.state_snapshot()
    # journal tamper: the completed task reappears in the pending queue
    snap["pending"].append(
        Task(
            shard_name=task.shard_name,
            start=task.start,
            end=task.end,
            type=task.type,
            uid=done_uid,
        ).to_dict()
    )
    d2 = TaskDispatcher({"s": (0, 256)}, records_per_task=64, shuffle_seed=8)
    d2.restore_state(snap)
    d2.add_observer(checker)
    while True:
        tid, t = d2.get(worker_id=1)
        if t is None:
            break
        d2.report(tid, success=True)
    violations = checker.check()
    assert any(
        v.invariant == "exactly_once" and "double" in v.detail
        for v in violations
    )


# ---- master_recovery invariant + journal_rollback falsification -------------


def _ha_config(tmp_path):
    from elasticdl_tpu.chaos.harness import ChaosJobConfig
    from elasticdl_tpu.chaos.plan import named_plan

    return ChaosJobConfig(
        plan=named_plan("master_kill_mid_epoch", 2),
        workdir=str(tmp_path),
        master_ha=True,
    )


def _write_events(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")


def test_master_recovery_invariant_passes_on_clean_journal(tmp_path):
    from elasticdl_tpu.chaos.harness import _check_master_recovery

    config = _ha_config(tmp_path)
    telemetry_dir = os.path.join(str(tmp_path), "telemetry")
    _write_events(
        os.path.join(telemetry_dir, "events.jsonl"),
        [{"event": "master_restart", "generation": 0, "monotonic": 5.0}],
    )
    journal_dir = os.path.join(str(tmp_path), "journal")
    os.makedirs(journal_dir)
    _write_events(
        journal_path(journal_dir),
        [
            {"kind": "snapshot", "state": {}},
            {"kind": "generation", "cluster_version": 1},
            {"kind": "generation", "cluster_version": 2},
        ],
    )
    verdict = _check_master_recovery(config, telemetry_dir, master_lives=2)
    assert verdict["status"] == "PASS", verdict


def test_master_recovery_invariant_trips_on_generation_rollback(tmp_path):
    """The journal_rollback falsification shape: a generation fence
    recorded LOWER than its predecessor must FAIL the invariant."""
    from elasticdl_tpu.chaos.harness import (
        _check_master_recovery,
        _corrupt_journal_rollback,
    )

    config = _ha_config(tmp_path)
    telemetry_dir = os.path.join(str(tmp_path), "telemetry")
    _write_events(
        os.path.join(telemetry_dir, "events.jsonl"),
        [{"event": "master_restart", "generation": 0, "monotonic": 5.0}],
    )
    journal_dir = os.path.join(str(tmp_path), "journal")
    os.makedirs(journal_dir)
    _write_events(
        journal_path(journal_dir), [{"kind": "snapshot", "state": {}}]
    )
    _corrupt_journal_rollback(journal_dir)
    verdict = _check_master_recovery(config, telemetry_dir, master_lives=2)
    assert verdict["status"] == "FAIL"
    assert any("rolled back" in v for v in verdict["violations"])


def test_master_recovery_invariant_requires_restart_evidence(tmp_path):
    from elasticdl_tpu.chaos.harness import _check_master_recovery

    config = _ha_config(tmp_path)
    telemetry_dir = os.path.join(str(tmp_path), "telemetry")
    _write_events(os.path.join(telemetry_dir, "events.jsonl"), [])
    journal_dir = os.path.join(str(tmp_path), "journal")
    os.makedirs(journal_dir)
    _write_events(
        journal_path(journal_dir), [{"kind": "snapshot", "state": {}}]
    )
    verdict = _check_master_recovery(config, telemetry_dir, master_lives=2)
    assert verdict["status"] == "FAIL"
    assert any("master_restart" in v for v in verdict["violations"])


def test_master_recovery_invariant_trips_when_kill_never_fires(tmp_path):
    """Realization: a plan that demands a MASTER_KILL which never fired
    (at_step beyond the job, or a lost race with completion) must FAIL —
    deriving expectations from the observed life count alone would pass
    vacuously with master_lives=1."""
    from elasticdl_tpu.chaos.harness import _check_master_recovery

    config = _ha_config(tmp_path)
    telemetry_dir = os.path.join(str(tmp_path), "telemetry")
    _write_events(os.path.join(telemetry_dir, "events.jsonl"), [])
    journal_dir = os.path.join(str(tmp_path), "journal")
    os.makedirs(journal_dir)
    _write_events(
        journal_path(journal_dir), [{"kind": "snapshot", "state": {}}]
    )
    verdict = _check_master_recovery(config, telemetry_dir, master_lives=1)
    assert verdict["status"] == "FAIL"
    assert any("never realized" in v for v in verdict["violations"])


def test_harness_rejects_master_kill_plan_without_ha(tmp_path):
    """A plan demanding MASTER_KILL with master_ha off must refuse to
    run — silently dropping the kills would complete green with the
    fault never armed and no invariant recording it."""
    from elasticdl_tpu.chaos.harness import run_chaos_job

    config = _ha_config(tmp_path)
    config.master_ha = False
    with pytest.raises(ValueError, match="master_ha"):
        run_chaos_job(config)


def test_master_recovery_invariant_absent_without_master_kill(tmp_path):
    from elasticdl_tpu.chaos.harness import (
        ChaosJobConfig,
        _check_master_recovery,
    )
    from elasticdl_tpu.chaos.plan import named_plan

    config = ChaosJobConfig(
        plan=named_plan("preempt_one_worker", 2), workdir=str(tmp_path)
    )
    assert _check_master_recovery(config, "/nonexistent", 1) is None


# ---- master-downtime attribution (report + trace analyze) -------------------


def test_report_master_ha_section_measures_the_step_gap():
    from elasticdl_tpu.telemetry.report import master_ha_section

    events = [
        {"event": "step", "monotonic": 10.0, "worker_id": 0},
        {"event": "step", "monotonic": 11.0, "worker_id": 0},
        {"event": "master_restart", "generation": 0, "monotonic": 14.0},
        {
            "event": "journal_replay",
            "generation": 0,
            "monotonic": 14.1,
            "duration_secs": 0.1,
            "pending": 3,
            "active": 1,
            "epoch": 0,
        },
        {
            "event": "worker_rehome",
            "worker_id": 0,
            "monotonic": 15.0,
            "kept": 1,
            "requeued": 0,
        },
        {
            "event": "worker_rehome",
            "worker_id": 1,
            "monotonic": 15.2,
            "kept": 0,
            "requeued": 1,
        },
        {"event": "step", "monotonic": 16.0, "worker_id": 0},
    ]
    section = master_ha_section(events)
    (restart,) = section["restarts"]
    assert restart["downtime_secs"] == pytest.approx(5.0)
    assert restart["journal_replay_secs"] == pytest.approx(0.1)
    assert restart["workers_rehomed"] == [0, 1]
    assert restart["leases_kept"] == 1
    assert restart["leases_requeued"] == 1
    assert section["total_downtime_secs"] == pytest.approx(5.0)
    # no restarts -> no section: HA-less reports unchanged
    assert master_ha_section(events[:2]) is None


def test_trace_analyze_master_outage_phases_sum_exactly(tmp_path):
    """The tentpole's attribution contract: named master-outage phases
    sum EXACTLY to the measured step gap."""
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    spans = [
        {
            "span": "master_restart",
            "start": 13.0,
            "end": 14.0,
            "generation": 0,
            "role": "master",
        },
        {
            "span": "journal_replay",
            "start": 13.0,
            "end": 13.2,
            "generation": 0,
            "role": "master",
        },
        {
            "span": "worker_rehome",
            "start": 14.5,
            "end": 14.6,
            "generation": 0,
            "role": "master",
        },
    ]
    events = [
        {"event": "step", "monotonic": 10.0, "generation": 0,
         "worker_id": 0, "duration_secs": 0.1},
        {"event": "step", "monotonic": 16.0, "generation": 0,
         "worker_id": 0, "duration_secs": 0.1},
    ]
    with open(tmp_path / "spans.jsonl", "w", encoding="utf-8") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
    with open(tmp_path / "events.jsonl", "w", encoding="utf-8") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")
    analysis = analyze_telemetry_dir(str(tmp_path))
    (outage,) = analysis["master_outage"]
    assert outage["downtime_secs"] == pytest.approx(6.0)
    phases = outage["phases_secs"]
    assert sum(phases.values()) == pytest.approx(6.0)  # sum-exact
    assert phases["master_down"] == pytest.approx(3.0)  # 10 -> 13
    assert phases["journal_replay"] == pytest.approx(0.2)
    assert phases["master_restore"] == pytest.approx(0.8)
    assert phases["rehome_wait"] == pytest.approx(0.5)  # 14 -> 14.5
    assert phases["worker_rehome"] == pytest.approx(0.1)
    assert phases["resume_dispatch"] == pytest.approx(1.4)  # 14.6 -> 16
    assert outage["coverage"] == pytest.approx(1.0)


def test_trace_analyze_no_outage_without_restart_spans(tmp_path):
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    with open(tmp_path / "events.jsonl", "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                {"event": "step", "monotonic": 1.0, "generation": 0}
            )
            + "\n"
        )
    assert analyze_telemetry_dir(str(tmp_path))["master_outage"] == []


# ---- argv / golden byte-compat ----------------------------------------------


def test_ha_flags_default_none_and_never_reach_worker_argv():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def", "m.custom_model",
        "--training_data", "/data",
        "--minibatch_size", "32",
    ]
    args = parse_master_args(base)
    assert args.master_journal_dir is None
    assert args.rpc_retry_secs is None
    assert args.rehome_grace_secs is None
    plain = build_worker_arguments(args, 0, "localhost:1")
    # HA on: worker argv must be BYTE-IDENTICAL (env carries the config)
    ha_args = parse_master_args(
        base
        + [
            "--master_journal_dir", "/tmp/j",
            "--rpc_retry_secs", "30",
            "--rehome_grace_secs", "9",
        ]
    )
    assert build_worker_arguments(ha_args, 0, "localhost:1") == plain
    assert not any("journal" in a or "retry" in a or "rehome" in a
                   for a in plain)


def test_master_kill_plans_parse_and_round_trip():
    from elasticdl_tpu.chaos.plan import FaultPlan, named_plan

    for name in ("master_kill_mid_epoch", "master_kill_during_reform"):
        plan = named_plan(name, 2)
        again = FaultPlan.from_json(plan.to_json())
        assert [f.kind for f in again.faults] == [
            f.kind for f in plan.faults
        ]
        assert again.master_kill_faults()
    reform_kill = named_plan("master_kill_during_reform", 2)
    triggers = {f.trigger for f in reform_kill.master_kill_faults()}
    assert triggers == {"reform"}
    # MASTER_KILL is master-side but NOT a capacity fault
    assert not named_plan("master_kill_mid_epoch", 2).master_faults()


def test_capacity_driver_skips_faults_fired_in_a_previous_life(tmp_path):
    """Capacity faults must fire at most once per RUN, not per master
    life: the journal-restored model version is already past an
    executed fault's at_step, so a fresh driver built for the relaunch
    would immediately re-fire it."""
    from elasticdl_tpu.chaos.harness import _CapacityDriver
    from elasticdl_tpu.chaos.plan import Fault, FaultKind, FaultPlan

    plan = FaultPlan(
        name="shrink-then-kill",
        faults=[
            Fault(
                kind=FaultKind.REDUCE_CAPACITY,
                fault_id="shrink-1",
                at_step=4,
            ),
            Fault(
                kind=FaultKind.MASTER_KILL,
                fault_id="kill-1",
                at_step=8,
            ),
        ],
    )
    events_path = os.path.join(str(tmp_path), "events.jsonl")
    fired: set[str] = set()
    life0 = _CapacityDriver(object(), plan, events_path, fired=fired)
    assert [f.fault_id for f in life0._pending] == ["shrink-1"]
    # life 0 executes the shrink, then the master is killed
    fired.add("shrink-1")
    life1 = _CapacityDriver(object(), plan, events_path, fired=fired)
    assert life1._pending == []  # relaunch must not shrink again


def test_fault_rejects_unknown_trigger():
    from elasticdl_tpu.chaos.plan import Fault, FaultKind

    with pytest.raises(ValueError):
        Fault(
            kind=FaultKind.MASTER_KILL, fault_id="x", trigger="eventually"
        )


# ---- gloo fast-fail linger in HA mode ---------------------------------------


def test_lockstep_lingers_on_crash_when_master_ha_is_on(monkeypatch):
    """Satellite: a crashed lockstep process must linger during a master
    outage (master HA on) even WITHOUT a replica server, so the
    relaunched master can fence it instead of finding a ghost."""
    from elasticdl_tpu.worker.lockstep import LockstepWorker

    worker = LockstepWorker.__new__(LockstepWorker)
    worker._replica_server = None
    worker._process_id = 0

    monkeypatch.delenv(MASTER_ADDR_FILE_ENV, raising=False)
    assert not worker._ha_mode()
    monkeypatch.setenv(MASTER_ADDR_FILE_ENV, "/tmp/j/master_addr")
    assert worker._ha_mode()

    # the linger path must tolerate a missing replica server (pre-HA it
    # unconditionally dereferenced it) and honor the cap env
    slept = []
    monkeypatch.setattr(
        "elasticdl_tpu.worker.lockstep.time.sleep",
        lambda secs: slept.append(secs),
    )
    monkeypatch.setenv(LockstepWorker._LINGER_ENV, "7")
    worker._linger_for_harvest()
    assert slept == [7.0]
    monkeypatch.setenv(LockstepWorker._LINGER_ENV, "0")
    worker._linger_for_harvest()  # disabled: returns without sleeping
    assert slept == [7.0]


def test_worker_rehomes_on_boot_id_change(monkeypatch):
    """The lockstep worker's re-home trigger: a CHANGED boot id on a
    heartbeat response fires exactly one rehome RPC presenting the
    in-flight lease."""
    from elasticdl_tpu.worker.lockstep import LockstepWorker

    worker = LockstepWorker.__new__(LockstepWorker)
    worker._worker_id = 3
    worker._cluster_version = 0
    worker._current_task_id = 17
    worker._master_boot_id = None

    rehomes = []

    class FakeMaster:
        def rehome_worker(self, request):
            rehomes.append(request)
            return msg.RehomeResponse(
                accepted=True,
                cluster_version=0,
                boot_id="b2",
                accepted_leases=list(request.lease_ids),
            )

    worker._master = FakeMaster()
    worker._note_master_boot("")  # HA off: no-op
    worker._note_master_boot("b1")  # first sighting: remember, no RPC
    worker._note_master_boot("b1")  # unchanged: no RPC
    assert rehomes == []
    worker._note_master_boot("b2")  # the restart
    assert len(rehomes) == 1
    assert rehomes[0].worker_id == 3
    assert rehomes[0].lease_ids == [17]
    worker._note_master_boot("b2")  # settled: no second RPC
    assert len(rehomes) == 1


def test_task_stream_rehome_presents_leases_with_tracing_off():
    """The task-stream worker's lease ledger is independent of tracing:
    with no tracer installed (HA on, telemetry off) a re-home must still
    present every unreported lease — the ledger is NOT the tracing
    side-structure (which is empty when tracing is off)."""
    from elasticdl_tpu.worker.worker import Worker

    class NoTracing:
        @staticmethod
        def get_tracer():
            return None

    class NoCompileDeltas:
        @staticmethod
        def attach(counters):
            return 0

        @staticmethod
        def commit(mark):
            pass

    leased = [
        msg.TaskResponse(task_id=21, shard_name="s", start=0, end=8),
        msg.TaskResponse(task_id=22, shard_name="s", start=8, end=16),
        msg.TaskResponse(task_id=99),  # WAIT poll: not a lease
    ]
    rehomes = []

    class FakeMaster:
        def get_task(self, request):
            return leased.pop(0)

        def report_task_result(self, request):
            return None

        def rehome_worker(self, request):
            rehomes.append(request)
            return msg.RehomeResponse(
                accepted=True,
                cluster_version=0,
                boot_id="b2",
                accepted_leases=list(request.lease_ids),
            )

    worker = Worker.__new__(Worker)
    worker._worker_id = 5
    worker._master = FakeMaster()
    worker._tracing = NoTracing()
    worker._task_traces = {}
    worker._inflight_leases = set()
    worker._compile_deltas = NoCompileDeltas()
    worker._master_boot_id = "b1"
    worker._master_cluster_version = 0

    worker.get_task()
    worker.get_task()
    worker.get_task()  # the WAIT poll
    assert worker._task_traces == {}  # tracing off: trace memo unused
    assert worker._inflight_leases == {21, 22}
    worker.report_task_result(21)
    assert worker._inflight_leases == {22}

    worker._note_master_boot("b2")
    assert len(rehomes) == 1
    assert rehomes[0].lease_ids == [22]


def test_heartbeat_presents_pre_outage_generation_to_rehome():
    """The rehome fence must see the generation the worker held ACROSS
    the outage: if the beat adopted the restarted master's
    cluster_version before re-homing, the servicer would compare the
    new master's generation to itself and the fence would be vacuous."""
    import time as _time

    from elasticdl_tpu.worker.worker import Worker

    class NoTracing:
        @staticmethod
        def get_tracer():
            return None

    rehomes = []

    worker = Worker.__new__(Worker)
    worker._worker_id = 7
    worker._tracing = NoTracing()
    worker._inflight_leases = {31}
    worker._trainer = None
    worker._stopped = False
    worker._master_boot_id = "b1"
    worker._master_cluster_version = 3  # the pre-outage world

    class FakeRestartedMaster:
        def heartbeat(self, request):
            worker._stopped = True  # one beat is enough
            return msg.HeartbeatResponse(cluster_version=7, boot_id="b2")

        def rehome_worker(self, request):
            rehomes.append(request)
            return msg.RehomeResponse(
                accepted=True,
                cluster_version=7,
                boot_id="b2",
                accepted_leases=list(request.lease_ids),
            )

    worker._master = FakeRestartedMaster()
    worker._start_heartbeats(interval_secs=0.01)
    deadline = _time.monotonic() + 10.0
    while not rehomes and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert rehomes, "heartbeat never triggered the re-home"
    assert rehomes[0].cluster_version == 3  # NOT the new master's 7
    # accepted: the beat then adopts the restarted master's generation
    deadline = _time.monotonic() + 10.0
    while worker._master_cluster_version != 7 and (
        _time.monotonic() < deadline
    ):
        _time.sleep(0.01)
    assert worker._master_cluster_version == 7


def test_rehome_failure_keeps_pre_outage_generation():
    """While a re-home is pending (RPC failed), the beat must NOT adopt
    the new master's generation — the retry on the next beat has to
    present the pre-outage one."""
    from elasticdl_tpu.worker.worker import Worker

    worker = Worker.__new__(Worker)
    worker._worker_id = 7
    worker._inflight_leases = set()
    worker._master_boot_id = "b1"
    worker._master_cluster_version = 3

    class DownMaster:
        def rehome_worker(self, request):
            raise ConnectionError("master gone again")

    worker._master = DownMaster()
    assert worker._note_master_boot("b2") is False
    assert worker._master_boot_id == "b1"  # retried on the next beat
    assert worker._master_cluster_version == 3


def test_rehome_survives_concurrent_lease_mutation():
    """The heartbeat thread snapshots _inflight_leases while the task
    thread mutates it: a mid-iteration RuntimeError must leave the boot
    id unchanged so the NEXT beat retries the handshake — not advance
    it and silently skip re-homing forever."""
    from elasticdl_tpu.worker.worker import Worker

    class RacingSet(set):
        def __iter__(self):
            raise RuntimeError("Set changed size during iteration")

    worker = Worker.__new__(Worker)
    worker._worker_id = 7
    worker._inflight_leases = RacingSet()
    worker._master_boot_id = "b1"
    worker._master_cluster_version = 3
    worker._master = object()  # must not be reached past the snapshot

    assert worker._note_master_boot("b2") is False
    assert worker._master_boot_id == "b1"  # NOT advanced: will retry

    rehomes = []

    class FakeMaster:
        def rehome_worker(self, request):
            rehomes.append(request)
            return msg.RehomeResponse(
                accepted=True, cluster_version=3, boot_id="b2"
            )

    worker._inflight_leases = set()
    worker._master = FakeMaster()
    assert worker._note_master_boot("b2") is True  # the retry lands
    assert len(rehomes) == 1
    assert worker._master_boot_id == "b2"


def test_rehome_drops_leases_the_master_did_not_reaccept():
    """accepted_leases consumption: a presented lease absent from the
    response (e.g. leased in the journal's unflushed batch tail) leaves
    the ledger — its report would be dropped server-side and the task
    re-trains from the queue — while leases added DURING the handshake
    survive untouched."""
    from elasticdl_tpu.worker.worker import Worker

    worker = Worker.__new__(Worker)
    worker._worker_id = 7
    worker._inflight_leases = {21, 22}
    worker._master_boot_id = "b1"
    worker._master_cluster_version = 0

    class FakeMaster:
        def rehome_worker(self, request):
            # the task thread races a NEW lease in mid-handshake
            worker._inflight_leases.add(33)
            return msg.RehomeResponse(
                accepted=True,
                cluster_version=0,
                boot_id="b2",
                accepted_leases=[21],  # 22 was in the lost batch tail
            )

    worker._master = FakeMaster()
    assert worker._note_master_boot("b2") is True
    assert worker._inflight_leases == {21, 33}  # 22 dropped, 33 kept


def test_lockstep_rehome_failure_retries_on_next_beat():
    """The lockstep copy of the handshake: a failed re-home RPC leaves
    the boot id unchanged, so the next heartbeat fires it again."""
    from elasticdl_tpu.worker.lockstep import LockstepWorker

    worker = LockstepWorker.__new__(LockstepWorker)
    worker._worker_id = 3
    worker._cluster_version = 0
    worker._current_task_id = 17
    worker._master_boot_id = "b1"

    attempts = []

    class FlappingMaster:
        def rehome_worker(self, request):
            attempts.append(request)
            if len(attempts) == 1:
                raise ConnectionError("master gone again")
            return msg.RehomeResponse(
                accepted=True,
                cluster_version=0,
                boot_id="b2",
                accepted_leases=list(request.lease_ids),
            )

    worker._master = FlappingMaster()
    worker._note_master_boot("b2")
    assert worker._master_boot_id == "b1"  # failed: not advanced
    worker._note_master_boot("b2")  # next beat retries
    assert len(attempts) == 2
    assert worker._master_boot_id == "b2"


# ---- deferred callbacks, rehome settle, stage release across restart --------


def _drain(d: TaskDispatcher, worker_id=0):
    while True:
        tid, task = d.get(worker_id=worker_id)
        if task is None:
            return
        d.report(tid, success=True)


def _save_model_journal(d, tmp_path):
    journal = _journal_for(d, tmp_path, snapshot_every=10_000)
    d.add_deferred_callback_create_save_model_task("/tmp/export")
    _drain(d)
    assert d.invoke_deferred_callback()
    journal.flush()
    return journal


def test_save_model_task_is_journaled_like_any_other(tmp_path):
    """``_create_save_model_task`` must notify ``on_tasks_created`` with
    a uid-carrying task: a master killed between the SAVE_MODEL creation
    and the next snapshot would otherwise replay a dispatcher that
    silently never exports the final model."""
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    _save_model_journal(d, tmp_path)
    restored = load_state(str(tmp_path))
    save_tasks = [
        t
        for t in restored["dispatcher"]["pending"]
        if int(t["type"]) == int(TaskType.SAVE_MODEL)
    ]
    assert len(save_tasks) == 1
    assert int(save_tasks[0]["uid"]) > 0
    assert save_tasks[0]["extended"] == {"saved_model_path": "/tmp/export"}
    assert restored["callbacks_invoked"] == 1


def test_callback_consumption_journals_after_execution(tmp_path):
    """At-LEAST-once deferred work: the ``callback`` record lands AFTER
    the records the callback produced.  A crash in between replays the
    callback un-consumed WITH the task it already created — the re-run
    is tolerated (report dedup, path overwrite); the reverse order would
    drop the final export silently."""
    from elasticdl_tpu.telemetry.events import read_jsonl

    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    _save_model_journal(d, tmp_path)
    records = read_jsonl(journal_path(str(tmp_path)))
    created_at = [
        i
        for i, r in enumerate(records)
        if r["kind"] == "tasks_created"
        and any(
            int(t["type"]) == int(TaskType.SAVE_MODEL)
            for t in r.get("tasks", [])
        )
    ]
    callback_at = [
        i for i, r in enumerate(records) if r["kind"] == "callback"
    ]
    assert created_at and callback_at
    assert created_at[0] < callback_at[0]
    # the crash window: the callback record lost in the tail — replay
    # keeps the callback pending AND the task it created
    truncated = replay(records[: callback_at[0]])
    assert truncated["callbacks_invoked"] == 0
    assert any(
        int(t["type"]) == int(TaskType.SAVE_MODEL)
        for t in truncated["dispatcher"]["pending"]
    )


def _rehome_deadline_master(live, pending={1, 2}):
    from types import SimpleNamespace

    dead_calls: list = []
    telemetry_calls: list = []
    fake = SimpleNamespace(
        _rehome_deadline=time.monotonic() - 1.0,
        _rehome_lock=threading.Lock(),
        _rehome_pending=set(pending),
        servicer=SimpleNamespace(
            live_workers=lambda: list(live), cluster_version=3
        ),
        telemetry=SimpleNamespace(
            worker_dead=lambda missing, cv: telemetry_calls.append(
                (missing, cv)
            )
        ),
        _handle_dead_workers=dead_calls.append,
    )
    return fake, dead_calls, telemetry_calls


def test_rehome_deadline_settles_alive_workers():
    """A pending worker that heartbeated THIS master life is alive even
    if it never presented the handshake (spawned just before the outage,
    it may never have seen the previous boot id): settle it — only the
    truly silent workers lose their leases."""
    from elasticdl_tpu.master.master import Master

    fake, dead_calls, telemetry_calls = _rehome_deadline_master(live=[1])
    Master._check_rehome_deadline(fake)
    assert dead_calls == [[2]]
    assert telemetry_calls == [([2], 3)]
    assert fake._rehome_deadline is None
    assert fake._rehome_pending == set()


def test_rehome_deadline_all_alive_declares_nobody_dead():
    from elasticdl_tpu.master.master import Master

    fake, dead_calls, telemetry_calls = _rehome_deadline_master(live=[1, 2])
    Master._check_rehome_deadline(fake)
    assert dead_calls == []
    assert telemetry_calls == []
    assert fake._rehome_deadline is None


def test_stage_release_clears_the_lost_stage_marker(tmp_path):
    """A stage every process already fetched must NOT replay as a lost
    replica set — the restart would report a false disk-fallback."""
    d = TaskDispatcher({"s": (0, 128)}, records_per_task=64, shuffle_seed=3)
    journal = _journal_for(d, tmp_path)
    journal.record_stage(generation=2, version=7, complete=True)
    journal.flush()
    staged = load_state(str(tmp_path))
    assert staged["stage"] == {
        "generation": 2,
        "version": 7,
        "complete": True,
    }
    journal.record_stage_released(2)
    journal.flush()
    assert load_state(str(tmp_path))["stage"] is None


def test_restore_stage_release_fires_sink_once_when_fully_served():
    """The servicer side of the release: the journal sink fires exactly
    once, when the LAST process of the restoring generation fetches its
    copy (same-process refetches don't count toward release)."""
    from elasticdl_tpu.master.servicer import MasterServicer

    d, _ = _leased_dispatcher()
    servicer = MasterServicer(32, d)
    released: list = []
    servicer.set_stage_released_sink(released.append)
    servicer.set_restore_stage(
        {
            "generation": 0,
            "version": 5,
            "checksum": "c",
            "payload": b"x",
            "world_size": 2,
        }
    )
    req = msg.GetRestoreStateRequest
    assert servicer.get_restore_state(
        req(cluster_version=0, process_id=0)
    ).has
    assert servicer.get_restore_state(
        req(cluster_version=0, process_id=0)
    ).has
    assert released == []
    assert servicer.get_restore_state(
        req(cluster_version=0, process_id=1)
    ).has
    assert released == [0]
    # the payload left master RAM: a late asker gets the disk fallback
    assert not servicer.get_restore_state(
        req(cluster_version=0, process_id=2)
    ).has


# ---- slow end-to-end: the chaos plans through the real harness --------------


@pytest.mark.slow
def test_master_kill_mid_epoch_end_to_end(tmp_path):
    """Kill the master mid-epoch with SIGKILL semantics; the relaunched
    master must replay the journal, the workers must re-home, and every
    invariant (including master_recovery) must PASS."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("master_kill_mid_epoch", 2),
            workdir=str(tmp_path / "chaos"),
            num_records=256,
            num_epochs=2,
            num_workers=2,
            master_ha=True,
            run_timeout_secs=300.0,
        )
    )
    failed = [i for i in report["invariants"] if i["status"] != "PASS"]
    assert not failed, failed
    assert report["invariants_ok"], report
    assert report["master_lives"] == 2
    assert report["master_ha"]["restarts"]


@pytest.mark.slow
def test_master_kill_during_reform_end_to_end(tmp_path):
    """The delayed-master-restart regression (gloo fast-fail linger):
    the collective partner dies, the master dies inside the resulting
    re-formation, and the survivor must still be around for the
    relaunched master to fence — the job completes."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("master_kill_during_reform", 2),
            workdir=str(tmp_path / "chaos"),
            num_records=256,
            num_epochs=2,
            num_workers=2,
            master_ha=True,
            run_timeout_secs=300.0,
        )
    )
    failed = [i for i in report["invariants"] if i["status"] != "PASS"]
    assert not failed, failed
    assert report["invariants_ok"], report
    # the preemption + the master kill both fired
    kinds = {e.get("kind") for e in report["faults_injected"]}
    assert {"preempt_worker", "master_kill"} <= kinds
