"""Data layer tests: EDLIO codec (python + native interchange), readers,
dataset pipeline, generators (SURVEY §4 tier 1)."""

import os

import numpy as np
import pytest

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.csv_reader import CSVDataReader
from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.data.parallel_transform import ParallelTransform
from elasticdl_tpu.data.reader import decode_example, encode_example
from elasticdl_tpu.data.recordio import _pyimpl
from elasticdl_tpu.data.recordio_reader import RecordIODataReader
from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.master.task_dispatcher import Task
from elasticdl_tpu.utils.constants import TaskType


def _write_py(path, payloads):
    with _pyimpl.Writer(path) as w:
        for p in payloads:
            w.write(p)


PAYLOADS = [b"alpha", b"bravo" * 100, b"", b"delta", bytes(range(256))]


class TestPyCodec:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "f.edlio")
        _write_py(path, PAYLOADS)
        assert _pyimpl.num_records(path) == 5
        with _pyimpl.Scanner(path) as s:
            assert list(s) == PAYLOADS

    def test_ranged_scan(self, tmp_path):
        path = str(tmp_path / "f.edlio")
        _write_py(path, PAYLOADS)
        with _pyimpl.Scanner(path, 1, 2) as s:
            assert list(s) == PAYLOADS[1:3]
        with _pyimpl.Scanner(path, 4, -1) as s:
            assert list(s) == PAYLOADS[4:]
        with _pyimpl.Scanner(path, 5) as s:
            assert list(s) == []

    def test_out_of_range_start(self, tmp_path):
        path = str(tmp_path / "f.edlio")
        _write_py(path, PAYLOADS)
        with pytest.raises(IndexError):
            _pyimpl.Scanner(path, 6)

    def test_corrupt_detection(self, tmp_path):
        path = str(tmp_path / "f.edlio")
        _write_py(path, PAYLOADS)
        data = bytearray(open(path, "rb").read())
        data[10] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(data))
        with pytest.raises(_pyimpl.CorruptFileError):
            list(_pyimpl.Scanner(path))

    def test_truncated_file(self, tmp_path):
        path = str(tmp_path / "f.edlio")
        _write_py(path, PAYLOADS)
        data = open(path, "rb").read()
        open(path, "wb").write(data[:-10])
        with pytest.raises(_pyimpl.CorruptFileError):
            _pyimpl.num_records(path)


class TestNativeCodec:
    @pytest.fixture(autouse=True)
    def _build(self):
        from elasticdl_tpu.data.recordio.build import build

        if build(quiet=True) is None:
            pytest.skip("g++ unavailable")
        assert recordio.native_available()

    def test_native_roundtrip(self, tmp_path):
        path = str(tmp_path / "n.edlio")
        with recordio.Writer(path) as w:
            for p in PAYLOADS:
                w.write(p)
        assert recordio.num_records(path) == 5
        with recordio.Scanner(path) as s:
            assert list(s) == PAYLOADS

    def test_interchange_native_writes_python_reads(self, tmp_path):
        path = str(tmp_path / "n.edlio")
        with recordio.Writer(path) as w:  # native
            for p in PAYLOADS:
                w.write(p)
        with _pyimpl.Scanner(path, 1, 3) as s:
            assert list(s) == PAYLOADS[1:4]

    def test_interchange_python_writes_native_reads(self, tmp_path):
        path = str(tmp_path / "p.edlio")
        _write_py(path, PAYLOADS)
        with recordio.Scanner(path, 2, -1) as s:
            assert list(s) == PAYLOADS[2:]

    def test_native_large_batch(self, tmp_path):
        path = str(tmp_path / "big.edlio")
        payloads = [os.urandom(1000) for _ in range(5000)]
        with recordio.Writer(path) as w:
            for p in payloads:
                w.write(p)
        with recordio.Scanner(path, 100, 4900) as s:
            got = list(s)
        assert got == payloads[100:]

    def test_native_corrupt_detection(self, tmp_path):
        path = str(tmp_path / "c.edlio")
        with recordio.Writer(path) as w:
            for p in PAYLOADS:
                w.write(p)
        data = bytearray(open(path, "rb").read())
        data[9] ^= 0x01
        open(path, "wb").write(bytes(data))
        with pytest.raises(recordio.CorruptFileError):
            list(recordio.Scanner(path))


class TestExampleCodec:
    def test_roundtrip(self):
        ex = {
            "image": np.random.randint(0, 255, (28, 28), dtype=np.uint8),
            "label": np.int64(7),
        }
        out = decode_example(encode_example(ex))
        np.testing.assert_array_equal(out["image"], ex["image"])
        assert out["label"] == 7


class TestReaders:
    def test_recordio_reader_end_to_end(self, tmp_path):
        data_dir = synthetic.gen_mnist(
            str(tmp_path / "mnist"), num_records=64, num_shards=3
        )
        reader = RecordIODataReader(data_dir=data_dir)
        shards = reader.create_shards()
        assert len(shards) == 3
        assert sum(n for _, n in shards.values()) == 64
        name, (start, count) = next(iter(shards.items()))
        task = Task(name, 0, min(10, count), TaskType.TRAINING)
        records = list(reader.read_records(task))
        assert len(records) == task.num_records
        ex = decode_example(records[0])
        assert ex["image"].shape == (28, 28)

    def test_csv_reader(self, tmp_path):
        path = str(tmp_path / "d.csv")
        with open(path, "w") as f:
            f.write("a,b,label\n")
            for i in range(10):
                f.write(f"{i},{i*2},{i%2}\n")
        reader = CSVDataReader(data_path=path)
        shards = reader.create_shards()
        assert shards == {path: (0, 10)}
        task = Task(path, 2, 5, TaskType.TRAINING)
        rows = list(reader.read_records(task))
        assert rows == [["2", "4", "0"], ["3", "6", "1"], ["4", "8", "0"]]
        assert reader.metadata.column_names == ["a", "b", "label"]

    def test_factory_dispatch(self, tmp_path):
        csv = tmp_path / "x.csv"
        csv.write_text("a\n1\n")
        assert isinstance(
            create_data_reader(str(csv)), CSVDataReader
        )
        assert isinstance(
            create_data_reader(str(tmp_path)), RecordIODataReader
        )

    def test_factory_custom_reader(self):
        class MyReader:
            def __init__(self, **kw):
                self.kw = kw

        r = create_data_reader("/x", custom_reader=MyReader, foo=1)
        assert isinstance(r, MyReader) and r.kw["foo"] == 1


class TestDataset:
    def test_map_batch(self):
        ds = (
            Dataset.from_records(list(range(10)))
            .map(lambda x: {"v": np.float32(x)})
            .batch(4)
        )
        batches = list(ds)
        assert len(batches) == 3
        np.testing.assert_array_equal(batches[0]["v"], [0, 1, 2, 3])
        assert batches[2]["v"].shape == (2,)

    def test_batch_drop_remainder(self):
        ds = Dataset.from_records(list(range(10))).batch(4, drop_remainder=True)
        assert len(list(ds)) == 2

    def test_tuple_elements(self):
        ds = Dataset.from_records(
            [(np.ones((2,)), np.int64(i)) for i in range(4)]
        ).batch(2)
        x, y = next(iter(ds))
        assert x.shape == (2, 2) and y.shape == (2,)

    def test_shuffle_deterministic_and_complete(self):
        base = list(range(100))
        ds = Dataset.from_records(base).shuffle(16, seed=3)
        out1, out2 = list(ds), list(ds)
        assert out1 == out2
        assert sorted(out1) == base
        assert out1 != base

    def test_prefetch_preserves_order_and_errors(self):
        ds = Dataset.from_records(list(range(50))).prefetch(4)
        assert list(ds) == list(range(50))

        def boom():
            yield 1
            raise RuntimeError("producer failed")

        with pytest.raises(RuntimeError, match="producer failed"):
            list(Dataset.from_generator(boom).prefetch(2))

    def test_prefetch_releases_producer_on_abandoned_stream(self):
        """A consumer breaking out mid-stream (eval loop on error, a
        take(), a GC'd generator) must release the producer thread —
        a blocking q.put would leak one thread + its buffered batches
        per abandoned stream for the life of the process."""
        import threading
        import time

        started = threading.active_count()
        produced = []

        def source():
            for i in range(10_000):
                produced.append(i)
                yield i

        for _ in range(5):
            it = iter(Dataset.from_generator(source).prefetch(2))
            assert next(it) == 0
            it.close()  # abandon mid-stream (what a `break` does at GC)
        deadline = time.monotonic() + 5
        while (
            threading.active_count() > started
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert threading.active_count() <= started, (
            f"{threading.active_count() - started} prefetch producer "
            "thread(s) leaked"
        )
        assert len(produced) < 100  # producer stopped early, too

    def test_repeat_take(self):
        ds = Dataset.from_records([1, 2, 3]).repeat().take(7)
        assert list(ds) == [1, 2, 3, 1, 2, 3, 1]

    def test_reiterable(self):
        ds = Dataset.from_records([1, 2, 3]).map(lambda x: x * 2)
        assert list(ds) == list(ds) == [2, 4, 6]


class TestParallelTransform:
    def test_order_preserved(self):
        pt = ParallelTransform(lambda x: x * x, num_workers=4)
        assert list(pt.apply(range(100))) == [x * x for x in range(100)]


class TestGenerators:
    @pytest.mark.parametrize("name", sorted(synthetic.GENERATORS))
    def test_all_generators_produce_readable_shards(self, tmp_path, name):
        out = synthetic.GENERATORS[name](
            str(tmp_path / name), num_records=32, num_shards=2
        )
        reader = RecordIODataReader(data_dir=out)
        shards = reader.create_shards()
        assert sum(n for _, n in shards.values()) == 32
        path, (start, count) = next(iter(shards.items()))
        rec = next(
            iter(
                reader.read_records(
                    Task(path, 0, 1, TaskType.TRAINING)
                )
            )
        )
        ex = decode_example(rec)
        # sequence records pack input+target into one tokens array; every
        # other schema carries separate feature + label keys
        assert isinstance(ex, dict) and len(ex) >= (
            1 if name == "sequence" else 2
        )

    def test_frappe_labels_learnable(self, tmp_path):
        """Labels must correlate with features (not pure noise)."""
        out = synthetic.gen_frappe(
            str(tmp_path / "frappe"), num_records=512, num_shards=1
        )
        reader = RecordIODataReader(data_dir=out)
        path = next(iter(reader.create_shards()))
        labels = [
            int(decode_example(r)["label"])
            for r in reader.read_records(
                Task(path, 0, 512, TaskType.TRAINING)
            )
        ]
        # both classes present, neither vanishingly rare
        pos = sum(labels)
        assert 64 < pos < 448


class TestBatchDecode:
    """Fused decode+batch fast path (native edl_decode_batch) vs the
    per-record decoder: identical outputs, graceful fallbacks."""

    def _records(self, n=64):
        rng = np.random.RandomState(3)
        return [
            encode_example(
                {
                    "image": rng.randint(0, 255, (8, 8)).astype(np.uint8),
                    "dense": rng.randn(5).astype(np.float32),
                    "label": np.int64(i % 7),  # scalar feature
                }
            )
            for i in range(n)
        ]

    def test_matches_per_record_decode(self):
        from elasticdl_tpu.data.reader import decode_example_batch

        recs = self._records()
        out = decode_example_batch(recs)
        ref = [decode_example(r) for r in recs]
        assert set(out) == {"image", "dense", "label"}
        assert out["image"].shape == (64, 8, 8)
        assert out["label"].shape == (64,)
        for key in out:
            np.testing.assert_array_equal(
                out[key], np.stack([d[key] for d in ref])
            )

    def test_native_path_taken(self):
        """On this build the native codec exists, and the C call must
        succeed for uniform dense records (no silent fallback)."""
        from elasticdl_tpu.data import reader

        if not recordio.native_available():
            pytest.skip("native codec not built")
        recs = self._records(8)
        first = decode_example(recs[0])
        assert reader._native_decode_batch(recs, first) is not None

    def test_python_fallback_matches(self, monkeypatch):
        from elasticdl_tpu.data import reader

        recs = self._records(16)
        native = reader.decode_example_batch(recs)
        monkeypatch.setattr(
            reader, "_native_decode_batch", lambda *a: None
        )
        fallback = reader.decode_example_batch(recs)
        for key in native:
            np.testing.assert_array_equal(native[key], fallback[key])

    def test_bfloat16_feature(self):
        import ml_dtypes

        from elasticdl_tpu.data.reader import decode_example_batch

        bf16 = ml_dtypes.bfloat16
        recs = [
            encode_example({"x": np.arange(4, dtype=np.float32).astype(bf16)})
            for _ in range(4)
        ]
        out = decode_example_batch(recs)
        assert out["x"].dtype == bf16
        assert out["x"].shape == (4, 4)

    def test_single_and_empty(self):
        from elasticdl_tpu.data.reader import decode_example_batch

        assert decode_example_batch([]) == {}
        one = decode_example_batch(self._records(1))
        assert one["image"].shape == (1, 8, 8)

    def test_batch_list(self):
        ds = Dataset.from_records(list(range(7))).batch_list(3)
        assert list(ds) == [[0, 1, 2], [3, 4, 5], [6]]


class TestBatchedModelPipeline:
    def test_batch_parse_equals_dataset_fn(self, tmp_path):
        """The vectorized fast path must produce byte-identical batches
        to the per-record dataset_fn path (same shuffle stream)."""
        from elasticdl_tpu.data.dataset import batched_model_pipeline
        from elasticdl_tpu.trainer.state import Modes
        from elasticdl_tpu.utils.model_utils import get_model_spec

        out = synthetic.gen_mnist(
            str(tmp_path / "m"), num_records=70, num_shards=1, seed=5
        )
        reader = RecordIODataReader(data_dir=out)
        path = next(iter(reader.create_shards()))
        records = list(
            reader.read_records(Task(path, 0, 70, TaskType.TRAINING))
        )
        spec = get_model_spec(
            "", "mnist_functional_api.mnist_functional_api.custom_model"
        )
        assert spec.batch_parse is not None

        fast = list(
            batched_model_pipeline(
                Dataset.from_records(records),
                spec,
                Modes.TRAINING,
                reader.metadata,
                batch_size=32,
                shuffle_records=True,
            )
        )
        spec.batch_parse = None  # force the classic per-record path
        classic = list(
            batched_model_pipeline(
                Dataset.from_records(records),
                spec,
                Modes.TRAINING,
                reader.metadata,
                batch_size=32,
            )
        )
        assert len(fast) == len(classic) == 3
        # the fast path ships the wire form (uint8); composing the
        # model's device_parse (the in-step half) must reproduce the
        # dataset_fn batches exactly
        assert spec.device_parse is not None
        for (ff, fl), (cf, cl) in zip(fast, classic):
            assert ff["image"].dtype == np.uint8
            np.testing.assert_array_equal(
                np.asarray(spec.device_parse(ff)["image"]), cf["image"]
            )
            np.testing.assert_array_equal(fl, cl)

    def test_prediction_mode_features_only(self, tmp_path):
        from elasticdl_tpu.data.dataset import batched_model_pipeline
        from elasticdl_tpu.trainer.state import Modes
        from elasticdl_tpu.utils.model_utils import get_model_spec

        out = synthetic.gen_mnist(
            str(tmp_path / "p"), num_records=8, num_shards=1, seed=6
        )
        reader = RecordIODataReader(data_dir=out)
        path = next(iter(reader.create_shards()))
        records = list(
            reader.read_records(Task(path, 0, 8, TaskType.PREDICTION))
        )
        spec = get_model_spec(
            "", "mnist_functional_api.mnist_functional_api.custom_model"
        )
        batches = list(
            batched_model_pipeline(
                Dataset.from_records(records),
                spec,
                Modes.PREDICTION,
                reader.metadata,
                batch_size=8,
            )
        )
        assert len(batches) == 1
        assert set(batches[0]) == {"image"}
        # wire form: uint8 on the host side, f32 after the in-step
        # device_parse (applied by build_predict_step)
        assert batches[0]["image"].dtype == np.uint8
        assert (
            np.asarray(spec.device_parse(batches[0])["image"]).dtype
            == np.float32
        )

    def test_renamed_dataset_fn_disables_fast_path(self):
        """--dataset_fn selects a different parse; batch_parse must not
        silently bypass it (it pairs with the DEFAULT dataset_fn only)."""
        from elasticdl_tpu.utils.model_utils import get_model_spec

        spec = get_model_spec(
            "",
            "mnist_functional_api.mnist_functional_api.custom_model",
            dataset_fn="batch_parse",  # any non-default name
        )
        assert spec.batch_parse is None
        assert spec.dataset_fn is not None

    def test_corrupt_payload_fuzz_never_crashes(self):
        """Bit-flipped / truncated / garbage payloads must either decode
        via the fallback or raise a clean Python error — the native
        parser returns a negative code rather than reading out of
        bounds (incl. the u32-overflow case hdr_len ~ 0xFFFFFFFC)."""
        from elasticdl_tpu.data import reader

        rng = np.random.RandomState(11)
        good = TestBatchDecode()._records(8)
        first = decode_example(good[0])

        def mutate(payload, kind):
            b = bytearray(payload)
            if kind == 0 and len(b) > 8:  # bit flip
                b[rng.randint(4, len(b))] ^= 1 << rng.randint(8)
            elif kind == 1:  # truncate
                del b[rng.randint(1, len(b)):]
            elif kind == 2:  # garbage tail
                b.extend(rng.bytes(17))
            elif kind == 3:  # u32-overflow header length
                b[8:12] = (0xFFFFFFFC).to_bytes(4, "little")
            else:  # pure garbage
                b = bytearray(rng.bytes(max(9, len(b) // 2)))
            return bytes(b)

        for trial in range(200):
            recs = list(good)
            recs[rng.randint(1, len(recs))] = mutate(
                good[rng.randint(0, len(good))], trial % 5
            )
            try:
                out = reader._native_decode_batch(recs, dict(first))
            except Exception:
                continue  # clean Python-level error is acceptable
            if out is not None:
                # accepted: the mutation must not have clobbered shapes
                assert out["image"].shape == (8, 8, 8)


def test_odps_conversion_utils_roundtrip(tmp_path):
    """ODPS rows (mixed int/float/str, batched and single, with Nones)
    -> EDLIO shards readable by the standard reader
    (reference odps_recordio_conversion_utils.py:80-136)."""
    from elasticdl_tpu.data.odps_recordio_conversion_utils import (
        write_recordio_shards_from_iterator,
    )
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.data.reader import decode_example

    rows = [
        [1, 2.5, "alpha"],
        [2, None, "beta"],
        [None, 0.5, "gamma"],
        [4, 1.5, "delta"],
        [5, 2.0, "eps"],
    ]
    # iterator yields one batch of 3 then single rows (both shapes the
    # ODPS tunnel reader produces)
    it = iter([rows[:3], rows[3], rows[4]])
    out = tmp_path / "conv"
    n = write_recordio_shards_from_iterator(
        it, ["a", "b", "c"], str(out), records_per_shard=2
    )
    assert n == 5
    import os

    shards = sorted(os.listdir(out))
    assert len(shards) == 3  # 2+2+1
    reader = RecordIODataReader(data_dir=str(out))
    got = []
    for name, (start, count) in sorted(reader.create_shards().items()):
        task = type(
            "T", (), {"shard_name": name, "start": start, "end": start + count}
        )
        got.extend(decode_example(r) for r in reader.read_records(task))
    assert len(got) == 5
    assert int(got[0]["a"]) == 1 and float(got[0]["b"]) == 2.5
    assert bytes(got[0]["c"]).decode() == "alpha"
    assert float(got[1]["b"]) == 0.0  # None -> zero, reference behavior
    assert int(got[2]["a"]) == 0


def test_pyspark_gen_partition_body(tmp_path):
    """The spark job's partition body converts a tar's files to EDLIO
    shards without pyspark (reference spark_gen_recordio.py:21-64)."""
    import tarfile

    from elasticdl_tpu.data.recordio_gen.pyspark_gen.spark_gen_recordio import (
        convert_tar_partition,
        list_tar_data_files,
    )
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.data.reader import decode_example, encode_example

    tar_path = tmp_path / "data.tar"
    with tarfile.open(tar_path, "w") as tar:
        for i, name in enumerate(["3_a.bin", "7_b.bin", ".hidden"]):
            p = tmp_path / name
            p.write_bytes(bytes([i]) * 4)
            tar.add(p, arcname=name)

    files = list_tar_data_files(str(tar_path))
    assert files == ["3_a.bin", "7_b.bin"]  # dotfile skipped

    def prepare(fileobj, filename):
        label = int(filename.split("/")[-1].split("_")[0])
        payload = np.frombuffer(fileobj.read(), dtype=np.uint8)
        return encode_example({"x": payload, "label": np.int64(label)})

    out = tmp_path / "out"
    out.mkdir()
    n = convert_tar_partition(
        str(tar_path), files, prepare, str(out), partition_id=0,
        records_per_file=1,
    )
    assert n == 2
    reader = RecordIODataReader(data_dir=str(out))
    labels = []
    for name, (start, count) in sorted(reader.create_shards().items()):
        task = type(
            "T", (), {"shard_name": name, "start": start, "end": start + count}
        )
        labels.extend(
            int(decode_example(r)["label"]) for r in reader.read_records(task)
        )
    assert sorted(labels) == [3, 7]
