"""Peer state replication (ISSUE 4): store/wire/directory units, the
heartbeat advertisement loop, generation-fenced restore staging, the
hot-restore path on a real trainer, chaos falsification hooks, and the
trace/report surfaces that prove restore came from peer RAM.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.replication import blob as blob_mod
from elasticdl_tpu.replication.directory import ReplicaDirectory
from elasticdl_tpu.replication.replicator import (
    PeerReplicator,
    restore_from_replica,
)
from elasticdl_tpu.replication.service import (
    ReplicaClient,
    ReplicaServicer,
    start_replica_server,
)
from elasticdl_tpu.replication.store import ReplicaShard, ReplicaStore
from elasticdl_tpu.rpc import messages as msg


def _shard(
    source: int,
    version: int,
    dense: dict | None = None,
    parts: dict | None = None,
    generation: int = 0,
) -> ReplicaShard:
    payload = blob_mod.encode_snapshot(dense or {}, parts or {})
    return ReplicaShard(
        source=source,
        version=version,
        generation=generation,
        checksum=blob_mod.blob_checksum(payload),
        payload=payload,
    )


# ---- blob codec -------------------------------------------------------------


def test_blob_round_trip_and_merge():
    dense = {"params/w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    parts_a = {
        "params/emb": (
            np.arange(0, 4, dtype=np.int64),
            np.full((4, 2), 1.0, np.float32),
        )
    }
    parts_b = {
        "params/emb": (
            np.arange(4, 8, dtype=np.int64),
            np.full((4, 2), 2.0, np.float32),
        )
    }
    a = blob_mod.decode_snapshot(blob_mod.encode_snapshot(dense, parts_a))
    np.testing.assert_array_equal(a[0]["params/w"], dense["params/w"])
    merged_dense, merged_parts = blob_mod.merge_snapshots(
        [a, blob_mod.decode_snapshot(blob_mod.encode_snapshot({}, parts_b))]
    )
    assert set(merged_dense) == {"params/w"}
    ids, rows = merged_parts["params/emb"]
    assert sorted(ids.tolist()) == list(range(8))
    # disjoint ranges concatenate; values per range preserved
    assert rows[list(ids).index(0)][0] == 1.0
    assert rows[list(ids).index(7)][0] == 2.0


def test_blob_checksum_detects_truncation():
    payload = blob_mod.encode_snapshot(
        {"w": np.ones((4, 4), np.float32)}, {}
    )
    checksum = blob_mod.blob_checksum(payload)
    assert blob_mod.blob_checksum(payload[:-1]) != checksum


# ---- store ------------------------------------------------------------------


def test_store_commit_and_holdings():
    store = ReplicaStore(generation=2)
    ok, _reason = store.put(_shard(0, 6, generation=2))
    assert ok
    assert store.get(0).version == 6
    holdings = store.holdings()
    assert holdings[0]["source"] == 0 and holdings[0]["generation"] == 2


def test_store_refuses_torn_stale_and_cross_generation():
    store = ReplicaStore(generation=0)
    good = _shard(1, 6)
    torn = ReplicaShard(1, 8, 0, good.checksum, good.payload[:-2])
    ok, reason = store.put(torn)
    assert (ok, reason) == (False, "checksum_mismatch")
    assert store.put(good)[0]
    ok, reason = store.put(_shard(1, 6))  # duplicate of held version
    assert (ok, reason) == (False, "stale_version")
    ok, reason = store.put(_shard(1, 8, generation=1))  # stale world
    assert (ok, reason) == (False, "generation_mismatch")
    assert store.get(1).version == 6  # last good shard untouched
    assert store.rejected == 3


def test_store_retains_previous_version_for_older_complete_sets():
    """A host commits its own new snapshot BEFORE the neighbor ack: the
    previous version must survive the commit, or a death in that window
    destroys the last COMPLETE replica set (review finding)."""
    store = ReplicaStore(generation=0)
    for version in (2, 4, 6):
        assert store.put(_shard(0, version))[0]
    assert store.versions(0) == [4, 6]  # keeps the two newest
    assert store.get(0).version == 6  # default = newest
    assert store.get(0, version=4).version == 4
    assert store.get(0, version=2) is None  # pruned
    # older than everything retained at capacity: refused
    ok, reason = store.put(_shard(0, 1))
    assert (ok, reason) == (False, "stale_version")
    # advertisement stays newest-per-source
    assert store.holdings()[0]["version"] == 6


# ---- replica service (wire) -------------------------------------------------


def test_replica_service_push_fetch_probe_round_trip():
    store = ReplicaStore(generation=0)
    server, port = start_replica_server(store)
    client = ReplicaClient(f"127.0.0.1:{port}")
    try:
        shard = _shard(0, 4, {"w": np.ones((2, 2), np.float32)})
        resp = client.push_replica(
            msg.PushReplicaRequest(
                source=shard.source,
                version=shard.version,
                generation=shard.generation,
                checksum=shard.checksum,
                payload=shard.payload,
            )
        )
        assert resp.accepted
        probe = client.fetch_replica(
            msg.FetchReplicaRequest(source=0, probe=True)
        )
        assert probe.has and probe.version == 4 and probe.payload == b""
        full = client.fetch_replica(msg.FetchReplicaRequest(source=0))
        assert full.payload == shard.payload
        assert not client.fetch_replica(
            msg.FetchReplicaRequest(source=3)
        ).has
    finally:
        client.close()
        server.stop(grace=0)


def test_replica_servicer_rejects_torn_push_in_process():
    servicer = ReplicaServicer(ReplicaStore(generation=0))
    shard = _shard(0, 4)
    resp = servicer.push_replica(
        msg.PushReplicaRequest(
            source=0,
            version=4,
            generation=0,
            checksum=shard.checksum,
            payload=shard.payload[:-1],
        )
    )
    assert not resp.accepted and resp.reason == "checksum_mismatch"
    assert servicer.store.get(0) is None


# ---- directory + harvest ----------------------------------------------------


def _serve(store: ReplicaStore):
    server, port = start_replica_server(store)
    return server, f"127.0.0.1:{port}"


def test_directory_harvest_picks_freshest_complete_set():
    # survivor holds its own shard at v6 and the victim's pushed v6
    store = ReplicaStore(generation=0)
    store.put(_shard(0, 6, {"w": np.full((2, 2), 6.0, np.float32)}))
    store.put(_shard(1, 6))
    server, addr = _serve(store)
    try:
        directory = ReplicaDirectory()
        directory.update(
            0,
            {
                "addr": addr,
                "process_id": 0,
                "generation": 0,
                "holdings": store.holdings(),
            },
        )
        stage = directory.harvest(
            live_worker_ids=[0], num_sources=2, generation=0, staged_for=1
        )
        assert stage is not None
        assert stage["version"] == 6 and stage["generation"] == 1
        dense, _parts = blob_mod.decode_snapshot(stage["payload"])
        np.testing.assert_array_equal(
            dense["w"], np.full((2, 2), 6.0, np.float32)
        )
        assert directory.harvests == 1
    finally:
        server.stop(grace=0)


def test_directory_harvest_uses_older_complete_set_after_torn_push():
    """kill_during_replication window: the survivor's own shard
    advanced to v6 but the victim's v6 push never landed — harvest must
    assemble the OLDER complete set (v4) from the retained versions
    instead of falling back to disk."""
    store = ReplicaStore(generation=0)
    store.put(_shard(0, 4, {"w": np.full((2, 2), 4.0, np.float32)}))
    store.put(_shard(0, 6, {"w": np.full((2, 2), 6.0, np.float32)}))
    store.put(_shard(1, 4))  # victim's last complete push
    server, addr = _serve(store)
    try:
        directory = ReplicaDirectory()
        directory.update(
            0,
            {
                "addr": addr,
                "process_id": 0,
                "generation": 0,
                "holdings": store.holdings(),
            },
        )
        stage = directory.harvest(
            live_worker_ids=[0], num_sources=2, generation=0, staged_for=1
        )
        assert stage is not None and stage["version"] == 4
        dense, _parts = blob_mod.decode_snapshot(stage["payload"])
        np.testing.assert_array_equal(
            dense["w"], np.full((2, 2), 4.0, np.float32)
        )
    finally:
        server.stop(grace=0)


def test_directory_harvest_incomplete_coverage_falls_back():
    """No version of the victim's shard was ever received: there is no
    complete set at ANY version — harvest must return None (the
    disk-fallback rule), never a torn mix."""
    store = ReplicaStore(generation=0)
    store.put(_shard(0, 6))  # own shard advanced to 6...
    # ...but the victim's shard (source 1) was never received at all
    server, addr = _serve(store)
    try:
        directory = ReplicaDirectory()
        directory.update(
            0,
            {
                "addr": addr,
                "process_id": 0,
                "generation": 0,
                "holdings": store.holdings(),
            },
        )
        assert (
            directory.harvest(
                live_worker_ids=[0],
                num_sources=2,
                generation=0,
                staged_for=1,
            )
            is None
        )
        assert directory.harvest_failures == 1
    finally:
        server.stop(grace=0)


def test_directory_harvest_ignores_dead_and_stale_generation():
    directory = ReplicaDirectory()
    directory.update(
        5, {"addr": "127.0.0.1:1", "process_id": 0, "generation": 0,
            "holdings": []},
    )
    # dead worker excluded -> no addrs -> disk fallback
    assert directory.harvest([], 1, 0, 1) is None
    # stale-generation advertisement excluded the same way
    assert directory.harvest([5], 1, 3, 4) is None
    directory.forget_worker(5)
    assert directory.peers(0) == {}


def test_directory_peers_are_string_keyed_for_the_wire():
    """msgpack decode (strict_map_key) rejects int map keys — the peer
    map rides a HeartbeatResponse, so keys must be strings end to end."""
    directory = ReplicaDirectory()
    directory.update(
        0, {"addr": "127.0.0.1:9", "process_id": 1, "generation": 0,
            "holdings": []},
    )
    peers = directory.peers(0)
    assert peers == {"1": "127.0.0.1:9"}
    decoded = msg.decode(
        msg.encode(msg.HeartbeatResponse(replica_peers=peers))
    )
    assert decoded.replica_peers == {"1": "127.0.0.1:9"}


def test_directory_coverage_stats_counts_pushes():
    directory = ReplicaDirectory()
    for version in (2, 4):
        directory.update(
            0,
            {
                "addr": "a",
                "process_id": 0,
                "generation": 0,
                "holdings": [
                    {"source": 0, "version": version, "generation": 0,
                     "checksum": "x"}
                ],
            },
        )
    stats = directory.coverage_stats()
    assert stats["pushes_by_generation"] == {"0": 2}
    gen0 = stats["generations"][0]
    assert gen0["hosts_covered"] == [0]
    assert gen0["shard_versions"] == {"0": 4}


# ---- master servicer integration --------------------------------------------


def _servicer() -> MasterServicer:
    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=64)
    return MasterServicer(32, dispatcher)


def test_heartbeat_carries_advertisement_up_and_peers_down():
    servicer = _servicer()
    directory = ReplicaDirectory()
    servicer.set_replica_directory(directory)
    resp = servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=4,
            replica={
                "addr": "127.0.0.1:7", "process_id": 0, "generation": 0,
                "holdings": [],
            },
        )
    )
    assert resp.replica_peers == {"0": "127.0.0.1:7"}
    # a replication-less worker's heartbeat is unchanged
    resp = servicer.heartbeat(msg.HeartbeatRequest(worker_id=1))
    assert resp.accepted
    servicer.forget_worker(0)
    assert directory.peers(0) == {}


def test_heartbeat_wire_compat_with_pre_replication_payloads():
    """Old payloads lack the replica fields entirely; decode must fill
    defaults (same contract as the PR-3 trace fields)."""
    import msgpack

    old_request = msgpack.packb(
        {
            "kind": "HeartbeatRequest",
            "body": {"worker_id": 3, "step": 1, "timestamp": 0.0},
        },
        use_bin_type=True,
    )
    decoded = msg.decode(old_request)
    assert decoded.replica == {}
    old_response = msgpack.packb(
        {
            "kind": "HeartbeatResponse",
            "body": {"accepted": True, "should_quiesce": False,
                     "cluster_version": 0},
        },
        use_bin_type=True,
    )
    assert msg.decode(old_response).replica_peers == {}


def test_restore_stage_is_generation_fenced():
    servicer = _servicer()
    assert not servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1)
    ).has
    servicer.set_restore_stage(
        {"generation": 2, "version": 6, "checksum": "c", "payload": b"x"}
    )
    assert not servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1)
    ).has
    staged = servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=2)
    )
    assert staged.has and staged.version == 6 and staged.payload == b"x"
    servicer.set_restore_stage(None)
    assert not servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=2)
    ).has


def test_restore_stage_released_after_all_processes_fetch():
    """The staged payload is a full model-state copy; once every
    process of the restoring generation has its copy it must leave
    master RAM (review finding)."""
    servicer = _servicer()
    servicer.set_restore_stage(
        {"generation": 1, "version": 6, "checksum": "c", "payload": b"x",
         "world_size": 2}
    )
    first = servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1, process_id=0)
    )
    assert first.has
    # the same process asking again does NOT release the stage
    assert servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1, process_id=0)
    ).has
    assert servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1, process_id=1)
    ).has
    # every process served: the payload is gone
    assert not servicer.get_restore_state(
        msg.GetRestoreStateRequest(cluster_version=1, process_id=0)
    ).has


# ---- replicator cadence ------------------------------------------------------


class _StepTrainer:
    def __init__(self, step):
        self.step = step
        self.state = None


@pytest.fixture()
def _fake_snapshot(monkeypatch):
    from elasticdl_tpu.parallel import elastic

    monkeypatch.setattr(
        elastic,
        "state_checkpoint_parts",
        lambda state, mesh, materialize_dense=True: (
            {"w": np.ones((1,), np.float32)} if materialize_dense else {},
            {},
        ),
    )


def _replicator(steps: int = 0, process_id: int = 0) -> PeerReplicator:
    return PeerReplicator(
        ReplicaStore(generation=0),
        process_id=process_id,
        num_processes=2,
        generation=0,
        addr="127.0.0.1:0",
        replication_steps=steps,
    )


def test_replicator_every_boundary_cadence(_fake_snapshot):
    rep = _replicator(steps=0)
    assert rep.maybe_replicate(_StepTrainer(2), mesh=None)
    assert not rep.maybe_replicate(_StepTrainer(2), mesh=None)  # no new step
    assert rep.maybe_replicate(_StepTrainer(4), mesh=None)
    # local commit happened even with no peer discovered yet
    assert rep._store.get(0).version == 4
    assert rep.push_failures == 2 and rep.pushes == 0


def test_replicator_milestone_cadence_and_restore_alignment(_fake_snapshot):
    rep = _replicator(steps=4)
    assert not rep.maybe_replicate(_StepTrainer(3), mesh=None)
    assert rep.maybe_replicate(_StepTrainer(6), mesh=None)  # crossed 4
    assert not rep.maybe_replicate(_StepTrainer(7), mesh=None)
    rep.note_restored_version(6)
    assert not rep.maybe_replicate(_StepTrainer(7), mesh=None)
    assert rep.maybe_replicate(_StepTrainer(12), mesh=None)


def test_replicator_ring_push_delivers_to_neighbor(_fake_snapshot):
    neighbor_store = ReplicaStore(generation=0)
    server, addr = _serve(neighbor_store)
    try:
        rep = _replicator(process_id=0)
        assert rep.neighbor == 1
        rep.set_peers({"1": addr})
        rep.replicate_now(_StepTrainer(6), mesh=None)
        assert rep.pushes == 1
        delivered = neighbor_store.get(0)
        assert delivered is not None and delivered.version == 6
    finally:
        rep.close()
        server.stop(grace=0)


def test_replicator_advertisement_shape(_fake_snapshot):
    rep = _replicator()
    rep.replicate_now(_StepTrainer(2), mesh=None)
    ad = rep.advertisement()
    assert ad["addr"] == "127.0.0.1:0" and ad["process_id"] == 0
    assert ad["holdings"][0]["version"] == 2


# ---- hot restore on a real trainer ------------------------------------------


class _StageMaster:
    """In-process master stub serving one staged restore payload."""

    def __init__(self, stage: dict | None):
        self._stage = stage

    def get_restore_state(self, request):
        if (
            self._stage is None
            or self._stage["generation"] != request.cluster_version
        ):
            return msg.RestoreStateResponse()
        return msg.RestoreStateResponse(
            has=True,
            version=self._stage["version"],
            checksum=self._stage["checksum"],
            payload=self._stage["payload"],
        )


def _tiny_trainer():
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from elasticdl_tpu.parallel.distributed import SPMDTrainer
    from elasticdl_tpu.parallel.mesh import MeshConfig

    class _M(nn.Module):
        @nn.compact
        def __call__(self, features, training: bool = False):
            return nn.Dense(2)(features["x"])

    mesh = MeshConfig.from_string("dp=2").create()
    feats = {"x": np.ones((4, 3), np.float32)}
    trainer = SPMDTrainer(
        mesh,
        _M(),
        lambda labels, outputs: jnp.mean(outputs**2),
        optax.sgd(0.1),
        feats,
    )
    return trainer, mesh


def test_restore_from_replica_lands_at_replicated_step():
    from elasticdl_tpu.parallel import elastic

    trainer, mesh = _tiny_trainer()
    # snapshot the current state as the replicated version 6
    dense, parts = elastic.state_checkpoint_parts(trainer.state, mesh)
    payload = blob_mod.encode_snapshot(dense, parts)
    stage = {
        "generation": 1,
        "version": 6,
        "checksum": blob_mod.blob_checksum(payload),
        "payload": payload,
    }
    # scramble the live state so the restore is observable
    import jax

    scrambled = jax.tree_util.tree_map(
        lambda a: a * 0.0, trainer.state.params
    )
    trainer.state = trainer.state.replace(params=scrambled)
    version = restore_from_replica(
        trainer, _StageMaster(stage), cluster_version=1, process_id=0
    )
    assert version == 6
    assert int(trainer.state.step) == 6
    restored, _ = elastic.state_checkpoint_parts(trainer.state, mesh)
    for name, value in dense.items():
        np.testing.assert_array_equal(restored[name], value)


def test_restore_from_replica_declines_stage_older_than_disk():
    """replication_steps coarser than checkpoint_steps can leave the
    staged replica BEHIND the newest disk milestone — the replica path
    must decline so restore never loses work relative to disk."""
    trainer, mesh = _tiny_trainer()
    from elasticdl_tpu.parallel import elastic

    dense, parts = elastic.state_checkpoint_parts(trainer.state, mesh)
    payload = blob_mod.encode_snapshot(dense, parts)
    stage = {
        "generation": 1,
        "version": 4,
        "checksum": blob_mod.blob_checksum(payload),
        "payload": payload,
    }
    master = _StageMaster(stage)
    assert restore_from_replica(trainer, master, 1, min_version=8) is None
    assert restore_from_replica(trainer, master, 1, min_version=4) == 4


def test_restore_from_replica_falls_through_without_stage():
    trainer, _mesh = _tiny_trainer()
    assert (
        restore_from_replica(
            trainer, _StageMaster(None), cluster_version=1
        )
        is None
    )
    # wrong generation (fenced) and torn payload both fall through
    payload = b"not-a-snapshot"
    stage = {
        "generation": 1,
        "version": 6,
        "checksum": "00000000",
        "payload": payload,
    }
    assert (
        restore_from_replica(trainer, _StageMaster(stage), 1) is None
    )


# ---- chaos integration -------------------------------------------------------


def test_injector_kill_during_replication_fires_from_push_hook(
    tmp_path, monkeypatch
):
    from elasticdl_tpu.chaos.hooks import ChaosInjector
    from elasticdl_tpu.chaos.plan import Fault, FaultKind, FaultPlan

    killed = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: killed.append(sig))
    fault = Fault(
        kind=FaultKind.KILL_DURING_REPLICATION,
        fault_id="rk",
        at_step=4,
        process_id=0,
    )
    inj = ChaosInjector(
        FaultPlan(name="t", faults=[fault]),
        process_id=0,
        cluster_version=0,
        worker_id=0,
        events_path=str(tmp_path / "e.jsonl"),
    )
    inj.on_step(4)  # arms only; never fires at a step boundary
    assert not killed
    inj.on_replica_push(2)  # below at_step
    assert not killed
    inj.on_replica_push(4)
    assert killed
    events = [
        json.loads(line)
        for line in open(tmp_path / "e.jsonl", encoding="utf-8")
    ]
    assert events[0]["phase"] == "replica_push"


def test_replication_plans_registered():
    from elasticdl_tpu.chaos.plan import FaultKind, builtin_plans
    from elasticdl_tpu.chaos.runner import REPLICATION_PLANS

    plans = builtin_plans(2)
    assert plans["preempt_after_replication"].faults[0].kind == (
        FaultKind.PREEMPT
    )
    assert plans["kill_during_replication"].faults[0].kind == (
        FaultKind.KILL_DURING_REPLICATION
    )
    assert REPLICATION_PLANS <= set(plans)


def test_harness_no_lost_steps_checker(tmp_path):
    from elasticdl_tpu.chaos.harness import (
        ChaosJobConfig,
        _check_no_lost_steps,
    )
    from elasticdl_tpu.chaos.plan import FaultPlan

    # the checker takes the ALREADY-PARSED event list (one shared parse
    # per run since PR 7), so the test feeds lists directly
    config = ChaosJobConfig(
        plan=FaultPlan(name="t"), workdir=str(tmp_path), replication=True
    )
    kill = [{"kind": "preempt_worker", "monotonic": 100.0}]
    events = [
        {"event": "replica_push", "step": 6, "monotonic": 99.0},
        {"event": "replica_restore", "step": 6, "monotonic": 105.0},
    ]
    verdict = _check_no_lost_steps(config, events, kill)
    assert verdict["status"] == "PASS"
    # restoring below the replicated step = lost steps
    events = [
        {"event": "replica_push", "step": 6, "monotonic": 99.0},
        {"event": "replica_restore", "step": 4, "monotonic": 105.0},
    ]
    assert _check_no_lost_steps(config, events, kill)["status"] == (
        "FAIL"
    )
    # no restore at all = FAIL; replication off = not applicable
    events = [{"event": "replica_push", "step": 6, "monotonic": 99.0}]
    assert _check_no_lost_steps(config, events, kill)["status"] == (
        "FAIL"
    )
    config.replication = False
    assert _check_no_lost_steps(config, events, kill) is None


# ---- dispatcher liveness (found by the replication smoke) -------------------


def test_finished_accounts_for_unopened_epochs():
    """A multi-epoch job whose current epoch drained is NOT finished:
    epoch N+1 opens lazily on the next get().  Without this, a worker
    death at the last task of an epoch ended the job one epoch early
    with no re-formation."""
    dispatcher = TaskDispatcher(
        {"s": (0, 64)}, records_per_task=64, num_epochs=2
    )
    task_id, task = dispatcher.get(0)
    assert task is not None
    dispatcher.report(task_id, success=True)
    # epoch 0 drained, epoch 1 not yet opened: still not finished
    assert not dispatcher.finished()
    task_id, task = dispatcher.get(0)  # opens epoch 1
    assert task is not None
    dispatcher.report(task_id, success=True)
    assert dispatcher.finished()


# ---- trace analyzer + report surfaces ---------------------------------------


def _write_jsonl(path, records):
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record) + "\n")


def test_trace_analyze_attributes_replica_phases(tmp_path):
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    run = tmp_path / "telemetry"
    run.mkdir()
    _write_jsonl(
        run / "events.jsonl",
        [
            {"event": "step", "generation": 0, "monotonic": 10.0,
             "step": 6, "worker_id": 0},
            {"event": "step", "generation": 1, "monotonic": 20.0,
             "step": 7, "worker_id": 2},
        ],
    )
    _write_jsonl(
        run / "spans.jsonl",
        [
            {"span": "reform", "trace_id": "t", "span_id": "r",
             "parent_span_id": "", "start": 11.0, "end": 16.0,
             "generation": 1, "role": "master"},
            {"span": "replica_harvest", "trace_id": "t", "span_id": "h",
             "parent_span_id": "r", "start": 11.2, "end": 12.0,
             "generation": 1, "role": "master"},
            {"span": "reform_fence_recover", "trace_id": "t",
             "span_id": "f", "parent_span_id": "r", "start": 12.0,
             "end": 12.5, "generation": 1, "role": "master"},
            {"span": "reform_relaunch", "trace_id": "t", "span_id": "l",
             "parent_span_id": "r", "start": 12.5, "end": 14.0,
             "generation": 1, "role": "master"},
            {"span": "replica_restore", "trace_id": "u", "span_id": "x",
             "parent_span_id": "", "start": 16.0, "end": 18.0,
             "generation": 1, "role": "worker", "step": 6},
        ],
    )
    analysis = analyze_telemetry_dir(str(run))
    gap = analysis["reform_downtime"][0]
    phases = gap["phases_secs"]
    assert phases["replica_harvest"] == pytest.approx(0.8)
    assert phases["replica_restore"] == pytest.approx(2.0)
    assert "checkpoint_restore" not in phases
    # phase attribution still sums EXACTLY to the measured downtime
    assert sum(phases.values()) == pytest.approx(gap["downtime_secs"])


def test_report_embeds_replica_coverage(tmp_path):
    from elasticdl_tpu.telemetry.report import analyze_events

    events = [
        {"event": "step", "generation": 0, "monotonic": 1.0, "step": 1,
         "worker_id": 0, "records": 32},
        {"event": "replica_push", "generation": 0, "monotonic": 1.5,
         "step": 2, "source": 0, "target": 1, "ok": True},
        {"event": "replica_push", "generation": 0, "monotonic": 1.6,
         "step": 2, "source": 1, "target": 0, "ok": True},
        {"event": "replica_harvest", "generation": 1, "monotonic": 2.0,
         "complete": True, "version": 2},
        {"event": "replica_restore", "generation": 1, "monotonic": 2.5,
         "step": 2},
    ]
    run = analyze_events(events, faults=[])
    replication = run["replication"]
    assert replication["pushes_by_generation"] == {0: 2}
    assert replication["hosts_covered_by_generation"] == {0: [0, 1]}
    assert replication["shard_versions_by_generation"] == {0: 2}
    assert replication["restores"] == [{"generation": 1, "step": 2}]
    assert replication["harvests"][0]["complete"] is True
    # replication-less runs keep their schema unchanged
    assert "replication" not in analyze_events(events[:1], faults=[])
