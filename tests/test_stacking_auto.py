"""The `--steps_per_dispatch auto` sizing rule (trainer/stacking.py)."""

import numpy as np

from elasticdl_tpu.trainer import stacking


def test_auto_k_pins_the_sizing_rule():
    """The rule that replaced the r3 hand-tuned constants: a 7MB put
    target sizes the dispatch group, so on the tunneled dev link (130ms
    dispatches) 803KB f32 mnist batches get k=9 and the 205KB uint8
    wire gets k=36 — superseding r3's hand-tuned k=16, whose 12.8MB f32
    groups sat exactly on the link's transfer cliff.  Tiny deepfm
    batches cap at MAX_AUTO_K; cheap-dispatch hosts get k=1 (no
    stacking needed)."""
    mnist_bytes = 256 * 28 * 28 * 4 + 256 * 4  # f32 images + i32 labels
    # the 7MB put target (calibrated: 5-6.5MB puts sustain the link's
    # fast path, >=12MB collapses) sizes f32 mnist to 9 and the uint8
    # wire to 36 — r3's hand-tuned k=16 shipped 12.8MB f32 groups that
    # sat exactly on the cliff
    assert stacking.auto_steps_per_dispatch(mnist_bytes, 0.13) == 9
    mnist_u8 = 256 * 28 * 28 + 256 * 4  # uint8 wire (device_parse)
    assert stacking.auto_steps_per_dispatch(mnist_u8, 0.13) == 36
    deepfm_bytes = 4096 * 10 * 2 + 4096 * 4  # int16 wire ids
    assert (
        stacking.auto_steps_per_dispatch(deepfm_bytes, 0.13)
        == stacking.MAX_AUTO_K
    )
    # cheap dispatch (local PCIe): stacking buys nothing, keep hooks
    # per-step
    assert stacking.auto_steps_per_dispatch(mnist_bytes, 0.0005) == 1
    # degenerate inputs
    assert stacking.auto_steps_per_dispatch(0, 0.13) == 1
    # a batch bigger than the cliff still dispatches (k=1)
    assert (
        stacking.auto_steps_per_dispatch(
            stacking.TRANSFER_CLIFF_BYTES * 2, 0.13
        )
        == 1
    )


def test_choose_stack_k_shared_rule():
    """THE stack_k selection rule the three runtimes share: stacking
    only in training and only for k>1; 'auto' passes through except in
    lockstep worlds (allow_auto=False — a per-process auto probe could
    deadlock the collectives)."""
    assert stacking.choose_stack_k(4, training=True) == 4
    assert stacking.choose_stack_k("auto", training=True) == "auto"
    assert stacking.choose_stack_k("auto", True, allow_auto=False) is None
    assert stacking.choose_stack_k(4, training=False) is None
    assert stacking.choose_stack_k(1, training=True) is None
    assert stacking.choose_stack_k(None, training=True) is None
    assert stacking.choose_stack_k(0, training=True) is None


def test_resolve_explicit_k_passthrough():
    assert stacking.resolve_steps_per_dispatch(4) == 4
    assert stacking.resolve_steps_per_dispatch(None) == 1
    assert stacking.resolve_steps_per_dispatch(0) == 1


def test_resolve_auto_uses_batch_bytes(monkeypatch):
    monkeypatch.setattr(stacking, "_DISPATCH_OVERHEAD", [0.13])
    feats = {"image": np.zeros((256, 28, 28), np.float32)}
    labels = np.zeros(256, np.int32)
    assert stacking.resolve_steps_per_dispatch(
        "auto", (feats, labels)
    ) == 9
    # cheap link -> 1
    monkeypatch.setattr(stacking, "_DISPATCH_OVERHEAD", [0.0001])
    assert (
        stacking.resolve_steps_per_dispatch("auto", (feats, labels)) == 1
    )


def test_run_stacked_steps_resolves_auto(monkeypatch):
    """'auto' flows through the grouping loop: with a fake expensive
    link the first batch's bytes pick the group size."""
    monkeypatch.setattr(stacking, "_DISPATCH_OVERHEAD", [0.13])

    class FakeTrainer:
        def __init__(self):
            self.stacked_calls = []
            self.single_calls = 0

        def pad_batch(self, tree):
            return tree, 1

        def place_padded(self, tree):
            return tree

        def place_stacked(self, tree):
            return tree

        def train_step(self, f, l):
            self.single_calls += 1

        def train_steps_stacked(self, f, l):
            import jax

            self.stacked_calls.append(
                jax.tree_util.tree_leaves(f)[0].shape[0]
            )

    # ~1.05MB batches (f32 features + f64 labels) -> auto k = 6
    batch = ({"x": np.zeros((256, 1024), np.float32)}, np.zeros(256))
    batches = [batch] * 26
    trainer = FakeTrainer()
    n = stacking.run_stacked_steps(lambda: trainer, iter(batches), "auto")
    assert n == 26 * 256
    # four full groups + the 2-batch leftover group
    assert trainer.stacked_calls == [6, 6, 6, 6, 2]
    assert trainer.single_calls == 0
