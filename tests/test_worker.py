"""Worker runtime: exactly-once task accounting + the in-process
distributed harness (reference test_utils.distributed_train_and_evaluate:
real servicer + real data + worker.run() to completion, process boundary
collapsed)."""

import numpy as np
import pytest

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_reader import RecordIODataReader
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.args import parse_worker_args
from elasticdl_tpu.utils.constants import JobType, TaskType
from elasticdl_tpu.worker.task_data_service import TaskDataService
from elasticdl_tpu.worker.worker import Worker, derive_job_type


class _ScriptedWorker:
    """Feeds TaskDataService a fixed task list; records reports."""

    def __init__(self, tasks):
        self._tasks = list(tasks)
        self.reported = []

    def get_task(self, task_type=-1):
        if self._tasks:
            return self._tasks.pop(0)
        return msg.TaskResponse()  # job complete

    def report_task_result(
        self, task_id, err_msg="", exec_counters=None, include_timing=False
    ):
        self.reported.append((task_id, err_msg, exec_counters or {}))


def _task(task_id, start, end, shard="s0"):
    return msg.TaskResponse(
        task_id=task_id,
        shard_name=shard,
        start=start,
        end=end,
        type=int(TaskType.TRAINING),
    )


class _CountingReader:
    """Reader yielding one record per index (no files involved)."""

    metadata = None

    def read_records(self, task):
        for i in range(task.start, task.end):
            yield i


def _wire_tds(scripted):
    """Hand-wire a TaskDataService (no reader-factory I/O)."""
    import threading
    from collections import deque

    tds = TaskDataService.__new__(TaskDataService)
    tds._worker = scripted
    tds._training_with_evaluation = False
    tds._wait_sleep_secs = 0
    tds.data_reader = _CountingReader()
    tds._lock = threading.Lock()
    tds._pending_save_model_task = None
    tds._has_warmed_up = True  # skip warm-up (no factory reader)
    tds._failed_record_count = 0
    tds._reported_record_count = 0
    tds._current_task = None
    tds._pending_tasks = deque()
    tds._last_poll_was_wait = False
    return tds


@pytest.mark.parametrize(
    "task_sizes,batch",
    [
        ([10, 10, 10], 4),   # counts straddle task boundaries
        ([3, 3, 3], 7),      # one count covers several whole tasks
        ([8], 8),            # exact fit
        ([5, 2, 9], 6),      # mixed
    ],
)
def test_exactly_once_task_accounting(task_sizes, batch):
    """The count-based pop-while accounting (reference
    task_data_service.py:75-107) is pipeline-agnostic: tasks registered
    via the live lease API, counts reported in arbitrary groupings —
    including groupings that straddle or span whole tasks — must report
    each task exactly once, in order."""
    starts = np.cumsum([0] + task_sizes[:-1])
    tasks = [
        _task(i + 1, int(s), int(s) + n)
        for i, (s, n) in enumerate(zip(starts, task_sizes))
    ]
    scripted = _ScriptedWorker(tasks)
    tds = _wire_tds(scripted)

    leased = []
    while True:
        _tid, task = tds.lease_task()
        if task is None:
            break
        leased.append(task)
    assert [t.task_id for t in leased] == [t.task_id for t in tasks]

    total = sum(task_sizes)
    for _ in range(total // batch):
        tds.report_record_done(batch)
    if total % batch:
        tds.report_record_done(total % batch)

    reported_ids = [r[0] for r in scripted.reported]
    assert reported_ids == [t.task_id for t in tasks]  # each exactly once
    assert not tds._pending_tasks


def _worker_args(data_dir, extra=()):
    return parse_worker_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            data_dir,
            "--minibatch_size",
            "16",
            "--worker_id",
            "0",
            "--master_addr",
            "inprocess",
            "--compute_dtype",
            "float32",
            *extra,
        ]
    )


def _master_for(data_dir, **dispatcher_kw):
    reader = RecordIODataReader(data_dir=data_dir)
    task_d = TaskDispatcher(
        reader.create_shards(), records_per_task=32, **dispatcher_kw
    )
    return task_d, MasterServicer(16, task_d)


def test_worker_trains_to_completion(tmp_path):
    data_dir = synthetic.gen_mnist(
        str(tmp_path / "mnist"), num_records=96, num_shards=2, seed=0
    )
    task_d, master = _master_for(data_dir)
    args = _worker_args(data_dir)
    worker = Worker(args, master, job_type=JobType.TRAINING_ONLY)
    worker.run()

    assert task_d.finished()
    counters = task_d.counters(TaskType.TRAINING)
    assert counters.total_records == 96
    assert counters.failed_records == 0
    assert worker.trainer is not None and worker.trainer.step == 96 // 16
    # worker reported its version to the master (drives eval triggers)
    assert master.get_model_version() == worker.trainer.step


def test_worker_predicts_with_processor(tmp_path):
    data_dir = synthetic.gen_mnist(
        str(tmp_path / "mnist"), num_records=48, num_shards=1, seed=0
    )
    reader = RecordIODataReader(data_dir=data_dir)
    task_d = TaskDispatcher(
        None, prediction_shards=reader.create_shards(), records_per_task=32
    )
    master = MasterServicer(16, task_d)
    args = parse_worker_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--prediction_data",
            data_dir,
            "--minibatch_size",
            "16",
            "--worker_id",
            "0",
            "--master_addr",
            "inprocess",
            "--compute_dtype",
            "float32",
        ]
    )
    assert derive_job_type(args) == JobType.PREDICTION_ONLY

    collected = []

    class _Collector:
        def process(self, predictions, worker_id):
            collected.append(np.asarray(predictions))

    worker = Worker(args, master)
    worker._spec.prediction_outputs_processor = _Collector()
    worker.run()

    assert task_d.finished()
    assert sum(c.shape[0] for c in collected) == 48
    assert all(c.shape[1] == 10 for c in collected)


def test_worker_save_model_task(tmp_path):
    data_dir = synthetic.gen_mnist(
        str(tmp_path / "mnist"), num_records=64, num_shards=1, seed=0
    )
    export_dir = str(tmp_path / "export")
    task_d, master = _master_for(data_dir)
    task_d.add_deferred_callback_create_save_model_task(export_dir)
    args = _worker_args(data_dir)
    worker = Worker(args, master, job_type=JobType.TRAINING_ONLY)
    worker.run()

    assert task_d.finished()
    from elasticdl_tpu.utils.export_utils import load_exported_model

    model, flat_params, _ = load_exported_model(export_dir)
    assert flat_params  # exported parameters present
    assert model is not None


def test_taskstream_training_uses_vectorized_plane(tmp_path, monkeypatch):
    """VERDICT r4 #3: the task-stream worker's TRAINING loop runs on the
    vectorized pipeline (the reference's one worker runtime got tf.data's
    C++ input for training, worker.py:972-979) — and its task-report
    sequence is identical to the classic per-record path's."""
    from elasticdl_tpu.data import fast_pipeline

    data_dir = synthetic.gen_mnist(
        str(tmp_path / "mnist"), num_records=96, num_shards=2, seed=0
    )

    def run(force_classic: bool, extra=()):
        task_d, master = _master_for(data_dir)
        reports = []
        orig_report = master.report_task_result

        def recording_report(request):
            reports.append(request.task_id)
            return orig_report(request)

        master.report_task_result = recording_report
        vectorized_calls = {"n": 0}
        orig_vec = fast_pipeline._vectorized_task_batches

        def counting_vec(*a, **kw):
            vectorized_calls["n"] += 1
            return orig_vec(*a, **kw)

        monkeypatch.setattr(
            fast_pipeline, "_vectorized_task_batches", counting_vec
        )
        worker = Worker(
            _worker_args(data_dir, extra=extra),
            master,
            job_type=JobType.TRAINING_ONLY,
        )
        if force_classic:
            worker._spec.batch_parse = None  # chooser takes classic
        worker.run()
        assert task_d.finished()
        assert task_d.counters(TaskType.TRAINING).total_records == 96
        return reports, vectorized_calls["n"], worker.trainer.step

    fast_reports, fast_vec, fast_steps = run(force_classic=False)
    assert fast_vec > 0  # the vectorized decoder actually ran
    classic_reports, classic_vec, classic_steps = run(force_classic=True)
    assert classic_vec == 0
    # exactly-once semantics are path-independent: same task-report
    # sequence, same step count (96 records / batch 16 either way)
    assert fast_reports == classic_reports
    assert fast_steps == classic_steps == 96 // 16

    # PreStacked dispatch groups flow through the same accounting:
    # k=2 stacks each 32-record task's two batches into one dispatch
    stacked_reports, stacked_vec, stacked_steps = run(
        force_classic=False, extra=("--steps_per_dispatch", "2")
    )
    assert stacked_vec > 0
    assert stacked_reports == fast_reports
    assert stacked_steps == fast_steps


def test_worker_failure_is_counted(tmp_path):
    """A poisoned batch produces err reports but the job still completes
    (records marked failed, reference task_data_service.py:50-73)."""
    data_dir = synthetic.gen_mnist(
        str(tmp_path / "mnist"), num_records=64, num_shards=1, seed=0
    )
    task_d, master = _master_for(data_dir)
    args = _worker_args(data_dir)
    worker = Worker(args, master, job_type=JobType.TRAINING_ONLY)

    calls = {"n": 0}
    orig = worker._process_minibatch

    def flaky(task_type, feats, labels):
        calls["n"] += 1
        if calls["n"] == 2:
            return "injected failure"
        return orig(task_type, feats, labels)

    worker._process_minibatch = flaky
    worker.run()
    assert task_d.finished()
    assert task_d.counters(TaskType.TRAINING).failed_records == 16
