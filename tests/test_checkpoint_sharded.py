"""Sharded-table checkpointing: per-part saves + re-shardable restore.

The reference property under test (common/save_utils.py:208-261): a
checkpoint written under one shard count restores under another.  Here the
unit of sharding is the mesh layout — a vocab-sharded table written from
an ``ep=4`` mesh must restore onto an ``ep=2`` mesh — and tables are
written as per-part ``(ids, rows)`` without ever materializing whole.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.layers.embedding import Embedding
from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.parallel.distributed import SPMDTrainer
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.parallel.sharding import Rule
from elasticdl_tpu.trainer.checkpointing import (
    PeriodicCheckpointer,
    restore_trainer_state,
)
from elasticdl_tpu.utils import save_utils

VOCAB, DIM = 64, 8


class _TinyEmbModel(nn.Module):
    @nn.compact
    def __call__(self, features, training: bool = False):
        pooled = Embedding(
            input_dim=VOCAB, output_dim=DIM, combiner="mean"
        )(features["ids"])
        return nn.Dense(1)(pooled)


def _loss(labels, outputs):
    return jnp.mean((outputs.squeeze(-1) - labels) ** 2)


def _feats(batch=8, k=4, seed=0):
    rng = np.random.RandomState(seed)
    return (
        {"ids": rng.randint(0, VOCAB, size=(batch, k)).astype(np.int32)},
        rng.rand(batch).astype(np.float32),
    )


def _trainer(mesh_shape: str):
    mesh = MeshConfig.from_string(mesh_shape).create()
    feats, _ = _feats()
    return (
        SPMDTrainer(
            mesh,
            _TinyEmbModel(),
            _loss,
            optax.sgd(0.1),
            feats,
            rules=(Rule(r"embedding$", P("ep", None)),),
            embedding_threshold=None,
        ),
        mesh,
    )


def _table(trainer) -> np.ndarray:
    return np.asarray(trainer.state.params["Embedding_0"]["embedding"])


def test_state_checkpoint_parts_classifies_sharded_table():
    trainer, mesh = _trainer("dp=2,ep=4")
    dense, parts = elastic.state_checkpoint_parts(trainer.state, mesh)
    assert "params/Embedding_0/embedding" in parts
    ids, rows = parts["params/Embedding_0/embedding"]
    # single process owns all 4 vocab ranges
    assert np.array_equal(np.sort(ids), np.arange(VOCAB))
    assert rows.shape == (VOCAB, DIM)
    # replicated leaves go to dense, not parts
    assert "params/Dense_0/kernel" in dense
    assert "params/Embedding_0/embedding" not in dense


def test_reshard_restore_ep4_to_ep2(tmp_path):
    trainer, mesh = _trainer("dp=2,ep=4")
    feats, labels = _feats(seed=1)
    trainer.train_step(
        trainer.place_batch(feats), trainer.place_batch(labels)
    )
    want_table = _table(trainer)

    ckpt = PeriodicCheckpointer(str(tmp_path / "ckpt"), checkpoint_steps=1)
    ckpt.save_now(trainer, mesh)
    ckpt.flush()  # async by default: join the write before restoring

    trainer2, _ = _trainer("dp=4,ep=2")
    assert not np.allclose(_table(trainer2), want_table)

    class _Args:
        checkpoint_dir = str(tmp_path / "ckpt")
        checkpoint_dir_for_init = ""

    version = restore_trainer_state(trainer2, _Args())
    assert version == 1
    assert trainer2.step == 1
    np.testing.assert_array_equal(_table(trainer2), want_table)
    np.testing.assert_array_equal(
        np.asarray(trainer2.state.params["Dense_0"]["kernel"]),
        np.asarray(trainer.state.params["Dense_0"]["kernel"]),
    )


def test_multi_part_assembly_roundtrip(tmp_path):
    """Parts written by different (simulated) hosts reassemble by explicit
    ids regardless of write order."""
    rng = np.random.RandomState(0)
    table = rng.rand(10, 3).astype(np.float32)
    saver = save_utils.CheckpointSaver(str(tmp_path))
    # part 1 written FIRST (no retention), chief part 0 last
    saver.save(
        5,
        dense={},
        embeddings={"t": (np.arange(5, 10), table[5:])},
        part=1,
        num_parts=2,
        enforce_retention=False,
    )
    assert save_utils.latest_version(str(tmp_path)) is None  # no manifest yet
    saver.save(
        5,
        dense={"w": np.ones(2)},
        embeddings={"t": (np.arange(0, 5), table[:5])},
        part=0,
        num_parts=2,
    )
    assert save_utils.latest_version(str(tmp_path)) == 5
    dense, embeddings, _ = save_utils.restore_checkpoint(str(tmp_path))
    assembled = save_utils.assemble_embedding_tables(embeddings)
    np.testing.assert_array_equal(assembled["t"], table)
    assert "w" in dense


def test_restore_falls_back_past_torn_version(tmp_path):
    """A version whose part file was torn by a mid-save SIGKILL must not
    block restore: the loader falls back to the next older intact one."""
    saver = save_utils.CheckpointSaver(str(tmp_path))
    saver.save(1, dense={"w": np.full(3, 1.0)})
    saver.save(2, dense={"w": np.full(3, 2.0)})
    # tear version 2's part file (valid-looking: file exists)
    part = tmp_path / "version-2" / "variables-0-of-1.npz"
    part.write_bytes(b"PK\x03\x04 torn")
    assert save_utils.latest_version(str(tmp_path)) == 2
    dense, _, _ = save_utils.restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(dense["w"], np.full(3, 1.0))


def test_restore_row_range_filter(tmp_path):
    rng = np.random.RandomState(0)
    table = rng.rand(8, 2).astype(np.float32)
    saver = save_utils.CheckpointSaver(str(tmp_path))
    saver.save(1, dense={}, embeddings={"t": (np.arange(8), table)})
    _, embeddings, _ = save_utils.restore_checkpoint(
        str(tmp_path), table_row_ranges={"t": [(2, 4), (6, 8)]}
    )
    ids, rows = embeddings["t"]
    np.testing.assert_array_equal(np.sort(ids), [2, 3, 6, 7])
    np.testing.assert_array_equal(rows[np.argsort(ids)], table[[2, 3, 6, 7]])


def test_assemble_rejects_incomplete_parts():
    with pytest.raises(ValueError):
        save_utils.assemble_embedding_tables(
            {"t": (np.array([0, 2]), np.zeros((2, 3)))}
        )


def test_async_save_flush_and_error_surfacing(tmp_path):
    """Async checkpointing: the write happens off-thread, flush() joins
    it, and a write failure is re-raised on the caller's thread at the
    next flush (never swallowed)."""
    trainer, mesh = _trainer("dp=2,ep=4")
    feats, labels = _feats(seed=3)
    trainer.train_step(
        trainer.place_batch(feats), trainer.place_batch(labels)
    )

    ckpt = PeriodicCheckpointer(str(tmp_path / "ok"), checkpoint_steps=1)
    assert ckpt.maybe_save(trainer, mesh)
    ckpt.flush()
    assert save_utils.latest_version(str(tmp_path / "ok")) == 1
    # milestone already passed: no duplicate save
    assert not ckpt.maybe_save(trainer, mesh)
    ckpt.flush()  # idempotent with nothing in flight

    # failure path: break the saver underneath the async writer
    bad = PeriodicCheckpointer(str(tmp_path / "bad"), checkpoint_steps=1)

    def _boom(*a, **k):
        raise IOError("disk full")

    bad._saver.save = _boom
    bad.save_now(trainer, mesh)
    with pytest.raises(IOError, match="disk full"):
        bad.flush()
    bad.flush()  # error is delivered once, then cleared


def test_sync_mode_writes_inline(tmp_path):
    trainer, mesh = _trainer("dp=2,ep=4")
    feats, labels = _feats(seed=4)
    trainer.train_step(
        trainer.place_batch(feats), trainer.place_batch(labels)
    )
    ckpt = PeriodicCheckpointer(
        str(tmp_path / "sync"), checkpoint_steps=1, async_write=False
    )
    ckpt.save_now(trainer, mesh)
    # no flush needed: the write completed inline
    assert save_utils.latest_version(str(tmp_path / "sync")) == 1
