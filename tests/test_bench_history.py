"""bench_history's roofm trend column (ISSUE 20 satellite): the
measured-roofline pair is recovered from every artifact health state —
compact parsed lines (r06+), full-artifact anatomy shapes (r02/r03),
truncated tails — and the rendered table tolerates rounds that predate
the pair or where the device was unreachable.
"""

from __future__ import annotations

import importlib.util
import json
import os

_SPEC = importlib.util.spec_from_file_location(
    "bench_history",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "bench_history.py",
    ),
)
bench_history = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_history)


def test_roofm_pair_rounds_delta_and_rejects_non_numeric():
    pair = bench_history._roofm_pair(0.912, 0.695)
    assert pair == {"on": 0.912, "off": 0.695, "delta": 0.217}
    assert bench_history._roofm_pair(None, 0.5) is None
    assert bench_history._roofm_pair(0.5, "n/a") is None


def test_roofm_from_parsed_compact_and_anatomy_shapes():
    # the compact shape (r06+): roofm/roofm0 keys straight on the model
    compact = {
        "models": {
            "mnist_e2e": {"roofm": 0.91, "roofm0": 0.7, "spsc": 100.0}
        }
    }
    assert bench_history._roofm_from_parsed(compact) == {
        "mnist_e2e": {"on": 0.91, "off": 0.7, "delta": 0.21}
    }
    # the full-artifact shape (r02/r03 parsed blocks): the pair lives
    # under anatomy.prefetch_on/off.e2e_vs_roofline
    full = {
        "models": {
            "mnist_e2e": {
                "anatomy": {
                    "prefetch_on": {"e2e_vs_roofline": 0.8},
                    "prefetch_off": {"e2e_vs_roofline": 0.6},
                }
            },
            # single-window rounds contribute nothing, not an error
            "mnist_step": {"samples_per_sec_per_chip": 9.0},
        }
    }
    assert bench_history._roofm_from_parsed(full) == {
        "mnist_e2e": {"on": 0.8, "off": 0.6, "delta": 0.2}
    }


def test_roofm_from_tail_recovers_truncated_compact_fragment():
    tail = (
        '... {"metric":"samples_per_sec_per_chip","value":123.4,'
        '"models":{"mnist_e2e":{"spsc":123.4,"roofm":0.905,'
        '"roofm0":0.688,"bst":0.031'
    )
    assert bench_history._roofm_from_tail(tail) == {
        "mnist_e2e": {"on": 0.905, "off": 0.688, "delta": 0.217}
    }
    assert bench_history._roofm_from_tail("") == {}


def _write_round(tmp_path, n, body):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps(body))
    return str(path)


def test_load_round_tail_only_roofm_counts_as_recovery(tmp_path):
    path = _write_round(
        tmp_path,
        7,
        {
            "n": 7,
            "rc": 0,
            "parsed": None,
            "tail": '"mnist_e2e":{"roofm":0.912,"roofm0":0.695,"bst"',
        },
    )
    entry = bench_history.load_round(path)
    # a tail whose ONLY surviving fragment is the roofm pair is still a
    # recovered round, not "no result recovered"
    assert entry["status"] == "recovered_from_tail"
    assert entry["roofm"]["mnist_e2e"]["delta"] == 0.217


def test_history_renders_roofm_table_across_health_states(tmp_path):
    # r01: predates the pair entirely (headline only)
    _write_round(
        tmp_path,
        1,
        {
            "n": 1,
            "rc": 0,
            "parsed": {
                "metric": "samples_per_sec_per_chip",
                "value": 100.0,
            },
        },
    )
    # r02: device unreachable
    _write_round(
        tmp_path,
        2,
        {
            "n": 2,
            "rc": 1,
            "parsed": {
                "metric": "samples_per_sec_per_chip",
                "value": None,
                "error": "no TPU reachable",
            },
        },
    )
    # r03: compact round carrying the pair
    _write_round(
        tmp_path,
        3,
        {
            "n": 3,
            "rc": 0,
            "parsed": {
                "metric": "samples_per_sec_per_chip",
                "value": 120.0,
                "models": {
                    "mnist_e2e": {
                        "spsc": 120.0,
                        "roofm": 0.912,
                        "roofm0": 0.695,
                    }
                },
            },
        },
    )
    history = bench_history.build_history(str(tmp_path))
    assert history["roofm_models"] == ["mnist_e2e"]
    text = bench_history.format_history(history)
    assert "measured roofline ratio" in text
    assert "0.912/0.695 (+0.217)" in text
    # the pre-pair and unreachable rounds render "-" in the new table
    roofm_lines = [
        line
        for line in text.splitlines()
        if line.strip().startswith("mnist_e2e")
        and "0.912/0.695" in line
    ]
    assert len(roofm_lines) == 1
    assert roofm_lines[0].count("-") >= 2


def test_history_without_pairs_renders_no_roofm_table(tmp_path):
    _write_round(
        tmp_path,
        1,
        {
            "n": 1,
            "rc": 0,
            "parsed": {
                "metric": "samples_per_sec_per_chip",
                "value": 100.0,
                "models": {
                    "mnist_step": {"samples_per_sec_per_chip": 100.0}
                },
            },
        },
    )
    history = bench_history.build_history(str(tmp_path))
    assert history["roofm_models"] == []
    text = bench_history.format_history(history)
    assert "measured roofline ratio" not in text
