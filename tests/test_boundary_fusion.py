"""Boundary fusion (ISSUE 20): cross-task staging through one
persistent stager, the fused task loop's exactly-once discipline under
boundary-timed preemption, the tunable pipeline depth, the admission
degrade of the staging memory ledger, and the boundary_stall counter's
trip from heartbeat to the master's /metrics mirror.
"""

from __future__ import annotations

import logging
import time

import numpy as np
import pytest

from elasticdl_tpu.trainer import device_pipeline
from elasticdl_tpu.trainer.device_pipeline import (
    BOUNDARY_FUSION_ENV,
    DEVICE_PREFETCH_ENV,
    PIPELINE_DEPTH_ENV,
    STAGING_BUDGET_ENV,
    DeviceStager,
    TaskMark,
    resolve_boundary_fusion,
    resolve_pipeline_depth,
    run_pipelined_task_stream,
    stage_depth,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for env in (
        DEVICE_PREFETCH_ENV,
        BOUNDARY_FUSION_ENV,
        PIPELINE_DEPTH_ENV,
        STAGING_BUDGET_ENV,
    ):
        monkeypatch.delenv(env, raising=False)
    device_pipeline._reset_totals_for_tests()
    yield
    device_pipeline._reset_totals_for_tests()


class _LogCapture(logging.Handler):
    """default_logger doesn't propagate (stderr handler only), so
    caplog can't see it — attach directly."""

    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


@pytest.fixture()
def framework_log():
    from elasticdl_tpu.utils.log_utils import default_logger

    handler = _LogCapture()
    default_logger.addHandler(handler)
    yield handler
    default_logger.removeHandler(handler)


# ---- flag / env resolution ---------------------------------------------------


def test_resolve_boundary_fusion_flag_wins_env_falls_back(
    monkeypatch, framework_log
):
    assert resolve_boundary_fusion(None) is False
    assert resolve_boundary_fusion(True) is True
    assert resolve_boundary_fusion(False) is False
    monkeypatch.setenv(BOUNDARY_FUSION_ENV, "1")
    assert resolve_boundary_fusion(None) is True
    assert resolve_boundary_fusion(False) is False
    for falsey in ("0", "false", "no", "off", ""):
        monkeypatch.setenv(BOUNDARY_FUSION_ENV, falsey)
        assert resolve_boundary_fusion(None) is False
    assert not framework_log.records
    # a typo fails SAFE (off) and complains loudly
    monkeypatch.setenv(BOUNDARY_FUSION_ENV, "ture")
    assert resolve_boundary_fusion(None) is False
    assert any(
        r.levelno == logging.ERROR and BOUNDARY_FUSION_ENV in r.getMessage()
        for r in framework_log.records
    )


def test_resolve_pipeline_depth_flag_env_and_malformed(
    monkeypatch, framework_log
):
    assert resolve_pipeline_depth(None) == device_pipeline.RETIRE_WINDOW
    assert resolve_pipeline_depth(4) == 4
    assert resolve_pipeline_depth(0) == 1  # clamp, never a dead pipeline
    monkeypatch.setenv(PIPELINE_DEPTH_ENV, "3")
    assert resolve_pipeline_depth(None) == 3
    assert resolve_pipeline_depth(5) == 5  # flag still beats env
    assert not framework_log.records
    for bad in ("zero", "0", "-2", "2.5"):
        framework_log.records.clear()
        monkeypatch.setenv(PIPELINE_DEPTH_ENV, bad)
        # malformed env fails SAFE to the proven default, loudly
        assert (
            resolve_pipeline_depth(None) == device_pipeline.RETIRE_WINDOW
        )
        assert any(
            r.levelno == logging.ERROR
            and PIPELINE_DEPTH_ENV in r.getMessage()
            for r in framework_log.records
        )


def test_stage_depth_honors_pipeline_depth():
    assert stage_depth(None) == device_pipeline.RETIRE_WINDOW
    assert stage_depth(None, 4) == 4
    assert stage_depth(None, 1) == 1
    # --step_anatomy still wins: exact per-group walls need the barrier
    assert stage_depth(object(), 4) == 1


def test_new_flags_never_reach_worker_argv():
    from elasticdl_tpu.utils.args import (
        build_worker_arguments,
        parse_master_args,
    )

    base = [
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data",
        "/tmp/x",
    ]
    off = parse_master_args(base)
    on = parse_master_args(
        base
        + [
            "--device_prefetch",
            "true",
            "--boundary_fusion",
            "true",
            "--pipeline_depth",
            "4",
        ]
    )
    argv_off = build_worker_arguments(off, 0, "localhost:1")
    argv_on = build_worker_arguments(on, 0, "localhost:1")
    assert "--boundary_fusion" not in argv_on
    assert "--pipeline_depth" not in argv_on
    # the whole feature travels by env: worker argv stays byte-identical
    assert argv_on == argv_off


# ---- fused task stream: grouping, ordering, exactly-once ---------------------


class _FakeTrainer:
    """Host-only trainer double: real padding, identity placement."""

    step = 0

    def __init__(self):
        self.dispatched = []  # (kind, first feature value) per dispatch

    def pad_to(self, tree, rows):
        def _pad(x):
            x = np.asarray(x)
            if x.shape[0] == rows:
                return x
            return np.concatenate(
                [x, np.repeat(x[-1:], rows - x.shape[0], axis=0)]
            )

        import jax

        return jax.tree_util.tree_map(_pad, tree)

    def row_mask(self, n, rows):
        mask = np.zeros(rows, np.float32)
        mask[:n] = 1.0
        return mask

    def place_batch(self, tree):
        return tree

    def place_stacked(self, tree):
        return tree

    def train_step(self, f, l, w=None):
        self.dispatched.append(("single", float(np.asarray(f).flat[0])))
        return np.float32(0.0)

    def train_steps_stacked(self, f, l, w=None):
        self.dispatched.append(("stacked", float(np.asarray(f).flat[0])))
        return np.float32(0.0)


def _task_batches(tid, sizes):
    # every row of a task's features carries the task id, so a dispatch
    # record tells us exactly which task's data it consumed
    return [
        (
            np.full((n, 4), float(tid), np.float32),
            np.zeros((n,), np.int32),
        )
        for n in sizes
    ]


def _tasks(n_tasks, sizes):
    for tid in range(1, n_tasks + 1):
        yield tid, f"task-{tid}", iter(_task_batches(tid, sizes))


def test_task_stream_grouping_resets_per_task():
    """The END/START marks flush the producer's grouping, so a task's
    trailing odd batch NEVER stacks with the next task's first batch —
    the dispatch-shape sequence is identical to running each task
    through the serial loop."""
    trainer = _FakeTrainer()
    total = run_pipelined_task_stream(
        lambda: trainer,
        _tasks(3, [8, 8, 8]),
        2,
        canonical_rows=8,
    )
    assert total == 3 * 24
    # per task: one stacked [8,8] group + one trailing single — thrice
    assert trainer.dispatched == [
        ("stacked", 1.0),
        ("single", 1.0),
        ("stacked", 2.0),
        ("single", 2.0),
        ("stacked", 3.0),
        ("single", 3.0),
    ]


def test_task_stream_reports_exactly_once_in_order():
    trainer = _FakeTrainer()
    starts, dones = [], []
    total = run_pipelined_task_stream(
        lambda: trainer,
        _tasks(3, [8, 8]),
        2,
        canonical_rows=8,
        task_start=lambda tid, task: starts.append((tid, task)),
        task_done=lambda tid, task, n: dones.append((tid, task, n)),
    )
    assert total == 3 * 16
    assert starts == [(1, "task-1"), (2, "task-2"), (3, "task-3")]
    assert dones == [
        (1, "task-1", 16),
        (2, "task-2", 16),
        (3, "task-3", 16),
    ]


def test_task_stream_retires_window_before_reporting(monkeypatch):
    """Exactly-once across the async window: when task_done(tid) runs,
    every dispatch so far has retired — a report can never cover an
    un-retired group whose compute might still fail."""
    events = []
    real_block = device_pipeline.jax.block_until_ready

    def tracked_block(out):
        events.append(("retire",))
        return real_block(out)

    monkeypatch.setattr(
        device_pipeline.jax, "block_until_ready", tracked_block
    )

    class _Tracking(_FakeTrainer):
        def train_step(self, f, l, w=None):
            events.append(("dispatch",))
            return super().train_step(f, l, w)

        def train_steps_stacked(self, f, l, w=None):
            events.append(("dispatch",))
            return super().train_steps_stacked(f, l, w)

    trainer = _Tracking()
    run_pipelined_task_stream(
        lambda: trainer,
        _tasks(3, [8] * 6),
        2,
        canonical_rows=8,
        task_done=lambda tid, task, n: events.append(("done", tid)),
    )
    for i, event in enumerate(events):
        if event[0] == "done":
            before = events[:i]
            dispatched = sum(1 for e in before if e[0] == "dispatch")
            retired = sum(1 for e in before if e[0] == "retire")
            assert retired == dispatched, (
                f"task {event[1]} reported with "
                f"{dispatched - retired} un-retired dispatches"
            )
    assert [e[1] for e in events if e[0] == "done"] == [1, 2, 3]


def test_boundary_timed_preemption_discards_staged_groups(monkeypatch):
    """The reclaim fence exactly at a boundary: task N's report raises
    (lease reclaimed) AFTER its window retired and BEFORE task N+1's
    first dispatch — the already-staged next-task groups die un-taken
    (never dispatched, never reported), so a re-lease replays them from
    scratch without double-reporting task N."""
    stagers = []
    real_stager = device_pipeline.DeviceStager

    def capture(*args, **kwargs):
        stager = real_stager(*args, **kwargs)
        stagers.append(stager)
        return stager

    monkeypatch.setattr(device_pipeline, "DeviceStager", capture)

    trainer = _FakeTrainer()
    dones = []

    def task_done(tid, task, n):
        dones.append((tid, n))
        if tid == 2:
            raise RuntimeError("lease reclaimed")

    with pytest.raises(RuntimeError, match="lease reclaimed"):
        run_pipelined_task_stream(
            lambda: trainer,
            _tasks(4, [8, 8]),
            2,
            canonical_rows=8,
            task_done=task_done,
        )
    # tasks 1 and 2 reported exactly once; 3 and 4 never
    assert dones == [(1, 16), (2, 16)]
    # no group from task 3 or 4 was ever dispatched, even though the
    # stager was pre-staging them while task 2 computed
    assert {tag for _, tag in trainer.dispatched} == {1.0, 2.0}
    # the fused loop closed its stager on the way out: the producer is
    # dead and the staged-but-undispatched groups are unreachable
    for stager in stagers:
        stager._thread.join(timeout=5)
        assert not stager._thread.is_alive()


def test_task_stream_reraises_boundary_staging_errors():
    """A pad/place failure while staging ACROSS a boundary keeps the
    serial path's crash contract in the grouped runtimes: the error
    surfaces at the failed group's dispatch position (the worker's
    per-group serial fallback is pinned separately in its own loop)."""

    class _BadPadAfterFirstTask(_FakeTrainer):
        pads = 0

        def pad_to(self, tree, rows):
            type(self).pads += 1
            # task 1 is one full group (2 batches x features+labels =
            # 4 pads) on the serial warmup; every later pad happens on
            # the cross-task stager thread
            if type(self).pads > 4:
                raise ValueError("bad batch at the boundary")
            return super().pad_to(tree, rows)

    trainer = _BadPadAfterFirstTask()
    dones = []
    with pytest.raises(ValueError, match="bad batch at the boundary"):
        run_pipelined_task_stream(
            lambda: trainer,
            _tasks(3, [8, 8]),
            2,
            canonical_rows=8,
            task_done=lambda tid, task, n: dones.append(tid),
        )
    # task 1 completed and reported before the boundary stage failed;
    # task 2 never reported (its group never dispatched)
    assert dones == [1]
    assert {tag for _, tag in trainer.dispatched} == {1.0}


def test_worker_fused_feed_carries_non_training_tasks_as_payload():
    """The worker's fused stream routes non-training tasks AROUND the
    stager as an END-mark payload: the stager must hand marks through
    in stream order with the payload intact (the serial fallback at the
    boundary consumes it)."""
    marks = []
    batches = _task_batches(1, [8, 8])

    def feed():
        yield TaskMark(TaskMark.START, 1, "train")
        for item in batches:
            yield item
        yield TaskMark(TaskMark.END, 1, "train")
        yield TaskMark(TaskMark.END, 2, "eval", payload=["sentinel"])

    stager = DeviceStager(
        lambda: _FakeTrainer(), feed(), 2, canonical_rows=8
    )
    groups = 0
    try:
        while True:
            kind, payload = stager.next_event()
            if kind == device_pipeline._STAGE_KIND_DONE:
                break
            if kind == device_pipeline._STAGE_KIND_MARK:
                marks.append((payload.kind, payload.tid, payload.payload))
            else:
                groups += 1
    finally:
        stager.close()
    assert groups == 1  # [8,8] staged as one stacked group
    assert marks == [
        (TaskMark.START, 1, None),
        (TaskMark.END, 1, None),
        (TaskMark.END, 2, ["sentinel"]),
    ]


# ---- admission control (memory ledger) ---------------------------------------


def test_staging_budget_degrades_depth_to_one_loudly(
    monkeypatch, framework_log
):
    monkeypatch.setenv(STAGING_BUDGET_ENV, "1")
    stager = DeviceStager(
        lambda: _FakeTrainer(),
        iter(_task_batches(1, [8] * 6)),
        2,
        canonical_rows=8,
        depth=3,
    )
    try:
        groups = list(stager)
    finally:
        stager.close()
    assert len(groups) == 3  # every group still arrives, just serially
    assert stager._admitted == 1
    warnings = [
        r.getMessage()
        for r in framework_log.records
        if r.levelno == logging.WARNING
    ]
    assert any("degrading staging depth" in m for m in warnings)
    # loud but not noisy: the degrade logs ONCE for the stager's life
    assert (
        sum("degrading staging depth" in m for m in warnings) == 1
    )


def test_staging_budget_malformed_env_falls_back(
    monkeypatch, framework_log
):
    monkeypatch.setenv(STAGING_BUDGET_ENV, "lots")
    # malformed byte count: ERROR + headroom fallback, never a crash
    device_pipeline.staging_budget_bytes()
    assert any(
        r.levelno == logging.ERROR and STAGING_BUDGET_ENV in r.getMessage()
        for r in framework_log.records
    )


# ---- boundary_stall: counter -> heartbeat -> master mirror -------------------


def test_boundary_counters_unarmed_cost_nothing_armed_accumulate():
    # unarmed (no stager ever ran, no anatomy): pure gate, no totals
    device_pipeline.note_task_boundary()
    device_pipeline.note_boundary_dispatch()
    assert device_pipeline.heartbeat_snapshot() == {}
    # arm via staging activity, then measure one boundary gap
    device_pipeline._note_staged(0.0)
    device_pipeline.note_task_boundary()
    time.sleep(0.02)
    device_pipeline.note_boundary_dispatch()
    snap = device_pipeline.heartbeat_snapshot()
    assert set(snap) == {
        "groups",
        "stall_ms",
        "stage_ms",
        "boundaries",
        "boundary_stall_ms",
    }
    assert snap["boundaries"] == 1
    assert snap["boundary_stall_ms"] >= 10
    # a dispatch with no pending mark adds nothing
    device_pipeline.note_boundary_dispatch()
    assert device_pipeline.heartbeat_snapshot()["boundaries"] == 1
    # clear disarms a pending mark (end of run): no phantom boundary
    device_pipeline.note_task_boundary()
    device_pipeline.clear_boundary_mark()
    device_pipeline.note_boundary_dispatch()
    assert device_pipeline.heartbeat_snapshot()["boundaries"] == 1


def _servicer():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    shards = {"s": (0, 8)}
    return MasterServicer(4, TaskDispatcher(shards, records_per_task=4))


def test_master_mirrors_boundary_stall_counter():
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry.master_hooks import MasterTelemetry

    servicer = _servicer()
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            step=1,
            prefetch={
                "groups": 7,
                "stall_ms": 3,
                "stage_ms": 29,
                "boundaries": 4,
                "boundary_stall_ms": 57,
            },
        )
    )
    totals = servicer.prefetch_stats_totals()
    assert totals["boundaries"] == 4
    assert totals["boundary_stall_ms"] == 57
    telemetry = MasterTelemetry()
    telemetry._servicer = servicer
    text = telemetry.registry.exposition()
    assert "elasticdl_boundary_stall_ms_total 57" in text


# ---- LocalExecutor e2e: fused-vs-off bit-exact parity ------------------------


def test_local_executor_fused_parity_bitexact(tmp_path):
    """The whole fused path (reader -> decode -> TaskPrefetcher ->
    cross-task stager -> fused dispatch loop) is bit-identical to the
    serial path across FOUR task boundaries: same step program, same
    grouping, same pinned shuffle — only the boundary discipline
    differs."""
    import jax as _jax

    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    train_dir = synthetic.gen_mnist(
        str(tmp_path / "train"), num_records=256, num_shards=2, seed=0
    )

    def run(fused: str):
        args = parse_master_args(
            [
                "--model_def",
                "mnist_functional_api.mnist_functional_api.custom_model",
                "--training_data",
                train_dir,
                "--minibatch_size",
                "32",
                "--records_per_task",
                "64",
                "--num_epochs",
                "1",
                "--compute_dtype",
                "float32",
                "--steps_per_dispatch",
                "2",
                "--shuffle_seed",
                "7",
                "--device_prefetch",
                fused,
                "--boundary_fusion",
                fused,
            ]
        )
        ex = LocalExecutor(args)
        ex.run()
        return _jax.device_get(ex.state.params), int(ex.state.step)

    params_off, steps_off = run("false")
    params_on, steps_on = run("true")
    assert steps_off == steps_on == 8
    for x, y in zip(
        _jax.tree_util.tree_leaves(params_off),
        _jax.tree_util.tree_leaves(params_on),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
