"""Multi-process lockstep SPMD training: the N-workers-one-model bar.

Reference quality bar (worker_ps_interaction_test.py): parameters trained
through the distributed path must match a local run on the same data.
Here the bar is strictly stronger: ≥2 REAL worker processes joined in one
``jax.distributed`` world must produce

1. bitwise-identical final parameters on every process (they hold the
   same replicated state, updated by the same collectives), and
2. final parameters matching a single-process run on the same data/seed
   (tolerance-level: 1-device vs 2-device reduction orders differ).

The elasticity test kills one of the worker processes mid-epoch
(reference k8s_instance_manager_test.py really deletes pods) and asserts
the job completes with all records accounted and a measured re-formation
latency.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.utils.args import parse_master_args

# Worker subprocesses must see exactly ONE cpu device each (the conftest's
# 8-device XLA_FLAGS would give every process 8) and must not inherit any
# TPU platform plugin preference.
_WORKER_ENVS = "JAX_PLATFORMS=cpu,XLA_FLAGS= "


def _master_args(
    train_dir,
    extra,
    model_def="mnist_functional_api.mnist_functional_api.custom_model",
    envs=_WORKER_ENVS,
):
    return parse_master_args(
        [
            "--model_def",
            model_def,
            "--training_data",
            train_dir,
            "--minibatch_size",
            "32",
            "--compute_dtype",
            "float32",
            "--shuffle_seed",
            "11",
            "--distribution_strategy",
            "AllreduceStrategy",
            "--jax_platform",
            "cpu",
            "--envs",
            envs,
            "--port",
            "0",
            *extra,
        ]
    )


def _run_master(args):
    from elasticdl_tpu.master.main import main as master_main
    from elasticdl_tpu.utils.args import build_arguments_from_parsed_result

    return master_main(build_arguments_from_parsed_result(args))


def _load_identical_final_states(dump_dir):
    """Both processes' dumps must be bitwise-identical (replicated state
    after identical collectives: exact); returns process 0's dump."""
    p0 = np.load(os.path.join(dump_dir, "final_state_p0.npz"))
    p1 = np.load(os.path.join(dump_dir, "final_state_p1.npz"))
    assert set(p0.files) == set(p1.files) and p0.files
    for key in p0.files:
        assert np.array_equal(p0[key], p1[key]), key
    return p0


@pytest.mark.slow
def test_two_process_lockstep_matches_single_process(tmp_path, monkeypatch):
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=192, num_shards=2, seed=3
    )
    dump_dir = str(tmp_path / "dump")
    monkeypatch.setenv("ELASTICDL_TPU_DUMP_STATE", dump_dir)

    args = _master_args(
        train, ["--num_workers", "2", "--records_per_task", "96"]
    )
    assert _run_master(args) == 0

    p0 = _load_identical_final_states(dump_dir)

    # single-process comparison on the SAME data and task order
    monkeypatch.delenv("ELASTICDL_TPU_DUMP_STATE")
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.trainer.state import state_to_checkpoint

    local_args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "32",
            "--records_per_task",
            "96",
            "--compute_dtype",
            "float32",
            "--shuffle_seed",
            "11",
        ]
    )
    executor = LocalExecutor(local_args)
    executor.run()
    local = state_to_checkpoint(executor.state)
    for key in p0.files:
        # tolerance covers 8-device (LocalExecutor SPMD over the virtual
        # mesh) vs 2-device reduction-order noise amplified through
        # BatchNorm over 6 steps; a data-partitioning bug (each worker
        # training on half the data) shows up as O(1e-1) divergence and
        # still fails loudly
        np.testing.assert_allclose(
            np.asarray(local[key], dtype=np.float64),
            np.asarray(p0[key], dtype=np.float64),
            rtol=5e-3,
            atol=3e-2,
            err_msg=key,
        )


@pytest.mark.slow
def test_lockstep_sharded_table_checkpoint_and_resume(tmp_path):
    """2 processes x 2 devices, mesh dp=2,ep=2: the deepfm tables shard
    over ep WITHIN each process while dp REPLICATES them across processes
    — the layout where per-part checkpointing must dedupe writers (only
    the lowest owning process writes a range) and restore must place each
    process's rows without materializing full tables.  Run 1 writes
    2-part checkpoints; run 2 resumes from them."""
    train = synthetic.gen_frappe(
        str(tmp_path / "t"), num_records=256, num_shards=2, seed=4
    )
    ckpt_dir = str(tmp_path / "ckpt")
    extra = [
        "--num_workers",
        "2",
        "--records_per_task",
        "128",
        "--mesh_shape",
        "dp=2,ep=2",
        "--checkpoint_dir",
        ckpt_dir,
        "--checkpoint_steps",
        "2",
    ]
    deepfm = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    envs2 = "JAX_PLATFORMS=cpu,XLA_FLAGS=--xla_force_host_platform_device_count=2"
    args = _master_args(train, extra, model_def=deepfm, envs=envs2)
    assert _run_master(args) == 0

    versions = sorted(
        d for d in os.listdir(ckpt_dir) if d.startswith("version-")
    )
    assert versions
    latest = os.path.join(ckpt_dir, versions[-1])
    names = sorted(os.listdir(latest))
    assert "variables-0-of-2.npz" in names and "variables-1-of-2.npz" in names
    # both table parts together cover each padded table exactly once
    from elasticdl_tpu.utils import save_utils

    dense, embeddings, _ = save_utils.restore_checkpoint(ckpt_dir)
    tables = save_utils.assemble_embedding_tables(embeddings)
    assert tables, "expected sharded tables in the checkpoint"

    # run 2: same world, resumes from the checkpoint (multi-process
    # row-sliced restore) and completes
    args2 = _master_args(train, extra, model_def=deepfm, envs=envs2)
    assert _run_master(args2) == 0
    versions2 = sorted(
        int(d.split("-", 1)[1])
        for d in os.listdir(ckpt_dir)
        if d.startswith("version-")
    )
    # resumed step counter keeps counting up from run 1's final version
    assert versions2[-1] > int(versions[-1].split("-", 1)[1])


@pytest.mark.slow
def test_lockstep_ring_attention_across_processes(tmp_path, monkeypatch):
    """Multi-HOST long context: 2 worker processes, mesh dp=1,sp=2 — the
    sequence dimension spans the PROCESS boundary, so ring attention's
    ppermute hops ride the cross-process collective transport (gloo here,
    ICI/DCN on pods).  Both processes must finish with bitwise-identical
    replicated parameters."""
    train = synthetic.gen_sequence(
        str(tmp_path / "t"), num_records=64, num_shards=1, seq_len=32, seed=6
    )
    dump_dir = str(tmp_path / "dump")
    monkeypatch.setenv("ELASTICDL_TPU_DUMP_STATE", dump_dir)
    args = _master_args(
        train,
        [
            "--num_workers",
            "2",
            "--records_per_task",
            "32",
            "--mesh_shape",
            "dp=1,sp=2",
        ],
        model_def="long_seq_transformer.long_seq_transformer.custom_model",
    )
    assert _run_master(args) == 0

    p0 = _load_identical_final_states(dump_dir)
    for key in p0.files:
        assert np.isfinite(p0[key]).all(), key


@pytest.mark.slow
def test_lockstep_worker_kill_reforms_and_completes(tmp_path):
    """SIGKILL one of 2 workers mid-run; the master must re-form the world
    and finish the job with every record accounted (reference behavior:
    k8s_instance_manager.py:241-275 + task_dispatcher.py:299-309)."""
    from elasticdl_tpu.master.main import build_master

    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=384, num_shards=2, seed=5
    )
    args = _master_args(
        train,
        [
            "--num_workers",
            "2",
            "--records_per_task",
            "64",
            "--num_epochs",
            "2",
            "--checkpoint_dir",
            str(tmp_path / "ckpt"),
            "--checkpoint_steps",
            "2",
            "--heartbeat_timeout_secs",
            "5",
        ],
    )
    master = build_master(args)
    master.prepare()
    rc: list[int] = []
    runner = threading.Thread(target=lambda: rc.append(master.run()))
    runner.start()
    try:
        # wait for real progress: a checkpoint version on disk
        deadline = time.monotonic() + 300
        ckpt_dir = str(tmp_path / "ckpt")
        while time.monotonic() < deadline:
            if os.path.isdir(ckpt_dir) and any(
                name.startswith("version-") for name in os.listdir(ckpt_dir)
            ):
                break
            time.sleep(0.5)
        else:
            pytest.fail("no checkpoint appeared; job never progressed")

        victims = master.instance_manager.worker_ids()
        assert len(victims) == 2
        victim_proc = master.instance_manager._procs[victims[-1]]
        os.kill(victim_proc.pid, signal.SIGKILL)

        runner.join(timeout=600)
        assert not runner.is_alive(), "master never finished after the kill"
    finally:
        master.request_stop()
        runner.join(timeout=30)

    assert rc == [0]
    assert master.task_d.finished()
    from elasticdl_tpu.utils.constants import TaskType

    train_counters = master.task_d.counters(TaskType.TRAINING)
    # 2 epochs x 384 records, created once per epoch; recovery re-queues
    # WITHOUT re-counting, so the total must be exact
    assert train_counters.total_records == 768
    assert master.reform_events, "worker kill never triggered a re-formation"
    assert master.reform_events[0]["latency_secs"] > 0


@pytest.mark.slow
def test_two_process_lockstep_stacked_dispatch(tmp_path, monkeypatch):
    """--steps_per_dispatch in a REAL 2-process world: both processes
    compute the same grouping from the same deterministic batch stream,
    the scanned dispatch carries the same collectives, and the final
    parameters are bitwise-identical across processes (the lockstep
    invariant) and close to the per-step run (same updates, different
    program fusion)."""
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=192, num_shards=2, seed=3
    )
    dump_dir = str(tmp_path / "dump_stacked")
    monkeypatch.setenv("ELASTICDL_TPU_DUMP_STATE", dump_dir)
    args = _master_args(
        train,
        [
            "--num_workers",
            "2",
            "--records_per_task",
            "96",
            "--steps_per_dispatch",
            "3",  # 96 records / 32 batch = 3 steps -> one dispatch/task
        ],
    )
    assert _run_master(args) == 0
    stacked = _load_identical_final_states(dump_dir)

    dump_dir2 = str(tmp_path / "dump_perstep")
    monkeypatch.setenv("ELASTICDL_TPU_DUMP_STATE", dump_dir2)
    args = _master_args(
        train, ["--num_workers", "2", "--records_per_task", "96"]
    )
    assert _run_master(args) == 0
    per_step = _load_identical_final_states(dump_dir2)

    for key in stacked.files:
        # cross-PROGRAM comparison: same updates, different fusion, the
        # float noise amplified through BatchNorm over 6 steps — same
        # tolerance as the 2-process-vs-single comparison above.  (The
        # lockstep invariant itself — bitwise-identical params ACROSS
        # PROCESSES — was already asserted exactly by
        # _load_identical_final_states for both runs.)  A grouping bug
        # (processes disagreeing on batches) is O(1e-1) and still fails.
        np.testing.assert_allclose(
            np.asarray(stacked[key], dtype=np.float64),
            np.asarray(per_step[key], dtype=np.float64),
            rtol=5e-3,
            atol=3e-2,
            err_msg=key,
        )
