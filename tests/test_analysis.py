"""elastic-lint (elasticdl_tpu.analysis): falsification + waiver tests.

Every checker must be PROVEN falsifiable: a fixture tree seeded with
one violation per checker (tests/testdata/analysis_fixtures/) must
yield rc 1 naming that checker, a clean fixture must yield rc 0, and a
waiver must round-trip (matching waiver silences the finding; a stale
or reason-less waiver is itself a finding).  The repo itself must be
clean — the same gate scripts/run_tier1.sh enforces.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(
    REPO_ROOT, "tests", "testdata", "analysis_fixtures"
)
NO_WAIVERS = os.path.join(FIXTURES, "does_not_exist.toml")


def run_on_fixture(name: str, waivers_path: str = NO_WAIVERS):
    from elasticdl_tpu.analysis import run_analysis

    root = os.path.join(FIXTURES, name)
    return run_analysis(paths=[root], root=root, waivers_path=waivers_path)


def unwaived_by_checker(result: dict) -> dict[str, list[dict]]:
    grouped: dict[str, list[dict]] = {}
    for finding in result["findings"]:
        if not finding["waived"]:
            grouped.setdefault(finding["checker"], []).append(finding)
    return grouped


# ---- falsification: each seeded fixture trips exactly its checker ----------


@pytest.mark.parametrize(
    "fixture, checker, expected_symbols",
    [
        (
            "lock_violation",
            "lock-discipline",
            # sneaky pins the escape-hatch grammar: prose mentioning
            # "(single-threaded ...)" inside a lock-holding comment, or
            # a lock-holding for a DIFFERENT lock, must not exempt
            {
                "Store.drop:_items",
                "Store.bump:_count",
                "Store.sneaky:_items",
            },
        ),
        (
            "rpc_violation",
            "rpc-contract",
            {
                "connect:FixtureClient",
                "_METHODS:brand_new_unclassified_call",
                "RETRYABLE_METHODS:forbidden_call",
            },
        ),
        (
            "flag_violation",
            "flag-hygiene",
            {"new_feature", "leaky_master_knob", "removed_long_ago"},
        ),
        (
            "hot_violation",
            "hot-path",
            # decorated_gate pins annotation detection on decorated defs
            {"record_step:clock", "decorated_gate:alloc"},
        ),
        (
            "thread_violation",
            "thread-discipline",
            {"fire_and_forget:orphan"},
        ),
        (
            "telemetry_violation",
            "telemetry-names",
            {"metric:BadCamelName", "multisite:metric:twice_registered"},
        ),
    ],
)
def test_seeded_violation_trips_its_checker(fixture, checker, expected_symbols):
    result = run_on_fixture(fixture)
    assert not result["ok"]
    grouped = unwaived_by_checker(result)
    assert checker in grouped, grouped
    symbols = {f["symbol"] for f in grouped[checker]}
    assert expected_symbols <= symbols, symbols


def test_hot_fixture_also_catches_stray_print():
    grouped = unwaived_by_checker(run_on_fixture("hot_violation"))
    assert any(
        f["symbol"].startswith("print:") for f in grouped["hot-path"]
    )


def test_clean_fixture_passes():
    result = run_on_fixture("clean")
    assert result["ok"], result["findings"]
    assert result["unwaived"] == 0


def test_lock_fixture_clean_file_not_flagged():
    """The lock-holding / with-lock patterns in the clean sibling file
    produce nothing — only the seeded violations fire."""
    grouped = unwaived_by_checker(run_on_fixture("lock_violation"))
    assert all(
        f["path"] == "store.py" for f in grouped["lock-discipline"]
    )


# ---- waivers ---------------------------------------------------------------


def _write_waiver(tmp_path, body: str) -> str:
    path = str(tmp_path / "waivers.toml")
    with open(path, "w", encoding="utf-8") as f:
        f.write(body)
    return path


WAIVE_ALL = """
[[waiver]]
checker = "lock-discipline"
path = "store.py"
symbol = "Store.drop:_items"
reason = "fixture: exercised by the waiver round-trip test"

[[waiver]]
checker = "lock-discipline"
path = "store.py"
symbol = "Store.bump:_count"
reason = "fixture: exercised by the waiver round-trip test"

[[waiver]]
checker = "lock-discipline"
path = "store.py"
symbol = "Store.sneaky:_items"
reason = "fixture: exercised by the waiver round-trip test"
"""


def test_waiver_round_trip(tmp_path):
    """A matching waiver silences the finding (rc 0), and the waived
    findings are still carried in the result, marked."""
    waivers = _write_waiver(tmp_path, WAIVE_ALL)
    result = run_on_fixture("lock_violation", waivers_path=waivers)
    assert result["ok"], result["findings"]
    assert result["waived"] == 3
    assert {f["symbol"] for f in result["findings"] if f["waived"]} == {
        "Store.drop:_items",
        "Store.bump:_count",
        "Store.sneaky:_items",
    }


def test_stale_waiver_is_a_finding(tmp_path):
    waivers = _write_waiver(
        tmp_path,
        WAIVE_ALL
        + """
[[waiver]]
checker = "lock-discipline"
path = "store.py"
symbol = "Store.gone:_items"
reason = "this finding no longer exists"
""",
    )
    result = run_on_fixture("lock_violation", waivers_path=waivers)
    assert not result["ok"]
    grouped = unwaived_by_checker(result)
    assert "waiver-hygiene" in grouped
    assert any(
        "stale waiver" in f["message"] for f in grouped["waiver-hygiene"]
    )


def test_waiver_without_reason_is_a_finding(tmp_path):
    waivers = _write_waiver(
        tmp_path,
        """
[[waiver]]
checker = "lock-discipline"
path = "store.py"
symbol = "Store.drop:_items"
reason = ""
""",
    )
    result = run_on_fixture("lock_violation", waivers_path=waivers)
    grouped = unwaived_by_checker(result)
    assert any(
        "missing required non-empty" in f["message"]
        for f in grouped.get("waiver-hygiene", ())
    )
    # and the waiver does NOT apply
    assert "lock-discipline" in grouped


def test_unparseable_waiver_line_is_loud(tmp_path):
    waivers = _write_waiver(
        tmp_path, "[[waiver]]\nchecker = unquoted_value\n"
    )
    result = run_on_fixture("clean", waivers_path=waivers)
    grouped = unwaived_by_checker(result)
    assert any(
        "unparseable" in f["message"]
        for f in grouped.get("waiver-hygiene", ())
    )


# ---- the repo itself is clean (the tier-1 gate) -----------------------------


def test_repo_has_zero_unwaived_findings():
    from elasticdl_tpu.analysis import run_analysis

    result = run_analysis()
    unwaived = [f for f in result["findings"] if not f["waived"]]
    assert result["ok"], "\n".join(
        f"{f['path']}:{f['line']} [{f['checker']}] {f['symbol']}: {f['message']}"
        for f in unwaived
    )


def test_every_rpc_method_is_classified():
    """The real method tables and the real registry agree — the
    new-method-fails-until-classified contract, pinned from the Python
    side too (the analyzer pins it from the AST side)."""
    from elasticdl_tpu.replication.service import REPLICA_METHODS
    from elasticdl_tpu.rpc.deadline import STATE_TRANSFER_METHODS
    from elasticdl_tpu.rpc.idempotency import IDEMPOTENCY
    from elasticdl_tpu.rpc.retry import DEFAULT_IDEMPOTENT
    from elasticdl_tpu.rpc.service import _METHODS, MASTER_RETRYABLE_METHODS

    for method in (
        set(_METHODS)
        | set(REPLICA_METHODS)
        | set(MASTER_RETRYABLE_METHODS)
        | set(DEFAULT_IDEMPOTENT)
        | set(STATE_TRANSFER_METHODS)
    ):
        assert method in IDEMPOTENCY, method
        classification, why = IDEMPOTENCY[method]
        assert classification and why
    retryable = set(MASTER_RETRYABLE_METHODS) | set(DEFAULT_IDEMPOTENT)
    for method in retryable:
        assert IDEMPOTENCY[method][0] != "not-retryable", method


# ---- CLI --------------------------------------------------------------------


def test_cli_json_and_artifact(tmp_path):
    artifact = str(tmp_path / "analysis_result.json")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.analysis",
            "--json",
            "--output",
            artifact,
            "--root",
            os.path.join(FIXTURES, "thread_violation"),
            "--waivers",
            NO_WAIVERS,
            os.path.join(FIXTURES, "thread_violation"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    result = json.loads(proc.stdout)
    assert result["unwaived"] == 1
    assert result["findings"][0]["checker"] == "thread-discipline"
    # the human rendering went to stderr, not into the JSON stream
    assert "thread-discipline" in proc.stderr
    with open(artifact, encoding="utf-8") as f:
        assert json.load(f) == result


def test_cli_checker_subset():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.analysis",
            "--checkers",
            "telemetry-names",
            "--root",
            os.path.join(FIXTURES, "thread_violation"),
            "--waivers",
            NO_WAIVERS,
            os.path.join(FIXTURES, "thread_violation"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    # the thread violation is invisible to the telemetry-names checker
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_unknown_checker_fails():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "elasticdl_tpu.analysis",
            "--checkers",
            "no-such-checker",
            "--waivers",
            NO_WAIVERS,
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "unknown checker" in proc.stdout + proc.stderr


def test_parse_error_is_a_finding(tmp_path):
    from elasticdl_tpu.analysis import run_analysis

    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n")
    result = run_analysis(
        paths=[str(tmp_path)], root=str(tmp_path), waivers_path=NO_WAIVERS
    )
    assert not result["ok"]
    assert result["findings"][0]["checker"] == "parse-error"


# ---- the shared monotone max-merge helper (ISSUE 11 satellite) --------------


def test_max_merge_counters_monotone_and_watch():
    from elasticdl_tpu.utils.merge import max_merge_counters

    merged: dict[str, int] = {}
    rose = max_merge_counters(
        merged, {"retries": 3, "deadline_exceeded": 1}, watch={"deadline_exceeded"}
    )
    assert rose and merged == {"retries": 3, "deadline_exceeded": 1}
    # a stale (reordered) beat can never walk a counter backward
    rose = max_merge_counters(
        merged, {"retries": 1, "deadline_exceeded": 1}, watch={"deadline_exceeded"}
    )
    assert not rose
    assert merged == {"retries": 3, "deadline_exceeded": 1}
    # malformed values are skipped, not fatal
    rose = max_merge_counters(
        merged, {"retries": "junk", "unavailable": 2}, watch={"unavailable"}
    )
    assert rose and merged["unavailable"] == 2 and merged["retries"] == 3


def test_max_merge_phase_stats_nested_monotone():
    from elasticdl_tpu.utils.merge import max_merge_phase_stats

    merged: dict[str, dict] = {}
    max_merge_phase_stats(
        merged,
        {
            "device_compute": {
                "ms": 10.0,
                "count": 4,
                "buckets": {"0.1": 4},
            }
        },
    )
    max_merge_phase_stats(
        merged,
        {
            "device_compute": {"ms": 8.0, "count": 3, "buckets": {"0.1": 3}},
            "h2d_transfer": {"ms": 1.5, "count": 4, "buckets": {}},
            "garbage": "not-a-dict",
        },
    )
    assert merged["device_compute"] == {
        "ms": 10.0,
        "count": 4,
        "buckets": {"0.1": 4},
    }
    assert merged["h2d_transfer"]["ms"] == 1.5
    assert "garbage" not in merged


def test_servicer_heartbeat_uses_shared_merge():
    """End-to-end pin: reordered heartbeats cannot walk the servicer's
    exposed totals backward (the shared-rule consumers)."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.rpc import messages as msg

    servicer = MasterServicer(
        minibatch_size=4,
        task_dispatcher=TaskDispatcher({"s": (0, 8)}, records_per_task=8),
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=0, rpc={"retries": 5})
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(worker_id=0, rpc={"retries": 2})
    )
    assert servicer.rpc_stats_totals()["retries"] == 5
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            phases={"assemble": {"ms": 7.0, "count": 2, "buckets": {}}},
        )
    )
    servicer.heartbeat(
        msg.HeartbeatRequest(
            worker_id=0,
            phases={"assemble": {"ms": 6.0, "count": 1, "buckets": {}}},
        )
    )
    assert servicer.phase_stats_totals()["assemble"]["ms"] == 7.0
