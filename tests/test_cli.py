"""CLI surface: train/evaluate/predict/clean subcommands
(reference client.py:13-47 + the client_test.sh end-to-end pattern)."""

import numpy as np
import pytest

from elasticdl_tpu import api
from elasticdl_tpu.client import main as cli_main
from elasticdl_tpu.data.recordio_gen import synthetic


def _common(model="mnist_functional_api.mnist_functional_api.custom_model"):
    return [
        "--model_def",
        model,
        "--minibatch_size",
        "16",
        "--records_per_task",
        "32",
        "--compute_dtype",
        "float32",
        "--distribution_strategy",
        "Local",
    ]


def test_cli_train_local(tmp_path):
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    rc = cli_main(
        [
            "train",
            *_common(),
            "--training_data",
            train,
            "--checkpoint_dir",
            str(tmp_path / "ckpt"),
            "--checkpoint_steps",
            "2",
        ]
    )
    assert rc == 0
    import os

    assert any(
        d.startswith("version-") for d in os.listdir(str(tmp_path / "ckpt"))
    )


def test_cli_evaluate_from_checkpoint(tmp_path):
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    rc = cli_main(
        [
            "train",
            *_common(),
            "--training_data",
            train,
            "--checkpoint_dir",
            str(tmp_path / "ckpt"),
            "--checkpoint_steps",
            "2",
        ]
    )
    assert rc == 0
    evald = synthetic.gen_mnist(
        str(tmp_path / "e"), num_records=32, num_shards=1, seed=1
    )
    import os

    versions = sorted(os.listdir(str(tmp_path / "ckpt")))
    rc = cli_main(
        [
            "evaluate",
            *_common(),
            "--validation_data",
            evald,
            "--checkpoint_dir_for_init",
            str(tmp_path / "ckpt" / versions[-1]),
        ]
    )
    assert rc == 0


def test_cli_predict(tmp_path):
    pred = synthetic.gen_mnist(
        str(tmp_path / "p"), num_records=32, num_shards=1, seed=2
    )
    rc = cli_main(["predict", *_common(), "--prediction_data", pred])
    assert rc == 0


def test_cli_clean_without_docker():
    import argparse

    result = api.clean(argparse.Namespace(docker_image_repository="", all=False))
    assert "removed" in result


def test_cli_rejects_unknown_command():
    assert cli_main(["frobnicate"]) == 2
    assert cli_main([]) == 2
    assert cli_main(["--help"]) == 0


def test_api_validates_required_data(tmp_path):
    from elasticdl_tpu.utils.args import parse_master_args

    args = parse_master_args(_common())
    with pytest.raises(ValueError, match="training_data"):
        api.train(args)
    with pytest.raises(ValueError, match="validation_data"):
        api.evaluate(args)
    with pytest.raises(ValueError, match="prediction_data"):
        api.predict(args)


@pytest.mark.slow
def test_cli_distributed_train(tmp_path):
    """AllreduceStrategy routes through the master + subprocess workers
    (the client_test.sh analogue, minikube collapsed to localhost)."""
    train = synthetic.gen_mnist(
        str(tmp_path / "t"), num_records=64, num_shards=1, seed=0
    )
    rc = cli_main(
        [
            "train",
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "16",
            "--records_per_task",
            "32",
            "--compute_dtype",
            "float32",
            "--distribution_strategy",
            "AllreduceStrategy",
            "--num_workers",
            "2",  # REAL multi-process: 2 workers, one lockstep world
            "--jax_platform",
            "cpu",
            "--envs",
            "JAX_PLATFORMS=cpu,XLA_FLAGS= ",
            "--port",
            "0",
            "--output",
            str(tmp_path / "export"),
        ]
    )
    assert rc == 0
    from elasticdl_tpu.utils.export_utils import load_exported_model

    model, flat, _ = load_exported_model(str(tmp_path / "export"))
    assert flat
