"""Attention stack: pallas flash kernel, ring attention over sp, and the
long-context transformer model (no reference counterpart — long-context
sequence parallelism is a first-class TPU-build capability).

All kernel tests compare against the jnp oracle ``mha_reference``; ring
attention runs on the virtual 8-device mesh with the sequence sharded
over sp (the pallas kernel runs in interpreter mode on CPU — same code
path the TPU compiles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.attention import (
    attention,
    flash_attention,
    mha_reference,
    set_attention_mesh,
)
from elasticdl_tpu.ops.ring_attention import ring_attention
from elasticdl_tpu.parallel.mesh import MeshConfig


@pytest.fixture(autouse=True)
def _reset_attention_mesh():
    yield
    set_attention_mesh(None)


def _qkv(b=2, s=128, h=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, s, h, d).astype(np.float32)  # noqa: E731
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_gradients_match_reference():
    """custom_vjp: pallas kernels in both directions must produce the
    same gradients as differentiating the oracle directly."""
    q, k, v = _qkv(b=1, s=64, h=2, d=16)

    def loss_fl(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


@pytest.mark.parametrize(
    "s,h,kvh,causal,bq,bk",
    [
        (64, 4, 4, False, 32, 32),   # multi-block, MHA
        (64, 4, 4, True, 32, 32),    # causal block skipping (both kernels)
        (128, 4, 2, True, 32, 32),   # GQA group 2: dk/dv group-sum
        (96, 6, 2, False, 32, 32),   # GQA group 3, non-pow2 seq
        (64, 2, 1, True, 32, 16),    # MQA, uneven q/k blocks
    ],
)
def test_flash_backward_kernels_blockwise(s, h, kvh, causal, bq, bk):
    """The dQ and dK/dV pallas kernels against jax.vjp of the oracle —
    per-cotangent (not just a scalar loss), across block layouts and
    GQA groupings.  Tolerances span the kernels' matmul-precision
    envelope (same order as the forward's)."""
    rng = np.random.RandomState(7)
    d = 16
    q = jnp.asarray(rng.randn(2, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(2, s, kvh, d), jnp.float32)
    v = jnp.asarray(rng.randn(2, s, kvh, d), jnp.float32)
    g = jnp.asarray(rng.randn(2, s, h, d), jnp.float32)

    _, vjp_fl = jax.vjp(
        lambda q, k, v: flash_attention(
            q, k, v, causal, None, bq, bk
        ),
        q, k, v,
    )
    _, vjp_ref = jax.vjp(
        lambda q, k, v: mha_reference(q, k, v, causal), q, k, v
    )
    for got, want, name in zip(vjp_fl(g), vjp_ref(g), "qkv"):
        np.testing.assert_allclose(
            np.asarray(got),
            np.asarray(want),
            atol=2e-2,
            rtol=2e-2,
            err_msg=f"d{name} s={s} h={h} kvh={kvh} causal={causal}",
        )


def test_flash_handles_non_divisible_blocks():
    # seq 96 with preferred block 128 -> _pick_block falls back to a divisor
    q, k, v = _qkv(s=96)
    out = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_gqa_all_implementations_agree():
    """Grouped-query attention (kv heads < q heads): flash, ring, and
    ulysses all match the oracle computed with repeated KV heads."""
    from elasticdl_tpu.ops.ulysses import ulysses_attention

    rng = np.random.RandomState(3)
    q = rng.randn(2, 64, 8, 16).astype(np.float32)
    k = rng.randn(2, 64, 2, 16).astype(np.float32)  # 2 kv heads, group 4
    v = rng.randn(2, 64, 2, 16).astype(np.float32)
    ref = mha_reference(q, k, v, causal=True)

    fl = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(fl), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    ring = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    uly = ulysses_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(uly), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    # ulysses' small-kv path: kv heads divide sp, so the un-repeated kv
    # rides the all_to_all and flash's GQA indexing runs per shard
    k4 = rng.randn(2, 64, 4, 16).astype(np.float32)
    v4 = rng.randn(2, 64, 4, 16).astype(np.float32)
    ref4 = mha_reference(q, k4, v4, causal=True)
    uly4 = ulysses_attention(q, k4, v4, mesh=mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(uly4), np.asarray(ref4), atol=2e-5, rtol=2e-5
    )


def test_gqa_gradients_and_transformer_on_sp_mesh():
    """GQA flash gradients match differentiating the oracle, and a GQA
    transformer trains end-to-end with ring attention on an sp mesh."""
    rng = np.random.RandomState(4)
    q = rng.randn(1, 32, 4, 8).astype(np.float32)
    k = rng.randn(1, 32, 2, 8).astype(np.float32)
    v = rng.randn(1, 32, 2, 8).astype(np.float32)
    g_fl = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (mha_reference(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_fl, g_ref):
        assert a.shape == b.shape  # kv grads keep the GQA shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )

    import optax

    from elasticdl_tpu.models import long_seq_transformer as lm
    from elasticdl_tpu.parallel.distributed import SPMDTrainer

    feats = {"tokens": rng.randint(0, 64, (4, 32)).astype(np.int32)}
    labels = rng.randint(0, 64, (4, 32)).astype(np.int32)
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    trainer = SPMDTrainer(
        mesh,
        lm.custom_model(
            vocab_size=64,
            num_layers=1,
            embed_dim=32,
            num_heads=4,
            num_kv_heads=2,
        ),
        lm.loss,
        optax.adam(3e-3),
        feats,
    )
    losses = [
        float(
            trainer.train_step(
                trainer.place_batch(feats), trainer.place_batch(labels)
            )["loss"]
        )
        for _ in range(4)
    ]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_gqa_rejects_indivisible_heads():
    q, k, v = _qkv(h=4)
    bad_k = k[:, :, :3]  # 4 q heads, 3 kv heads
    with pytest.raises(ValueError):
        flash_attention(q, bad_k, v[:, :, :3])


def test_gqa_layer_shrinks_kv_projection():
    import flax.linen as nn  # noqa: F401
    import jax.numpy as jnp

    from elasticdl_tpu.layers.attention import MultiHeadSelfAttention

    layer = MultiHeadSelfAttention(num_heads=4, num_kv_heads=2, causal=True)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 32), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    assert variables["params"]["query"]["kernel"].shape == (32, 4, 8)
    assert variables["params"]["key"]["kernel"].shape == (32, 2, 8)
    out = layer.apply(variables, x)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference_on_sp_mesh(causal):
    q, k, v = _qkv()
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, axis_name="sp", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_with_sharded_inputs_under_jit():
    """Ring attention composes with GSPMD: seq-sharded inputs go in, the
    shard_map runs inside jit, and no all-gather of the full sequence is
    needed for correctness."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(b=4, s=256)
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    sh = NamedSharding(mesh, P("dp", "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, causal=True)

    out = run(qs, ks, vs)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    """The all-to-all sequence-parallel alternative: heads reshard over
    sp, full-sequence flash per head group, reshard back."""
    from elasticdl_tpu.ops.ulysses import ulysses_attention

    q, k, v = _qkv(h=4)  # heads must divide sp
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ulysses_rejects_indivisible_heads():
    from elasticdl_tpu.ops.ulysses import ulysses_attention

    q, k, v = _qkv(h=2)
    mesh = MeshConfig.from_string("sp=4").create()
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh=mesh)


def test_attention_dispatch_honors_sp_impl():
    """set_attention_mesh(..., sp_impl='ulysses') routes dispatch through
    the all-to-all implementation; both agree with the oracle."""
    q, k, v = _qkv(h=4)
    ref = mha_reference(q, k, v, causal=True)
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    set_attention_mesh(mesh, sp_impl="ulysses")
    out = attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_sp_impl_validation_and_scope_preservation():
    """A typo'd sp_impl raises; the trainer's step scopes (sp_impl=None)
    preserve a globally selected implementation instead of resetting it
    to ring."""
    from elasticdl_tpu.ops.attention import (
        attention_mesh_scope,
        get_attention_mesh,
    )

    mesh = MeshConfig.from_string("sp=4").create()
    with pytest.raises(ValueError):
        set_attention_mesh(mesh, sp_impl="ulyses")  # typo

    set_attention_mesh(mesh, sp_impl="ulysses")
    with attention_mesh_scope(mesh):  # what SPMDTrainer does per step
        assert get_attention_mesh()[2] == "ulysses"
    assert get_attention_mesh()[2] == "ulysses"


def test_transformer_trains_with_ulysses(tmp_path):
    """End-to-end: global ulysses selection survives SPMDTrainer's
    scoping and the jitted step trains."""
    import optax

    from elasticdl_tpu.models import long_seq_transformer as lm
    from elasticdl_tpu.parallel.distributed import SPMDTrainer

    rng = np.random.RandomState(0)
    feats = {"tokens": rng.randint(0, 64, (4, 32)).astype(np.int32)}
    labels = rng.randint(0, 64, (4, 32)).astype(np.int32)
    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    set_attention_mesh(mesh, sp_impl="ulysses")
    trainer = SPMDTrainer(
        mesh,
        lm.custom_model(
            vocab_size=64, num_layers=1, embed_dim=32, num_heads=4
        ),
        lm.loss,
        optax.adam(3e-3),
        feats,
    )
    losses = [
        float(
            trainer.train_step(
                trainer.place_batch(feats), trainer.place_batch(labels)
            )["loss"]
        )
        for _ in range(4)
    ]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_attention_dispatch_uses_ring_on_sp_mesh():
    """attention() picks ring on an sp>1 mesh and flash otherwise; both
    agree with the oracle, so dispatch is observable via the mesh rules
    (ring requires seq % sp == 0 — exercised by construction)."""
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=True)

    set_attention_mesh(None)
    out_local = attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_local), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    mesh = MeshConfig.from_string("sp=8").create()
    set_attention_mesh(mesh)
    out_ring = attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_transformer_trains_on_sp_mesh(tmp_path):
    """End-to-end: the transformer LM trains through SPMDTrainer on a
    dp=2,sp=4 mesh — sequence-sharded batches, ring attention inside the
    jitted step — and the loss drops."""
    import optax

    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.models import long_seq_transformer as lm
    from elasticdl_tpu.parallel.distributed import SPMDTrainer
    from elasticdl_tpu.trainer.state import Modes

    data_dir = synthetic.gen_sequence(
        str(tmp_path / "seq"),
        num_records=64,
        num_shards=1,
        seq_len=64,
        seed=0,
    )
    reader = RecordIODataReader(data_dir=data_dir)
    shards = reader.create_shards()
    name, (start, count) = next(iter(shards.items()))
    task = type(
        "T", (), {"shard_name": name, "start": start, "end": start + count}
    )
    ds = lm.dataset_fn(
        Dataset.from_generator(lambda: reader.read_records(task)),
        Modes.TRAINING,
        reader.metadata,
    )
    batches = list(ds.batch(16))

    mesh = MeshConfig.from_string("dp=2,sp=4").create()
    model = lm.custom_model(num_layers=1, embed_dim=64, num_heads=2)
    trainer = SPMDTrainer(
        mesh, model, lm.loss, optax.adam(3e-3), batches[0][0]
    )
    losses = []
    for _ in range(3):
        for feats, labels in batches:
            m = trainer.train_step(
                trainer.place_batch(feats), trainer.place_batch(labels)
            )
            losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # the sequence dim really is sharded over sp on device
    placed = trainer.place_batch(batches[0][0])
    spec = placed["tokens"].sharding.spec
    assert spec[1] == "sp", spec


def test_transformer_tp_sp_mesh(tmp_path):
    """Full 3-D parallelism: dp=2 x tp=2 x sp=2 — tp shards QKV by head
    (megatron-style, ring keeps heads tp-sharded), sp shards the
    sequence.  The jitted step must compile, run, and match a replicated
    single-device step's loss on the same batch."""
    import optax

    from elasticdl_tpu.models import long_seq_transformer as lm
    from elasticdl_tpu.parallel.distributed import SPMDTrainer

    rng = np.random.RandomState(0)
    feats = {"tokens": rng.randint(0, 256, (4, 64)).astype(np.int32)}
    labels = rng.randint(0, 256, (4, 64)).astype(np.int32)
    model = lm.custom_model(num_layers=1, embed_dim=64, num_heads=4)

    mesh3d = MeshConfig.from_string("dp=2,tp=2,sp=2").create()
    trainer3d = SPMDTrainer(
        mesh3d,
        model,
        lm.loss,
        optax.sgd(0.0),  # lr 0: loss compares pre-update params
        feats,
        rules=tuple(lm.sharding_rules(mesh3d)),
    )
    # the tp rules actually took: a QKV kernel is head-sharded
    qkv = trainer3d.state.params["block_0"]["attn"]["query"]["kernel"]
    assert "tp" in str(qkv.sharding.spec), qkv.sharding.spec

    mesh1 = MeshConfig.from_string("dp=1").create([jax.devices()[0]])
    trainer1 = SPMDTrainer(
        mesh1, model, lm.loss, optax.sgd(0.0), feats
    )
    m3 = trainer3d.train_step(
        trainer3d.place_batch(feats), trainer3d.place_batch(labels)
    )
    m1 = trainer1.train_step(
        trainer1.place_batch(feats), trainer1.place_batch(labels)
    )
    np.testing.assert_allclose(
        float(m3["loss"]), float(m1["loss"]), rtol=1e-4
    )


def test_transformer_spec_contract():
    """The model module satisfies the model-zoo spec surface."""
    from elasticdl_tpu.utils.model_utils import get_model_spec

    spec = get_model_spec(
        "", "long_seq_transformer.long_seq_transformer.custom_model"
    )
    assert spec.build_model() is not None
    assert spec.loss is not None and spec.dataset_fn is not None
    assert spec.eval_metrics_fn is not None


def test_flash_non_power_of_two_blocks_chunking():
    """Regression: chunk size must stay a multiple of the block size —
    a chunk smaller than the block ran ZERO in-chunk sub-blocks and
    emitted all-NaN output (0/0) silently."""
    rng = np.random.RandomState(0)
    q = rng.randn(1, 2304, 2, 32).astype(np.float32)
    k = rng.randn(1, 2304, 2, 32).astype(np.float32)
    v = rng.randn(1, 2304, 2, 32).astype(np.float32)
    out = np.asarray(
        flash_attention(q, k, v, causal=True, block_q=384, block_k=384)
    )
    ref = np.asarray(mha_reference(q, k, v, causal=True))
    assert not np.isnan(out).any()
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)
