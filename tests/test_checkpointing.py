"""PeriodicCheckpointer edge paths the replication fallback depends on.

The replica subsystem's disk-fallback rule leans on two previously
untested contracts of the checkpoint path:

1. an async background WRITE error surfaces on the training thread at
   ``flush()`` (a job must never report complete — or a restore trust a
   directory — with a silently failed write behind it);
2. ``keep_checkpoint_max`` retention actually garbage-collects old
   versions, and ``latest_version`` keeps answering from the survivors.
"""

from __future__ import annotations

import numpy as np
import pytest

from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.trainer.checkpointing import PeriodicCheckpointer
from elasticdl_tpu.utils import save_utils


class _FakeTrainer:
    def __init__(self, step: int):
        self.step = step
        self.state = None


@pytest.fixture()
def _host_snapshot(monkeypatch):
    """Bypass the device snapshot: these tests pin the WRITE machinery,
    not the sharding split (tests/test_checkpoint_sharded.py owns that)."""
    monkeypatch.setattr(
        elastic,
        "state_checkpoint_parts",
        lambda state, mesh, materialize_dense=True: (
            {"params/w": np.ones((2, 2), np.float32)},
            {},
        ),
    )


def test_flush_reraises_background_write_error(
    tmp_path, _host_snapshot, monkeypatch
):
    ckpt = PeriodicCheckpointer(str(tmp_path / "ckpt"), checkpoint_steps=1)

    def boom(*args, **kwargs):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt._saver, "save", boom)
    ckpt.save_now(_FakeTrainer(step=3), mesh=None)
    # the failure happened on the writer thread; the training thread
    # must see it at the next flush — and exactly once
    with pytest.raises(OSError, match="disk full"):
        ckpt.flush()
    ckpt.flush()  # error was consumed; a second flush is clean


def test_flush_on_unwind_logs_instead_of_masking(
    tmp_path, _host_snapshot, monkeypatch
):
    """On an error unwind the flush failure must NOT replace the root
    cause; on a clean exit it must raise exactly like flush()."""
    ckpt = PeriodicCheckpointer(str(tmp_path / "ckpt"), checkpoint_steps=1)
    monkeypatch.setattr(
        ckpt._saver,
        "save",
        lambda *a, **k: (_ for _ in ()).throw(OSError("torn")),
    )
    ckpt.save_now(_FakeTrainer(step=1), mesh=None)
    ckpt.flush_on_unwind(clean_exit=False)  # swallowed (logged)
    ckpt.save_now(_FakeTrainer(step=2), mesh=None)
    with pytest.raises(OSError, match="torn"):
        ckpt.flush_on_unwind(clean_exit=True)


def test_save_waits_for_inflight_write_error(
    tmp_path, _host_snapshot, monkeypatch
):
    """The next save joins the previous in-flight write first, so a
    write error can never be dropped between two saves."""
    ckpt = PeriodicCheckpointer(str(tmp_path / "ckpt"), checkpoint_steps=1)
    monkeypatch.setattr(
        ckpt._saver,
        "save",
        lambda *a, **k: (_ for _ in ()).throw(OSError("late")),
    )
    ckpt.save_now(_FakeTrainer(step=1), mesh=None)
    with pytest.raises(OSError, match="late"):
        ckpt.save_now(_FakeTrainer(step=2), mesh=None)


def test_keep_checkpoint_max_garbage_collection(tmp_path):
    root = str(tmp_path / "ckpt")
    saver = save_utils.CheckpointSaver(root, keep_checkpoint_max=2)
    for version in (2, 4, 6, 8):
        saver.save(
            version,
            dense={"params/w": np.full((2, 2), float(version))},
            extra={"model_version": version},
        )
    assert save_utils._list_versions(root) == [6, 8]
    assert save_utils.latest_version(root) == 8
    dense, _embeddings, extra = save_utils.restore_checkpoint(root)
    assert extra["model_version"] == 8
    np.testing.assert_array_equal(
        dense["params/w"], np.full((2, 2), 8.0)
    )


def test_keep_checkpoint_max_zero_keeps_everything(tmp_path):
    root = str(tmp_path / "ckpt")
    saver = save_utils.CheckpointSaver(root, keep_checkpoint_max=0)
    for version in (1, 2, 3, 4, 5):
        saver.save(version, dense={}, extra={})
    assert save_utils._list_versions(root) == [1, 2, 3, 4, 5]


def test_milestone_crossing_schedule(tmp_path, _host_snapshot):
    """Task boundaries are not step multiples: a boundary that JUMPS
    over a milestone must still save, and restoring realigns the
    milestone so the next boundary does not double-save."""
    saved = []

    ckpt = PeriodicCheckpointer(str(tmp_path / "ckpt"), checkpoint_steps=4)
    ckpt.save_now = lambda trainer, mesh: saved.append(trainer.step)
    assert not ckpt.maybe_save(_FakeTrainer(3), mesh=None)
    assert ckpt.maybe_save(_FakeTrainer(6), mesh=None)  # crossed 4
    assert not ckpt.maybe_save(_FakeTrainer(7), mesh=None)
    ckpt.note_restored_version(6)
    assert not ckpt.maybe_save(_FakeTrainer(7), mesh=None)
    assert ckpt.maybe_save(_FakeTrainer(12), mesh=None)
    assert saved == [6, 12]
