"""Embedding stack: lookup math, combiners, auto-partitioning on a mesh.

Mirrors the reference's embedding tests (embedding_delegate / layer tests)
plus the model_handler 2MB policy (model_handler.py:47-55), on the virtual
8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.layers.embedding import (
    Embedding,
    SparseEmbedding,
    auto_partition_rules,
    embedding_lookup,
    safe_embedding_lookup_sparse,
)
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.utils.model_handler import (
    DistributedModelHandler,
    ModelHandler,
)
from elasticdl_tpu.utils.constants import DistributionStrategy


@pytest.fixture(scope="module")
def table():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.rand(16, 4).astype(np.float32))


def test_dense_lookup_and_pad_masking(table):
    ids = jnp.array([[0, 3], [5, -1]])
    out = embedding_lookup(table, ids)
    assert out.shape == (2, 2, 4)
    np.testing.assert_allclose(out[0, 0], table[0])
    np.testing.assert_allclose(out[1, 1], np.zeros(4))  # pad -> zeros


@pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
def test_combiners_match_manual(table, combiner):
    ids = jnp.array([[1, 2, -1], [4, -1, -1]])
    out = safe_embedding_lookup_sparse(table, ids, combiner=combiner)
    rows0 = np.asarray(table)[[1, 2]]
    row1 = np.asarray(table)[4]
    if combiner == "sum":
        exp0, exp1 = rows0.sum(0), row1
    elif combiner == "mean":
        exp0, exp1 = rows0.mean(0), row1
    else:
        exp0, exp1 = rows0.sum(0) / np.sqrt(2.0), row1
    np.testing.assert_allclose(out[0], exp0, rtol=1e-6)
    np.testing.assert_allclose(out[1], exp1, rtol=1e-6)


def test_empty_row_yields_zeros(table):
    ids = jnp.array([[-1, -1]])
    for combiner in ("sum", "mean", "sqrtn"):
        out = safe_embedding_lookup_sparse(table, ids, combiner=combiner)
        np.testing.assert_allclose(out, np.zeros((1, 4)))


def test_weighted_mean(table):
    ids = jnp.array([[1, 2, -1]])
    w = jnp.array([[3.0, 1.0, 7.0]])  # pad weight must be ignored
    out = safe_embedding_lookup_sparse(table, ids, weights=w, combiner="mean")
    exp = (3 * np.asarray(table)[1] + 1 * np.asarray(table)[2]) / 4.0
    np.testing.assert_allclose(out[0], exp, rtol=1e-6)


def test_embedding_module_dense_and_sparse():
    dense = Embedding(input_dim=10, output_dim=3)
    ids = jnp.array([[1, 2], [3, 4]])
    params = dense.init(jax.random.PRNGKey(0), ids)
    out = dense.apply(params, ids)
    assert out.shape == (2, 2, 3)

    sparse = SparseEmbedding(input_dim=10, output_dim=3, combiner="mean")
    params = sparse.init(jax.random.PRNGKey(0), ids)
    out = sparse.apply(params, ids)
    assert out.shape == (2, 3)


def test_embedding_gradients_flow():
    """Gradient wrt the table is nonzero exactly on looked-up rows — the
    property the reference gets from BET tape.watch + scatter
    (embedding_delegate.py:257-272)."""
    model = Embedding(input_dim=8, output_dim=2, combiner="sum")
    ids = jnp.array([[1, 3]])
    params = model.init(jax.random.PRNGKey(0), ids)

    def loss(p):
        return model.apply(p, ids).sum()

    g = jax.grad(loss)(params)["params"]["embedding"]
    g = np.asarray(g)
    assert np.all(g[[1, 3]] == 1.0)
    untouched = np.delete(g, [1, 3], axis=0)
    assert np.all(untouched == 0.0)


def test_auto_partition_rules_thresholds():
    mesh = MeshConfig.from_string("dp=2,tp=4").create(jax.devices("cpu")[:8])
    params = {
        "big": {"embedding": np.zeros((1024, 1024), np.float32)},  # 4MB
        "small": {"embedding": np.zeros((8, 4), np.float32)},
        "dense": {"kernel": np.zeros((1024, 1024), np.float32)},
    }
    rules = auto_partition_rules(params, mesh)
    assert len(rules) == 1
    assert rules[0].matches("big/embedding")
    assert not rules[0].matches("small/embedding")
    assert not rules[0].matches("dense/kernel")
    assert rules[0].spec == P("tp", None)


def test_auto_partition_prefers_ep_axis():
    mesh = MeshConfig.from_string("dp=2,ep=4").create(jax.devices("cpu")[:8])
    params = {"emb": {"embedding": np.zeros((1024, 1024), np.float32)}}
    (rule,) = auto_partition_rules(params, mesh)
    assert rule.spec == P("ep", None)


def test_model_handler_factory():
    assert isinstance(
        ModelHandler.get_model_handler(DistributionStrategy.PARAMETER_SERVER),
        DistributedModelHandler,
    )
    h = ModelHandler.get_model_handler(DistributionStrategy.LOCAL)
    assert type(h) is ModelHandler
    assert h.sharding_rules({}, None) == ()


def test_sharded_embedding_trains_on_mesh():
    """End-to-end: a model with a >2MB table trains SPMD on an 8-device
    mesh with the table actually laid out over the ep axis."""
    import flax.linen as nn

    from elasticdl_tpu.parallel.distributed import SPMDTrainer

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, feats, training=False):
            emb = Embedding(
                input_dim=4096, output_dim=256, combiner="mean", name="wide"
            )(feats["ids"])
            return nn.Dense(2)(emb)

    mesh = MeshConfig.from_string("dp=2,ep=4").create(jax.devices("cpu")[:8])
    rng = np.random.RandomState(0)
    feats = {"ids": rng.randint(0, 4096, (8, 5)).astype(np.int32)}
    labels = rng.randint(0, 2, 8).astype(np.int32)

    def loss_fn(labels, logits):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.reshape(-1)
        ).mean()

    trainer = SPMDTrainer(mesh, Tiny(), loss_fn, optax.sgd(0.1), feats)
    spec = trainer.state_specs.params["wide"]["embedding"]
    assert spec == P("ep", None)  # 4096*256*4B = 4MB > 2MB threshold
    m = trainer.train_step(
        trainer.place_batch(feats), trainer.place_batch(labels)
    )
    assert np.isfinite(float(m["loss"]))
    # optimizer state sharded identically to the table (replaces
    # OptimizerWrapper slot injection, ps/optimizer_wrapper.py:279-304)
    sgd_momentum_free = trainer.state.opt_state
    del sgd_momentum_free
    m2 = trainer.train_step(
        trainer.place_batch(feats), trainer.place_batch(labels)
    )
    assert float(m2["loss"]) < float(m["loss"]) + 1.0


def test_out_of_vocab_id_zero_gradient(table):
    """Falsification of the clip bug: under jit ``jnp.take`` CLIPS an
    out-of-vocab id onto the LAST table row — without the upper-bound
    mask it would join the combine AND receive gradient, silently
    corrupting that row.  An out-of-range id (either direction) must
    contribute exactly zero output and exactly zero gradient, the PR-5
    mask contract extended to the upper bound."""
    rows = np.asarray(table).shape[0]
    ids = jnp.array([[1, rows, rows + 83]])  # one-past and far out

    def loss(t):
        return safe_embedding_lookup_sparse(t, ids, combiner="sum").sum()

    g = np.asarray(jax.jit(jax.grad(loss))(table))
    assert np.all(g[1] == 1.0)
    assert np.all(np.delete(g, [1], axis=0) == 0.0)  # esp. the last row
    # the combine excluded the OOV ids from value AND denominator
    for combiner in ("sum", "mean", "sqrtn"):
        out = jax.jit(
            lambda t: safe_embedding_lookup_sparse(t, ids, combiner=combiner)
        )(table)
        np.testing.assert_allclose(
            out[0], np.asarray(table)[1], rtol=1e-6
        )


def test_dense_lookup_out_of_range_zeros_and_zero_gradient(table):
    rows = np.asarray(table).shape[0]
    ids = jnp.array([0, rows, rows + 7])
    out = jax.jit(lambda t: embedding_lookup(t, ids))(table)
    np.testing.assert_allclose(out[0], np.asarray(table)[0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1:]), 0.0)
    g = np.asarray(
        jax.jit(jax.grad(lambda t: embedding_lookup(t, ids).sum()))(table)
    )
    assert np.all(g[0] == 1.0)
    assert np.all(g[1:] == 0.0)  # the clip target (last row) included


def test_vocab_pad_multiple_allocates_padded_table():
    model = SparseEmbedding(
        input_dim=5383, output_dim=4, vocab_pad_multiple=128
    )
    assert model.padded_input_dim == 5504
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3), jnp.int32))
    assert params["params"]["embedding"].shape == (5504, 4)
    # padded rows are never looked up -> zero gradient on them
    ids = jnp.array([[5382, -1, -1]])

    def loss(p):
        return model.apply(p, ids).sum()

    g = np.asarray(jax.grad(loss)(params)["params"]["embedding"])
    assert np.all(g[5383:] == 0.0)
    assert np.any(g[5382] != 0.0)
