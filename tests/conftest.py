"""Test harness configuration.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY §4: the reference collapses the
process boundary but keeps the protocol objects real; we collapse the pod
slice into 8 host-platform devices but keep the mesh/sharding real).

Must run before the first ``import jax`` anywhere in the test session.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA compile parallelism sane on small CI machines.
os.environ.setdefault("JAX_ENABLE_X64", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's TPU plugin ('axon') registers itself with priority and
# ignores JAX_PLATFORMS, so force the CPU backend through the config API too
# (env alone is not enough on this machine).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
