"""Tier-1 unit tests for the common substrate (SURVEY §4 tier 1).

Covers: hashing, tensor serde, args parsing + argv round-trip, params DSL.
Reference counterparts: ``args_test.py``, ``tensor_test.py``,
``hash_utils_test.py`` in ``elasticdl/python/tests/``.
"""

import numpy as np
import pytest

from elasticdl_tpu.utils import args as args_mod
from elasticdl_tpu.utils import hash_utils
from elasticdl_tpu.utils.tensor import (
    Tensor,
    deserialize_tensors,
    serialize_tensors,
)


class TestHashUtils:
    def test_string_to_id_stable_and_bounded(self):
        for n in (1, 2, 7, 64):
            ids = {hash_utils.string_to_id(f"var_{i}", n) for i in range(100)}
            assert all(0 <= i < n for i in ids)
        assert hash_utils.string_to_id("dense/kernel", 8) == (
            hash_utils.string_to_id("dense/kernel", 8)
        )

    def test_int_to_id(self):
        assert hash_utils.int_to_id(13, 4) == 1
        assert hash_utils.int_to_id(0, 4) == 0

    def test_scatter_ids_partitions_everything(self):
        ids = np.arange(100, dtype=np.int64)
        groups = hash_utils.scatter_ids(ids, 3)
        assert sum(len(g) for g in groups) == 100
        for shard, group in enumerate(groups):
            assert np.all(group % 3 == shard)

    def test_scatter_with_positions_roundtrip(self):
        ids = np.array([7, 2, 9, 2, 5, 16], dtype=np.int64)
        groups, positions = hash_utils.scatter_with_positions(ids, 4)
        rebuilt = np.empty_like(ids)
        for g, p in zip(groups, positions):
            rebuilt[p] = g
        np.testing.assert_array_equal(rebuilt, ids)


class TestTensorSerde:
    def test_dense_roundtrip(self):
        t = Tensor("w", np.random.randn(3, 4).astype(np.float32))
        r = Tensor.from_bytes(t.to_bytes())
        assert r.name == "w" and not r.is_sparse
        np.testing.assert_array_equal(r.values, t.values)

    def test_sparse_roundtrip(self):
        t = Tensor(
            "emb",
            np.random.randn(5, 8).astype(np.float32),
            np.array([3, 1, 4, 1, 5]),
        )
        r = Tensor.from_bytes(t.to_bytes())
        assert r.is_sparse
        np.testing.assert_array_equal(r.indices, t.indices)
        np.testing.assert_array_equal(r.values, t.values)

    def test_bfloat16_roundtrip(self):
        import ml_dtypes

        t = Tensor("b", np.ones((2, 2), dtype=ml_dtypes.bfloat16))
        r = Tensor.from_bytes(t.to_bytes())
        assert r.values.dtype == ml_dtypes.bfloat16

    def test_add_dense(self):
        a = Tensor("x", np.ones((2,), np.float32))
        b = Tensor("x", np.full((2,), 2.0, np.float32))
        np.testing.assert_array_equal((a + b).values, [3.0, 3.0])

    def test_add_sparse_concatenates(self):
        a = Tensor("e", np.ones((2, 3), np.float32), np.array([1, 2]))
        b = Tensor("e", np.zeros((1, 3), np.float32), np.array([7]))
        c = a + b
        np.testing.assert_array_equal(c.indices, [1, 2, 7])
        assert c.values.shape == (3, 3)

    def test_mixed_add_raises(self):
        a = Tensor("x", np.ones((2,), np.float32))
        b = Tensor("x", np.ones((2, 3), np.float32), np.array([0, 1]))
        with pytest.raises(ValueError):
            _ = a + b

    def test_collection_roundtrip(self):
        ts = {
            "a": Tensor("a", np.arange(6, dtype=np.int32).reshape(2, 3)),
            "b": Tensor("b", np.ones((4,), np.float64)),
        }
        out = deserialize_tensors(serialize_tensors(ts))
        assert set(out) == {"a", "b"}
        np.testing.assert_array_equal(out["a"].values, ts["a"].values)

    def test_row_mismatch_raises(self):
        with pytest.raises(ValueError):
            Tensor("e", np.ones((2, 3)), np.array([1, 2, 3]))


class TestArgs:
    def _master_argv(self, extra=()):
        return [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            "/tmp/mnist/train",
            *extra,
        ]

    def test_parse_master_defaults(self):
        args = args_mod.parse_master_args(self._master_argv())
        assert args.minibatch_size == 64
        assert args.num_workers == 1
        assert args.distribution_strategy == "Local"
        assert args.model_params_dict == {}

    def test_model_params_dsl(self):
        args = args_mod.parse_master_args(
            self._master_argv(
                ["--model_params", "hidden=128;dropout=0.5;name='deep'"]
            )
        )
        assert args.model_params_dict == {
            "hidden": 128,
            "dropout": 0.5,
            "name": "deep",
        }

    def test_envs_parse(self):
        args = args_mod.parse_master_args(
            self._master_argv(["--envs", "A=1,B=two"])
        )
        assert args.envs_dict == {"A": "1", "B": "two"}

    def test_num_minibatches_per_task_coercion(self):
        args = args_mod.parse_master_args(
            self._master_argv(
                ["--minibatch_size", "32", "--num_minibatches_per_task", "8"]
            )
        )
        assert args.records_per_task == 256

    def test_async_coerces_grads_to_wait(self):
        args = args_mod.parse_master_args(
            self._master_argv(
                ["--use_async", "true", "--grads_to_wait", "9"]
            )
        )
        assert args.grads_to_wait == 1

    def test_get_model_steps_coerced_to_sync(self):
        """Documented deviation: local-SGD does not apply over ICI; the
        flag is accepted (reference CLI parity) and coerced to 1."""
        args = args_mod.parse_master_args(
            self._master_argv(["--get_model_steps", "4"])
        )
        assert args.get_model_steps == 1

    def test_worker_argv_roundtrip(self):
        """Master argv -> worker argv -> reparse must preserve train flags
        (reference args.py:664-685)."""
        master = args_mod.parse_master_args(
            self._master_argv(
                [
                    "--minibatch_size",
                    "128",
                    "--num_epochs",
                    "3",
                    "--mesh_shape",
                    "dp=4,tp=2",
                    "--remat",
                    "true",
                    "--port",
                    "50099",
                ]
            )
        )
        argv = args_mod.build_worker_arguments(master, 7, "1.2.3.4:50099")
        worker = args_mod.parse_worker_args(argv)
        assert worker.worker_id == 7
        assert worker.master_addr == "1.2.3.4:50099"
        assert worker.minibatch_size == 128
        assert worker.num_epochs == 3
        assert worker.mesh_shape == "dp=4,tp=2"
        assert worker.remat is True
        assert not hasattr(worker, "port")

    def test_bad_params_entry_raises(self):
        with pytest.raises(ValueError):
            args_mod.parse_params_dict("novalue")


class TestModelUtils:
    def test_split_model_def(self):
        from elasticdl_tpu.utils.model_utils import _split_model_def

        path, fn = _split_model_def("a.b.custom_model")
        assert path.endswith("b.py") and fn == "custom_model"
        with pytest.raises(ValueError):
            _split_model_def("nomodule")
