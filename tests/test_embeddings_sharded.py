"""Sharded embedding subsystem: row ranges, dp-fallback rules, spill
tier, ledger accounting, sharded replica invariants, sharded serving.

The shard-placement tests PIN the uneven-split layout (vocab not
divisible by host count, n_hosts 1/2/3) and round-trip parity against
the dense layer's outputs; the chaos-invariant tests drive the pure
checkers with synthetic events — including the ``drop_shard_parts``
signature (has_sharded with zero rows) they must trip on; the serving
test proves a row-sharded table serves and hot-swaps with a flat
compile counter on the virtual 8-device mesh."""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu import embeddings as emb
from elasticdl_tpu.layers.embedding import safe_embedding_lookup_sparse
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.telemetry import memory as memory_ledger
from elasticdl_tpu.utils.constants import MeshAxis

DEEPFM_DEF = "deepfm_sharded_embedding.deepfm_sharded_embedding.custom_model"


# ---- row partitioning --------------------------------------------------------


def test_shard_row_ranges_uneven_pinned():
    # np.array_split semantics: the first (rows % hosts) shards carry
    # one extra row — pinned so host-tier ownership can never drift
    # from checkpoint-part ownership
    assert emb.shard_row_ranges(10, 1) == [(0, 10)]
    assert emb.shard_row_ranges(10, 2) == [(0, 5), (5, 10)]
    assert emb.shard_row_ranges(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert emb.shard_row_ranges(5383, 2) == [(0, 2692), (2692, 5383)]
    assert emb.shard_row_ranges(5383, 3) == [
        (0, 1795),
        (1795, 3589),
        (3589, 5383),
    ]
    # contiguous cover, no gaps/overlap, for every tested layout
    for rows in (1, 7, 5383):
        for hosts in (1, 2, 3):
            ranges = emb.shard_row_ranges(rows, hosts)
            assert ranges[0][0] == 0 and ranges[-1][1] == rows
            for (_, hi), (lo2, _) in zip(ranges, ranges[1:]):
                assert hi == lo2
    with pytest.raises(ValueError):
        emb.shard_row_ranges(10, 0)


def test_owning_shard():
    ranges = emb.shard_row_ranges(10, 3)
    assert [emb.owning_shard(r, ranges) for r in (0, 3, 4, 6, 7, 9)] == [
        0, 0, 1, 1, 2, 2,
    ]
    with pytest.raises(ValueError):
        emb.owning_shard(10, ranges)


# ---- axis selection and rules ------------------------------------------------


def test_embedding_axis_prefers_dedicated_then_falls_back_to_dp():
    devs = jax.devices("cpu")[:8]
    ep_mesh = MeshConfig.from_string("dp=2,ep=4").create(devs)
    assert emb.embedding_axis(ep_mesh) == MeshAxis.EP
    tp_mesh = MeshConfig.from_string("dp=2,tp=4").create(devs)
    assert emb.embedding_axis(tp_mesh) == MeshAxis.TP
    # pure-data-parallel world: the auto policy refuses dp, the
    # DECLARED-sharded policy falls back to it (elasticity: dp is the
    # one axis every re-formed world has)
    dp_mesh = MeshConfig.from_string("dp=8").create(devs)
    assert emb.embedding_axis(dp_mesh) == MeshAxis.DP
    assert emb.embedding_axis(dp_mesh, allow_dp=False) is None
    # divisibility gates the pick
    assert emb.embedding_axis(dp_mesh, rows=1000) == MeshAxis.DP  # 1000%8!=0? no
    assert emb.embedding_axis(dp_mesh, rows=1001) is None
    single = MeshConfig.from_string("").create(devs[:1])
    assert emb.embedding_axis(single) is None


def test_sharded_table_rules_dp_fallback_and_skip():
    devs = jax.devices("cpu")[:8]
    mesh = MeshConfig.from_string("").create(devs)  # inferred dp=8
    rules = emb.sharded_table_rules(
        mesh, {"embedding/embedding": 5504, "id_bias/embedding": 5504}
    )
    assert len(rules) == 2
    for rule in rules:
        assert rule.spec == P(MeshAxis.DP, None)
    assert rules[0].matches("embedding/embedding")
    assert rules[0].matches("params/embedding/embedding")
    assert not rules[0].matches("big_embedding/embedding")
    # a vocab no axis divides is skipped (downstream replicates)
    assert emb.sharded_table_rules(mesh, {"t/embedding": 5383}) == []


# ---- host tier: parity, uneven splits, ledger --------------------------------


@pytest.mark.parametrize("num_hosts", [1, 2, 3])
def test_host_table_parity_vs_dense_layer(num_hosts):
    """Uneven vocab (11 rows) split over 1/2/3 hosts: gather must equal
    the dense table row-for-row, and a combiner lookup over rows staged
    FROM the host tier must match the dense layer's output exactly."""
    rng = np.random.RandomState(7)
    vocab, dim = 11, 4
    dense = rng.rand(vocab, dim).astype(np.float32)
    table = emb.ShardedHostTable(
        f"parity{num_hosts}", vocab, dim, num_hosts=num_hosts, rows=dense
    )
    try:
        assert [s.shape[0] for s in table._shards] == [
            hi - lo for lo, hi in emb.shard_row_ranges(vocab, num_hosts)
        ]
        ids = np.array([0, 10, 3, 7, 3])
        np.testing.assert_array_equal(table.gather(ids), dense[ids])
        # round-trip parity against the dense layer: stage the touched
        # rows into a minitable and combine — same output as combining
        # over the full dense table
        batch = jnp.array([[1, 5, -1], [10, 0, 2]])
        rt = emb.SpillEmbeddingRuntime(
            {"t/embedding": table}, capacity=8, emit=lambda *a, **k: None
        )
        params = rt.minitable_params({"t": {"embedding": None}})
        staged, remapped, handle = rt.stage(params, np.asarray(batch))
        # negative sentinel ids pass through remapping untouched
        np.testing.assert_array_equal(
            np.asarray(remapped) < 0, np.asarray(batch) < 0
        )
        out_mini = safe_embedding_lookup_sparse(
            jnp.asarray(staged["t"]["embedding"]),
            jnp.asarray(remapped),
            combiner="sum",
        )
        out_dense = safe_embedding_lookup_sparse(
            jnp.asarray(dense), batch, combiner="sum"
        )
        np.testing.assert_allclose(out_mini, out_dense, rtol=1e-6)
    finally:
        table.close()


def test_host_table_refuses_out_of_range_ids():
    table = emb.ShardedHostTable("oob", 10, 2, num_hosts=2)
    try:
        with pytest.raises(ValueError):
            table.gather(np.array([0, 10]))
        with pytest.raises(ValueError):
            table.scatter(np.array([-1]), np.zeros((1, 2), np.float32))
    finally:
        table.close()


def test_ledger_components_and_identity_guarded_unregister():
    table = emb.ShardedHostTable("ledgered", 100, 8, num_hosts=2)
    sample = memory_ledger.MemoryLedger().sample()
    assert sample["components"][
        memory_ledger.COMPONENT_EMBEDDING_SPILL
    ] == table.nbytes
    # a replacement owner registers under the same component name;
    # closing the STALE owner must leave the replacement alone (the
    # identity guard)
    replacement = lambda: 12345  # noqa: E731
    memory_ledger.register_component(
        memory_ledger.COMPONENT_EMBEDDING_SPILL, replacement
    )
    table.close()
    sample2 = memory_ledger.MemoryLedger().sample()
    assert sample2["components"][
        memory_ledger.COMPONENT_EMBEDDING_SPILL
    ] == 12345
    memory_ledger.unregister_component(
        memory_ledger.COMPONENT_EMBEDDING_SPILL, replacement
    )
    # device-tier tracking mirrors the same contract
    emb.track_device_table("dev_t", lambda: 4096)
    got = memory_ledger.MemoryLedger().sample()["components"]
    assert got[memory_ledger.COMPONENT_EMBEDDING_TABLE] == 4096
    emb.untrack_device_table("dev_t")
    got2 = memory_ledger.MemoryLedger().sample()["components"]
    assert memory_ledger.COMPONENT_EMBEDDING_TABLE not in got2


# ---- tier admission ----------------------------------------------------------


def test_plan_placement_tiers_and_admission_fault(monkeypatch):
    monkeypatch.setenv(emb.DEVICE_BUDGET_ENV, str(1 << 20))
    small = emb.plan_placement(1 << 10, name="small")
    assert small.tier == "device"
    big = emb.plan_placement(1 << 24, name="big")  # 16MB > 1MB budget
    assert big.tier == "spill"
    assert big.host_available_bytes is not None
    events = []
    with pytest.raises(emb.EmbeddingAdmissionError):
        emb.plan_placement(
            1 << 62,
            name="monster",
            emit=lambda ev, **fields: events.append((ev, fields)),
        )
    assert events and events[0][0] == "embedding_spill_fault"
    assert events[0][1]["table"] == "monster"


# ---- spill runtime: parity with dense training, compile-once -----------------


def test_spill_runtime_trains_identically_to_dense_table():
    """K SGD steps through the stage -> unchanged jitted step -> commit
    loop must land the host table EXACTLY where dense full-table
    training lands it, with ONE compile total (fixed minitable shapes).
    Also pins id 0 -> slot 0 (np.unique sorts), the mask-zero seam."""
    from elasticdl_tpu.telemetry import compile_tracker

    vocab, dim, capacity = 50, 3, 32
    rng = np.random.RandomState(3)
    init = rng.rand(vocab, dim).astype(np.float32)
    batches = [
        rng.randint(0, vocab, size=(4, 5)).astype(np.int32) for _ in range(4)
    ]
    tx = optax.sgd(0.5)

    def loss_fn(p, ids):
        out = safe_embedding_lookup_sparse(
            p["emb"]["embedding"], ids, combiner="mean"
        )
        return (out * out).sum()

    @jax.jit
    def step(p, o, ids):
        g = jax.grad(loss_fn)(p, ids)
        updates, o = tx.update(g, o, p)
        return optax.apply_updates(p, updates), o

    # dense reference
    dense_p = {"emb": {"embedding": jnp.asarray(init)}}
    dense_o = tx.init(dense_p)
    for ids in batches:
        dense_p, dense_o = step(dense_p, dense_o, jnp.asarray(ids))

    # spill path: same batches through minitable staging
    table = emb.ShardedHostTable(
        "train", vocab, dim, num_hosts=3, rows=init
    )
    rt = emb.SpillEmbeddingRuntime(
        {"emb/embedding": table}, capacity=capacity,
        emit=lambda *a, **k: None,
    )
    try:
        base = rt.minitable_params({"emb": {"embedding": None}})
        opt = tx.init(base)
        compile_tracker.install()
        compiles0 = compile_tracker.compile_count()
        for ids in batches:
            staged, remapped, handle = rt.stage(base, ids)
            assert handle[0] == 0  # id 0 always staged, slot 0
            new_p, opt = step(staged, opt, jnp.asarray(remapped))
            rt.commit(new_p, handle)
        assert compile_tracker.compile_count() - compiles0 == 1
        np.testing.assert_allclose(
            table.gather(np.arange(vocab)),
            np.asarray(dense_p["emb"]["embedding"]),
            rtol=1e-5,
            atol=1e-6,
        )
        assert rt.gathers == len(batches)
    finally:
        rt.close()


def test_spill_runtime_capacity_overflow_raises():
    table = emb.ShardedHostTable("cap", 100, 2, num_hosts=2)
    rt = emb.SpillEmbeddingRuntime(
        {"t/embedding": table}, capacity=4, emit=lambda *a, **k: None
    )
    try:
        with pytest.raises(ValueError):
            rt.stage(
                rt.minitable_params({"t": {"embedding": None}}),
                np.arange(10).reshape(1, 10),
            )
    finally:
        rt.close()


# ---- sharded replica invariants (pure checkers, synthetic events) ------------


def _push(step, src, dst, src_slice, dst_slice, num_slices=2, **extra):
    return {
        "event": "replica_push",
        "step": step,
        "source": src,
        "target": dst,
        "source_slice": src_slice,
        "target_slice": dst_slice,
        "num_slices": num_slices,
        "ok": True,
        "monotonic": float(step),
        **extra,
    }


def test_cross_slice_coverage_sharded_extension():
    from elasticdl_tpu.chaos.harness import check_cross_slice_coverage

    healthy = [
        _push(2, 0, 1, 0, 1, has_sharded=True, sharded_tables=2,
              sharded_rows=2752),
        _push(2, 1, 0, 1, 0, has_sharded=True, sharded_tables=2,
              sharded_rows=2752),
    ]
    assert check_cross_slice_coverage(healthy, 2) == []
    # the drop_shard_parts signature: state HAS sharded tables, push
    # carried zero rows — the shard's only replica holds no coverage
    dropped = [
        _push(2, 0, 1, 0, 1, has_sharded=True, sharded_tables=2,
              sharded_rows=0),
        _push(2, 1, 0, 1, 0, has_sharded=True, sharded_tables=2,
              sharded_rows=2752),
    ]
    violations = check_cross_slice_coverage(dropped, 2)
    assert len(violations) == 1 and "zero rows" in violations[0]
    # dense-only states (no sharded tables) stay out of contract
    dense_only = [_push(2, 0, 1, 0, 1, has_sharded=False, sharded_rows=0)]
    assert check_cross_slice_coverage(dense_only, 2) == []


def test_no_lost_steps_sharded_extension(tmp_path):
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, _check_no_lost_steps
    from elasticdl_tpu.chaos.plan import FaultKind, named_plan

    config = ChaosJobConfig(
        plan=named_plan("preempt_one_worker", 2),
        workdir=str(tmp_path),
        replication=True,
    )
    fault_events = [{"kind": FaultKind.PREEMPT, "monotonic": 10.0}]

    def restore(step, rows):
        return {
            "event": "replica_restore",
            "step": step,
            "sharded_rows": rows,
            "monotonic": 11.0,
        }

    healthy = [
        _push(4, 0, 1, 0, 0, num_slices=1, has_sharded=True,
              sharded_rows=2752),
        restore(4, 5504),
    ]
    ok = _check_no_lost_steps(config, healthy, fault_events)
    assert ok["status"] == "PASS"
    # restore applied the dense leaves but zero table rows
    lost_rows = [
        _push(4, 0, 1, 0, 0, num_slices=1, has_sharded=True,
              sharded_rows=2752),
        restore(4, 0),
    ]
    bad = _check_no_lost_steps(config, lost_rows, fault_events)
    assert bad["status"] == "FAIL"
    assert any("zero sharded table rows" in v for v in bad["violations"])
    # pushes that never carried the rows in the first place
    never_pushed = [
        _push(4, 0, 1, 0, 0, num_slices=1, has_sharded=True,
              sharded_rows=0),
        restore(4, 0),
    ]
    bad2 = _check_no_lost_steps(config, never_pushed, fault_events)
    assert bad2["status"] == "FAIL"
    assert any("no replica to survive" in v for v in bad2["violations"])


# ---- sharded serving: rule-placed tables, zero-recompile hot swap ------------


def _export_deepfm(out_dir: str, version: int, scale: float = 1.0) -> str:
    from elasticdl_tpu.trainer.state import TrainState, init_model
    from elasticdl_tpu.trainer.step import resolve_optimizer
    from elasticdl_tpu.utils.export_utils import export_model
    from elasticdl_tpu.utils.model_utils import get_model_spec

    spec = get_model_spec("", DEEPFM_DEF)
    model = spec.build_model()
    sample = {"feature": np.zeros((1, 10), np.int32)}
    params, model_state = init_model(model, sample)
    params = jax.tree_util.tree_map(lambda x: x * scale + 0.01, params)
    state = TrainState.create(
        model.apply, params, resolve_optimizer(spec.optimizer), model_state
    )
    state = state.replace(step=jnp.asarray(version, jnp.int32))
    args = argparse.Namespace(
        model_zoo="", model_def=DEEPFM_DEF, model_params_dict={}
    )
    return export_model(out_dir, state, spec, args)


def test_serving_sharded_table_zero_recompile_hot_swap(tmp_path):
    """The serving engine must place the declared tables ROW-SHARDED
    over its mesh (a 100M-row table cannot materialize replicated per
    device), answer lookups against them, and hot-swap to a new version
    with the layout — and therefore the compiled program — unchanged."""
    from elasticdl_tpu.serving.engine import ServingEngine
    from elasticdl_tpu.telemetry import compile_tracker

    v1 = _export_deepfm(str(tmp_path / "v1"), version=3)
    v2 = _export_deepfm(str(tmp_path / "v2"), version=9, scale=2.0)
    engine = ServingEngine(v1, canonical_rows=8)
    rng = np.random.RandomState(0)
    feats = {"feature": rng.randint(1, 5383, size=(5, 10)).astype(np.int32)}
    out1 = engine.predict_rows(feats)["logits"]
    assert out1.shape[0] == 5
    # the table leaves are committed row-sharded over dp (the 8 virtual
    # devices), per the model's sharding_rules — not replicated
    for path in ("embedding", "id_bias"):
        leaf = engine._state.params[path]["embedding"]
        assert leaf.sharding.spec == P(MeshAxis.DP, None)
        assert (
            leaf.addressable_shards[0].data.shape[0]
            == leaf.shape[0] // len(jax.devices())
        )
    compile_tracker.install()
    flat0 = compile_tracker.compile_count()
    accepted, version, reason = engine.swap_from_export(v2)
    assert accepted and version == 9, reason
    out2 = engine.predict_rows(feats)["logits"]
    assert compile_tracker.compile_count() == flat0  # zero recompiles
    assert not np.allclose(out1, out2)  # genuinely the new version
    # sharded layout survived the swap treedef-preserving
    leaf = engine._state.params["embedding"]["embedding"]
    assert leaf.sharding.spec == P(MeshAxis.DP, None)


def test_spill_metrics_gauge_registered():
    # the one elasticdl_embedding_bytes registration site renders from
    # the subsystem registry
    emb.set_table_bytes("gauge_t", "spill", 777)
    text = emb.metrics_registry().exposition()
    assert "elasticdl_embedding_bytes" in text
    assert 'table="gauge_t"' in text
    emb.set_table_bytes("gauge_t", "spill", 0)
