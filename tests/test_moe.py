"""Mixture-of-experts layer: routing/dispatch correctness, the
load-balance loss joining the train loss, and expert parallelism over ep
on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers.moe import MoEMLP, moe_sharding_rules
from elasticdl_tpu.models import long_seq_transformer as lm
from elasticdl_tpu.parallel.distributed import SPMDTrainer
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.trainer.state import TrainState, init_model
from elasticdl_tpu.trainer.step import build_train_step


def _init_moe(x, **kw):
    layer = MoEMLP(num_experts=4, **kw)
    variables = layer.init(jax.random.PRNGKey(0), x, training=False)
    return layer, variables


def test_moe_output_shape_and_capacity_drop():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16), jnp.float32)
    layer, variables = _init_moe(x, capacity_factor=1.0)
    y = layer.apply(variables, x, training=False)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()

    # capacity so tight almost everything drops -> output mostly zeros
    tiny = MoEMLP(num_experts=4, capacity_factor=0.01)
    v2 = tiny.init(jax.random.PRNGKey(0), x, training=False)
    y2 = np.asarray(tiny.apply(v2, x, training=False))
    # 16 tokens / 4 experts * 0.01 -> capacity 1: at most 4 kept tokens
    nonzero_tokens = (np.abs(y2).sum(-1) > 1e-7).sum()
    assert nonzero_tokens <= 4, nonzero_tokens


def test_moe_grouped_dispatch_invariant_when_no_drops():
    """Grouping only bounds dispatch-tensor size (O(n * group_capacity),
    not O(n^2)); with capacity ample enough that nothing drops, the
    output must be identical for any group size."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
    outs = []
    for group_size in (4, 8, 1024):
        layer = MoEMLP(
            num_experts=2, capacity_factor=4.0, group_size=group_size
        )
        variables = layer.init(jax.random.PRNGKey(0), x, training=False)
        outs.append(np.asarray(layer.apply(variables, x, training=False)))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-6)


def test_moe_aux_loss_joins_train_loss():
    """The sown load-balance loss must reach the training loss (the
    step-builder's 'losses' collection support)."""
    rng = np.random.RandomState(0)
    feats = {"tokens": rng.randint(0, 64, (4, 16)).astype(np.int32)}
    labels = rng.randint(0, 64, (4, 16)).astype(np.int32)
    model = lm.custom_model(
        vocab_size=64,
        num_layers=1,
        embed_dim=32,
        num_heads=2,
        num_experts=4,
    )
    params, model_state = init_model(model, feats)
    assert "losses" in model_state, list(model_state)

    # before the train step: it donates the original state buffers
    plain = float(lm.loss(labels, model.apply(
        {"params": params, **model_state}, feats, training=False
    )))
    state = TrainState.create(
        model.apply, params, optax.sgd(0.0), model_state
    )
    train_step = build_train_step(lm.loss, compute_dtype=None)
    state, metrics = train_step(state, feats, labels)
    with_aux = float(metrics["loss"])
    aux_leaves = jax.tree_util.tree_leaves(state.model_state["losses"])
    aux = float(sum(np.asarray(a).sum() for a in aux_leaves))
    assert aux > 0
    # dropout=0, lr=0: train loss = plain forward loss + aux
    np.testing.assert_allclose(with_aux, plain + aux, rtol=2e-4)


def test_moe_transformer_trains_on_ep_mesh():
    """dp=2, ep=2, sp=2: experts sharded over ep, sequence over sp; the
    jitted step runs and the loss drops."""
    rng = np.random.RandomState(0)
    feats = {"tokens": rng.randint(0, 64, (4, 32)).astype(np.int32)}
    labels = rng.randint(0, 64, (4, 32)).astype(np.int32)
    mesh = MeshConfig.from_string("dp=2,ep=2,sp=2").create()
    model = lm.custom_model(
        vocab_size=64,
        num_layers=1,
        embed_dim=32,
        num_heads=2,
        num_experts=4,
    )
    trainer = SPMDTrainer(
        mesh,
        model,
        lm.loss,
        optax.adam(3e-3),
        feats,
        rules=tuple(lm.sharding_rules(mesh)),
    )
    w_in = trainer.state.params["block_0"]["moe"]["w_in"]
    assert "ep" in str(w_in.sharding.spec), w_in.sharding.spec

    losses = []
    for _ in range(6):
        m = trainer.train_step(
            trainer.place_batch(feats), trainer.place_batch(labels)
        )
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_moe_sharding_rules_match_paths():
    rules = moe_sharding_rules()
    assert any(r.matches("block_0/moe/w_in") for r in rules)
    assert any(r.matches("block_0/moe/w_out") for r in rules)
    assert not any(r.matches("block_0/moe/router/kernel") for r in rules)
