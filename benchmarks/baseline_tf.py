"""Reproducible reference baseline: the reference's training-loop design
(TF2 ``tf.function`` GradientTape step, the worker hot path of
``elasticdl/python/worker/worker.py:656-669``) for the three benchmark
models, measured on this host's CPU (the reference trains on CPU pods —
its base image is ``tensorflow/tensorflow:2.0.0-py3``,
``image_builder.py:206-208``).

Writes per-model samples/sec to ``benchmarks/baseline.json``; ``bench.py``
reads that file for its ``vs_baseline`` anchors.  Run::

    python benchmarks/baseline_tf.py [--steps 20] [--out benchmarks/baseline.json]

The Keras models mirror the reference model_zoo architectures
(``model_zoo/mnist_functional_api``, ``model_zoo/resnet50_subclass`` at
cifar10 shapes, ``model_zoo/deepfm_functional_api``) — same layer stacks
and batch sizes as the JAX side of ``bench.py``, so the comparison is
design-vs-design on identical math.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# The measurement is CPU-by-design (see module docstring); hide any
# accelerator so TF cannot grab it (overriding, not defaulting — a
# scheduler-exported CUDA_VISIBLE_DEVICES must not re-enable a GPU).
os.environ["CUDA_VISIBLE_DEVICES"] = "-1"

import tensorflow as tf  # noqa: E402

# identical batch sizes to bench.py's JAX side (the vs_baseline ratios
# must compare the same configuration)
BATCHES = {
    "mnist": 256,
    "resnet50_cifar10": 2048,
    "imagenet_resnet50": 128,
    # CTR-realistic batch; small batches measure per-step overhead, not
    # the embedding+FM math (same batch as bench.py's JAX side)
    "deepfm": 4096,
}


def mnist_model():
    inputs = tf.keras.Input(shape=(28, 28), name="image")
    x = tf.keras.layers.Reshape((28, 28, 1))(inputs)
    x = tf.keras.layers.Conv2D(32, (3, 3), activation="relu")(x)
    x = tf.keras.layers.Conv2D(64, (3, 3), activation="relu")(x)
    x = tf.keras.layers.BatchNormalization()(x)
    x = tf.keras.layers.MaxPooling2D((2, 2))(x)
    x = tf.keras.layers.Dropout(0.25)(x)
    x = tf.keras.layers.Flatten()(x)
    outputs = tf.keras.layers.Dense(10)(x)
    model = tf.keras.Model(inputs, outputs)
    loss = lambda labels, logits: tf.reduce_mean(  # noqa: E731
        tf.nn.sparse_softmax_cross_entropy_with_logits(
            labels=labels, logits=logits
        )
    )
    return model, loss


def resnet50_model():
    model = tf.keras.applications.ResNet50(
        weights=None, input_shape=(32, 32, 3), classes=10
    )
    loss = lambda labels, probs: tf.reduce_mean(  # noqa: E731
        tf.keras.losses.sparse_categorical_crossentropy(labels, probs)
    )
    return model, loss


def imagenet_resnet50_model():
    model = tf.keras.applications.ResNet50(
        weights=None, input_shape=(224, 224, 3), classes=1000
    )
    loss = lambda labels, probs: tf.reduce_mean(  # noqa: E731
        tf.keras.losses.sparse_categorical_crossentropy(labels, probs)
    )
    return model, loss


class DeepFMBaseline(tf.keras.Model):
    """Subclassed (Keras-3-safe) DeepFM: embedding + bias tables, FM
    second-order term, flatten->Dense(64)->Dense(1) deep tower."""

    def __init__(self, input_dim=5383, embedding_dim=64):
        super().__init__()
        self.emb = tf.keras.layers.Embedding(input_dim, embedding_dim)
        self.bias = tf.keras.layers.Embedding(input_dim, 1)
        self.flatten = tf.keras.layers.Flatten()
        self.fc = tf.keras.layers.Dense(64)
        self.out = tf.keras.layers.Dense(1)

    def call(self, ids, training=False):
        # identical math to elasticdl_tpu/models/deepfm_functional_api.py:
        # mask_zero on id 0, no activation on the deep tower
        mask = tf.cast(tf.not_equal(ids, 0), tf.float32)
        emb = self.emb(ids) * mask[..., None]
        first = tf.reduce_sum(
            tf.squeeze(self.bias(ids), -1) * mask, -1
        )
        sum_sq = tf.square(tf.reduce_sum(emb, 1))
        sq_sum = tf.reduce_sum(tf.square(emb), 1)
        fm = 0.5 * tf.reduce_sum(sum_sq - sq_sum, -1)
        deep = tf.squeeze(self.out(self.fc(self.flatten(emb))), -1)
        return first + fm + deep


def deepfm_model():
    loss = lambda labels, logits: tf.reduce_mean(  # noqa: E731
        tf.nn.sigmoid_cross_entropy_with_logits(
            labels=tf.cast(labels, tf.float32), logits=logits
        )
    )
    return DeepFMBaseline(), loss


def make_batch(name, rng):
    b = BATCHES[name]
    if name == "mnist":
        return (
            tf.constant(rng.rand(b, 28, 28).astype(np.float32)),
            tf.constant(rng.randint(0, 10, b).astype(np.int32)),
        )
    if name == "resnet50_cifar10":
        return (
            tf.constant(rng.rand(b, 32, 32, 3).astype(np.float32)),
            tf.constant(rng.randint(0, 10, b).astype(np.int32)),
        )
    if name == "imagenet_resnet50":
        return (
            tf.constant(rng.rand(b, 224, 224, 3).astype(np.float32)),
            tf.constant(rng.randint(0, 1000, b).astype(np.int32)),
        )
    return (
        tf.constant(rng.randint(0, 5383, (b, 10)).astype(np.int32)),
        tf.constant(rng.randint(0, 2, b).astype(np.int32)),
    )


MODELS = {
    "mnist": mnist_model,
    "resnet50_cifar10": resnet50_model,
    "imagenet_resnet50": imagenet_resnet50_model,
    "deepfm": deepfm_model,
}


def measure(name, steps, warmup=3):
    model, loss_fn = MODELS[name]()
    opt = tf.keras.optimizers.SGD(0.1)
    features, labels = make_batch(name, np.random.RandomState(0))

    @tf.function
    def train_step(features, labels):
        with tf.GradientTape() as tape:
            outputs = model(features, training=True)
            loss = loss_fn(labels, outputs)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    for _ in range(warmup):
        train_step(features, labels)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step(features, labels)
    _ = float(loss)  # sync
    dt = time.perf_counter() - t0
    return steps * BATCHES[name] / dt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=20)
    p.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "baseline.json"),
    )
    p.add_argument("--models", nargs="*", default=sorted(MODELS))
    args = p.parse_args(argv)

    # merge into an existing baseline file so a partial --models rerun
    # (e.g. after changing one model's batch size) keeps the other
    # anchors — every value in the file is script-produced either way
    results = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            results = json.load(f).get("samples_per_sec", {})
    for name in args.models:
        sps = measure(name, args.steps)
        results[name] = round(sps, 1)
        print(f"{name}: {sps:.1f} samples/sec", file=sys.stderr)
    payload = {
        "design": "tf2 tf.function GradientTape step, host CPU",
        "tf_version": tf.__version__,
        "batch_sizes": BATCHES,
        "samples_per_sec": results,
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
