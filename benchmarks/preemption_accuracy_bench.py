"""Accuracy-under-preemption gate (BASELINE.md config 5, conjunctive).

The reference's elastic acceptance is not "survives a kill" OR "reaches
accuracy" — it is both at once: a worker preempted mid-run must not cost
records (silently lost gradients) or double-train them (double-consumed
tasks), and the finished job must still clear the accuracy bar.  The r3
suite proved the two halves separately (``reform_bench.py`` checked
record accounting, the bench accuracy mode trained undisturbed); this
gate runs them TOGETHER (VERDICT r3 #3):

1. a real 2-process lockstep job trains synthetic mnist, one worker is
   SIGKILLed mid-run (the exact machinery of ``reform_bench.measure``),
   the world re-forms from hot standbys and the job completes —
   asserting exactly-once record accounting;
2. the job's final re-shardable checkpoint is restored into a
   single-process evaluator and scored on a held-out split — asserting
   the post-preemption model still clears the bar.

Prints ONE JSON line:
  {"accuracy": A, "records_ok": true, "reform_latency_secs": R,
   "threshold": 0.8, "pass": true}

Run standalone: ``python benchmarks/preemption_accuracy_bench.py``.
``bench.py`` invokes it in a ``JAX_PLATFORMS=cpu`` subprocess (the kill
job must never touch the chip the throughput configs are timing).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

THRESHOLD = 0.8
# 1024 records x 2 epochs = 64 steps at batch 32: comfortably past the
# bar for the learnable synthetic mnist (0.94 observed at 32 steps in
# tests/test_trainer_local.py) while keeping the 2-process CPU job short
NUM_RECORDS = 1024
NUM_EPOCHS = 2


def measure(workdir: str) -> dict:
    from benchmarks.reform_bench import measure as reform_measure

    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    reform = reform_measure(
        workdir, num_records=NUM_RECORDS, num_epochs=NUM_EPOCHS
    )

    # score the checkpoint the preempted-and-reformed job wrote; the
    # restore re-shards the 2-process lockstep layout onto this
    # process's local mesh (utils/save_utils.py reshard property)
    eval_dir = synthetic.gen_mnist(
        os.path.join(workdir, "eval"), num_records=512, num_shards=1, seed=9
    )
    ckpt = os.path.join(workdir, "ckpt")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--validation_data",
            eval_dir,
            "--minibatch_size",
            "32",
            "--records_per_task",
            "512",
            "--checkpoint_dir",
            ckpt,
            "--compute_dtype",
            "float32",
        ]
    )
    results = LocalExecutor(args).run()
    acc = float(results.get("accuracy", 0.0))
    return {
        "accuracy": round(acc, 4),
        "records_ok": bool(reform["records_ok"]),
        "reform_latency_secs": reform["reform_latency_secs"],
        "standby_activated": reform["standby_activated"],
        "threshold": THRESHOLD,
        "pass": bool(reform["records_ok"]) and acc >= THRESHOLD,
    }


def main():
    with tempfile.TemporaryDirectory() as workdir:
        print(json.dumps(measure(workdir)))


if __name__ == "__main__":
    main()
