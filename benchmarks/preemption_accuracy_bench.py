"""Accuracy-under-preemption gate (BASELINE.md config 5, conjunctive).

The reference's elastic acceptance is not "survives a kill" OR "reaches
accuracy" — it is both at once: a worker preempted mid-run must not cost
records (silently lost gradients) or double-train them (double-consumed
tasks), and the finished job must still clear the accuracy bar.  This
gate is a thin consumer of the chaos harness: ONE
``preempt_one_worker`` chaos job trains synthetic mnist to the accuracy
budget, the injected kill re-forms the world from hot standbys, the
harness asserts exactly-once record accounting, and the final
re-shardable checkpoint is restored into a single-process evaluator and
scored on a held-out split.

Prints ONE JSON line (schema unchanged since r3):
  {"accuracy": A, "records_ok": true, "reform_latency_secs": R,
   "threshold": 0.8, "pass": true}

Run standalone: ``python benchmarks/preemption_accuracy_bench.py``.
``bench.py`` invokes it in a ``JAX_PLATFORMS=cpu`` subprocess (the kill
job must never touch the chip the throughput configs are timing).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

THRESHOLD = 0.8
# 1024 records x 2 epochs = 64 steps at batch 32: comfortably past the
# bar for the learnable synthetic mnist (0.94 observed at 32 steps in
# tests/test_trainer_local.py) while keeping the 2-process CPU job short
NUM_RECORDS = 1024
NUM_EPOCHS = 2


def measure(workdir: str) -> dict:
    from benchmarks.reform_bench import measure as reform_measure

    reform = reform_measure(
        workdir,
        num_records=NUM_RECORDS,
        num_epochs=NUM_EPOCHS,
        evaluate=True,
    )
    acc = float(reform.get("accuracy", 0.0))
    return {
        "accuracy": round(acc, 4),
        "records_ok": bool(reform["records_ok"]),
        "reform_latency_secs": reform["reform_latency_secs"],
        "standby_activated": reform["standby_activated"],
        "threshold": THRESHOLD,
        "pass": bool(reform["records_ok"]) and acc >= THRESHOLD,
    }


def main():
    with tempfile.TemporaryDirectory() as workdir:
        print(json.dumps(measure(workdir)))


if __name__ == "__main__":
    main()
