"""Decode a jax.profiler trace into per-op / per-category roofline rows.

The in-tree ``StepProfiler`` (``--profile_dir``) captures an xplane
protobuf; the TensorBoard profile plugin in this image cannot parse it
(TF/plugin version skew), so this decodes the proto directly: every XLA
op event carries ``hlo_category``, ``flops``, ``bytes_accessed``,
``source`` and a device duration — enough to attribute step time and
compute achieved TFLOP/s / GB/s per category (the evidence behind the
cifar10 roofline analysis in ``docs/designs/mixed_precision_mfu.md``).

Usage:
  python benchmarks/trace_tools.py <trace_dir_or_xplane.pb>

Prints ONE JSON line: {"device_ms_per_step": ..., "categories": {...}}
(assumes the trace window held `steps` equal steps; pass --steps N,
default 3 — the StepProfiler window default is 5).
"""

from __future__ import annotations

import glob
import json
import os
import sys
from collections import defaultdict


def find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    files = glob.glob(
        os.path.join(path, "**", "*.xplane.pb"), recursive=True
    )
    if not files:
        raise FileNotFoundError(f"no *.xplane.pb under {path}")
    return max(files, key=os.path.getmtime)


def decode(xplane_path: str) -> dict:
    """{category: {"secs": s, "flops": f, "bytes": b}} for the TPU plane's
    'XLA Ops' line, plus the total device seconds."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(xplane_path, "rb") as f:
        xs.ParseFromString(f.read())
    planes = [p for p in xs.planes if p.name.startswith("/device:")]
    if not planes:
        raise ValueError(f"no device plane in {xplane_path}")
    plane = planes[0]
    stat_meta = {m.id: m.name for m in plane.stat_metadata.values()}
    meta = plane.event_metadata

    def stat(md, key):
        for s in md.stats:
            if stat_meta.get(s.metadata_id) == key:
                for field in (
                    "double_value",
                    "int64_value",
                    "uint64_value",
                    "str_value",
                ):
                    if s.HasField(field):
                        return getattr(s, field)
        return None

    lines = [l for l in plane.lines if l.name == "XLA Ops"]
    if not lines:
        raise ValueError(
            f"no 'XLA Ops' line; lines: {[l.name for l in plane.lines]}"
        )
    cats: dict = defaultdict(lambda: [0.0, 0.0, 0.0])
    for e in lines[0].events:
        md = meta[e.metadata_id]
        c = stat(md, "hlo_category") or "unknown"
        cats[c][0] += e.duration_ps / 1e12
        cats[c][1] += float(stat(md, "flops") or 0)
        cats[c][2] += float(stat(md, "bytes_accessed") or 0)
    return {
        c: {"secs": t, "flops": f, "bytes": b}
        for c, (t, f, b) in cats.items()
    }


def main():
    argv = sys.argv[1:]
    args = []
    steps = 3
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--steps" or a.startswith("--steps="):
            if "=" in a:
                steps = int(a.split("=", 1)[1])
            elif i + 1 < len(argv):  # space form: --steps N
                i += 1
                steps = int(argv[i])
            else:
                print("--steps requires a value", file=sys.stderr)
                return 2
        elif a.startswith("--"):
            pass  # unknown flags are ignored, never treated as paths
        else:
            args.append(a)
        i += 1
    if not args:
        print(__doc__)
        return 1
    cats = decode(find_xplane(args[0]))
    total = sum(v["secs"] for v in cats.values())
    out = {
        "device_ms_per_step": round(total / steps * 1000, 3),
        "categories": {
            c: {
                "time_pct": round(v["secs"] / total * 100, 1),
                "tflops_per_sec": round(v["flops"] / v["secs"] / 1e12, 1)
                if v["secs"]
                else 0,
                "gb_per_sec": round(v["bytes"] / v["secs"] / 1e9)
                if v["secs"]
                else 0,
            }
            for c, v in sorted(
                cats.items(), key=lambda kv: -kv[1]["secs"]
            )
        },
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
