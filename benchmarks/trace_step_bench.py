"""Capture a device trace of ONE bench step config and decode it into
the per-category roofline rows behind ``docs/designs/mixed_precision_mfu.md``.

Usage:
  python benchmarks/trace_step_bench.py <config_name> [--steps N]

Builds the config's SPMDTrainer exactly as ``bench.py _measure`` does,
warms the step up, then traces N per-step dispatches (same placed
buffers — the dispatch overhead is host-side and invisible to the
device plane this decodes) and prints ONE JSON line:

  {"config": ..., "device_ms_per_step": ..., "mfu_on_trace": ...,
   "categories": {cat: {time_pct, tflops_per_sec, gb_per_sec}},
   "attention": {...}}   # when the config runs the pallas flash kernel

``attention`` reports the flash kernel's share of device time and its
ACHIEVED TFLOP/s from the config's analytic attention flops — the
number XLA cost analysis cannot see (pallas custom calls report zero
flops), i.e. the evidence VERDICT r4 weak #6 asked for.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# pallas/custom-call categories the flash kernel can land in
_ATTN_CATEGORIES = ("custom-call", "custom call", "fusion.custom")


def main() -> int:
    args = sys.argv[1:]
    steps = 3
    positional = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--steps":
            i += 1
            steps = int(args[i])
        elif a.startswith("--steps="):
            steps = int(a.split("=", 1)[1])
        elif not a.startswith("--"):
            positional.append(a)
        i += 1
    if not positional:
        print(__doc__)
        return 1
    name = positional[0]

    import jax

    import bench
    from benchmarks import trace_tools
    from elasticdl_tpu.parallel.distributed import SPMDTrainer
    from elasticdl_tpu.parallel.mesh import MeshConfig
    from elasticdl_tpu.trainer.local_executor import build_optimizer
    from elasticdl_tpu.utils.model_utils import get_model_spec

    mesh = MeshConfig.from_string("").create()
    cfg = bench._configs(max(1, mesh.devices.size))[name]
    spec = get_model_spec(
        "", cfg["model_def"], model_params=cfg.get("model_params")
    )
    rules = ()
    if spec.sharding_rules is not None:
        rules = tuple(spec.sharding_rules(mesh))
    trainer = SPMDTrainer(
        mesh,
        spec.build_model(),
        spec.loss,
        build_optimizer(spec, None),
        cfg["features"],
        rules=rules,
        compute_dtype="bfloat16",
    )
    pf = trainer.place_batch(cfg["features"])
    pl = trainer.place_batch(cfg["labels"])

    trainer._train_step(trainer.state, pf, pl)  # compile + warm
    int(jax.device_get(trainer.state.step))

    with tempfile.TemporaryDirectory() as td:
        jax.profiler.start_trace(td)
        state = trainer.state
        for _ in range(steps):
            state, _ = trainer._train_step(state, pf, pl)
        int(jax.device_get(state.step))  # the only trusted barrier here
        jax.profiler.stop_trace()
        cats = trace_tools.decode(trace_tools.find_xplane(td))

    total_secs = sum(v["secs"] for v in cats.values())
    total_flops = sum(v["flops"] for v in cats.values())
    attn_flops = float(cfg.get("attn_flops_per_step", 0.0)) * steps
    out = {
        "config": name,
        "steps_traced": steps,
        "device_ms_per_step": round(total_secs / steps * 1000, 3),
        "categories": {
            c: {
                "time_pct": round(v["secs"] / total_secs * 100, 1),
                "tflops_per_sec": round(
                    v["flops"] / v["secs"] / 1e12, 1
                )
                if v["secs"]
                else 0,
                "gb_per_sec": round(v["bytes"] / v["secs"] / 1e9)
                if v["secs"]
                else 0,
            }
            for c, v in sorted(cats.items(), key=lambda kv: -kv[1]["secs"])
        },
    }
    peak = bench._peak_flops(mesh.devices.flatten()[0])
    if peak:
        out["mfu_on_trace"] = round(
            (total_flops + attn_flops) / total_secs / peak, 4
        )
    if attn_flops:
        attn_secs = sum(
            v["secs"]
            for c, v in cats.items()
            if any(tag in c.lower() for tag in _ATTN_CATEGORIES)
        )
        attn_bytes = sum(
            v["bytes"]
            for c, v in cats.items()
            if any(tag in c.lower() for tag in _ATTN_CATEGORIES)
        )
        out["attention"] = {
            "time_pct": round(attn_secs / total_secs * 100, 1)
            if total_secs
            else 0,
            # analytic flops (6*L*B*T^2*d) over the kernel's own device
            # time: the flash kernel's ACHIEVED TFLOP/s
            "achieved_tflops_per_sec": round(
                attn_flops / attn_secs / 1e12, 1
            )
            if attn_secs
            else None,
            "achieved_gb_per_sec": round(attn_bytes / attn_secs / 1e9)
            if attn_secs
            else None,
            "analytic_flops_per_step": attn_flops / steps,
            "pct_of_peak": round(attn_flops / attn_secs / peak * 100, 1)
            if attn_secs and peak
            else None,
        }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
