"""Sweep the flash-attention kernel geometry on a bench transformer config.

VERDICT r4 weak #6's alternative acceptance is taking the seq-8192
config's exposed headroom (block size / grid / VMEM knobs in
``ops/attention.py``).  This sweeps (``_SEQ_CHUNK``, ``block_q``,
``block_k``) on the FULL train step of a bench config — the same
fori_loop + data-dependent-readback timing as bench.py, so dispatch
latency and unreliable device sync cannot inflate anything — and prints
one JSON line of tokens/sec per geometry, best first.

Usage:
  python benchmarks/attention_sweep.py [config_name] [--steps N]
  (default config: transformer_seq8192)

Each geometry recompiles the step (~1-3 min on the tunneled dev link),
so the sweep list is small and targeted.  The current defaults
(chunk 2048, 512x512 blocks) are the r3-measured optimum; this exists
to re-test them at seq 8192 where the backward's chunk-carried scratch
changes the picture.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# (seq_chunk, block_q, block_k)
SWEEP = [
    (2048, 512, 512),  # current defaults (r3 optimum at seq <= 2048)
    (2048, 1024, 512),
    (2048, 512, 1024),
    (4096, 512, 512),
    (4096, 1024, 1024),
    (1024, 512, 512),
]


def main() -> int:
    args = sys.argv[1:]
    steps = 10
    positional = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--steps":
            i += 1
            steps = int(args[i])
        elif a.startswith("--steps="):
            steps = int(a.split("=", 1)[1])
        elif not a.startswith("--"):
            positional.append(a)
        i += 1
    name = positional[0] if positional else "transformer_seq8192"

    import jax

    import bench
    from elasticdl_tpu.ops import attention as attention_mod
    from elasticdl_tpu.parallel.distributed import SPMDTrainer
    from elasticdl_tpu.parallel.mesh import MeshConfig
    from elasticdl_tpu.trainer.local_executor import build_optimizer
    from elasticdl_tpu.utils.model_utils import get_model_spec

    mesh = MeshConfig.from_string("").create()
    cfg = bench._configs(max(1, mesh.devices.size))[name]
    spec = get_model_spec(
        "", cfg["model_def"], model_params=cfg.get("model_params")
    )
    rules = ()
    if spec.sharding_rules is not None:
        rules = tuple(spec.sharding_rules(mesh))

    orig_flash = attention_mod.flash_attention
    orig_chunk = attention_mod._SEQ_CHUNK
    tokens_per_step = cfg["batch"] * cfg.get("tokens_per_sample", 1)
    results = []
    for seq_chunk, bq, bk in SWEEP:
        attention_mod._SEQ_CHUNK = seq_chunk

        def patched(q, k, v, **kw):
            kw.setdefault("block_q", bq)  # noqa: B023 — rebound per loop
            kw.setdefault("block_k", bk)  # noqa: B023
            return orig_flash(q, k, v, **kw)

        attention_mod.flash_attention = patched
        try:
            trainer = SPMDTrainer(
                mesh,
                spec.build_model(),
                spec.loss,
                build_optimizer(spec, None),
                cfg["features"],
                rules=rules,
                compute_dtype="bfloat16",
            )
            pf = trainer.place_batch(cfg["features"])
            pl = trainer.place_batch(cfg["labels"])
            step_fn = trainer._train_step

            def many(state, f, l):
                return jax.lax.fori_loop(
                    0, steps, lambda _i, s: step_fn(s, f, l)[0], state
                )

            compiled = (
                jax.jit(many, donate_argnums=(0,))
                .lower(trainer.state, pf, pl)
                .compile()
            )
            state = compiled(trainer.state, pf, pl)  # warm
            int(jax.device_get(state.step))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                state = compiled(state, pf, pl)
                int(jax.device_get(state.step))
                best = min(best, time.perf_counter() - t0)
            rate = steps * tokens_per_step / best
            results.append(
                {
                    "seq_chunk": seq_chunk,
                    "block_q": bq,
                    "block_k": bk,
                    "tokens_per_sec_per_chip": round(rate),
                }
            )
            print(
                f"sweep: chunk={seq_chunk} bq={bq} bk={bk} -> "
                f"{rate:.0f} tok/s",
                file=sys.stderr,
            )
        except Exception as ex:  # noqa: BLE001 — a geometry may OOM VMEM
            results.append(
                {
                    "seq_chunk": seq_chunk,
                    "block_q": bq,
                    "block_k": bk,
                    "error": str(ex)[:160],
                }
            )
            print(
                f"sweep: chunk={seq_chunk} bq={bq} bk={bk} FAILED: "
                f"{str(ex)[:160]}",
                file=sys.stderr,
            )
        finally:
            attention_mod.flash_attention = orig_flash
            attention_mod._SEQ_CHUNK = orig_chunk

    results.sort(
        key=lambda r: -(r.get("tokens_per_sec_per_chip") or 0)
    )
    print(json.dumps({"config": name, "steps": steps, "sweep": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
