"""Elastic re-formation latency benchmark (BASELINE.md config 5).

A thin consumer of the chaos harness (``elasticdl_tpu.chaos.harness``):
a real 2-process lockstep job on the host CPU backend runs under the
``preempt_one_worker`` fault plan — one worker SIGKILLs itself at a
deterministic training step — and the harness measures the mesh
re-formation the master performs plus checks the elastic invariants
(reference behavior: pod kill -> task re-queue -> relaunch,
``elasticdl/python/master/k8s_instance_manager.py:241-275``).

Prints ONE JSON line (schema unchanged since r3):
  {"reform_latency_secs": R, "kill_to_step_secs": T,
   "detect_secs": D, "records_ok": true}

- ``reform_latency_secs`` — detection -> first step-task pull of the new
  world (the re-form cost the framework controls).
- ``kill_to_step_secs``  — SIGKILL -> first post-re-form step pull (adds
  the heartbeat detection window, like the reference's k8s watch delay).

Run standalone: ``python benchmarks/reform_bench.py``.  ``bench.py``
invokes it in a subprocess with ``JAX_PLATFORMS=cpu`` so the measurement
never touches the TPU chip the throughput configs are using.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

HEARTBEAT_TIMEOUT_SECS = 3


def measure(
    workdir: str,
    num_records: int = 512,
    num_epochs: int = 2,
    evaluate: bool = False,
) -> dict:
    """Run the kill-and-reform lockstep job through the chaos harness;
    returns the reform metrics (plus ``accuracy`` when ``evaluate``).

    Parameterized so the accuracy-under-preemption gate
    (``preemption_accuracy_bench.py``) can reuse the exact same
    kill/re-form machinery on a to-accuracy training budget."""
    from elasticdl_tpu.chaos.harness import ChaosJobConfig, run_chaos_job
    from elasticdl_tpu.chaos.plan import named_plan

    report = run_chaos_job(
        ChaosJobConfig(
            plan=named_plan("preempt_one_worker", num_workers=2),
            workdir=workdir,
            num_records=num_records,
            num_epochs=num_epochs,
            heartbeat_timeout_secs=HEARTBEAT_TIMEOUT_SECS,
            evaluate=evaluate,
        )
    )
    out = {
        "reform_latency_secs": report["reform_latency_secs"],
        "detect_secs": report["detect_secs"],
        "kill_to_step_secs": report["kill_to_step_secs"],
        "records_ok": report["records_ok"],
        "heartbeat_timeout_secs": HEARTBEAT_TIMEOUT_SECS,
        # >0 proves the re-formed world came from the hot-standby pool
        # (the cold-start path would dominate reform_latency_secs)
        "standby_activated": report["standby_activated"],
    }
    if not out["records_ok"]:
        out["rc"] = [report["rc"]] if report["rc"] is not None else []
        out["total_records"] = report.get("total_records")
    if evaluate:
        out["accuracy"] = report.get("accuracy", 0.0)
    return out


def main():
    with tempfile.TemporaryDirectory() as workdir:
        print(json.dumps(measure(workdir)))


if __name__ == "__main__":
    main()
