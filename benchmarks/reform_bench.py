"""Elastic re-formation latency benchmark (BASELINE.md config 5).

Runs a real 2-process lockstep job on the host CPU backend, SIGKILLs one
worker mid-epoch, and measures the mesh re-formation the master performs
(reference behavior: pod kill -> task re-queue -> relaunch,
``elasticdl/python/master/k8s_instance_manager.py:241-275``; here the
whole ``jax.distributed`` world is fenced, re-queued, and relaunched —
``master/master.py:_handle_dead_workers``).

Prints ONE JSON line:
  {"reform_latency_secs": R, "kill_to_step_secs": T,
   "detect_secs": D, "records_ok": true}

- ``reform_latency_secs`` — detection -> first step-task pull of the new
  world (the re-form cost the framework controls).
- ``kill_to_step_secs``  — SIGKILL -> first post-re-form step pull (adds
  the heartbeat detection window, like the reference's k8s watch delay).

Run standalone: ``python benchmarks/reform_bench.py``.  ``bench.py``
invokes it in a subprocess with ``JAX_PLATFORMS=cpu`` so the measurement
never touches the TPU chip the throughput configs are using.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

HEARTBEAT_TIMEOUT_SECS = 3


def measure(
    workdir: str, num_records: int = 512, num_epochs: int = 2
) -> dict:
    """Run the kill-and-reform lockstep job; returns the reform metrics.

    Parameterized so the accuracy-under-preemption gate
    (``preemption_accuracy_bench.py``) can reuse the exact same
    kill/re-form machinery on a to-accuracy training budget."""
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.master.main import build_master
    from elasticdl_tpu.utils.args import parse_master_args
    from elasticdl_tpu.utils.constants import TaskType

    train = synthetic.gen_mnist(
        os.path.join(workdir, "train"),
        num_records=num_records,
        num_shards=2,
        seed=3,
    )
    ckpt = os.path.join(workdir, "ckpt")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train,
            "--minibatch_size",
            "32",
            "--records_per_task",
            "64",
            "--num_epochs",
            str(num_epochs),
            "--compute_dtype",
            "float32",
            "--shuffle_seed",
            "5",
            "--jax_platform",
            "cpu",
            "--envs",
            "JAX_PLATFORMS=cpu,XLA_FLAGS= ",
            "--port",
            "0",
            "--distribution_strategy",
            "AllreduceStrategy",
            "--num_workers",
            "2",
            "--checkpoint_dir",
            ckpt,
            "--checkpoint_steps",
            "2",
            "--heartbeat_timeout_secs",
            str(HEARTBEAT_TIMEOUT_SECS),
        ]
    )
    master = build_master(args)
    master.prepare()
    rc: list[int] = []
    runner = threading.Thread(target=lambda: rc.append(master.run()))
    runner.start()
    killed_at = None
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if os.path.isdir(ckpt) and any(
                name.startswith("version-") for name in os.listdir(ckpt)
            ):
                break
            time.sleep(0.25)
        else:
            raise RuntimeError("job never reached the first checkpoint")

        victims = master.instance_manager.worker_ids()
        victim = master.instance_manager._procs[victims[-1]]
        killed_at = time.monotonic()
        os.kill(victim.pid, signal.SIGKILL)

        runner.join(timeout=600)
        if runner.is_alive():
            raise RuntimeError("master never finished after the kill")
    finally:
        master.request_stop()
        runner.join(timeout=30)

    counters = master.task_d.counters(TaskType.TRAINING)
    # the event CAUSED BY our kill: under heavy host contention a worker
    # can miss heartbeats while compiling and trigger a spurious pre-kill
    # re-form — blindly reading [0] then yields a negative detect_secs
    event = next(
        (
            e
            for e in master.reform_events
            if e["detected_at"] >= killed_at
        ),
        master.reform_events[0] if master.reform_events else {},
    )
    pull_at = master.servicer.first_stream_pull_at()
    out = {
        "reform_latency_secs": round(event.get("latency_secs", -1.0), 3),
        "detect_secs": (
            round(event["detected_at"] - killed_at, 3)
            if event and killed_at is not None
            else None
        ),
        "kill_to_step_secs": (
            round(pull_at - killed_at, 3)
            if pull_at is not None and killed_at is not None
            else None
        ),
        "records_ok": (
            rc == [0]
            and master.task_d.finished()
            and counters.total_records == num_epochs * num_records
        ),
        "heartbeat_timeout_secs": HEARTBEAT_TIMEOUT_SECS,
        # >0 proves the re-formed world came from the hot-standby pool
        # (the cold-start path would dominate reform_latency_secs)
        "standby_activated": master.instance_manager.standby_activations,
    }
    if not out["records_ok"]:
        out["rc"] = rc
        out["total_records"] = counters.total_records
    return out


def main():
    with tempfile.TemporaryDirectory() as workdir:
        print(json.dumps(measure(workdir)))


if __name__ == "__main__":
    main()
