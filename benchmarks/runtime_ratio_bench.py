"""Relative e2e data-plane throughput of the three training runtimes.

One host-CPU measurement on IDENTICAL data (deepfm/frappe shards — the
data-plane showcase config) for:

- ``LocalExecutor`` — the e2e reference point (``elasticdl train
  --distribution_strategy=Local``),
- the task-stream ``Worker`` against an in-process master — VERDICT r5
  #3's acceptance: its training throughput must sit within ~1.2x of
  LocalExecutor now that it shares the vectorized plane,
- a REAL 2-process lockstep world (``--num_workers 2``) — VERDICT r5
  #8: the every-process-reads-every-task design (worker/lockstep.py)
  has a host-decode cost that scales with world size; this records it
  as ``lockstep_e2e_vs_local`` instead of leaving it an assumption.
  On this one-core host the two processes also serialize their compute
  halves, so the ratio is a LOWER bound for multi-core hosts.

Window: first task-report -> last task-report (compile happens inside
the first task, so it is excluded), records = tasks-after-first x
records_per_task (all tasks equal-size by construction), with a final
device sync before the last mark.

Prints ONE JSON line:
  {"local_records_per_sec": L, "taskstream_records_per_sec": T,
   "taskstream_vs_local": T/L, "lockstep_records_per_sec": K,
   "lockstep_e2e_vs_local": K/L, ...}

Run standalone: ``python benchmarks/runtime_ratio_bench.py``; bench.py
invokes it in a ``JAX_PLATFORMS=cpu`` subprocess so it never touches
the TPU chip the throughput configs are timing.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the TPU plugin can ignore the env var alone (tunneled dev hosts): pin
# via config too, BEFORE any backend initializes — this benchmark must
# never touch the chip bench.py's throughput configs are timing
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

MODEL_DEF = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
NUM_RECORDS = 131072
RECORDS_PER_TASK = 16384
BATCH = 512
STEPS_PER_DISPATCH = 16


def _argv(train_dir: str, extra=()) -> list[str]:
    return [
        "--model_def",
        MODEL_DEF,
        "--training_data",
        train_dir,
        "--minibatch_size",
        str(BATCH),
        "--records_per_task",
        str(RECORDS_PER_TASK),
        "--num_epochs",
        "1",
        "--steps_per_dispatch",
        str(STEPS_PER_DISPATCH),
        "--compute_dtype",
        "float32",
        *extra,
    ]


class _TaskMarks:
    """Thread-safe (tid -> first-report wall time) recorder; lockstep
    worlds report each task once per process, so duplicates are
    ignored."""

    def __init__(self):
        self._lock = threading.Lock()
        self.marks: dict[int, float] = {}

    def record(self, tid: int):
        with self._lock:
            self.marks.setdefault(tid, time.perf_counter())

    def rate(self, final_sync=None) -> float:
        """Records/sec over the steady window (first report excluded —
        it absorbs the jit compile)."""
        times = sorted(self.marks.values())
        if len(times) < 2:
            raise RuntimeError(
                f"need >= 2 task reports for a window, got {len(times)}"
            )
        if final_sync is not None:
            final_sync()
            end = time.perf_counter()
        else:
            end = times[-1]
        return (len(times) - 1) * RECORDS_PER_TASK / (end - times[0])


def _measure_local(train_dir: str) -> float:
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    marks = _TaskMarks()

    class _Timed(LocalExecutor):
        def _train_task(self, task, batches=None):
            n = super()._train_task(task, batches)
            marks.record(id(task))
            return n

    executor = _Timed(parse_master_args(_argv(train_dir)))
    executor.run()

    def sync():
        import jax

        int(jax.device_get(executor.trainer.state.step))

    return marks.rate(final_sync=sync)


def _measure_taskstream(train_dir: str) -> float:
    from elasticdl_tpu.data.recordio_reader import RecordIODataReader
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.utils.args import parse_worker_args
    from elasticdl_tpu.utils.constants import JobType
    from elasticdl_tpu.worker.worker import Worker

    reader = RecordIODataReader(data_dir=train_dir)
    task_d = TaskDispatcher(
        reader.create_shards(), records_per_task=RECORDS_PER_TASK
    )
    master = MasterServicer(BATCH, task_d)
    marks = _TaskMarks()
    orig = master.report_task_result

    def recording(request):
        marks.record(request.task_id)
        return orig(request)

    master.report_task_result = recording
    worker = Worker(
        parse_worker_args(
            _argv(train_dir, extra=("--worker_id", "0"))
            + ["--master_addr", "inprocess"]
        ),
        master,
        job_type=JobType.TRAINING_ONLY,
    )
    worker.run()
    if not task_d.finished():
        raise RuntimeError("task-stream job did not finish")

    def sync():
        import jax

        int(jax.device_get(worker.trainer.state.step))

    return marks.rate(final_sync=sync)


def _measure_lockstep(train_dir: str) -> float:
    from elasticdl_tpu.master.main import build_master
    from elasticdl_tpu.utils.args import parse_master_args
    from elasticdl_tpu.utils.constants import TaskType

    args = parse_master_args(
        _argv(train_dir)
        + [
            "--distribution_strategy",
            "AllreduceStrategy",
            "--num_workers",
            "2",
            "--jax_platform",
            "cpu",
            "--envs",
            "JAX_PLATFORMS=cpu,XLA_FLAGS= ",
            "--port",
            "0",
        ]
    )
    master = build_master(args)
    marks = _TaskMarks()
    orig = master.task_d.report

    def recording(tid, success, **kw):
        out = orig(tid, success, **kw)
        marks.record(tid)
        return out

    master.task_d.report = recording
    master.prepare()
    rc = master.run()
    if rc != 0 or not master.task_d.finished():
        raise RuntimeError(f"lockstep job failed rc={rc}")
    counters = master.task_d.counters(TaskType.TRAINING)
    if counters.total_records != NUM_RECORDS:
        raise RuntimeError(
            f"lockstep processed {counters.total_records} != {NUM_RECORDS}"
        )
    # workers sync before reporting their last task; no device handle here
    return marks.rate()


def main():
    from elasticdl_tpu.data.recordio_gen import synthetic

    with tempfile.TemporaryDirectory() as td:
        train_dir = synthetic.gen_frappe(
            os.path.join(td, "train"),
            num_records=NUM_RECORDS,
            num_shards=8,
            seed=0,
        )
        local = _measure_local(train_dir)
        taskstream = _measure_taskstream(train_dir)
        lockstep = _measure_lockstep(train_dir)
    print(
        json.dumps(
            {
                "local_records_per_sec": round(local),
                "taskstream_records_per_sec": round(taskstream),
                "taskstream_vs_local": round(taskstream / local, 3),
                "lockstep_records_per_sec": round(lockstep),
                "lockstep_e2e_vs_local": round(lockstep / local, 3),
                "world_size": 2,
                "records": NUM_RECORDS,
                "batch": BATCH,
                "host_cores": os.cpu_count(),
                "platform": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
