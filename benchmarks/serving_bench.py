#!/usr/bin/env python3
"""Serving latency-SLO bench: p50/p99 under Poisson open-loop load.

The serving counterpart of the throughput benches: a real replica
(gRPC, micro-batcher, pre-compiled engine) is driven OPEN-LOOP — request
arrival times are pre-drawn from a seeded exponential process and fired
on schedule regardless of completions, so queueing delay under load is
measured, not hidden (a closed loop self-throttles and flatters p99).

Each QPS point reports p50/p95/p99 end-to-end latency AND the per-request
anatomy (queue_wait / assemble / h2d_transfer / device_compute /
d2h_transfer / untracked — the PR-9 phase discipline per request), with
the mean sum-residual asserted ~0 so a p99 miss is attributable to
queueing vs transfer vs compute by reading the artifact.

Two observability-plane blocks ride each point:

- ``trace_attribution`` — every request carries a client root span, so
  the replica's queue/engine spans land in per-request traces and the
  analyzer's serving critical path (sum-exact boundary sweep) reports
  the queue-vs-compute split of the measured wall, independent of the
  server's self-reported phases;
- ``slo`` — the point's signals (latency p99, queue_wait share, error
  rate) judged against the serving watchdog's default objectives: the
  same thresholds a production router would fire on, as a per-point
  pass/fail verdict.

    python benchmarks/serving_bench.py \
        [--model_dir DIR] [--qps 20,40,80] [--duration_secs 3] \
        [--rows_mix 1,4,8] [--minibatch_size 8] [--seed 0] \
        [--output SERVING_BENCH.json]

Without ``--model_dir`` a tiny MNIST job is trained and exported first
(self-contained CPU run; on a TPU host pass a real export).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np


def _train_tiny_export(workdir: str) -> str:
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.trainer.local_executor import LocalExecutor
    from elasticdl_tpu.utils.args import parse_master_args

    train_dir = synthetic.gen_mnist(
        os.path.join(workdir, "train"), num_records=32, num_shards=1, seed=1
    )
    export_dir = os.path.join(workdir, "export")
    args = parse_master_args(
        [
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data",
            train_dir,
            "--minibatch_size",
            "8",
            "--records_per_task",
            "32",
            "--num_epochs",
            "1",
            "--compute_dtype",
            "float32",
            "--output",
            export_dir,
        ]
    )
    LocalExecutor(args).run()
    return export_dir


def _percentiles(values: list, points=(50, 95, 99)) -> dict:
    if not values:
        return {f"p{p}": None for p in points}
    arr = np.asarray(values)
    return {f"p{p}": round(float(np.percentile(arr, p)), 4) for p in points}


def _sample_row_shape(model_dir: str):
    """A (row_shape, dtype, key) template for synthetic request rows,
    derived from the export's manifest (mnist-family: image rows)."""
    from elasticdl_tpu.utils.export_utils import read_manifest

    manifest = read_manifest(model_dir)
    model = manifest.get("model_def", "")
    if "mnist" in model:
        return (28, 28, 1), np.float32, "image"
    if "cifar" in model:
        return (32, 32, 3), np.float32, "image"
    if "iris" in model:
        return (4,), np.float32, "features"
    raise SystemExit(
        f"serving_bench: no synthetic request template for {model!r}; "
        "extend _sample_row_shape"
    )


def _slo_verdict(point: dict) -> dict:
    """The point's signals judged against the serving watchdog's
    DEFAULT objectives (fleet-state objectives — replica floor, swap
    reachability — have no meaning for one in-process replica and are
    omitted).  A bench artifact thereby says not just what the latency
    WAS but whether a default-config router would have fired on it."""
    from elasticdl_tpu.serving.watchdog import DEFAULT_SERVING_OBJECTIVES
    from elasticdl_tpu.telemetry import slo as slo_mod

    attempts = point["completed"] + point["errors"]
    signals = {}
    p99 = point["latency_ms"].get("p99")
    if p99 is not None:
        signals[slo_mod.SIGNAL_SERVING_LATENCY_P99_MS] = p99
    share = (point["anatomy"].get("queue_wait") or {}).get("share")
    if share is not None:
        signals[slo_mod.SIGNAL_QUEUE_WAIT_SHARE] = share
    if attempts:
        signals[slo_mod.SIGNAL_SERVING_ERROR_RATE] = (
            point["errors"] / attempts
        )
    objectives = {}
    for spec in DEFAULT_SERVING_OBJECTIVES:
        value = signals.get(spec["signal"])
        if value is None:
            continue
        threshold = float(spec["threshold"])
        bad = (
            value > threshold
            if spec["comparator"] == "above"
            else value < threshold
        )
        objectives[spec["name"]] = {
            "signal": spec["signal"],
            "value": round(float(value), 4),
            "comparator": spec["comparator"],
            "threshold": threshold,
            "ok": not bad,
        }
    return {
        "ok": all(o["ok"] for o in objectives.values()),
        "objectives": objectives,
    }


def _trace_attribution(point_dir: str) -> dict | None:
    """The analyzer's serving critical path over this point's traces:
    the queue-vs-compute split of measured request wall (sum-exact
    boundary sweep), plus honest coverage for the client-side time no
    server span explains."""
    from elasticdl_tpu.telemetry import tracing
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    tracing.flush()
    serving = analyze_telemetry_dir(point_dir).get("serving")
    if not serving:
        return None
    return {
        "requests": serving["requests"],
        "wall_secs_total": serving["wall_secs_total"],
        "phases_secs": serving["phases_secs"],
        "coverage": serving["coverage"],
        "dispatch_groups": serving["dispatch_groups"],
        "linked_dispatch_groups": serving["linked_dispatch_groups"],
    }


def run_point(
    client,
    qps: float,
    duration_secs: float,
    rows_mix: list,
    row_shape,
    dtype,
    key,
    rng: np.random.RandomState,
) -> dict:
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.telemetry import tracing

    n_requests = max(1, int(qps * duration_secs))
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    arrivals = np.cumsum(gaps)
    sizes = [int(rows_mix[i % len(rows_mix)]) for i in range(n_requests)]
    payloads = [
        msg.pack_array_tree(
            {key: rng.rand(n, *row_shape).astype(dtype)}
        )
        for n in sizes
    ]
    results: list = [None] * n_requests
    lock = threading.Lock()
    errors = [0]

    def fire(i: int, scheduled_at: float):
        # latency clocks from the SCHEDULED Poisson arrival, not worker
        # pickup: once the pool saturates, pickup-relative timing would
        # exclude exactly the queueing delay overload exists to measure
        # (silently closing the loop)
        tracer = tracing.get_tracer()
        span = (
            tracer.start_span(
                tracing.SPAN_PREDICT_REQUEST, request_id=f"bench-{i}"
            )
            if tracer is not None
            else None
        )
        try:
            response = client.predict(
                msg.PredictRequest(
                    request_id=f"bench-{i}",
                    features=payloads[i],
                    trace=span.context if span is not None else {},
                )
            )
        except Exception:  # noqa: BLE001 — an outage mid-point is data
            with lock:
                errors[0] += 1
            return
        finally:
            if span is not None:
                span.end()
        wall_ms = (time.monotonic() - scheduled_at) * 1000.0
        if response is None or response.error:
            with lock:
                errors[0] += 1
            return
        results[i] = (wall_ms, dict(response.phases), sizes[i])

    start = time.monotonic()
    offered = 0
    with ThreadPoolExecutor(max_workers=64) as pool:
        for i, at in enumerate(arrivals):
            delay = start + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            pool.submit(fire, i, start + at)
            offered += 1
    elapsed = time.monotonic() - start

    done = [r for r in results if r is not None]
    walls = [r[0] for r in done]
    server_totals = [r[1].get("total_ms", 0.0) for r in done]
    phase_names = sorted(
        {name for r in done for name in r[1] if name != "total_ms"}
    )
    anatomy = {}
    total_mean = float(np.mean(server_totals)) if server_totals else 0.0
    for name in phase_names:
        values = [r[1].get(name, 0.0) for r in done]
        anatomy[name] = {
            **_percentiles(values),
            "mean_ms": round(float(np.mean(values)), 4),
            "share": round(float(np.mean(values)) / total_mean, 4)
            if total_mean
            else None,
        }
    residuals = [
        r[1].get("total_ms", 0.0)
        - sum(v for k, v in r[1].items() if k != "total_ms")
        for r in done
    ]
    return {
        "qps_target": qps,
        "qps_offered": round(offered / elapsed, 2),
        "qps_completed": round(len(done) / elapsed, 2),
        "requests": offered,
        "completed": len(done),
        "errors": errors[0],
        "rows": sum(r[2] for r in done),
        "latency_ms": {
            **_percentiles(walls),
            "mean": round(float(np.mean(walls)), 4) if walls else None,
        },
        "server_total_ms": _percentiles(server_totals),
        "anatomy": anatomy,
        "anatomy_sum_residual_ms_mean": round(
            float(np.mean(np.abs(residuals))), 6
        )
        if residuals
        else None,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="serving latency bench")
    parser.add_argument("--model_dir", default="")
    parser.add_argument("--qps", default="20,40,80")
    parser.add_argument("--duration_secs", type=float, default=3.0)
    parser.add_argument("--rows_mix", default="1,4,8")
    parser.add_argument("--minibatch_size", type=int, default=8)
    parser.add_argument("--max_wait_ms", type=float, default=2.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--output", default="SERVING_BENCH.json")
    args = parser.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="edl_serving_bench_")
    model_dir = args.model_dir or _train_tiny_export(workdir)
    row_shape, dtype, key = _sample_row_shape(model_dir)

    from elasticdl_tpu.parallel.mesh import MeshConfig, batch_divisor
    from elasticdl_tpu.rpc import messages as msg
    from elasticdl_tpu.rpc.deadline import DeadlinePolicy
    from elasticdl_tpu.serving.replica import ServingClient, ServingReplica
    from elasticdl_tpu.trainer.stacking import canonical_batch_rows
    from elasticdl_tpu.utils.export_utils import read_manifest

    canonical = canonical_batch_rows(
        args.minibatch_size,
        batch_divisor(MeshConfig.from_string("").create()),
    )
    replica = ServingReplica(
        model_dir,
        canonical,
        max_wait_secs=args.max_wait_ms / 1000.0,
        port=0,
    ).start()
    client = ServingClient(
        f"localhost:{replica.port}", deadlines=DeadlinePolicy.from_secs(30)
    )
    rng = np.random.RandomState(args.seed)
    rows_mix = [int(x) for x in args.rows_mix.split(",") if x]
    try:
        # warmup: pay the one compile before any measured window
        warm = client.predict(
            msg.PredictRequest(
                request_id="warmup",
                features=msg.pack_array_tree(
                    {key: rng.rand(canonical, *row_shape).astype(dtype)}
                ),
            )
        )
        if warm.error:
            raise SystemExit(f"serving_bench: warmup failed: {warm.error}")
        compile0 = client.serving_status().compile_count
        points = []
        from elasticdl_tpu.telemetry import tracing

        for n, qps in enumerate(
            [float(x) for x in args.qps.split(",") if x]
        ):
            # one spans.jsonl per point: client roots + the replica's
            # queue/engine children (same process, same tracer), so the
            # attribution below covers exactly this point's requests
            point_dir = os.path.join(workdir, f"trace_point_{n}")
            tracing.install(point_dir, role="client")
            point = run_point(
                client,
                qps,
                args.duration_secs,
                rows_mix,
                row_shape,
                dtype,
                key,
                rng,
            )
            point["trace_attribution"] = _trace_attribution(point_dir)
            point["slo"] = _slo_verdict(point)
            tracing.uninstall()
            points.append(point)
        status = client.serving_status()
        artifact = {
            "bench": "serving",
            "stamped_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "model_dir": model_dir,
            "model_def": read_manifest(model_dir).get("model_def", ""),
            "model_version": status.model_version,
            "canonical_rows": canonical,
            "max_wait_ms": args.max_wait_ms,
            "rows_mix": rows_mix,
            "duration_secs_per_point": args.duration_secs,
            "seed": args.seed,
            "compile_count_post_warmup": compile0,
            "compile_count_final": status.compile_count,
            "steady_state_recompiles": status.compile_count - compile0,
            "points": points,
        }
    finally:
        client.close()
        replica.close()
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2)
    for point in points:
        attribution = point.get("trace_attribution") or {}
        phases = attribution.get("phases_secs") or {}
        attributed = sum(v for k, v in phases.items() if k != "unattributed")
        queue_share = (
            phases.get("queue_wait", 0.0) / attributed if attributed else None
        )
        print(
            f"qps {point['qps_target']:>6.1f}: offered "
            f"{point['qps_offered']:>7.1f}, p50 "
            f"{point['latency_ms']['p50']}ms, p99 "
            f"{point['latency_ms']['p99']}ms, errors {point['errors']}, "
            f"trace queue share "
            f"{queue_share if queue_share is None else round(queue_share, 3)}, "
            f"slo {'OK' if point['slo']['ok'] else 'VIOLATED'}"
        )
    print(
        f"serving_bench: OK -> {args.output} "
        f"(steady-state recompiles: {artifact['steady_state_recompiles']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
