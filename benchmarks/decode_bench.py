"""Host input-pipeline benchmark: fused native decode+batch vs the
per-record Python decoder (the data-plane half of the framework; the
device half is ``bench.py``).

Prints ONE JSON line:
  {"native_records_per_sec": N, "python_records_per_sec": N,
   "speedup": N, "batch": B, "record_bytes": R}

Run: ``python benchmarks/decode_bench.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from elasticdl_tpu.data import reader  # noqa: E402
from elasticdl_tpu.data import recordio  # noqa: E402

BATCH = 256
REPS = 50


def main():
    rng = np.random.RandomState(0)
    payloads = [
        reader.encode_example(
            {
                "image": rng.randint(0, 255, (28, 28)).astype(np.uint8),
                "label": np.int64(i % 10),
            }
        )
        for i in range(BATCH)
    ]

    def timeit(fn):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(REPS):
            fn()
        return (time.perf_counter() - t0) / REPS

    t_native = timeit(lambda: reader.decode_example_batch(payloads))

    orig = reader._native_decode_batch
    reader._native_decode_batch = lambda *a: None  # force the fallback
    try:
        t_python = timeit(lambda: reader.decode_example_batch(payloads))
    finally:
        reader._native_decode_batch = orig

    print(
        json.dumps(
            {
                "native_records_per_sec": round(BATCH / t_native),
                "python_records_per_sec": round(BATCH / t_python),
                "speedup": round(t_python / t_native, 1),
                "batch": BATCH,
                "record_bytes": len(payloads[0]),
                "native_codec_loaded": recordio.native_available(),
            }
        )
    )


if __name__ == "__main__":
    main()
