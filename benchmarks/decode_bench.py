"""Host input-pipeline benchmark: fused native decode+batch vs the
per-record Python decoder, plus the END-TO-END host pipeline rates —
the vectorized task pipeline (scan chunks -> native decode -> permute ->
slice, ``data/fast_pipeline.py``) against the classic per-record
generator chain, both from real EDLIO shards on disk.  (The data-plane
half of the framework; the device half is ``bench.py``, whose
``*_e2e.budget.host_pipeline_records_per_sec`` should match the
vectorized figure here.)

Prints ONE JSON line:
  {"native_records_per_sec": N, "python_records_per_sec": N,
   "speedup": N, "batch": B, "record_bytes": R,
   "pipeline": {"vectorized_records_per_sec": N,
                "classic_records_per_sec": N, "speedup": N}}

Run: ``python benchmarks/decode_bench.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

from elasticdl_tpu.data import reader  # noqa: E402
from elasticdl_tpu.data import recordio  # noqa: E402

BATCH = 256
REPS = 50


def main():
    rng = np.random.RandomState(0)
    payloads = [
        reader.encode_example(
            {
                "image": rng.randint(0, 255, (28, 28)).astype(np.uint8),
                "label": np.int64(i % 10),
            }
        )
        for i in range(BATCH)
    ]

    def timeit(fn):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(REPS):
            fn()
        return (time.perf_counter() - t0) / REPS

    t_native = timeit(lambda: reader.decode_example_batch(payloads))

    orig = reader._native_decode_batch
    reader._native_decode_batch = lambda *a: None  # force the fallback
    try:
        t_python = timeit(lambda: reader.decode_example_batch(payloads))
    finally:
        reader._native_decode_batch = orig

    print(
        json.dumps(
            {
                "native_records_per_sec": round(BATCH / t_native),
                "python_records_per_sec": round(BATCH / t_python),
                "speedup": round(t_python / t_native, 1),
                "batch": BATCH,
                "record_bytes": len(payloads[0]),
                "native_codec_loaded": recordio.native_available(),
                "pipeline": _pipeline_rates(),
            }
        )
    )


def _pipeline_rates(num_records: int = 131072, batch: int = 4096) -> dict:
    """Disk-to-minibatch rate of the vectorized task pipeline vs the
    classic per-record generator chain, on frappe-schema shards."""
    import tempfile

    from elasticdl_tpu.data.dataset import Dataset, batched_model_pipeline
    from elasticdl_tpu.data.fast_pipeline import build_task_batches
    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.data.recordio_gen import synthetic
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.trainer.state import Modes
    from elasticdl_tpu.utils.model_utils import get_model_spec

    with tempfile.TemporaryDirectory() as td:
        data_dir = synthetic.gen_frappe(
            os.path.join(td, "d"),
            num_records=num_records,
            num_shards=2,
            seed=0,
        )
        reader = create_data_reader(data_dir, records_per_task=num_records)
        spec = get_model_spec(
            "", "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
        )
        disp = TaskDispatcher(
            reader.create_shards(),
            records_per_task=num_records,
            num_epochs=1,
        )
        _tid, task = disp.get(0)

        def run_vectorized():
            n = 0
            for _f, labels in build_task_batches(
                reader,
                task,
                spec,
                Modes.TRAINING,
                reader.metadata,
                batch,
                shuffle_records=True,
            ):
                n += labels.shape[0]
            return n

        def run_classic():
            n = 0
            for _f, labels in batched_model_pipeline(
                Dataset.from_generator(lambda: reader.read_records(task)),
                spec,
                Modes.TRAINING,
                reader.metadata,
                batch,
                shuffle_records=True,
            ):
                n += labels.shape[0]
            return n

        out = {}
        for name, fn in (
            ("vectorized", run_vectorized),
            ("classic", run_classic),
        ):
            n = fn()  # warm (page cache, imports)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            out[f"{name}_records_per_sec"] = round(n / best)
        out["speedup"] = round(
            out["vectorized_records_per_sec"]
            / max(1, out["classic_records_per_sec"]),
            1,
        )
        return out


if __name__ == "__main__":
    main()
