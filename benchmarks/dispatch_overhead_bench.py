"""Measure the host-side dispatch overhead the device plane never sees:
the steady-state gap between dispatches WITHIN a task and the boundary
stall BETWEEN tasks, across the three execution disciplines — serial,
``--device_prefetch``, and ``--device_prefetch --boundary_fusion``.

Usage:
  python benchmarks/dispatch_overhead_bench.py [--tasks N] [--batches N]
      [--rows N] [--dim N] [--k N] [--iters N] [--fetch-ms F]
      [--bookkeeping-ms F] [--pipeline-depth N]

CPU-runnable by construction: the "model" is a jitted tanh/matmul tower
over ``(rows, dim)`` float32 batches — enough device work for overlap
to matter without a real model compile — the host stream sleeps
``fetch_ms`` per batch (standing in for record decode) and the
per-task boundary bookkeeping sleeps ``bookkeeping_ms`` (standing in
for the report RPC + milestone checks + memory sample).  All three
windows drive the REAL runtimes (``stacking.run_stacked_steps``,
``device_pipeline.run_pipelined_steps`` / ``run_pipelined_task_stream``)
with identical data, so the numbers isolate the dispatch-loop
discipline, not the workload.

Prints ONE JSON line:

  {"config": {...},
   "windows": {<mode>: {"wall_ms", "records_per_sec", "dispatches",
                        "boundaries", "boundary_stall_ms",
                        "mean_boundary_stall_ms",
                        "median_dispatch_gap_ms"}},
   "boundary_stall_vs_serial": {"prefetch": r, "fused": r}}

where ``boundary_stall_ms`` is the heartbeat counter's per-window delta
(the same number production ships and mirrors as
``elasticdl_boundary_stall_ms_total``) and ``median_dispatch_gap_ms``
is the consumer-thread gap between consecutive intra-task dispatches.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _BenchTrainer:
    """The minimal trainer surface the canonical-shape dispatch loops
    touch: pad/mask policy, placement, and the two jitted programs
    (weighted single step + stacked scan stand-in).  Like a real
    trainer it CARRIES STATE across dispatches, so the jitted chain
    serializes on device and blocking on the final state at a window's
    end waits for every dispatch in the window — without it, XLA's
    async dispatch would let a window's compute leak past its wall
    clock (and into the next window's measurements)."""

    def __init__(self, rows: int, dim: int, iters: int):
        import jax
        import jax.numpy as jnp
        import numpy as np

        self._jax = jax
        self._np = np
        w = (np.eye(dim) * 0.9 + 0.01).astype(np.float32)
        self._w = jax.device_put(w)
        self.state = jax.device_put(np.zeros((dim,), np.float32))

        def _tower(x):
            for _ in range(iters):
                x = jnp.tanh(x @ self._w)
            return x

        def _step(state, f, l, m):
            return state + _tower(f).sum(0) * (l.sum() + m.sum()) * 1e-6

        def _stacked(state, f, l, wts):
            flat = f.reshape((-1, f.shape[-1]))
            return state + _tower(flat).sum(0) * (
                l.sum() + wts.sum()
            ) * 1e-6

        self._step = jax.jit(_step)
        self._stacked = jax.jit(_stacked)

    def pad_to(self, x, rows: int):
        n = x.shape[0]
        if n == rows:
            return x
        pad = self._np.zeros((rows - n,) + x.shape[1:], x.dtype)
        return self._np.concatenate([x, pad])

    def row_mask(self, n: int, rows: int):
        mask = self._np.zeros((rows,), self._np.float32)
        mask[:n] = 1.0
        return mask

    def place_batch(self, x):
        return self._jax.device_put(x)

    def place_stacked(self, x):
        return self._jax.device_put(x)

    def train_step(self, f, l, m):
        self.state = self._step(self.state, f, l, m)
        return self.state

    def train_steps_stacked(self, f, l, wts):
        self.state = self._stacked(self.state, f, l, wts)
        return self.state

    def sync(self):
        self._jax.block_until_ready(self.state)


def _window_stats(
    wall_secs: float, stamps, dispatches_per_task: int,
    records: int, before: dict, after: dict,
):
    boundaries = after.get("boundaries", 0) - before.get("boundaries", 0)
    stall = after.get("boundary_stall_ms", 0) - before.get(
        "boundary_stall_ms", 0
    )
    intra = [
        (b - a) * 1000.0
        for i, (a, b) in enumerate(zip(stamps, stamps[1:]))
        # gaps that cross a task boundary are the boundary stall's job
        if (i + 1) % dispatches_per_task != 0
    ]
    return {
        "wall_ms": round(wall_secs * 1000.0, 1),
        "records_per_sec": round(records / wall_secs, 1),
        "dispatches": len(stamps),
        "boundaries": boundaries,
        "boundary_stall_ms": stall,
        "mean_boundary_stall_ms": round(stall / boundaries, 2)
        if boundaries
        else None,
        "median_dispatch_gap_ms": round(statistics.median(intra), 2)
        if intra
        else None,
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tasks", type=int, default=6)
    parser.add_argument("--batches", type=int, default=8)
    parser.add_argument("--rows", type=int, default=256)
    parser.add_argument("--dim", type=int, default=256)
    parser.add_argument("--k", type=int, default=2)
    parser.add_argument("--iters", type=int, default=8)
    parser.add_argument("--fetch-ms", type=float, default=2.0)
    parser.add_argument("--bookkeeping-ms", type=float, default=5.0)
    parser.add_argument("--pipeline-depth", type=int, default=None)
    args = parser.parse_args()
    if args.batches % args.k:
        parser.error("--batches must be a multiple of --k (full groups "
                     "only: partial-group handling is parity-pinned in "
                     "tests, not measured here)")

    import numpy as np

    from elasticdl_tpu.trainer import device_pipeline as dp
    from elasticdl_tpu.trainer.stacking import run_stacked_steps

    rng = np.random.default_rng(7)
    features = rng.standard_normal(
        (args.rows, args.dim), dtype=np.float32
    )
    labels = rng.standard_normal((args.rows,), dtype=np.float32)
    trainer = _BenchTrainer(args.rows, args.dim, args.iters)
    get_trainer = lambda: trainer  # noqa: E731

    def batches():
        for _ in range(args.batches):
            time.sleep(args.fetch_ms / 1000.0)
            yield features, labels

    def bookkeeping():
        time.sleep(args.bookkeeping_ms / 1000.0)

    # warm both the jitted program and the staging totals (arms the
    # boundary clock for the serial window too, so all three windows
    # measure with identical instrumentation state)
    dp.run_pipelined_steps(
        get_trainer, batches(), args.k, canonical_rows=args.rows
    )
    trainer.sync()
    dp.clear_boundary_mark()

    dispatches_per_task = args.batches // args.k
    records_per_window = args.tasks * args.batches * args.rows
    windows = {}

    for mode in ("serial", "prefetch", "fused"):
        stamps: list = []
        post = lambda: stamps.append(time.monotonic())  # noqa: E731
        before = dp.heartbeat_snapshot()
        t0 = time.monotonic()
        if mode == "fused":
            dp.run_pipelined_task_stream(
                get_trainer,
                ((i, None, batches()) for i in range(args.tasks)),
                args.k,
                post_group=post,
                canonical_rows=args.rows,
                task_done=lambda _tid, _task, _n: bookkeeping(),
                pipeline_depth=args.pipeline_depth,
            )
        else:
            for _ in range(args.tasks):
                run_stacked_steps(
                    get_trainer,
                    batches(),
                    args.k,
                    post_group=post,
                    canonical_rows=args.rows,
                    device_prefetch=(mode == "prefetch"),
                    pipeline_depth=args.pipeline_depth,
                )
                # runtime arm order: mark as soon as the task drained,
                # so the bookkeeping is inside the measured gap
                dp.note_task_boundary()
                bookkeeping()
        trainer.sync()
        wall = time.monotonic() - t0
        dp.clear_boundary_mark()
        windows[mode] = _window_stats(
            wall, stamps, dispatches_per_task,
            records_per_window, before, dp.heartbeat_snapshot(),
        )

    serial_stall = windows["serial"]["boundary_stall_ms"] or 1
    out = {
        "config": {
            "tasks": args.tasks,
            "batches_per_task": args.batches,
            "rows": args.rows,
            "dim": args.dim,
            "k": args.k,
            "iters": args.iters,
            "fetch_ms": args.fetch_ms,
            "bookkeeping_ms": args.bookkeeping_ms,
            "pipeline_depth": args.pipeline_depth
            or dp.resolve_pipeline_depth(),
        },
        "windows": windows,
        "boundary_stall_vs_serial": {
            mode: round(
                windows[mode]["boundary_stall_ms"] / serial_stall, 3
            )
            for mode in ("prefetch", "fused")
        },
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
