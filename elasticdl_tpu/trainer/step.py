"""Jitted step builders: train / evaluate / predict.

Reference: the worker's training step is a ``tf.function`` GradientTape over
``model.call`` followed by a gRPC gradient push (``worker.py:646-669`` +
``:444-530``).  The TPU build fuses all of it — forward, loss, backward,
optimizer update and (under a mesh) the gradient all-reduce — into a single
XLA program: with ``jax.jit`` over dp-sharded batches and replicated
parameters, GSPMD inserts the psum over ICI automatically, so the same step
function serves single-chip Local runs and multi-host meshes.

No data-dependent Python control flow exists inside the step; retries and
task accounting live outside (host side), mirroring the reference's split
between minibatch compute and control.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from elasticdl_tpu.trainer.state import TrainState


def _apply(state: TrainState, params, features, training: bool):
    """Run the model, handling mutable collections (batch_stats).

    Training forwards get a ``dropout`` rng folded from the step counter:
    deterministic per step (replay/restore-safe, identical across replicas
    of an SPMD step) yet fresh every step.
    """
    variables = {"params": params, **state.model_state}
    if training:
        rngs = {
            "dropout": jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        }
        if state.model_state:
            outputs, new_state = state.apply_fn(
                variables,
                features,
                training=True,
                mutable=list(state.model_state),
                rngs=rngs,
            )
            return outputs, new_state
        outputs = state.apply_fn(variables, features, training=True, rngs=rngs)
        return outputs, state.model_state
    outputs = state.apply_fn(variables, features, training=False)
    return outputs, state.model_state


def _cast_floats(tree, dtype):
    if dtype is None:
        return tree
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        else x,
        tree,
    )


def weighted_mean_loss(loss_fn, labels, outputs, weights):
    """``sum(w_i * loss_i) / sum(w_i)`` with per-row losses obtained by
    vmapping ``loss_fn`` over singleton batches.

    This is THE mask semantics of shape-canonical batching
    (docs/designs/shape_canonicalization.md): rows with weight 0 (the
    padding ``pad_to`` appends to reach the canonical batch shape)
    contribute exactly zero to THIS loss and therefore exactly zero
    gradient through it — unlike the old repeat-last-row padding, which
    silently over-weighted the repeated row.  For a ``loss_fn`` that is
    a mean of independent per-row terms (every zoo loss is), an all-ones
    weight vector reproduces ``loss_fn(labels, outputs)`` exactly up to
    reduction order.

    Scope: the exactness claim covers the primary loss path only.
    Batch-composition-dependent terms — sown auxiliary losses (MoE load
    balancing, regularizers; added to the total in ``forward_loss``) and
    batch statistics (BatchNorm) — still observe the padded fill rows of
    a tail batch, as they did under the legacy divisor padding (the
    canonical shape pads further; see the design doc's limits section).
    """

    def one_row(labels_row, outputs_row):
        labels_1 = jax.tree_util.tree_map(lambda x: x[None], labels_row)
        outputs_1 = jax.tree_util.tree_map(lambda x: x[None], outputs_row)
        return loss_fn(labels_1, outputs_1)

    per_row = jax.vmap(one_row)(labels, outputs)
    weights = weights.astype(per_row.dtype)
    # max(sum, 1) guards the (never-dispatched) all-zero mask; a real
    # dispatch always carries >= 1 real row
    return jnp.sum(weights * per_row) / jnp.maximum(jnp.sum(weights), 1.0)


_DONATION_WARNING_PATTERN = "Some donated buffers were not usable"


def _silence_unusable_donation_warning():
    """Batch donation is BEST-EFFORT by design: XLA aliases a donated
    batch into an output only when shapes/layouts permit and frees it
    early otherwise — on small models nothing aliases and jax warns per
    compile, so opting in makes that warning noise, not news.  The
    filter installs at most one live entry: repeated trainer builds
    (bench runs many configs per process) must not accumulate
    duplicates, and the presence CHECK (rather than a module latch)
    keeps it working after a ``catch_warnings`` block reset the global
    filter list.  Scope caveat: the filter is process-global, so it
    also mutes the same warning for state-only trainers built later —
    accepted, since state donation aliases by construction and has
    never fired it."""
    import warnings

    for entry in warnings.filters:
        if (
            entry[0] == "ignore"
            and getattr(entry[1], "pattern", None)
            == _DONATION_WARNING_PATTERN
        ):
            return
    warnings.filterwarnings(
        "ignore", message=_DONATION_WARNING_PATTERN
    )


def build_train_step(
    loss_fn: Callable,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    extra_grad_fn: Callable | None = None,
    state_shardings=None,
    device_parse: Callable | None = None,
    donate_batch: bool = False,
) -> Callable:
    """Build ``(state, features, labels[, weights]) -> (state, step_metrics)``.

    loss_fn: the model module's ``loss(labels, predictions)``.
    weights: optional per-row ``(batch,)`` sample weights — the loss
        becomes :func:`weighted_mean_loss`, so rows canonical-shape
        padding appended (weight 0) contribute zero gradient.  Omitting
        it (``None``) keeps the reference semantics bit-for-bit; the two
        call patterns are distinct jit cache entries, and the runtimes
        always pass a weight vector so they hold exactly one.
    donate_batch: extend donation from state-only to the batch and mask
        buffers (``--device_prefetch``, trainer/device_pipeline.py): a
        batch is dead after its dispatch, so XLA reuses its memory for
        outputs and steady-state dispatches allocate no fresh device
        buffers.  Callers must treat placed batch arrays as consumed —
        a read after the dispatch raises on the deleted Array.
    compute_dtype: cast float inputs (e.g. bfloat16) before the forward;
        parameters and optimizer state stay float32 (mixed precision on the
        MXU without loss-scale bookkeeping, since bf16 keeps fp32 range).
    remat: wrap the forward in ``jax.checkpoint`` to trade FLOPs for HBM.
    extra_grad_fn: optional hook ``(grads, state) -> grads`` (gradient
        clipping etc. normally belongs in the optax chain instead).
    state_shardings: optional sharding pytree matching the TrainState; when
        given, the updated state is pinned to the same mesh layout (the
        SPMD path) — this is the ONE step builder both LocalExecutor and
        SPMDTrainer share, so their step semantics cannot drift.
    device_parse: optional model hook run INSIDE the jitted step before
        the forward (and before compute_dtype casting): elementwise
        decode/normalization of compact wire dtypes (e.g. uint8 images
        -> f32/255), so the host->device transfer ships the small form.
    """

    def forward_loss(params, state, features, labels, weights):
        if device_parse is not None:
            features = device_parse(features)
        features = _cast_floats(features, compute_dtype)
        outputs, new_model_state = _apply(state, params, features, True)
        if weights is None:
            loss = loss_fn(labels, outputs)
        else:
            loss = weighted_mean_loss(loss_fn, labels, outputs, weights)
        # layer-contributed losses (MoE load balancing, regularizers):
        # any value sown into the "losses" collection joins the training
        # loss — the reference adds Keras model reg losses the same way
        # (worker.py:656-669)
        for leaf in jax.tree_util.tree_leaves(
            new_model_state.get("losses", {})
        ):
            loss = loss + jnp.sum(leaf)
        return loss.astype(jnp.float32), (outputs, new_model_state)

    if remat:
        forward_loss = jax.checkpoint(
            forward_loss, static_argnums=(), policy=None
        )

    def train_step(state: TrainState, features, labels, weights=None):
        grad_fn = jax.value_and_grad(forward_loss, has_aux=True)
        (loss, (_, new_model_state)), grads = grad_fn(
            state.params, state, features, labels, weights
        )
        if extra_grad_fn is not None:
            grads = extra_grad_fn(grads, state)
        new_state = state.apply_gradients(grads).replace(
            model_state=new_model_state
        )
        return new_state, {"loss": loss}

    donate_argnums = (0,) if donate else ()
    if donate_batch:
        donate_argnums = donate_argnums + (1, 2, 3)
        _silence_unusable_donation_warning()
    return jax.jit(
        train_step,
        donate_argnums=donate_argnums,
        out_shardings=None
        if state_shardings is None
        else (state_shardings, None),
    )


def build_eval_step(
    loss_fn: Callable | None = None,
    device_parse: Callable | None = None,
) -> Callable:
    """Build ``(state, features, labels[, weights]) ->
    outputs_or_(outputs, loss)``.

    Outputs are returned to the host and reported to the master for metric
    accumulation (reference worker.py:552-565 report_evaluation_metrics) —
    metrics themselves never run on device.  With per-row ``weights`` the
    returned loss is :func:`weighted_mean_loss` — exact over the REAL
    rows of a canonical-shape batch, so callers need no host-side loss
    recompute for padded tails.
    """

    def eval_step(state: TrainState, features, labels, weights=None):
        if device_parse is not None:
            features = device_parse(features)
        outputs, _ = _apply(state, state.params, features, False)
        if loss_fn is None:
            return outputs
        if weights is None:
            return outputs, loss_fn(labels, outputs)
        return outputs, weighted_mean_loss(loss_fn, labels, outputs, weights)

    return jax.jit(eval_step)


def build_predict_step(device_parse: Callable | None = None) -> Callable:
    def predict_step(state: TrainState, features):
        if device_parse is not None:
            features = device_parse(features)
        outputs, _ = _apply(state, state.params, features, False)
        return outputs

    return jax.jit(predict_step)


def resolve_optimizer(spec_optimizer, learning_rate: float | None = None):
    """The model module's ``optimizer`` export is either an optax
    ``GradientTransformation`` or a factory ``(lr=...) -> transformation``
    (the reference's contract returns a Keras optimizer,
    ``model_utils.py:94-150``)."""
    import optax

    if isinstance(spec_optimizer, optax.GradientTransformation):
        return spec_optimizer
    if callable(spec_optimizer):
        try:
            if learning_rate is not None:
                return spec_optimizer(lr=learning_rate)
            return spec_optimizer()
        except TypeError:
            return spec_optimizer()
    raise TypeError(
        f"optimizer spec must be an optax transformation or factory, got "
        f"{type(spec_optimizer)!r}"
    )
