"""Compute plane: jitted step builders, train state, metrics, local executor.

Reference: the worker's TF2 eager training step
(``elasticdl/python/worker/worker.py:646-669``) and the single-process
``LocalExecutor`` (``elasticdl/python/elasticdl/local_executor.py``).  The
TPU build compiles the whole step — forward, loss, backward, optimizer
update, gradient psum — into one XLA program via ``jax.jit`` with sharded
inputs (SURVEY §7).
"""

from elasticdl_tpu.trainer.state import Modes, TrainState

__all__ = ["TrainState", "Modes"]
