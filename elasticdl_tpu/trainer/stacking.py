"""Shared ``--steps_per_dispatch`` grouping: k minibatches -> one scanned
dispatch.

THE one implementation of the grouping/ragged-tail policy, used by both
runtimes (LocalExecutor and the lockstep worker) so their step semantics
cannot drift: equal-shape batches are padded (the per-step path's
``place_padded`` policy), stacked on a leading axis and run through
``SPMDTrainer.train_steps_stacked``; a shape change (a task's ragged tail
batch) or fewer than k leftovers fall back to single steps.  In lockstep
worlds every process sees the same deterministic batch stream per task,
so all processes compute the same grouping without communication.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Callable, Iterable

import jax
import numpy as np


def _batch_size(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(np.shape(leaves[0])[0]) if leaves else 0


def canonical_batch_rows(minibatch_size: int, divisor: int) -> int:
    """THE canonical per-step batch shape (shape-canonical batching,
    docs/designs/shape_canonicalization.md): ``minibatch_size`` rounded
    up to the mesh's batch divisor, so one padded-and-masked shape
    serves full batches, ragged tails AND shard divisibility — the
    jitted step compiles once per step kind instead of once per tail
    length."""
    div = max(1, int(divisor))
    return max(div, -(-int(minibatch_size) // div) * div)


class PreStacked:
    """A ready-made dispatch group: ``(k, B, ...)`` feature/label trees
    (typically zero-copy reshapes of a decode window —
    ``data/fast_pipeline.py``), dispatched as ONE stacked scan without
    the per-batch grouping path's k queue hops, k pad calls, and the
    np.stack copy.  ``num_records`` counts the real rows;
    ``sample_features`` is a (B, ...) view for lazy trainer creation."""

    __slots__ = ("features", "labels", "num_records", "sample_features")

    def __init__(self, features, labels, num_records, sample_features):
        self.features = features
        self.labels = labels
        self.num_records = num_records
        self.sample_features = sample_features

    @property
    def num_steps(self) -> int:
        return int(
            jax.tree_util.tree_leaves(self.features)[0].shape[0]
        )


# ---- `--steps_per_dispatch auto` sizing ------------------------------------

# stay under the host->device link's fast-path size per stacked put.
# Calibrated empirically on the tunneled dev link (r4 sweeps): 5.2MB and
# 6.3MB stacked puts sustain the fast path, 12.1MB and 12.8MB collapse
# ~2-20x, 25MB ~6x — so the sizing target stays at 7MB, comfortably
# inside the measured-good region.  Production hosts without a cliff can
# raise it via the env var.
TRANSFER_CLIFF_BYTES = int(
    os.environ.get("EDL_TRANSFER_CLIFF_BYTES", 7 << 20)
)
# dispatches cheaper than this don't need amortizing: k=1 keeps
# per-step hooks at full granularity.  ~100us is a normal local PCIe
# dispatch; the tunneled dev link measures ~130ms.
CHEAP_DISPATCH_SECS = 0.002
# scan-length cap: bounds compile time, host stacking memory, and hook
# (milestone/checkpoint) granularity; 64 measured fastest for small-
# record CTR batches on the dev link (one ~0.25s dispatch per 64 steps)
MAX_AUTO_K = 64

_DISPATCH_OVERHEAD: list = [None]
# one probe per process: the TaskPrefetcher producer thread (fast_pipeline
# auto sizing) and the main thread can both arrive here; concurrent probes
# would contend with each other and cache an inflated overhead
_DISPATCH_OVERHEAD_LOCK = threading.Lock()


def probe_dispatch_overhead(trials: int = 3) -> float:
    """Seconds per dispatch of a trivial jitted op on FRESH input
    buffers (best-of-``trials`` to shed contention), UNCACHED — the
    link-state measurement itself.  Fresh inputs matter: links that
    cache re-dispatched buffers (the dev tunnel) are an order of
    magnitude faster on repeated ones.  bench.py uses this directly to
    stamp the link state around its measurement windows; runtime
    callers want the cached :func:`measured_dispatch_overhead`."""
    import time

    f = jax.jit(lambda x: x + 1)
    jax.device_get(f(np.zeros(256, np.float32)))  # compile
    best = float("inf")
    for i in range(trials):
        x = np.full(256, float(i + 1), np.float32)  # fresh buffer
        t0 = time.perf_counter()
        jax.device_get(f(x))
        best = min(best, time.perf_counter() - t0)
    return best


def measured_dispatch_overhead() -> float:
    """Cached-per-process :func:`probe_dispatch_overhead` — the
    per-dispatch floor the auto-k sizing amortizes (~3 round trips,
    measured once)."""
    with _DISPATCH_OVERHEAD_LOCK:
        if _DISPATCH_OVERHEAD[0] is None:
            _DISPATCH_OVERHEAD[0] = probe_dispatch_overhead()
        return _DISPATCH_OVERHEAD[0]


def warm_dispatch_overhead_async():
    """Warm the per-process dispatch-overhead cache on a background
    thread, so the first ``'auto'`` sizing (on the TaskPrefetcher's
    producer thread) finds the probe already measured instead of paying
    its compile + 3 round trips on the first dispatch's critical path.
    Runtimes call this at BUILD time — before data flows — so the probe
    normally finishes while the host is otherwise reading its first
    shard; if a trainer-build compile does overlap the tail of the
    probe, best-of-3 sheds most of the contention (the same exposure
    the old on-demand probe had on the producer thread).  A no-op once
    the cache is hot."""
    if _DISPATCH_OVERHEAD[0] is not None:
        return None
    thread = threading.Thread(
        target=measured_dispatch_overhead,
        name="dispatch-probe-warm",
        daemon=True,
    )
    thread.start()
    return thread


def auto_steps_per_dispatch(
    batch_bytes: int, dispatch_overhead_secs: float
) -> int:
    """THE sizing rule: k = 1 when dispatch is cheap; otherwise the most
    batches whose stacked transfer stays under the link's put-size
    target, capped.

    Pinned by tests/test_stacking_auto.py: on a 130ms-dispatch link,
    803KB f32 mnist batches -> k=9 (7MB target), the ~200KB uint8-wire
    form -> k=36, tiny CTR batches -> MAX_AUTO_K; sub-ms dispatch ->
    k=1 on any batch size."""
    if dispatch_overhead_secs < CHEAP_DISPATCH_SECS or batch_bytes <= 0:
        return 1
    return max(1, min(MAX_AUTO_K, TRANSFER_CLIFF_BYTES // batch_bytes))


def choose_stack_k(steps_per_dispatch, training: bool, allow_auto: bool = True):
    """THE stack_k selection rule for ``build_task_batches`` callers —
    one definition instead of one per runtime.

    Returns ``None`` (no pipeline-side stacking) outside training, for
    k <= 1, and for ``'auto'`` when ``allow_auto=False`` — lockstep
    worlds set that: the pipeline's auto sizing probes per-process wall
    clock, and a k disagreement between processes would compile
    different stacked programs and deadlock the collectives (their
    plain-batch path re-sizes deterministically inside
    ``run_stacked_steps`` instead)."""
    if not training:
        return None
    k = steps_per_dispatch or 1
    if k == "auto":
        return "auto" if allow_auto else None
    return k if isinstance(k, int) and k > 1 else None


def resolve_steps_per_dispatch(
    k, sample_batch=None, deterministic: bool = False
) -> int:
    """Resolve a ``--steps_per_dispatch`` value (int or ``'auto'``).

    ``sample_batch``: one (features, labels) pair — its leaf bytes are
    the per-step transfer size.

    ``deterministic=True`` (lockstep worlds) resolves from the batch
    bytes ALONE — a pure function of the data, identical on every
    process.  The wall-clock overhead probe is per-process: around the
    CHEAP_DISPATCH_SECS threshold two co-scheduled processes could
    measure opposite sides of it, compile different stacked programs,
    and hang each other's collectives.  The byte rule without the probe
    merely stacks on hosts that didn't need it — safe (the scan is
    semantically identical and cheap-link stacking still amortizes a
    little), whereas a k disagreement deadlocks the world.
    """
    if k != "auto":
        return int(k or 1)
    if sample_batch is None:
        return 1
    batch_bytes = sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(sample_batch)
    )
    if deterministic:
        return auto_steps_per_dispatch(batch_bytes, float("inf"))
    return auto_steps_per_dispatch(
        batch_bytes, measured_dispatch_overhead()
    )


def assemble_canonical_group(trainer, group, k, rows):
    """THE canonical-group assembly policy — one definition site shared
    by the serial flush below and the device-pipeline stager, so the
    pipelined path can never drift from the serial baseline its parity
    is gated against.  ``group`` is ``[(features, labels, n_real)]``;
    returns ``("stacked", (feats, labels, weights))`` — a full group of
    k >= 2 padded and stacked into one scan input — or
    ``("singles", [(feats, labels, mask)])`` for anything shorter (the
    trailing-partial rule: those dispatch through the already-compiled
    single-step program, never a new scan length)."""
    padded = [
        (
            trainer.pad_to(f, rows),
            trainer.pad_to(l, rows),
            trainer.row_mask(n, rows),
        )
        for f, l, n in group
    ]
    if len(padded) >= 2 and len(padded) == k:
        stacked_f = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[p[0] for p in padded]
        )
        stacked_l = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[p[1] for p in padded]
        )
        stacked_w = np.stack([p[2] for p in padded])
        return "stacked", (stacked_f, stacked_l, stacked_w)
    return "singles", padded


def prestacked_weights(item: PreStacked) -> np.ndarray:
    """The all-ones ``(k, B)`` weight mask every PreStacked dispatch
    carries: ready-made groups hold full batches only, and the weights
    keep the ONE weighted scan shape shared with canonical plain
    groups.  One definition site for what was copied into each
    runtime's PreStacked branch."""
    leaf = jax.tree_util.tree_leaves(item.features)[0]
    return np.ones(leaf.shape[:2], np.float32)


def run_stacked_steps(
    get_trainer: Callable,
    batches: Iterable,
    k,
    pre_batch: Callable | None = None,
    post_group: Callable | None = None,
    dispatch_ctx: Callable | None = None,
    deterministic_auto: bool = False,
    canonical_rows: int | None = None,
    anatomy=None,
    device_prefetch: bool = False,
    pipeline_depth: int | None = None,
) -> int:
    """Drive ``batches`` of ``(features, labels)`` through the trainer in
    groups of ``k`` steps per dispatch; returns records processed.

    ``get_trainer``: called lazily (the runtimes create their trainer on
    the first batch — ``pre_batch`` is where that happens).
    ``pre_batch(features)``: per incoming batch (ensure-trainer,
    profiler hooks).  ``post_group()``: after every dispatch group
    (milestone hooks run at dispatch granularity, deviation D9a).
    ``dispatch_ctx()``: context manager wrapping each device dispatch
    (timing buckets).

    ``anatomy`` (an installed
    :class:`~elasticdl_tpu.telemetry.anatomy.AnatomyRecorder`, or None):
    per-dispatch phase attribution — fetch waits, pad/stack, placement,
    dispatch-to-ready and the post-group hooks are timed as disjoint
    phases summing exactly to each group's wall time, and each dispatch
    additionally blocks on its outputs so device time is measured, not
    queued.  ``None`` (the default) keeps the uninstrumented path: ONE
    branch per flush, no clock reads, identical dispatch behavior.

    ``canonical_rows`` (the runtimes pass
    :func:`canonical_batch_rows`): SHAPE-CANONICAL mode — every batch is
    padded to that fixed row count with a per-row zero/one weight mask
    threaded through the jitted step, so a task's ragged tail batch is
    just another masked group member instead of a new input shape.  The
    group never flushes on a shape change, the program cache holds
    exactly two entries (the weighted step + one scan-k variant), and in
    lockstep worlds every process dispatches identical shapes by
    construction — a tail shape disagreement can no longer deadlock the
    collectives.  A trailing partial group (fewer than k leftovers) runs
    its members through the already-compiled single-step program rather
    than compiling a third scan length.  ``None`` preserves the legacy
    pad-to-divisor behavior (tails flush the group early).

    ``device_prefetch`` (the runtimes resolve ``--device_prefetch`` /
    its forwarded env once at build): canonical-shape groups are
    assembled and PLACED on a background staging thread while the
    current group computes, and dispatch outputs retire one group
    behind in a bounded window (trainer/device_pipeline.py) — same
    grouping policy, same hook cadence, same accounting; the window is
    drained before this function returns, so callers report tasks only
    over retired groups.  Requires ``canonical_rows`` (staging buffers
    must never change shape); ignored — one boolean branch, right here
    — on the legacy path and when off.

    ``pipeline_depth`` (``--pipeline_depth``, default 2): the prefetch
    path's retire window / staging bound; unused on the serial path.
    """
    if device_prefetch and canonical_rows is not None:
        from elasticdl_tpu.trainer.device_pipeline import (
            run_pipelined_steps,
        )

        return run_pipelined_steps(
            get_trainer,
            batches,
            k,
            pre_batch=pre_batch,
            post_group=post_group,
            dispatch_ctx=dispatch_ctx,
            deterministic_auto=deterministic_auto,
            canonical_rows=canonical_rows,
            anatomy=anatomy,
            pipeline_depth=pipeline_depth,
        )
    # boundary-stall instrumentation (trainer/device_pipeline.py): the
    # first flush after a task boundary closes the pending mark — one
    # global load per flush when no mark is pending
    from elasticdl_tpu.trainer.device_pipeline import note_boundary_dispatch

    ctx = dispatch_ctx or contextlib.nullcontext
    group: list = []
    first_shape = None
    processed = 0
    canonical = canonical_rows is not None
    if anatomy is not None:
        # step anatomy (telemetry/anatomy.py): fetch waits are timed at
        # the stream seam, per-step hooks are timed as bookkeeping, and
        # the flush bodies below time assemble/placement/compute — the
        # disabled path takes none of these wrappers (one `is None`
        # branch per flush, no clock reads)
        from elasticdl_tpu.telemetry.anatomy import (
            PHASE_ASSEMBLE,
            PHASE_H2D_TRANSFER,
            timed_device_dispatch,
        )

        batches = anatomy.wrap_fetches(batches)
        pre_batch = anatomy.wrapped_hook(pre_batch)
        post_group = anatomy.wrapped_hook(post_group)

    def _flush_canonical():
        nonlocal processed
        if not group:
            return
        trainer = get_trainer()
        note_boundary_dispatch()
        steps = len(group)
        n_records = sum(n for _f, _l, n in group)
        if anatomy is None:
            kind, assembled = assemble_canonical_group(
                trainer, group, k, canonical_rows
            )
            if kind == "stacked":
                with ctx():
                    trainer.train_steps_stacked(
                        trainer.place_stacked(assembled[0]),
                        trainer.place_stacked(assembled[1]),
                        trainer.place_stacked(assembled[2]),
                    )
            else:
                # trailing partial group: k' single weighted steps through
                # the one compiled program — never a scan-k' compile
                for features, labels, mask in assembled:
                    with ctx():
                        trainer.train_step(
                            trainer.place_batch(features),
                            trainer.place_batch(labels),
                            trainer.place_batch(mask),
                        )
        else:
            # same dispatch decisions, each segment attributed; the
            # trailing block_until_ready trades a little async overlap
            # for a measured (not queued) device_compute phase
            with anatomy.phase(PHASE_ASSEMBLE):
                kind, assembled = assemble_canonical_group(
                    trainer, group, k, canonical_rows
                )
            if kind == "stacked":
                with anatomy.phase(PHASE_H2D_TRANSFER):
                    placed = (
                        trainer.place_stacked(assembled[0]),
                        trainer.place_stacked(assembled[1]),
                        trainer.place_stacked(assembled[2]),
                    )
                with ctx():
                    timed_device_dispatch(
                        anatomy,
                        lambda: trainer.train_steps_stacked(*placed),
                    )
            else:
                for features, labels, mask in assembled:
                    with anatomy.phase(PHASE_H2D_TRANSFER):
                        placed = (
                            trainer.place_batch(features),
                            trainer.place_batch(labels),
                            trainer.place_batch(mask),
                        )
                    with ctx():
                        timed_device_dispatch(
                            anatomy,
                            lambda placed=placed: trainer.train_step(
                                *placed
                            ),
                        )
        processed += n_records
        group.clear()
        if post_group is not None:
            post_group()
        if anatomy is not None:
            anatomy.commit(
                steps=steps,
                records=n_records,
                step=getattr(trainer, "step", None),
            )

    def _flush_legacy():
        nonlocal processed
        if not group:
            return
        trainer = get_trainer()
        note_boundary_dispatch()
        n_records = sum(_batch_size(g[1]) for g in group)
        if len(group) == 1:
            features, labels = group[0]
            with ctx():
                trainer.train_step(
                    trainer.place_padded(features),
                    trainer.place_padded(labels),
                )
            processed += n_records
        else:
            padded = [
                (trainer.pad_batch(f)[0], trainer.pad_batch(l)[0])
                for f, l in group
            ]
            stacked_f = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[p[0] for p in padded]
            )
            stacked_l = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[p[1] for p in padded]
            )
            with ctx():
                trainer.train_steps_stacked(
                    trainer.place_stacked(stacked_f),
                    trainer.place_stacked(stacked_l),
                )
            processed += n_records
        steps = len(group)
        group.clear()
        if post_group is not None:
            post_group()
        if anatomy is not None:
            # the legacy dispatch body is not segment-timed (the
            # runtimes' hot paths are canonical); commit what was
            # measured at the seams so intervals never leak across
            # dispatch windows — the dispatch itself lands in untracked
            anatomy.commit(steps=steps, records=n_records)

    _flush = _flush_canonical if canonical else _flush_legacy

    for item in batches:
        if isinstance(item, PreStacked):
            # a ready-made group: flush any pending plain batches (they
            # must dispatch in stream order), then dispatch directly
            _flush()
            first_shape = None
            if pre_batch is not None:
                # one call per STEP, matching the plain path's hook
                # cadence (profiler counts calls == steps)
                for _ in range(item.num_steps):
                    pre_batch(item.sample_features)
            trainer = get_trainer()
            note_boundary_dispatch()
            if anatomy is None:
                with ctx():
                    if canonical:
                        trainer.train_steps_stacked(
                            trainer.place_stacked(item.features),
                            trainer.place_stacked(item.labels),
                            trainer.place_stacked(prestacked_weights(item)),
                        )
                    else:
                        trainer.train_steps_stacked(
                            trainer.place_stacked(item.features),
                            trainer.place_stacked(item.labels),
                        )
            else:
                # a ready-made group has no pad/stack assembly — its
                # anatomy is placement + compute (+ the fetch/hook time
                # already attributed at the seams)
                with anatomy.phase(PHASE_H2D_TRANSFER):
                    if canonical:
                        placed = (
                            trainer.place_stacked(item.features),
                            trainer.place_stacked(item.labels),
                            trainer.place_stacked(prestacked_weights(item)),
                        )
                    else:
                        placed = (
                            trainer.place_stacked(item.features),
                            trainer.place_stacked(item.labels),
                        )
                with ctx():
                    timed_device_dispatch(
                        anatomy,
                        lambda: trainer.train_steps_stacked(*placed),
                    )
            processed += item.num_records
            if post_group is not None:
                post_group()
            if anatomy is not None:
                anatomy.commit(
                    steps=item.num_steps,
                    records=item.num_records,
                    step=getattr(trainer, "step", None),
                )
            continue
        features, labels = item
        if pre_batch is not None:
            pre_batch(features)
        if k == "auto":  # sized from the first real batch's bytes
            k = resolve_steps_per_dispatch(
                k, (features, labels), deterministic=deterministic_auto
            )
        if canonical:
            group.append((features, labels, _batch_size(labels)))
        else:
            shape = jax.tree_util.tree_leaves(features)[0].shape
            if first_shape is None:
                first_shape = shape
            if shape != first_shape:
                # ragged tail batch: flush the group, start a fresh one
                _flush()
                first_shape = shape
            group.append((features, labels))
        if len(group) == k:
            _flush()
            first_shape = None
    _flush()
    return processed
