"""Shared ``--steps_per_dispatch`` grouping: k minibatches -> one scanned
dispatch.

THE one implementation of the grouping/ragged-tail policy, used by both
runtimes (LocalExecutor and the lockstep worker) so their step semantics
cannot drift: equal-shape batches are padded (the per-step path's
``place_padded`` policy), stacked on a leading axis and run through
``SPMDTrainer.train_steps_stacked``; a shape change (a task's ragged tail
batch) or fewer than k leftovers fall back to single steps.  In lockstep
worlds every process sees the same deterministic batch stream per task,
so all processes compute the same grouping without communication.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Iterable

import jax
import numpy as np


def _batch_size(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(np.shape(leaves[0])[0]) if leaves else 0


def run_stacked_steps(
    get_trainer: Callable,
    batches: Iterable,
    k: int,
    pre_batch: Callable | None = None,
    post_group: Callable | None = None,
    dispatch_ctx: Callable | None = None,
) -> int:
    """Drive ``batches`` of ``(features, labels)`` through the trainer in
    groups of ``k`` steps per dispatch; returns records processed.

    ``get_trainer``: called lazily (the runtimes create their trainer on
    the first batch — ``pre_batch`` is where that happens).
    ``pre_batch(features)``: per incoming batch (ensure-trainer,
    profiler hooks).  ``post_group()``: after every dispatch (milestone
    hooks run at dispatch granularity, deviation D9a).
    ``dispatch_ctx()``: context manager wrapping each device dispatch
    (timing buckets).
    """
    ctx = dispatch_ctx or contextlib.nullcontext
    group: list = []
    first_shape = None
    processed = 0

    def _flush():
        nonlocal processed
        if not group:
            return
        trainer = get_trainer()
        if len(group) == 1:
            features, labels = group[0]
            with ctx():
                trainer.train_step(
                    trainer.place_padded(features),
                    trainer.place_padded(labels),
                )
            processed += _batch_size(labels)
        else:
            padded = [
                (trainer.pad_batch(f)[0], trainer.pad_batch(l)[0])
                for f, l in group
            ]
            stacked_f = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[p[0] for p in padded]
            )
            stacked_l = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[p[1] for p in padded]
            )
            with ctx():
                trainer.train_steps_stacked(
                    trainer.place_stacked(stacked_f),
                    trainer.place_stacked(stacked_l),
                )
            processed += sum(_batch_size(g[1]) for g in group)
        group.clear()
        if post_group is not None:
            post_group()

    for features, labels in batches:
        if pre_batch is not None:
            pre_batch(features)
        shape = jax.tree_util.tree_leaves(features)[0].shape
        if first_shape is None:
            first_shape = shape
        if shape != first_shape:
            # ragged tail batch: flush the group, start a fresh one
            _flush()
            first_shape = shape
        group.append((features, labels))
        if len(group) == k:
            _flush()
            first_shape = None
    _flush()
    return processed
