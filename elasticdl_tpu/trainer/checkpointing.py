"""Periodic checkpoint + resume, shared by both worker runtimes.

Reference: the PS checkpoints its shard every ``checkpoint_steps``
(``elasticdl/python/ps/servicer.py:216-231`` ->
``common/save_utils.py:126-150``) and restores re-sharded across a
different PS count (``save_utils.py:208-261``).  TPU equivalents:

- saving is driven by the live arrays' shardings
  (``elastic.state_checkpoint_parts``): replicated leaves come from the
  local replica, vocab-sharded tables are written as per-host
  ``(ids, rows)`` parts — no host ever materializes a whole distributed
  table;
- restore assembles parts into full tables by explicit row ids and
  re-places the state onto the CURRENT mesh (``jax.device_put`` with the
  trainer's shardings), so a checkpoint written on ``ep=4`` restores onto
  ``ep=2`` — same property, range-sharded instead of hash-sharded.
"""

from __future__ import annotations

import threading

import numpy as np

from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.utils import save_utils
from elasticdl_tpu.utils.log_utils import default_logger as logger


class PeriodicCheckpointer:
    """Milestone-crossing periodic saver (task boundaries are not step
    multiples, so exact-multiple checks would skip saves — same reasoning
    as the eval trigger fix)."""

    def __init__(
        self,
        checkpoint_dir: str,
        checkpoint_steps: int,
        keep_checkpoint_max: int = 3,
        process_id: int = 0,
        num_parts: int = 1,
        async_write: bool = True,
    ):
        self._saver = (
            save_utils.CheckpointSaver(checkpoint_dir, keep_checkpoint_max)
            if checkpoint_dir
            else None
        )
        self._steps = checkpoint_steps or 0
        self._process_id = process_id
        self._num_parts = max(1, num_parts)
        self._last_milestone = 0
        self._last_saved_version = -1
        # async: the device->host snapshot (and any gather collective)
        # stays on the training thread; only the disk write moves to a
        # background thread, so the step stream never waits on IO.  One
        # write in flight at most — the next save (or flush) joins the
        # previous one first, which bounds host memory and surfaces
        # write errors on the training thread.
        self._async = async_write
        self._writer: threading.Thread | None = None
        self._write_error: BaseException | None = None

    @property
    def enabled(self) -> bool:
        return self._saver is not None

    @property
    def is_chief(self) -> bool:
        return self._process_id == 0

    def note_restored_version(self, version: int):
        if self._steps:
            self._last_milestone = version // self._steps

    def maybe_save(self, trainer, mesh) -> bool:
        """Save if a ``checkpoint_steps`` milestone was crossed.  Call at
        task boundaries on EVERY process (saving is collective when any
        leaf needs a gather)."""
        if self._saver is None or not self._steps or trainer is None:
            return False
        milestone = trainer.step // self._steps
        if milestone <= self._last_milestone:
            return False
        self._last_milestone = milestone
        self.save_now(trainer, mesh)
        return True

    def save_now(self, trainer, mesh, skip_if_current: bool = False):
        """``skip_if_current``: no-op when this version was already
        saved (the end-of-training save after a milestone save of the
        final step would write the same checkpoint twice)."""
        version = trainer.step
        if skip_if_current and version == self._last_saved_version:
            return
        # chaos hook: a KILL_IN_CHECKPOINT fault dies HERE — after the
        # decision to save, before any byte is written — so resume must
        # fall back to the last complete checkpoint
        from elasticdl_tpu.chaos import hooks as chaos_hooks

        chaos_hooks.notify_checkpoint_save(int(version))
        from elasticdl_tpu.telemetry import worker_hooks as telemetry_hooks
        from elasticdl_tpu.telemetry.events import EVENT_CHECKPOINT_SAVE
        from elasticdl_tpu.telemetry.tracing import (
            SPAN_CHECKPOINT_SAVE,
            trace_span,
        )

        telemetry_hooks.emit_event(EVENT_CHECKPOINT_SAVE, step=int(version))
        # phase-edge memory sample: a checkpoint materializes a host
        # copy of the state — exactly when the footprint spikes
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.sample("checkpoint")
        # non-chiefs only write their table parts: don't pay device->host
        # copies for replicated leaves they would discard.  The span
        # covers the SYNCHRONOUS cost the training thread actually pays
        # (snapshot + any gather collective); the async disk write is
        # off the step critical path by design.
        with trace_span(SPAN_CHECKPOINT_SAVE, step=int(version)):
            dense, parts = elastic.state_checkpoint_parts(
                trainer.state, mesh, materialize_dense=self.is_chief
            )
        self._last_saved_version = version
        if not self._async:
            self._write(version, dense, parts)
            return
        self.flush()  # at most one write in flight (backpressure)
        self._writer = threading.Thread(
            target=self._write_guarded,
            args=(version, dense, parts),
            name=f"ckpt-writer-{version}",
            daemon=True,
        )
        self._writer.start()

    def flush(self):
        """Join the in-flight write (if any) and re-raise its error on
        the caller's thread.  Call before process exit / state restore
        so a job never 'completes' with an unwritten checkpoint."""
        writer, self._writer = self._writer, None
        if writer is not None:
            writer.join()
        error, self._write_error = self._write_error, None
        if error is not None:
            raise error

    def flush_on_unwind(self, clean_exit: bool):
        """``flush()`` for ``finally`` blocks: when the body raised
        (``clean_exit=False``), a failed write is logged instead of raised
        so it cannot replace the root cause in the worker's log; on a
        clean exit it raises exactly like ``flush()``.  The caller passes
        the flag explicitly (an ``ok`` variable set as the body's last
        statement) — sniffing ``sys.exc_info()`` here would also trip
        when ``run()`` is invoked inside some unrelated active handler."""
        try:
            self.flush()
        except Exception:
            if clean_exit:
                raise
            logger.exception(
                "Async checkpoint write failed during error unwind "
                "(original exception follows)"
            )

    def _write_guarded(self, version, dense, parts):
        try:
            self._write(version, dense, parts)
        except BaseException as e:  # noqa: BLE001 — re-raised in flush()
            self._write_error = e

    def _write(self, version, dense, parts):
        self._saver.save(
            version,
            dense=dense,
            embeddings=parts,
            part=self._process_id,
            num_parts=self._num_parts,
            extra={"model_version": version},
            # concurrent part writers must not race retention deletes
            enforce_retention=self.is_chief,
        )


def restore_trainer_state(trainer, args, process_id: int = 0) -> int | None:
    """Resume-from-own-checkpoint first (re-formation restart), then
    ``--checkpoint_dir_for_init`` (warm start from a prior job).  Returns
    the restored step (0 for a warm start), or None if nothing restored.

    Re-shardable restore (reference save_utils.py:208-261): sharded table
    parts carry explicit row ids, and each process places ONLY the rows
    its devices own under the CURRENT mesh — the checkpoint's part count
    / layout and the new mesh are independent, and no process
    materializes a whole distributed table.  Warm starts restore weights
    but reset the step counter (the old-job step count must not trigger
    this job's step-based eval/checkpoint milestones).
    """
    ckpt_dir = getattr(args, "checkpoint_dir", "") or ""
    resume = bool(ckpt_dir) and save_utils.latest_version(ckpt_dir) is not None
    restore_dir = (
        ckpt_dir
        if resume
        else (getattr(args, "checkpoint_dir_for_init", "") or "")
    )
    if not restore_dir:
        return None
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_CHECKPOINT_RESTORE,
        trace_span,
    )

    # reform-phase span: on a relaunched world the restore is a named
    # term of the downtime critical path (trace analyze)
    with trace_span(SPAN_CHECKPOINT_RESTORE, resume=bool(resume)):
        return _restore_trainer_state_traced(
            trainer, args, process_id, restore_dir, resume
        )


def _restore_trainer_state_traced(
    trainer, args, process_id, restore_dir, resume
):
    dense, embeddings, extra = save_utils.restore_checkpoint(
        restore_dir,
        # keep only rows this process's devices hold, per part, so a
        # table sharded across N hosts is never whole on any of them
        table_row_ranges=elastic.local_table_row_ranges(
            trainer.state, trainer.mesh
        ),
    )
    version = int(extra.get("model_version", 0) or 0)
    restored_step = version if resume else 0
    from elasticdl_tpu.chaos import hooks as chaos_hooks

    chaos_hooks.notify_checkpoint_restore(restored_step)
    from elasticdl_tpu.telemetry import worker_hooks as telemetry_hooks
    from elasticdl_tpu.telemetry.events import EVENT_CHECKPOINT_RESTORE

    telemetry_hooks.emit_event(
        EVENT_CHECKPOINT_RESTORE, step=restored_step, resume=bool(resume)
    )
    apply_restored_values(trainer, dense, embeddings, restored_step)
    logger.info(
        "Process %d restored state at version %d from %s%s",
        process_id,
        version,
        restore_dir,
        "" if resume else " (warm start; step reset to 0)",
    )
    return restored_step


def apply_restored_values(trainer, dense, embeddings, restored_step: int):
    """Re-place restored values onto the trainer's CURRENT mesh — the
    shared back half of the disk restore and the peer-replica hot
    restore (replication.replicator): ``dense`` values go in whole
    (replicated), table ``(ids, rows)`` parts are filtered to the rows
    this process's devices own, and the step counter lands at
    ``restored_step`` exactly."""
    import jax

    from elasticdl_tpu.trainer.state import checkpoint_to_state

    values = dict(dense)
    if embeddings:
        flat_state = elastic.flat_state_arrays(trainer.state)
        for name, (ids, rows) in embeddings.items():
            target = flat_state.get(name)
            if target is None:
                logger.warning(
                    "Checkpoint table %r has no model counterpart; skipped",
                    name,
                )
                continue
            values[name] = _place_table_rows(target, ids, rows, trainer.mesh)
    state = checkpoint_to_state(trainer.state, values)
    state = state.replace(step=np.asarray(restored_step, dtype=np.int32))
    trainer.state = jax.device_put(state, trainer.state_shardings)


def _place_table_rows(target, ids, rows, mesh):
    """Build the device Array for one restored table: select the rows this
    process's devices own (by explicit checkpoint ids) and assemble the
    global Array without materializing the full table on any host."""
    import jax

    sharding = getattr(target, "sharding", None)
    if sharding is None or not elastic.is_multiprocess_mesh(mesh):
        # single process: all rows are local; plain assembly
        return save_utils.assemble_embedding_tables({"t": (ids, rows)})["t"]
    shape = tuple(target.shape)
    ranges = elastic.local_batch_ranges(
        sharding, shape, elastic.my_process_index(mesh)
    )
    order = np.argsort(ids)
    ids_sorted = ids[order]
    segments = []
    for lo, hi in ranges:
        want = np.arange(lo, hi, dtype=ids_sorted.dtype)
        pos = np.searchsorted(ids_sorted, want)
        if pos.size and (
            pos.max() >= len(ids_sorted)
            or not np.array_equal(ids_sorted[pos], want)
        ):
            raise ValueError(
                f"checkpoint parts missing rows [{lo}, {hi}) of a table"
            )
        segments.append(rows[order[pos]])
    local = (
        np.concatenate(segments, axis=0)
        if segments
        else np.zeros((0,) + shape[1:], dtype=rows.dtype)
    )
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape=shape
    )
