"""Single-process training/evaluation/prediction loop.

Reference: ``elasticdl/python/elasticdl/local_executor.py`` — the LOCAL
strategy executor: no master process, no RPC, but the same task-based data
traversal.  Deviations: where the reference mocks tasks with a namedtuple
(``_MockedTask``), we drive a real in-process :class:`TaskDispatcher`, so
the exact task lifecycle (epochs, SAVE_MODEL callback, counters) is
exercised even in local runs; and the compute plane is the same
:class:`SPMDTrainer` the distributed workers run — a jitted SPMD step over
a mesh of ALL local devices (a Local job on a v5e-8 host trains
data-parallel across its 8 chips), with the same sharding rules,
re-shardable periodic checkpoints, and async writes.
"""

from __future__ import annotations

import jax
import numpy as np

from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.data.fast_pipeline import build_task_batches
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.parallel.distributed import SPMDTrainer, trim_pad
from elasticdl_tpu.parallel.mesh import MeshConfig
from elasticdl_tpu.trainer import metrics as metrics_lib
from elasticdl_tpu.trainer.checkpointing import (
    PeriodicCheckpointer,
    restore_trainer_state,
)
from elasticdl_tpu.trainer.state import Modes, TrainState
from elasticdl_tpu.trainer.step import resolve_optimizer
from elasticdl_tpu.utils.log_utils import default_logger as logger
from elasticdl_tpu.utils.model_utils import get_model_spec
from elasticdl_tpu.utils.timing_utils import Timing


def build_optimizer(spec, learning_rate=None):
    """Resolve the optimizer, honoring ``learning_rate_scheduler``.

    The reference mutates ``optimizer.learning_rate`` per model version
    (``common/lr_scheduler.py``); optax expresses the same thing as a
    schedule callable of the step, which every optax factory accepts as its
    learning rate.
    """
    if learning_rate is None and spec.learning_rate_scheduler is not None:
        scheduler = spec.learning_rate_scheduler
        return resolve_optimizer(spec.optimizer, lambda step: scheduler(step))
    return resolve_optimizer(spec.optimizer, learning_rate)


class LocalExecutor:
    def __init__(self, args):
        self._args = args
        self._spec = get_model_spec(
            args.model_zoo,
            args.model_def,
            model_params=args.model_params_dict,
            dataset_fn=args.dataset_fn,
            loss=args.loss,
            optimizer=args.optimizer,
            eval_metrics_fn=args.eval_metrics_fn,
        )
        self._model = self._spec.build_model()
        self._tx = build_optimizer(self._spec, args.learning_rate)
        reader_kwargs = dict(args.data_reader_params_dict)
        self._train_reader = (
            create_data_reader(
                args.training_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.training_data
            else None
        )
        self._eval_reader = (
            create_data_reader(
                args.validation_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.validation_data
            else None
        )
        self._predict_reader = (
            create_data_reader(
                args.prediction_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.prediction_data
            else None
        )
        if getattr(args, "jax_platform", ""):
            from elasticdl_tpu.parallel.elastic import configure_platform

            configure_platform(args.jax_platform)
        # all local devices; --mesh_shape picks the layout ('' = all on dp)
        self._mesh = MeshConfig.from_string(
            getattr(args, "mesh_shape", "") or ""
        ).create()
        self._trainer: SPMDTrainer | None = None
        # shape-canonical batching: every train/eval/predict batch is
        # padded to this fixed row count (mask-weighted), so each step
        # kind compiles exactly once — ragged tails reuse the program
        from elasticdl_tpu.parallel.mesh import batch_divisor
        from elasticdl_tpu.trainer.stacking import (
            canonical_batch_rows,
            warm_dispatch_overhead_async,
        )

        self._canonical_rows = canonical_batch_rows(
            args.minibatch_size, batch_divisor(self._mesh)
        )
        # device-path pipelining (--device_prefetch or the forwarded
        # env): resolved ONCE here; it selects the staged dispatch loop
        # and turns on batch-buffer donation in the trainer
        from elasticdl_tpu.trainer.device_pipeline import (
            resolve_boundary_fusion,
            resolve_device_prefetch,
            resolve_pipeline_depth,
        )

        self._device_prefetch = resolve_device_prefetch(
            getattr(args, "device_prefetch", None)
        )
        # cross-task staging (--boundary_fusion) and the tunable window
        # (--pipeline_depth): master-only, env-forwarded; defaults keep
        # the classic per-task drain at depth 2.  Fusion requires the
        # staged dispatch loop, so it is gated on device_prefetch.
        self._boundary_fusion = self._device_prefetch and resolve_boundary_fusion(
            getattr(args, "boundary_fusion", None)
        )
        self._pipeline_depth = resolve_pipeline_depth(
            getattr(args, "pipeline_depth", None)
        )
        if getattr(args, "steps_per_dispatch", 1) == "auto":
            # measure the link overhead off the first dispatch's
            # critical path (the probe result feeds the auto-k sizing)
            warm_dispatch_overhead_async()
        self._checkpointer = PeriodicCheckpointer(
            getattr(args, "checkpoint_dir", "") or "",
            getattr(args, "checkpoint_steps", 0) or 0,
            keep_checkpoint_max=getattr(args, "keep_checkpoint_max", 3),
        )
        self._timing = Timing(
            enabled=args.log_level == "DEBUG", logger=logger
        )
        # per-step telemetry samples (events.jsonl for the report CLI);
        # --telemetry_dir or the inherited env enables it
        import os as _os

        from elasticdl_tpu.telemetry import tracing
        from elasticdl_tpu.telemetry import worker_hooks as telemetry_hooks

        telemetry_dir = getattr(args, "telemetry_dir", "") or _os.environ.get(
            telemetry_hooks.TELEMETRY_DIR_ENV, ""
        )
        self._telemetry = telemetry_hooks.install(telemetry_dir)
        # process-wide compile counter (+ `compile` trace spans): the
        # observable face of the compile-once guarantee
        from elasticdl_tpu.telemetry import compile_tracker

        compile_tracker.install()
        # span tracer on the same run dir (sampled step spans, checkpoint
        # and profile-window spans) — the single-process path of the
        # distributed trace
        tracing.install(
            telemetry_dir,
            sample_rate=getattr(args, "trace_sample_rate", None),
        )
        self._tracing = tracing
        # per-dispatch phase anatomy (--step_anatomy or the forwarded
        # env): host_fetch/assemble/h2d/device_compute/bookkeeping
        # summing exactly to each dispatch's wall time — feeds the
        # report's goodput section and the goodput smoke
        from elasticdl_tpu.telemetry import anatomy as anatomy_mod

        self._anatomy_mod = anatomy_mod
        anatomy_mod.install_if_enabled(
            getattr(args, "step_anatomy", None),
            model_def=getattr(args, "model_def", "") or "",
        )
        # memory ledger (telemetry/memory.py): component byte accounting
        # sampled at task boundaries + phase edges; enabled exactly when
        # telemetry is (its surfaces all hang off the telemetry dir)
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._memory_mod = memory_mod
        memory_mod.install_if_enabled(telemetry_dir)
        memory_mod.register_trainer_state(
            lambda: self._trainer.state if self._trainer is not None else None
        )
        self._last_eval_milestone = 0
        from elasticdl_tpu.utils.profiling import StepProfiler

        self._profiler = StepProfiler(
            getattr(args, "profile_dir", ""),
            num_steps=getattr(args, "profile_steps", 5),
        )

    # ---- plumbing ---------------------------------------------------------

    def _task_dataset(
        self, reader, task, mode: Modes, prefetch: int = 2
    ) -> Dataset:
        # prefetch=0 on the training path: TaskPrefetcher's producer
        # thread IS the overlap there; eval/predict (main-thread
        # consumers) keep the in-dataset prefetch.
        # stack_k: training batches arrive as ready-made PreStacked
        # dispatch groups (zero-copy reshapes built on the producer
        # thread) when --steps_per_dispatch > 1 — the per-batch group
        # assembly otherwise costs ~1-2ms x k on the consumer thread.
        from elasticdl_tpu.trainer.stacking import choose_stack_k

        stack_k = choose_stack_k(
            getattr(self._args, "steps_per_dispatch", 1),
            mode == Modes.TRAINING,
        )
        from elasticdl_tpu.parallel.mesh import batch_divisor

        return build_task_batches(
            reader,
            task,
            self._spec,
            mode,
            reader.metadata,
            self._args.minibatch_size,
            shuffle_records=mode == Modes.TRAINING,
            prefetch=prefetch,
            stack_k=stack_k,
            stack_divisor=batch_divisor(self._mesh),
        )

    def _ensure_trainer(self, sample_features):
        if self._trainer is not None:
            return
        from elasticdl_tpu.telemetry.tracing import (
            SPAN_TRAINER_BUILD,
            trace_span,
        )

        with trace_span(SPAN_TRAINER_BUILD):
            rules = ()
            if self._spec.sharding_rules is not None:
                rules = tuple(self._spec.sharding_rules(self._mesh))
            compute_dtype = getattr(self._args, "compute_dtype", "float32")
            from elasticdl_tpu.trainer.device_pipeline import (
                resolve_donate_state,
            )

            self._trainer = SPMDTrainer(
                self._mesh,
                self._model,
                self._spec.loss,
                self._tx,
                sample_features,
                rules=rules,
                compute_dtype=None
                if compute_dtype == "float32"
                else compute_dtype,
                remat=bool(getattr(self._args, "remat", False)),
                donate=resolve_donate_state(self._args),
                device_parse=self._spec.device_parse,
                donate_batch=self._device_prefetch,
            )
            version = restore_trainer_state(self._trainer, self._args)
        if version is not None:
            self._checkpointer.note_restored_version(version)
            if self._args.evaluation_steps:
                # milestones evaluated before the restore point must not
                # re-fire on the first post-restore step (mirrors
                # note_restored_version for checkpoints)
                self._last_eval_milestone = (
                    version // self._args.evaluation_steps
                )

    def _place_canonical(self, tree):
        return self._trainer.place_canonical(tree, self._canonical_rows)

    @property
    def _version(self) -> int:
        return self._trainer.step if self._trainer is not None else 0

    # ---- phases -----------------------------------------------------------

    def _train_task(self, task, batches=None) -> int:
        """One implementation for every ``--steps_per_dispatch`` (k=1 is
        a group of one): the shared grouping policy in
        ``trainer.stacking.run_stacked_steps``.  Eval/checkpoint hooks
        run per dispatch group, so step-based triggers fire at dispatch
        granularity (D9a; identical to per-step at k=1).

        ``batches``: pre-built minibatch stream (the prefetching run
        loop passes one so host decode overlaps device compute); default
        builds the task's pipeline inline (retry paths, tests)."""
        from elasticdl_tpu.trainer.stacking import run_stacked_steps

        return run_stacked_steps(
            lambda: self._trainer,
            batches
            if batches is not None
            else self._task_dataset(self._train_reader, task, Modes.TRAINING),
            getattr(self._args, "steps_per_dispatch", 1) or 1,
            pre_batch=self._pre_batch,
            post_group=self._post_step_hooks,
            dispatch_ctx=lambda: self._timing.record("batch_process"),
            canonical_rows=self._canonical_rows,
            anatomy=self._anatomy_mod.get_recorder(),
            device_prefetch=self._device_prefetch,
            pipeline_depth=self._pipeline_depth,
        )

    def _pre_batch(self, features):
        from elasticdl_tpu.telemetry.tracing import record_step_span
        from elasticdl_tpu.telemetry.worker_hooks import record_step

        self._ensure_trainer(features)
        # the profiler counts CALLS, one per minibatch == one per
        # step; no version argument (the version only advances at
        # the dispatch, so it would repeat within a group — ADVICE
        # r3 finding 3)
        self._profiler.on_step()
        record_step(self._version, self._args.minibatch_size)
        record_step_span(self._version)

    def _post_step_hooks(self):
        # milestone-CROSSING, not exact-multiple: with steps_per_dispatch
        # the version advances k at a time, so an exact modulo check
        # would silently skip milestones (same rationale as the eval
        # service's add_evaluation_task_if_needed)
        if self._args.evaluation_steps:
            milestone = self._version // self._args.evaluation_steps
            if milestone > self._last_eval_milestone:
                self._last_eval_milestone = milestone
                self.evaluate(tag=f"step {self._version}")
        self._checkpointer.maybe_save(self._trainer, self._mesh)

    def evaluate(self, tag: str = "final") -> dict:
        if self._eval_reader is None or self._trainer is None:
            return {}
        eval_metrics = (
            self._spec.eval_metrics_fn()
            if self._spec.eval_metrics_fn
            else {"loss": metrics_lib.Mean()}
        )
        shards = self._eval_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            evaluation_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        loss_mean = metrics_lib.Mean()
        while True:
            tid, task = dispatcher.get_eval_task(0)
            if task is None:
                break
            for features, labels in self._task_dataset(
                self._eval_reader, task, Modes.EVALUATION
            ):
                n = _batch_size(labels)
                # mask-weighted in-step loss: exact over the REAL rows,
                # so no host-side loss recompute is needed — and the
                # canonical shape means the eval program compiles once
                outputs, loss = self._trainer.eval_step(
                    self._place_canonical(features),
                    self._place_canonical(labels),
                    self._trainer.place_mask(n, self._canonical_rows),
                )
                outputs = trim_pad(jax.device_get(outputs), n)
                metrics_lib.update_metric_tree(
                    eval_metrics, np.asarray(labels), outputs
                )
                loss_mean.update_value(float(jax.device_get(loss)), n)
            dispatcher.report(tid, True)
        results = metrics_lib.metric_tree_results(eval_metrics)
        results["loss"] = loss_mean.result()
        logger.info("Evaluation (%s): %s", tag, results)
        return results

    def predict(self) -> list:
        if self._predict_reader is None:
            return []
        shards = self._predict_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            prediction_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        outputs_all = []
        while True:
            tid, task = dispatcher.get(0)
            if task is None:
                break
            for features in self._task_dataset(
                self._predict_reader, task, Modes.PREDICTION
            ):
                self._ensure_trainer(features)
                n = _batch_size(features)
                outputs = self._trainer.predict_step(
                    self._place_canonical(features)
                )
                processed = trim_pad(jax.device_get(outputs), n)
                if self._spec.prediction_outputs_processor is not None:
                    self._spec.prediction_outputs_processor.process(
                        processed, worker_id=0
                    )
                outputs_all.append(processed)
            dispatcher.report(tid, True)
        return outputs_all

    def run(self) -> dict:
        """Train (with periodic eval), then final eval; returns final
        metrics (reference local_executor.py:73-95)."""
        if self._train_reader is None:
            if self._eval_reader is not None:
                # evaluation-only job needs initialized state
                self._init_from_eval_data()
                return self.evaluate()
            self.predict()
            return {}
        shards = self._train_reader.create_shards()
        dispatcher = TaskDispatcher(
            shards,
            records_per_task=self._args.records_per_task,
            num_epochs=self._args.num_epochs,
            shuffle_seed=getattr(self._args, "shuffle_seed", None),
        )
        total = 0
        ok = False
        from elasticdl_tpu.trainer.host_pipeline import TaskPrefetcher

        # decode-ahead bounded to ~two dispatch groups of batches
        # ('auto' resolves per-batch inside run_stacked_steps; size the
        # buffer for the largest k auto can pick)
        k = getattr(self._args, "steps_per_dispatch", 1) or 1
        from elasticdl_tpu.trainer.stacking import MAX_AUTO_K

        k = MAX_AUTO_K if k == "auto" else int(k)
        prefetcher = TaskPrefetcher(
            lambda: dispatcher.get(0),
            lambda task: self._task_dataset(
                self._train_reader, task, Modes.TRAINING, prefetch=0
            ),
            max_buffered_batches=max(4, 2 * k),
        )
        from elasticdl_tpu.trainer.device_pipeline import (
            clear_boundary_mark,
            note_task_boundary,
        )

        try:
            if self._boundary_fusion:
                # cross-task staging (--boundary_fusion): one persistent
                # stager walks the whole task stream, and the per-task
                # bookkeeping below runs as the task_done callback after
                # each task's window drains (exactly-once preserved)
                from elasticdl_tpu.trainer.device_pipeline import (
                    run_pipelined_task_stream,
                )

                def _task_done(tid, task, records):
                    dispatcher.report(tid, True)
                    # task boundaries are the single-process run's
                    # periodic memory cadence (no heartbeat to ride)
                    self._memory_mod.sample()

                total = run_pipelined_task_stream(
                    lambda: self._trainer,
                    iter(prefetcher),
                    getattr(self._args, "steps_per_dispatch", 1) or 1,
                    pre_batch=self._pre_batch,
                    post_group=self._post_step_hooks,
                    dispatch_ctx=lambda: self._timing.record(
                        "batch_process"
                    ),
                    canonical_rows=self._canonical_rows,
                    anatomy=self._anatomy_mod.get_recorder(),
                    task_done=_task_done,
                    pipeline_depth=self._pipeline_depth,
                )
            else:
                for tid, task, batches in prefetcher:
                    with self._timing.record("task_process"):
                        total += self._train_task(task, batches)
                    # the training call drained its window: the device
                    # is idle from here until the next task's first
                    # dispatch — that whole gap (report + sample
                    # included) is the boundary_stall counter
                    note_task_boundary()
                    dispatcher.report(tid, True)
                    # task boundaries are the single-process run's
                    # periodic memory cadence (no heartbeat to ride)
                    self._memory_mod.sample()
            ok = True
        finally:
            # a pending mark must not leak into a later run in this
            # process (the smoke runs several windows back to back)
            clear_boundary_mark()
            prefetcher.close()
            try:
                # an in-flight async checkpoint (or a parked write error)
                # must not be abandoned by a mid-training exception — nor
                # may a failed flush replace that exception
                self._checkpointer.flush_on_unwind(clean_exit=ok)
            finally:
                # flush (or diagnose) the trace even on error — a leaked
                # active trace poisons later start_trace calls
                self._profiler.stop()
                self._tracing.flush()
        logger.info(
            "Training complete: %d records, %d steps", total, self._version
        )
        self._memory_mod.sample("job_end")
        from elasticdl_tpu.telemetry.worker_hooks import publish_timing

        publish_timing(self._timing)
        self._timing.report_timing(reset=True)
        if self._checkpointer.enabled and self._trainer is not None:
            self._checkpointer.save_now(
                self._trainer, self._mesh, skip_if_current=True
            )
            self._checkpointer.flush()
        results = self.evaluate()
        if self._args.output and self._trainer is not None:
            from elasticdl_tpu.utils.export_utils import export_model

            export_model(
                self._args.output,
                self._trainer.state,
                self._spec,
                self._args,
            )
        return results

    def _init_from_eval_data(self):
        shards = self._eval_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            evaluation_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        tid, task = dispatcher.get_eval_task(0)
        if task is None:
            return
        for features, _ in self._task_dataset(
            self._eval_reader, task, Modes.EVALUATION
        ):
            self._ensure_trainer(features)
            break

    @property
    def state(self) -> TrainState | None:
        return self._trainer.state if self._trainer is not None else None

    @property
    def trainer(self) -> SPMDTrainer | None:
        return self._trainer


def _batch_size(tree) -> int:
    if isinstance(tree, dict):
        tree = next(iter(tree.values()))
    return int(np.shape(tree)[0]) if np.ndim(tree) else 1


