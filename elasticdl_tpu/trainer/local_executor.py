"""Single-process training/evaluation/prediction loop.

Reference: ``elasticdl/python/elasticdl/local_executor.py`` — the LOCAL
strategy executor: no master process, no RPC, but the same task-based data
traversal.  Deviations: where the reference mocks tasks with a namedtuple
(``_MockedTask``), we drive a real in-process :class:`TaskDispatcher`, so
the exact task lifecycle (epochs, SAVE_MODEL callback, counters) is
exercised even in local runs; and the train step is a jitted JAX program
on the local chip instead of an eager GradientTape.
"""

from __future__ import annotations

import numpy as np

from elasticdl_tpu.data.dataset import Dataset, batched_model_pipeline
from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.trainer import metrics as metrics_lib
from elasticdl_tpu.trainer.state import (
    Modes,
    TrainState,
    checkpoint_to_state,
    init_model,
    state_to_checkpoint,
)
from elasticdl_tpu.trainer.step import (
    build_eval_step,
    build_predict_step,
    build_train_step,
    resolve_optimizer,
)
from elasticdl_tpu.utils import save_utils, tree_utils
from elasticdl_tpu.utils.constants import TaskType
from elasticdl_tpu.utils.log_utils import default_logger as logger
from elasticdl_tpu.utils.model_utils import get_model_spec
from elasticdl_tpu.utils.timing_utils import Timing


def build_optimizer(spec, learning_rate=None):
    """Resolve the optimizer, honoring ``learning_rate_scheduler``.

    The reference mutates ``optimizer.learning_rate`` per model version
    (``common/lr_scheduler.py``); optax expresses the same thing as a
    schedule callable of the step, which every optax factory accepts as its
    learning rate.
    """
    if learning_rate is None and spec.learning_rate_scheduler is not None:
        scheduler = spec.learning_rate_scheduler
        return resolve_optimizer(spec.optimizer, lambda step: scheduler(step))
    return resolve_optimizer(spec.optimizer, learning_rate)


class LocalExecutor:
    def __init__(self, args):
        self._args = args
        self._spec = get_model_spec(
            args.model_zoo,
            args.model_def,
            model_params=args.model_params_dict,
            dataset_fn=args.dataset_fn,
            loss=args.loss,
            optimizer=args.optimizer,
            eval_metrics_fn=args.eval_metrics_fn,
        )
        self._model = self._spec.build_model()
        self._tx = build_optimizer(self._spec, args.learning_rate)
        reader_kwargs = dict(args.data_reader_params_dict)
        self._train_reader = (
            create_data_reader(
                args.training_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.training_data
            else None
        )
        self._eval_reader = (
            create_data_reader(
                args.validation_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.validation_data
            else None
        )
        self._predict_reader = (
            create_data_reader(
                args.prediction_data,
                records_per_task=args.records_per_task,
                custom_reader=self._spec.custom_data_reader,
                **reader_kwargs,
            )
            if args.prediction_data
            else None
        )
        self._state: TrainState | None = None
        self._train_step = None
        self._eval_step = None
        self._predict_step = None
        self._saver = (
            save_utils.CheckpointSaver(
                args.checkpoint_dir, args.keep_checkpoint_max
            )
            if args.checkpoint_dir
            else None
        )
        self._timing = Timing(
            enabled=args.log_level == "DEBUG", logger=logger
        )
        from elasticdl_tpu.utils.profiling import StepProfiler

        self._profiler = StepProfiler(
            getattr(args, "profile_dir", ""),
            num_steps=getattr(args, "profile_steps", 5),
        )

    # ---- plumbing ---------------------------------------------------------

    def _task_dataset(self, reader, task, mode: Modes) -> Dataset:
        ds = Dataset.from_generator(lambda: reader.read_records(task))
        return batched_model_pipeline(
            ds,
            self._spec,
            mode,
            reader.metadata,
            self._args.minibatch_size,
            shuffle_records=mode == Modes.TRAINING,
            prefetch=2,
        )

    def _ensure_state(self, sample_features):
        if self._state is not None:
            return
        params, model_state = init_model(self._model, sample_features)
        self._state = TrainState.create(
            self._model.apply, params, self._tx, model_state
        )
        if self._args.checkpoint_dir_for_init:
            dense, embeddings, extra = save_utils.restore_checkpoint(
                self._args.checkpoint_dir_for_init
            )
            # worker-written checkpoints carry sharded tables as parts
            dense.update(save_utils.assemble_embedding_tables(embeddings))
            self._state = checkpoint_to_state(self._state, dense)
            logger.info(
                "Initialized parameters from checkpoint %s (version %s)",
                self._args.checkpoint_dir_for_init,
                extra.get("model_version", "?"),
            )
        self._train_step = build_train_step(
            self._spec.loss,
            compute_dtype=None
            if self._args.compute_dtype == "float32"
            else self._args.compute_dtype,
            remat=self._args.remat,
            donate=self._args.donate_state,
        )
        self._eval_step = build_eval_step(self._spec.loss)
        self._predict_step = build_predict_step()

    def _maybe_checkpoint(self):
        if (
            self._saver is not None
            and self._args.checkpoint_steps
            and self._version % self._args.checkpoint_steps == 0
        ):
            self._saver.save(
                self._version,
                dense=state_to_checkpoint(self._state),
                extra={"model_version": self._version},
            )

    @property
    def _version(self) -> int:
        return int(self._state.step) if self._state is not None else 0

    # ---- phases -----------------------------------------------------------

    def _train_task(self, task) -> int:
        processed = 0
        for batch in self._task_dataset(self._train_reader, task, Modes.TRAINING):
            features, labels = batch
            self._ensure_state(features)
            self._profiler.on_step(self._version)
            with self._timing.record("batch_process"):
                self._state, step_metrics = self._train_step(
                    self._state, features, labels
                )
            processed += _batch_size(labels)
            if (
                self._args.evaluation_steps
                and self._version % self._args.evaluation_steps == 0
            ):
                self.evaluate(tag=f"step {self._version}")
            self._maybe_checkpoint()
        return processed

    def evaluate(self, tag: str = "final") -> dict:
        if self._eval_reader is None or self._state is None:
            return {}
        eval_metrics = (
            self._spec.eval_metrics_fn()
            if self._spec.eval_metrics_fn
            else {"loss": metrics_lib.Mean()}
        )
        shards = self._eval_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            evaluation_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        loss_mean = metrics_lib.Mean()
        while True:
            tid, task = dispatcher.get_eval_task(0)
            if task is None:
                break
            for features, labels in self._task_dataset(
                self._eval_reader, task, Modes.EVALUATION
            ):
                outputs, loss = self._eval_step(self._state, features, labels)
                metrics_lib.update_metric_tree(
                    eval_metrics, np.asarray(labels), _to_numpy(outputs)
                )
                loss_mean.update_value(loss, _batch_size(labels))
            dispatcher.report(tid, True)
        results = metrics_lib.metric_tree_results(eval_metrics)
        results["loss"] = loss_mean.result()
        logger.info("Evaluation (%s): %s", tag, results)
        return results

    def predict(self) -> list:
        if self._predict_reader is None:
            return []
        shards = self._predict_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            prediction_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        outputs_all = []
        while True:
            tid, task = dispatcher.get(0)
            if task is None:
                break
            for features in self._task_dataset(
                self._predict_reader, task, Modes.PREDICTION
            ):
                self._ensure_state(features)
                outputs = self._predict_step(self._state, features)
                processed = _to_numpy(outputs)
                if self._spec.prediction_outputs_processor is not None:
                    self._spec.prediction_outputs_processor.process(
                        processed, worker_id=0
                    )
                outputs_all.append(processed)
            dispatcher.report(tid, True)
        return outputs_all

    def run(self) -> dict:
        """Train (with periodic eval), then final eval; returns final
        metrics (reference local_executor.py:73-95)."""
        if self._train_reader is None:
            if self._eval_reader is not None:
                # evaluation-only job needs initialized state
                self._init_from_eval_data()
                return self.evaluate()
            self.predict()
            return {}
        shards = self._train_reader.create_shards()
        dispatcher = TaskDispatcher(
            shards,
            records_per_task=self._args.records_per_task,
            num_epochs=self._args.num_epochs,
            shuffle_seed=getattr(self._args, "shuffle_seed", None),
        )
        total = 0
        try:
            while True:
                tid, task = dispatcher.get(0)
                if task is None:
                    break
                with self._timing.record("task_process"):
                    total += self._train_task(task)
                dispatcher.report(tid, True)
        finally:
            # flush (or diagnose) the trace even on a mid-training error —
            # a leaked active trace poisons later start_trace calls
            self._profiler.stop()
        logger.info(
            "Training complete: %d records, %d steps", total, self._version
        )
        self._timing.report_timing(reset=True)
        if self._saver is not None:
            self._saver.save(
                self._version,
                dense=state_to_checkpoint(self._state),
                extra={"model_version": self._version},
            )
        results = self.evaluate()
        if self._args.output and self._state is not None:
            from elasticdl_tpu.utils.export_utils import export_model

            export_model(
                self._args.output, self._state, self._spec, self._args
            )
        return results

    def _init_from_eval_data(self):
        shards = self._eval_reader.create_shards()
        dispatcher = TaskDispatcher(
            None,
            evaluation_shards=shards,
            records_per_task=self._args.records_per_task,
        )
        tid, task = dispatcher.get_eval_task(0)
        if task is None:
            return
        for features, _ in self._task_dataset(
            self._eval_reader, task, Modes.EVALUATION
        ):
            self._ensure_state(features)
            break

    @property
    def state(self) -> TrainState | None:
        return self._state


def _batch_size(labels) -> int:
    if isinstance(labels, dict):
        labels = next(iter(labels.values()))
    return int(np.shape(labels)[0]) if np.ndim(labels) else 1


def _to_numpy(outputs):
    if isinstance(outputs, dict):
        return {k: np.asarray(v) for k, v in outputs.items()}
    return np.asarray(outputs)
