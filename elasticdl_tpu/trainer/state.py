"""Train state: the carried pytree of a training run.

Replaces the reference's scattered mutable state (Keras model variables +
optimizer slots living in PS pods, ``ps/parameters.py``) with one immutable
pytree that jit steps thread through — params, optax optimizer state,
mutable model collections (BatchNorm statistics), and the step counter.
Because it is a single pytree, sharding it over a mesh, checkpointing it,
and re-sharding it on mesh re-formation are all uniform tree operations.
"""

from __future__ import annotations

import enum
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct


class Modes(str, enum.Enum):
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any  # mutable collections (e.g. batch_stats); {} if none
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: Any = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u, self.params, updates
        )
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
        )

    @classmethod
    def create(cls, apply_fn, params, tx, model_state=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            model_state=model_state or {},
            apply_fn=apply_fn,
            tx=tx,
        )


def init_model(model, sample_features, rng_seed: int = 0):
    """Initialize a flax module from one example batch.

    Returns (params, model_state) with mutable collections (batch_stats)
    split out of the variable dict.
    """
    rng = jax.random.PRNGKey(rng_seed)
    variables = model.init(rng, sample_features, training=False)
    params = variables.get("params", {})
    model_state = {k: v for k, v in variables.items() if k != "params"}
    return params, model_state


def state_to_checkpoint(state: TrainState) -> dict:
    """Flatten params + mutable collections into one name-keyed dict.

    Parameter names get a ``params/`` prefix and collections keep their
    collection name (``batch_stats/...``), so one flat namespace holds the
    whole restorable model (reference checkpoints similarly key by
    variable name, save_utils.py:100-116).
    """
    from elasticdl_tpu.utils import tree_utils

    out = {
        f"params/{k}": v
        for k, v in tree_utils.tree_to_dict(state.params).items()
    }
    if state.model_state:
        out.update(tree_utils.tree_to_dict(state.model_state))
    return out


def checkpoint_to_state(state: TrainState, flat: dict) -> TrainState:
    """Inverse of :func:`state_to_checkpoint`; optimizer state restarts
    fresh (matching the reference, which restores variables only)."""
    from elasticdl_tpu.utils import tree_utils

    params = tree_utils.dict_to_tree(
        {
            k[len("params/"):]: v
            for k, v in flat.items()
            if k.startswith("params/")
        },
        state.params,
    )
    model_state = state.model_state
    rest = {k: v for k, v in flat.items() if not k.startswith("params/")}
    if model_state and rest:
        model_state = tree_utils.dict_to_tree(rest, model_state)
    return state.replace(params=params, model_state=model_state)


def count_params(params) -> int:
    return sum(
        leaf.size for leaf in jax.tree_util.tree_leaves(params)
    )
